"""Driver benchmark: SceneFlow-recipe training throughput, stereo-pairs/sec/chip.

Runs the flagship RAFTStereo training step with the reference's published
SceneFlow recipe (batch 8, 22 train iters, n_downsample 2, mixed precision —
reference README.md:130) on synthetic data with the training crop size
(320x720, train_stereo.py:228), and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "pairs/sec/chip", "vs_baseline": N/20}

Baseline: the driver's north-star target of 20 stereo-pairs/sec/chip
(BASELINE.json). On non-TPU hosts a reduced shape is used so the benchmark
stays runnable; the JSON notes the platform so numbers are not comparable
across platforms.

Harness design (r4): every attempt runs in a FRESH SUBPROCESS. Round 3 lost
its number to cumulative in-process leakage — two remote-compile HTTP 500s
pinned their attempts' buffers (state + batch + compiled pieces, retained via
the exception traceback) and every later attempt, down to batch 2, died
RESOURCE_EXHAUSTED on a 16 GB chip that had run batch 8 the round before.
Subprocess isolation guarantees each attempt starts with empty HBM and
survives a wedged compile helper (per-attempt timeout). The chain is ordered
primary -> proven banker -> fallbacks; the banker (b8 + hires-blocks encoder
remat + the r4 best schedule, 9.55-9.64 pairs/s measured over five r4 runs)
banks a number before anything risky, with the full blocks-remat config
(9.40-9.41) as the below-par fallback behind it, and the parent emits the
BEST successful JSON even if other attempts fail.
"""

import json
import os
import subprocess
import sys
import time

# The dated JSON-line sink lives in obs/ (shared with run telemetry); the
# re-export keeps the harnesses' `from bench import append_json_log` working.
# obs.events is stdlib-only — the parent stays immune to a wedged jax import.
from raft_stereo_tpu.obs.events import append_json_log  # noqa: F401

BASELINE_PAIRS_PER_SEC_PER_CHIP = 20.0
_RESULT_MARK = "BENCH_RESULT_JSON:"

# Per-attempt wall-clock cap: compile (remote helper, observed 1-4 min on the
# big graphs) + 8 steps + import overhead. A wedged helper burns one slot,
# not the round.
_ATTEMPT_TIMEOUT_S = int(os.environ.get("BENCH_ATTEMPT_TIMEOUT_S", "1500"))
# Overall budget: once exceeded, remaining attempts are skipped and the best
# banked result (if any) is emitted. (The r3 driver let a 9-attempt chain
# run ~80 min; 4800 s keeps headroom below that.)
_DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "4800"))


def run_bench(batch, h, w, train_iters, steps, fused_loss=False,
              remat_encoders=False, fused_lookup=None,
              upsample_tile_budget=None, remat_loss_tail=True,
              fold_enc_saves=None, scan_unroll=1,
              refinement_save_policy=None, corr_implementation="reg",
              corr_storage_dtype="bfloat16", batched_scan_wgrad=None,
              residual_dtype=None, compile_only=False):
    # Persistent compilation cache, shared across attempt subprocesses AND
    # driver runs: the tunneled remote-compile helper goes through long
    # degraded windows (r3: every big graph rejected; r4: wedged for hours);
    # once a recipe has compiled ONCE on a healthy helper, later attempts
    # reuse the executable instead of gambling on service health.
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "10")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
    from raft_stereo_tpu.models import init_model
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState, make_train_step

    platform = jax.devices()[0].platform
    n_chips = jax.device_count()

    # bf16 volume storage has been the bench default since r4 (0.001% EPE
    # cost, PARITY.md r2; halves the B*H*W^2 residency); the explicit kwarg
    # lets the frontier harness A/B it instead of baking it in silently.
    cfg = RAFTStereoConfig(mixed_precision=True,
                           corr_implementation=corr_implementation,
                           corr_storage_dtype=corr_storage_dtype,
                           remat_encoders=remat_encoders,
                           fused_lookup=fused_lookup,
                           upsample_tile_budget=upsample_tile_budget,
                           remat_loss_tail=remat_loss_tail,
                           fold_enc_saves=fold_enc_saves,
                           scan_unroll=scan_unroll,
                           refinement_save_policy=refinement_save_policy,
                           batched_scan_wgrad=batched_scan_wgrad,
                           residual_dtype=residual_dtype)
    tcfg = TrainConfig(batch_size=batch, train_iters=train_iters,
                       num_steps=200000, image_size=(h, w))

    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, h, w, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)

    rng = np.random.default_rng(1234)
    batch_data = {
        "image1": jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)), jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)), jnp.float32),
        "flow": jnp.asarray(rng.uniform(-64, 0, (batch, h, w, 1)), jnp.float32),
        "valid": jnp.ones((batch, h, w), jnp.float32),
    }

    if n_chips > 1:
        # shard the step over all chips so pairs/sec/chip is meaningful; the
        # fused (in-scan/tile-layout) loss — the fastest measured step — is
        # plumbed through the pjit path, so the sharded recipe matches the
        # single-chip one.
        from raft_stereo_tpu.parallel.data_parallel import make_pjit_train_step
        from raft_stereo_tpu.parallel.mesh import make_mesh, replicated, shard_batch
        mesh = make_mesh(n_chips, 1)
        state = jax.device_put(state, replicated(mesh))
        batch_data = shard_batch(mesh, batch_data)
        step = make_pjit_train_step(model, tx, train_iters, mesh,
                                    fused_loss=fused_loss)
    else:
        step = jax.jit(make_train_step(model, tx, train_iters,
                                       fused_loss=fused_loss),
                       donate_argnums=(0,))

    # Run telemetry (optional): the parent chain points BENCH_RUN_DIR at the
    # rotated runs/bench/current so every attempt leaves schema events —
    # compile time, per-step phase split, throughput, and the xla_memory/
    # xla_cost introspection records the compare gate (obs/compare.py) and
    # `cli.py telemetry` read. Fail-open: a telemetry bug must not cost the
    # round its number.
    tel = None
    run_dir = os.environ.get("BENCH_RUN_DIR")
    if run_dir:
        try:
            from raft_stereo_tpu.obs import Telemetry
            tel = Telemetry(run_dir, stall_deadline_s=None)
            tel.run_start(config=dict(
                batch=batch, h=h, w=w, train_iters=train_iters, steps=steps,
                compile_only=bool(compile_only),
                corr_storage_dtype=corr_storage_dtype,
                remat_encoders=str(remat_encoders)))
        except Exception as e:
            print(f"bench telemetry disabled: {e!r}", file=sys.stderr)
            tel = None

    # AOT compile + introspection, both modes: ``lower().compile()`` builds
    # the identical executable and persistent-cache key the first jitted
    # dispatch would (same HLO, same compile options — the compile-retry
    # harness's premise), and the compiled object's memory_analysis()/
    # cost_analysis() say what the recipe NEEDS before it runs: peak bytes
    # vs chip capacity, temp residency, flops/byte (obs/xla.py).
    from raft_stereo_tpu.obs.xla import compact_xla_summary, introspect_compiled
    t0 = time.perf_counter()
    compiled = step.lower(state, batch_data).compile()
    compile_s = time.perf_counter() - t0
    if tel is not None:
        tel.emit("compile", duration_s=round(compile_s, 3),
                 source="bench_aot")
    xla = compact_xla_summary(introspect_compiled(
        compiled, tel, source=f"bench_b{batch}", extra={"batch": batch}))

    def _result(metric, value, unit, **extra):
        out = {
            "metric": metric, "value": value, "unit": unit,
            "platform": platform, "batch": batch,
            "train_iters": train_iters, "image_size": [h, w],
            # The scan-backward A/B flag (PERF.md r8): which refinement
            # backward produced this number — "batched_wgrad" (custom VJP,
            # ops/scan_grad.py) or "autodiff" (the pinned-off control).
            "scan_backward": ("batched_wgrad" if batched_scan_wgrad
                              else "autodiff"),
            # The correlation A/B flag (r18): which lookup produced this
            # number — "reg" materializes the B*H*W^2 pyramid, "fused" is
            # the memoryless W2-blocked Pallas kernel.
            "corr_implementation": corr_implementation,
        }
        if xla is not None:
            out["xla"] = xla
        out.update(extra)
        return out

    if compile_only:
        # Compile-retry harness mode (scripts/bank_monolith.py): the AOT
        # compile above already landed the executable in the persistent
        # cache — no timed steps.
        if tel is not None:
            tel.emit("run_end", steps=0, ok=True)
            tel.close()
        return _result("compile_only", round(compile_s, 1), "s_compile")

    # Warmup: one donated-state step + one steady-state step. The loss fetch
    # (device->host transfer of an executable output) is the synchronization
    # point: on tunneled TPU devices (axon), block_until_ready has been
    # observed to return before queued executions finish, but a host transfer
    # of an output scalar cannot complete until its executable does.
    state, _ = compiled(state, batch_data)
    state, metrics = compiled(state, batch_data)
    float(metrics["loss"])

    # Lagged fetch: sync step i-1's metrics while step i runs on-device, so
    # the device never idles on the host round-trip; the final fetch still
    # bounds every step's completion (steady-state training throughput).
    t0 = time.perf_counter()
    prev = None
    for i in range(steps):
        td0 = time.perf_counter()
        state, metrics = compiled(state, batch_data)
        td1 = time.perf_counter()
        if prev is not None:
            float(prev["loss"])
        tf1 = time.perf_counter()
        prev = metrics
        if tel is not None:
            tel.step(i + 1, data_wait_s=0.0, dispatch_s=td1 - td0,
                     fetch_s=tf1 - td1, batch_size=batch)
    float(prev["loss"])
    dt = time.perf_counter() - t0

    pairs_per_sec = batch * steps / dt
    per_chip = pairs_per_sec / n_chips
    if tel is not None:
        tel.throughput(per_chip, steps=steps, window_s=round(dt, 3))
        tel.memory()
        tel.emit("run_end", steps=steps, ok=True)
        tel.close()
    return _result(
        "sceneflow_train_throughput", round(per_chip, 3), "pairs/sec/chip",
        vs_baseline=round(per_chip / BASELINE_PAIRS_PER_SEC_PER_CHIP, 3))


# The SceneFlow-recipe flagship shape (reference README.md:130 batch at
# train_stereo.py:228 crop), shared by the attempt chain and the external
# harnesses (scripts/bank_monolith.py, scripts/batch_frontier.py): identical
# kwargs => identical HLO => identical persistent-cache key, which is the
# whole premise of the compile-retry harness.
FLAGSHIP_RECIPE = dict(h=320, w=720, train_iters=22, steps=6)


def primary_attempt_kwargs():
    """EXACT kwargs of the chain's primary (monolithic b8) attempt."""
    from raft_stereo_tpu.config import R4_BEST_SCHEDULE
    return dict(batch=8, fused_loss=True, **R4_BEST_SCHEDULE,
                **FLAGSHIP_RECIPE)


# r4's measured banker number (hires-blocks remat + one-shot upsample +
# saved loss tail + unfolded saves; 9.55-9.64 over five runs, mean ~9.58
# — par sits just under the noise floor so an ordinary banker run clears
# it): attempts marked "below_par" keep running until the banked best
# reaches it, so regressions in newer paths can't silently cap the round.
_PAR_PAIRS_PER_SEC = 9.5

# Timed steps for the banker attempt (the recipe's 6 elsewhere): 12 halves
# the sample noise of the banked number (VERDICT r5 #2 — the r5 artifact
# wobbled 0.7% below README's in-round best on a 6-step sample). Only the
# banker pays for it: fallbacks exist to land ANY number, the banker to
# land a STABLE one.
_BANKER_TIMED_STEPS = 12


def _attempt_chain(on_tpu):
    """Ordered attempt list. ``when`` controls skipping:

    * ``always`` — run regardless of banked results (could beat them),
    * ``below_par`` — run unless the banked best already meets
      ``_PAR_PAIRS_PER_SEC``,
    * ``unbanked`` — run only while no result is banked yet (fallbacks).
    """
    if not on_tpu:
        return [dict(kw=dict(batch=2, h=96, w=160, train_iters=4, steps=3),
                     when="always", note=None),
                # The scan-backward A/B rides the reduced chain too so
                # non-TPU rounds still leave both-paths artifacts in
                # attempts.jsonl (numbers not comparable across platforms).
                dict(kw=dict(batch=2, h=96, w=160, train_iters=4, steps=3,
                             batched_scan_wgrad=True),
                     when="always",
                     note="scan custom-VJP A/B (batched weight grads)"),
                # The fused-vs-reg correlation A/B (r18): the first row
                # above is the reg control; this runs the identical recipe
                # on the memoryless kernel end-to-end (interpret-mode
                # Pallas on CPU — a correctness/pipeline artifact, not a
                # speed number).
                dict(kw=dict(batch=2, h=96, w=160, train_iters=4, steps=3,
                             corr_implementation="fused"),
                     when="always",
                     note="memoryless fused-corr A/B (reg control above)")]
    recipe = FLAGSHIP_RECIPE
    # The r4-measured winning schedule (9.42 pairs/s): one-shot post-scan
    # upsample (the lax.map chunking's serialization cost -0.12), SAVED
    # loss tail (the rematerialized tail's backward recompute cost -0.2;
    # its residency fits b8 alongside UNFOLDED blocks-remat saves, whose
    # lane-dense fold cost -0.39). fused_lookup auto already resolves OFF
    # (-1.5, PERF.md "r4 A/B"). Shared with scripts/profile_step.py via
    # config.R4_BEST_SCHEDULE (keys = RAFTStereoConfig field names = the
    # run_bench kwarg names) so the profiled schedule tracks the banker.
    from raft_stereo_tpu.config import R4_BEST_SCHEDULE
    best_sched = dict(R4_BEST_SCHEDULE)
    return [
        # Primary: monolithic deferred-upsample + fused-loss b8 — the fastest
        # variant IF the compile service accepts it (rejected every session
        # since r1; r5 root-caused the rejection to a broken env var in the
        # terminal's big-graph compile subprocess, PERF.md — the retry
        # harness still probes in case the terminal image gets fixed, and a
        # banked compile is permanent via .jax_cache). Tighter timeout: when
        # it fails it fails by AOT-OOM or HTTP 500 within ~5 min; a wedged
        # helper must not eat the banker's slot.
        dict(kw=primary_attempt_kwargs(), when="always", note=None,
             timeout_s=900),
        # BANKER: hi-res-only block remat (fnet remats just its layer1
        # blocks — the ones running entirely at post-stem resolution —
        # cnet and everything else saved) — compiles at b8 and measured
        # 9.55-9.64 over five runs vs 9.40-9.41 for full blocks-remat;
        # rematting less (layer1_0 alone, in either scoping) is
        # helper-rejected, the measured frontier. below_par (not
        # unbanked): even if the primary lands, a below-par primary must
        # not cap the round. Timed steps are doubled vs the recipe
        # (VERDICT r5 #2): a single 6-step sample put the banked number
        # anywhere in the 9.55-9.64 band, sometimes under already-published
        # figures; with the executable .jax_cache-warm the compile is free,
        # so the budget goes to measurement. `steps` is host-side loop
        # count — the HLO and persistent-cache key are unchanged.
        dict(kw=dict(batch=8, fused_loss=True,
                     remat_encoders="blocks_hires", **best_sched,
                     **{**recipe, "steps": _BANKER_TIMED_STEPS}),
             when="below_par", note="hires-blocks banker, r4 best schedule"),
        # Scan-backward A/B (PERF.md r8): the banker schedule with the
        # custom-VJP refinement scan ON — batched weight gradients + bf16
        # residual stacks (residual_dtype bounds the (input, cotangent)
        # stacks that made this lever memory-infeasible in the r4
        # analysis). `always`, so benchmark day banks whichever backward
        # is faster: if this beats the banker it becomes the round's
        # number, if it regresses the gate above already banked the
        # autodiff control — either way both rows land in attempts.jsonl
        # and the banked JSON line carries the scan_backward flag.
        dict(kw=dict(batch=8, fused_loss=True,
                     remat_encoders="blocks_hires",
                     batched_scan_wgrad=True, residual_dtype="bfloat16",
                     **best_sched, **recipe),
             when="always",
             note="scan custom-VJP A/B (batched weight grads, bf16 "
                  "residual stacks); pinned-off control = banker"),
        # Fused-vs-reg correlation A/B (r18): the banker schedule with the
        # memoryless W2-blocked lookup in place of the materialized volume
        # pyramid. `always`, mirroring the scan A/B: if deleting the
        # B*H*W^2 residency buys throughput (or the banker stops fitting),
        # this becomes the round's number; either way both rows land in
        # attempts.jsonl and the banked JSON line carries
        # corr_implementation. The banker row above is the reg control.
        dict(kw=dict(batch=8, fused_loss=True,
                     remat_encoders="blocks_hires",
                     corr_implementation="fused", **best_sched, **recipe),
             when="always",
             note="memoryless fused-corr A/B at the banker schedule; "
                  "reg control = banker"),
        # The full blocks-remat config: ~1.7 GB less residency than the
        # banker and proven over three rounds of sessions — the next stop
        # if the banker's extra saves stop fitting.
        dict(kw=dict(batch=8, fused_loss=True, remat_encoders="blocks",
                     **best_sched, **recipe),
             when="below_par", note="blocks-remat fallback, r4 best schedule"),
        # Memory-safe insurance: rematerialized loss tail + default
        # (chunk-on-pressure) upsample budget trades ~0.6 pairs/s for
        # ~2-3 GB less residency (8.72-8.84 measured) — for a day when the
        # banker's saved-tail residency no longer fits.
        dict(kw=dict(batch=8, fused_loss=True, remat_encoders="blocks",
                     **recipe),
             when="unbanked", note="rematerialized-tail fallback"),
        # The r5 batch-frontier's best non-b8 point (9.01 measured; the
        # full-encoder-remat family is the only schedule the terminal's
        # compile subprocess accepts above b8 — PERF.md "r5: the batch-scaling frontier").
        # NOT the reference recipe's batch: the JSON carries batch=16 so
        # the row is clearly labeled; it only runs if every b8 path above
        # failed to bank.
        dict(kw=dict(batch=16, fused_loss=True, remat_encoders=True,
                     **recipe),
             when="unbanked", note="b16 frontier fallback (non-reference "
                                   "batch, see PERF.md batch-scaling frontier)"),
        # Fallbacks, expected slower than the banker — only run while
        # nothing is banked. (split_step was DELETED in r5: its b8 pieces
        # hit the same deterministic compile-subprocess bug as the monolith
        # in every probe window, falsifying its premise — see PERF.md "r5:
        # the monolith rejection root-caused".)
        dict(kw=dict(batch=8, fused_loss=True, remat_encoders="norms",
                     **recipe),
             when="unbanked", note="norms-remat fallback, same recipe"),
        dict(kw=dict(batch=8, fused_loss=True, remat_encoders=True, **recipe),
             when="unbanked", note="encoder-remat fallback, same recipe"),
        dict(kw=dict(batch=4, fused_loss=True, **recipe),
             when="unbanked", note="reduced batch fallback"),
        dict(kw=dict(batch=2, h=224, w=480, train_iters=22, steps=6,
                     fused_loss=True),
             when="unbanked", note="reduced recipe fallback"),
    ]


def run_attempt_subprocess_detailed(kw, timeout_s=None, lock_wait_s=1800.0):
    """Run one attempt in a fresh interpreter under the exclusive .tpu_lock.

    The lock is acquired in the PARENT, before the child's timeout clock
    starts: the background compile-retry prober (scripts/bank_monolith.py)
    can hold the chip for its full per-attempt budget, and an attempt that
    spent its whole subprocess timeout blocked on the lock would be killed
    without ever running. Lock-wait gets its own budget (``lock_wait_s``,
    polled non-blocking so a crashed holder's auto-released lock is picked
    up promptly).

    Returns ``(result_dict_or_None, error_tail_or_None, wall_seconds)`` —
    the single copy of the launch/parse/error-extraction protocol, shared
    with bank_monolith.
    """
    import fcntl
    timeout_s = timeout_s or _ATTEMPT_TIMEOUT_S
    here = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.abspath(__file__),
           "--attempt", json.dumps(kw)]
    t0 = time.monotonic()
    with open(os.path.join(here, ".tpu_lock"), "w") as lf:
        deadline = time.monotonic() + lock_wait_s
        while True:
            try:
                fcntl.flock(lf, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except BlockingIOError:
                if time.monotonic() > deadline:
                    return (None, f"tpu lock not acquired in {lock_wait_s}s",
                            time.monotonic() - t0)
                time.sleep(5.0)
        try:
            proc = subprocess.run(
                cmd, cwd=here, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, timeout=timeout_s, text=True)
        except subprocess.TimeoutExpired:
            return (None, f"timeout after {timeout_s}s",
                    time.monotonic() - t0)
    out = proc.stdout or ""
    for line in out.splitlines():
        if line.startswith(_RESULT_MARK):
            try:
                return (json.loads(line[len(_RESULT_MARK):]), None,
                        time.monotonic() - t0)
            except json.JSONDecodeError:
                break
    # surface the actual error line, not the traceback boilerplate
    lines = out.splitlines()
    err_lines = [l for l in lines if "Error" in l or "RESOURCE" in l
                 or "INTERNAL" in l][-3:]
    tail = "\n".join(err_lines or lines[-8:])
    return (None, f"rc={proc.returncode}: {tail}", time.monotonic() - t0)


def _run_attempt_subprocess(kw, timeout_s=None):
    """run_chain's runner: result dict or None, errors to stderr."""
    result, err, _ = run_attempt_subprocess_detailed(kw, timeout_s)
    if result is None:
        print(f"bench attempt {kw} failed: {err}", file=sys.stderr)
    return result


def _rotate_bench_run_dir():
    """Rotate the chain's telemetry dir: runs/bench/current -> previous.

    Every attempt child (which inherits ``BENCH_RUN_DIR``) appends its
    schema events to ``current``; keeping the prior chain's log as
    ``previous`` gives the rehearsal's regression gate
    (``scripts/rehearse_round.py`` compare leg / obs/compare.py) its
    baseline without any bookkeeping elsewhere. An externally-set
    ``BENCH_RUN_DIR`` is respected untouched (harnesses that want their
    own dir, e.g. tests).
    """
    if os.environ.get("BENCH_RUN_DIR"):
        return os.environ["BENCH_RUN_DIR"]
    import shutil
    root = os.environ.get(
        "BENCH_RUN_ROOT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "runs", "bench"))
    current = os.path.join(root, "current")
    previous = os.path.join(root, "previous")
    try:
        if os.path.isdir(current):
            shutil.rmtree(previous, ignore_errors=True)
            os.rename(current, previous)
    except OSError as e:
        print(f"bench run-dir rotation failed (continuing): {e}",
              file=sys.stderr)
    os.environ["BENCH_RUN_DIR"] = current
    return current


def _probe_on_tpu():
    """Platform probe in a child process, crash-proof: a wedged TPU-plugin
    import (the degraded environment this harness exists for) must not take
    the parent down. Inconclusive probes assume TPU — this is the driver's
    TPU benchmark, every attempt is subprocess-isolated and time-bounded,
    and a wrong-shape CPU number would be worse than a late failure."""
    for t in (300, 120):
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
                timeout=t)
        except Exception as e:
            print(f"platform probe failed: {e!r}; retrying", file=sys.stderr)
            continue
        lines = probe.stdout.strip().splitlines()
        if probe.returncode == 0 and lines:
            return lines[-1] == "tpu"
        print(f"platform probe rc={probe.returncode}; retrying",
              file=sys.stderr)
    print("platform probe inconclusive; assuming TPU", file=sys.stderr)
    return True


def main():
    if "--attempt" in sys.argv:
        # Child mode: one attempt, fresh HBM, result on a marked line.
        # Serialization on the single 16 GB chip is the PARENT's job
        # (run_attempt_subprocess_detailed holds .tpu_lock around the child),
        # so two concurrent b8 residencies can't OOM each other; the lock
        # releases automatically if the parent's timeout kills the child.
        kw = json.loads(sys.argv[sys.argv.index("--attempt") + 1])
        result = run_bench(**kw)
        print(_RESULT_MARK + json.dumps(result), flush=True)
        return 0

    # Parent mode: probe the platform cheaply (no jax import in the parent —
    # keep the parent immune to anything an attempt can break). The probe's
    # own wall clock counts against the deadline.
    t_start = time.monotonic()
    on_tpu = _probe_on_tpu()
    _rotate_bench_run_dir()
    log_path = os.environ.get(
        "BENCH_ATTEMPTS_LOG",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "runs", "bench", "attempts.jsonl"))
    best = run_chain(_attempt_chain(on_tpu), _run_attempt_subprocess,
                     t_start=t_start, log_path=log_path)
    if best is None:
        print("all bench attempts failed", file=sys.stderr)
        return 1
    print(json.dumps(best))
    return 0


def run_chain(attempts, runner, t_start=None, deadline_s=None, log_path=None):
    """Drive the attempt chain: gate by ``when`` tier, keep the best result.

    Separated from main() so the gating policy — the part that decides
    whether the round reports a number at all — is unit-testable with a
    stubbed runner (tests/test_bench_chain.py).

    ``log_path``: optional JSONL attempt log through the shared obs/ sink —
    every attempt outcome (ok/failed/skipped/deadline) becomes a dated
    record instead of a bespoke stderr print, so a round's history is a
    machine-readable artifact (mirrored to stderr; stdout stays the parsed
    result protocol).
    """
    if t_start is None:
        t_start = time.monotonic()
    if deadline_s is None:
        deadline_s = _DEADLINE_S

    def log(entry):
        if log_path:
            append_json_log(log_path, entry, stream=sys.stderr)

    best = None
    for i, att in enumerate(attempts):
        base = {"attempt": i, "kw": att["kw"], "note": att.get("note"),
                "when": att["when"]}
        if att["when"] == "unbanked" and best is not None:
            log({**base, "status": "skipped", "reason": "already banked"})
            continue
        if (att["when"] == "below_par" and best is not None
                and best["value"] >= _PAR_PAIRS_PER_SEC):
            log({**base, "status": "skipped", "reason": "banked best at par"})
            continue
        if time.monotonic() - t_start > deadline_s:
            print("bench deadline reached; stopping the chain",
                  file=sys.stderr)
            log({**base, "status": "deadline",
                 "elapsed_s": round(time.monotonic() - t_start, 1)})
            break
        result = runner(att["kw"], att.get("timeout_s"))
        if result is None:
            log({**base, "status": "failed"})
            continue
        if att.get("note"):
            result["note"] = att["note"]
        print(f"bench attempt ok: {result}", file=sys.stderr)
        log({**base, "status": "ok", "result": result})
        if best is None or result["value"] > best["value"]:
            best = result
    return best


if __name__ == "__main__":
    sys.exit(main())
