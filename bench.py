"""Driver benchmark: SceneFlow-recipe training throughput, stereo-pairs/sec/chip.

Runs the flagship RAFTStereo training step with the reference's published
SceneFlow recipe (batch 8, 22 train iters, n_downsample 2, mixed precision —
reference README.md:130) on synthetic data with the training crop size
(320x720, train_stereo.py:228), and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "pairs/sec/chip", "vs_baseline": N/20}

Baseline: the driver's north-star target of 20 stereo-pairs/sec/chip
(BASELINE.json). On non-TPU hosts a reduced shape is used so the benchmark
stays runnable; the JSON notes the platform so numbers are not comparable
across platforms.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.training.optim import fetch_optimizer
from raft_stereo_tpu.training.state import TrainState, make_train_step

BASELINE_PAIRS_PER_SEC_PER_CHIP = 20.0


def run_bench(batch, h, w, train_iters, steps, fused_loss=False,
              remat_encoders=False, split_step=False):
    platform = jax.devices()[0].platform
    n_chips = jax.device_count()

    cfg = RAFTStereoConfig(mixed_precision=True,
                           corr_storage_dtype="bfloat16",
                           remat_encoders=remat_encoders)
    tcfg = TrainConfig(batch_size=batch, train_iters=train_iters,
                       num_steps=200000, image_size=(h, w))

    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, h, w, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)

    rng = np.random.default_rng(1234)
    batch_data = {
        "image1": jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)), jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)), jnp.float32),
        "flow": jnp.asarray(rng.uniform(-64, 0, (batch, h, w, 1)), jnp.float32),
        "valid": jnp.ones((batch, h, w), jnp.float32),
    }

    if n_chips > 1:
        # shard the step over all chips so pairs/sec/chip is meaningful; the
        # fused (in-scan/tile-layout) loss — the fastest measured step — is
        # plumbed through the pjit path, so the sharded recipe matches the
        # single-chip one.
        from raft_stereo_tpu.parallel.data_parallel import make_pjit_train_step
        from raft_stereo_tpu.parallel.mesh import make_mesh, replicated, shard_batch
        mesh = make_mesh(n_chips, 1)
        state = jax.device_put(state, replicated(mesh))
        batch_data = shard_batch(mesh, batch_data)
        step = make_pjit_train_step(model, tx, train_iters, mesh,
                                    fused_loss=fused_loss)
    elif split_step:
        # three-piece split compilation (training/split_step.py): the
        # plain-b8 schedule — full encoder residuals, no encoder recompute —
        # through graphs the degraded remote compile helper accepts
        from raft_stereo_tpu.training.split_step import make_split_train_step
        step = make_split_train_step(model, tx, train_iters,
                                     fused_loss=fused_loss)
    else:
        step = jax.jit(make_train_step(model, tx, train_iters,
                                       fused_loss=fused_loss),
                       donate_argnums=(0,))

    # Warmup: compile + one steady-state step. The loss fetch (device->host
    # transfer of an executable output) is the synchronization point: on
    # tunneled TPU devices (axon), block_until_ready has been observed to
    # return before queued executions finish, but a host transfer of an output
    # scalar cannot complete until its executable does.
    state, _ = step(state, batch_data)
    state, metrics = step(state, batch_data)
    float(metrics["loss"])

    # Lagged fetch: sync step i-1's metrics while step i runs on-device, so
    # the device never idles on the host round-trip; the final fetch still
    # bounds every step's completion (steady-state training throughput).
    t0 = time.perf_counter()
    prev = None
    for _ in range(steps):
        state, metrics = step(state, batch_data)
        if prev is not None:
            float(prev["loss"])
        prev = metrics
    float(prev["loss"])
    dt = time.perf_counter() - t0

    pairs_per_sec = batch * steps / dt
    per_chip = pairs_per_sec / n_chips
    return {
        "metric": "sceneflow_train_throughput",
        "value": round(per_chip, 3),
        "unit": "pairs/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_PAIRS_PER_SEC_PER_CHIP, 3),
        "platform": platform,
        "batch": batch,
        "train_iters": train_iters,
        "image_size": [h, w],
    }


def main():
    on_tpu = jax.devices()[0].platform == "tpu"

    # SceneFlow recipe (README.md:130); reduced shapes keep CPU smoke runs
    # fast. The tunneled TPU compile service has been observed to 500 on the
    # largest graphs when degraded — fall back to reduced recipes (flagged in
    # the JSON) rather than report nothing.
    if on_tpu:
        attempts = [
            # Primary: the monolithic deferred-upsample + fused-loss step —
            # the fastest variant IF the compile service accepts it (it has
            # rejected every monolithic b8 graph since r1).
            dict(batch=8, h=320, w=720, train_iters=22, steps=6,
                 fused_loss=True),
            # "norms" encoder remat: save conv outputs + norm stats,
            # recompute only elementwise glue — no conv re-runs. Plain
            # backward's residuals (24.9 GB at b8: fp32 norm intermediates +
            # bool relu masks) cannot fit the 16 GB chip, which is the
            # monolith failure's root cause; this policy keeps the MXU work
            # saved at ~7 GB.
            dict(batch=8, h=320, w=720, train_iters=22, steps=6,
                 fused_loss=True, remat_encoders="norms",
                 _note="norms-remat (save convs, recompute glue), same recipe"),
            # Split-compilation: the same step as three pieces the helper
            # accepts (probe_compile.py) — plain-b8 schedule, full encoder
            # residuals, no encoder recompute (OOMs at b8; viable for
            # smaller shapes if the monolith is rejected).
            dict(batch=8, h=320, w=720, train_iters=22, steps=6,
                 fused_loss=True, split_step=True,
                 _note="split-compilation step, same recipe"),
            dict(batch=8, h=320, w=720, train_iters=22, steps=6,
                 _note="stacked-loss fallback, same recipe"),
            # The remote compile helper's failures are size-proportional:
            # when the full batch-8 graph is rejected, walk down through
            # smaller-footprint variants of the same recipe before shrinking
            # the batch (throughput rises with batch, t(B) = fixed + k*B).
            dict(batch=8, h=320, w=720, train_iters=22, steps=6,
                 fused_loss=True, remat_encoders="blocks",
                 _note="encoder-block-remat fallback, same recipe"),
            dict(batch=8, h=320, w=720, train_iters=22, steps=6,
                 fused_loss=True, remat_encoders=True,
                 _note="encoder-remat fallback, same recipe"),
            dict(batch=6, h=320, w=720, train_iters=22, steps=6,
                 fused_loss=True, _note="reduced batch (6) fallback"),
            dict(batch=4, h=320, w=720, train_iters=22, steps=6,
                 fused_loss=True, _note="reduced batch fallback"),
            dict(batch=2, h=224, w=480, train_iters=22, steps=6,
                 fused_loss=True, _note="reduced recipe fallback"),
        ]
    else:
        attempts = [dict(batch=2, h=96, w=160, train_iters=4, steps=3)]

    last_err = None
    for kw in attempts:
        kw = dict(kw)
        note = kw.pop("_note", None)
        try:
            result = run_bench(**kw)
        except Exception as e:  # remote-compile failure / OOM
            last_err = e
            print(f"bench attempt {kw} failed: {type(e).__name__}: "
                  f"{str(e)[:160]}", file=sys.stderr)
            continue
        if note:
            result["note"] = note
        print(json.dumps(result))
        return 0
    print(f"all bench attempts failed: {last_err}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
