// Native host-side data-path kernels for raft_stereo_tpu.
//
// The reference delegates its host data path to torch's C++ DataLoader
// machinery (SURVEY §2.1 component 5); this library is the framework's own
// native equivalent for the decode hot loop: a zero-copy (mmap) PFM decoder
// with the bottom-up row flip and byte-order swap fused into the single
// output write, plus a fused uint8->float32 batch collator. Exposed through
// a minimal C ABI consumed via ctypes (no pybind11 dependency by design).
//
// Build: `make -C native` -> libstereodata.so. Python side:
// raft_stereo_tpu/data/native.py (builds on demand, falls back to numpy).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// Parse a PFM header. Returns 0 on success and fills width/height/channels/
// little_endian/data_offset; negative error codes otherwise.
int pfm_probe(const char* path, int32_t* width, int32_t* height,
              int32_t* channels, int32_t* little_endian,
              int64_t* data_offset) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  char tag[3] = {0, 0, 0};
  if (std::fscanf(f, "%2s", tag) != 1) { std::fclose(f); return -2; }
  if (tag[0] != 'P' || (tag[1] != 'F' && tag[1] != 'f')) {
    std::fclose(f);
    return -3;
  }
  *channels = tag[1] == 'F' ? 3 : 1;
  double scale;
  if (std::fscanf(f, "%d %d %lf", width, height, &scale) != 3 ||
      *width <= 0 || *height <= 0) {
    std::fclose(f);
    return -4;
  }
  // The scale line ends with a newline; tolerate CRLF-written files by
  // consuming to (and including) the '\n' rather than a single byte —
  // mirrors the numpy reference's readline() and keeps data_offset exact.
  int ch;
  do {
    ch = std::fgetc(f);
  } while (ch != '\n' && ch != EOF);
  if (ch == EOF) { std::fclose(f); return -5; }
  *little_endian = scale < 0.0 ? 1 : 0;
  *data_offset = std::ftell(f);
  std::fclose(f);
  return 0;
}

static inline float bswap_float(float v) {
  uint32_t u;
  std::memcpy(&u, &v, 4);
  u = __builtin_bswap32(u);
  std::memcpy(&v, &u, 4);
  return v;
}

// Decode the PFM payload at `path` into `out` (H*W*C float32, top-down row
// order — the flip from PFM's bottom-up storage happens during the copy).
// Returns 0 on success.
int pfm_decode(const char* path, int64_t data_offset, int32_t width,
               int32_t height, int32_t channels, int32_t little_endian,
               float* out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -2; }
  const int64_t row_elems = static_cast<int64_t>(width) * channels;
  const int64_t payload = row_elems * height * 4;
  if (st.st_size < data_offset + payload) { close(fd); return -3; }

  void* mapped = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (mapped == MAP_FAILED) return -4;
  // data_offset is rarely 4-byte aligned, so all payload access goes through
  // byte pointers + memcpy (direct float loads would be UB / SIGBUS).
  const char* src = static_cast<const char*>(mapped) + data_offset;

  for (int32_t r = 0; r < height; ++r) {
    // PFM rows run bottom-to-top; write them top-down.
    const char* src_row = src + static_cast<int64_t>(height - 1 - r) * row_elems * 4;
    float* dst_row = out + static_cast<int64_t>(r) * row_elems;
    if (little_endian) {
      std::memcpy(dst_row, src_row, row_elems * 4);
    } else {
      for (int64_t i = 0; i < row_elems; ++i) {
        float v;
        std::memcpy(&v, src_row + i * 4, 4);
        dst_row[i] = bswap_float(v);
      }
    }
  }
  munmap(mapped, st.st_size);
  return 0;
}

// Fused collate: stack `n` uint8 HWC images into one float32 (N,H,W,C)
// buffer (the loader's stack + astype(float32) in a single pass).
void collate_u8_to_f32(const uint8_t** images, int32_t n, int64_t elems,
                       float* out) {
  for (int32_t i = 0; i < n; ++i) {
    const uint8_t* src = images[i];
    float* dst = out + static_cast<int64_t>(i) * elems;
    for (int64_t j = 0; j < elems; ++j) dst[j] = static_cast<float>(src[j]);
  }
}

}  // extern "C"
