// Native host-side data-path kernels for raft_stereo_tpu.
//
// The reference delegates its host data path to torch's C++ DataLoader
// machinery (SURVEY §2.1 component 5); this library is the framework's own
// native equivalent for the decode hot loop: a zero-copy (mmap) PFM decoder
// with the bottom-up row flip and byte-order swap fused into the single
// output write, plus a fused uint8->float32 batch collator. Exposed through
// a minimal C ABI consumed via ctypes (no pybind11 dependency by design).
//
// Build: `make -C native` -> libstereodata.so. Python side:
// raft_stereo_tpu/data/native.py (builds on demand, falls back to numpy).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>

// STEREODATA_HAVE_ZLIB is defined by the Makefile exactly when its link
// probe succeeds, so the compile-time and link-time decisions cannot
// disagree (a header-only system must not leave an undefined `uncompress`).
#ifdef STEREODATA_HAVE_ZLIB
#include <zlib.h>
#endif

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// Parse a PFM header. Returns 0 on success and fills width/height/channels/
// little_endian/data_offset; negative error codes otherwise.
int pfm_probe(const char* path, int32_t* width, int32_t* height,
              int32_t* channels, int32_t* little_endian,
              int64_t* data_offset) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  char tag[3] = {0, 0, 0};
  if (std::fscanf(f, "%2s", tag) != 1) { std::fclose(f); return -2; }
  if (tag[0] != 'P' || (tag[1] != 'F' && tag[1] != 'f')) {
    std::fclose(f);
    return -3;
  }
  *channels = tag[1] == 'F' ? 3 : 1;
  double scale;
  if (std::fscanf(f, "%d %d %lf", width, height, &scale) != 3 ||
      *width <= 0 || *height <= 0) {
    std::fclose(f);
    return -4;
  }
  // The scale line ends with a newline; tolerate CRLF-written files by
  // consuming to (and including) the '\n' rather than a single byte —
  // mirrors the numpy reference's readline() and keeps data_offset exact.
  int ch;
  do {
    ch = std::fgetc(f);
  } while (ch != '\n' && ch != EOF);
  if (ch == EOF) { std::fclose(f); return -5; }
  *little_endian = scale < 0.0 ? 1 : 0;
  *data_offset = std::ftell(f);
  std::fclose(f);
  return 0;
}

static inline float bswap_float(float v) {
  uint32_t u;
  std::memcpy(&u, &v, 4);
  u = __builtin_bswap32(u);
  std::memcpy(&v, &u, 4);
  return v;
}

// Decode the PFM payload at `path` into `out` (H*W*C float32, top-down row
// order — the flip from PFM's bottom-up storage happens during the copy).
// Returns 0 on success.
int pfm_decode(const char* path, int64_t data_offset, int32_t width,
               int32_t height, int32_t channels, int32_t little_endian,
               float* out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -2; }
  const int64_t row_elems = static_cast<int64_t>(width) * channels;
  const int64_t payload = row_elems * height * 4;
  if (st.st_size < data_offset + payload) { close(fd); return -3; }

  void* mapped = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (mapped == MAP_FAILED) return -4;
  // data_offset is rarely 4-byte aligned, so all payload access goes through
  // byte pointers + memcpy (direct float loads would be UB / SIGBUS).
  const char* src = static_cast<const char*>(mapped) + data_offset;

  for (int32_t r = 0; r < height; ++r) {
    // PFM rows run bottom-to-top; write them top-down.
    const char* src_row = src + static_cast<int64_t>(height - 1 - r) * row_elems * 4;
    float* dst_row = out + static_cast<int64_t>(r) * row_elems;
    if (little_endian) {
      std::memcpy(dst_row, src_row, row_elems * 4);
    } else {
      for (int64_t i = 0; i < row_elems; ++i) {
        float v;
        std::memcpy(&v, src_row + i * 4, 4);
        dst_row[i] = bswap_float(v);
      }
    }
  }
  munmap(mapped, st.st_size);
  return 0;
}

// Fused collate: stack `n` uint8 HWC images into one float32 (N,H,W,C)
// buffer (the loader's stack + astype(float32) in a single pass).
void collate_u8_to_f32(const uint8_t** images, int32_t n, int64_t elems,
                       float* out) {
  for (int32_t i = 0; i < n; ++i) {
    const uint8_t* src = images[i];
    float* dst = out + static_cast<int64_t>(i) * elems;
    for (int64_t j = 0; j < elems; ++j) dst[j] = static_cast<float>(src[j]);
  }
}

// ---------------------------------------------------------------------------
// 16-bit grayscale PNG decoder (the KITTI disparity codec: uint16 PNG,
// disparity = value/256, 0 = invalid — reference frame_utils.py:124-127).
// Scope: non-interlaced 16-bit greyscale (color type 0), the only form KITTI
// ships; anything else returns an error so callers fall back to cv2.

static inline uint32_t read_be32(const unsigned char* p) {
  return (static_cast<uint32_t>(p[0]) << 24) | (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

static inline int paeth(int a, int b, int c) {
  int p = a + b - c;
  int pa = p > a ? p - a : a - p;
  int pb = p > b ? p - b : b - p;
  int pc = p > c ? p - c : c - p;
  if (pa <= pb && pa <= pc) return a;
  return pb <= pc ? b : c;
}

#ifndef STEREODATA_HAVE_ZLIB
// zlib headers unavailable at build time: PNG support degrades to the cv2
// fallback (probe reports unsupported); the PFM/collate fast paths stay.
int png16_probe(const char*, int32_t*, int32_t*) { return -100; }
int png16_decode(const char*, int32_t, int32_t, uint16_t*) { return -100; }
#else
// Probe a PNG header: returns 0 and fills width/height when the file is a
// supported (16-bit grey, non-interlaced) PNG; negative error otherwise.
int png16_probe(const char* path, int32_t* width, int32_t* height) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char hdr[33];
  size_t got = std::fread(hdr, 1, sizeof(hdr), f);
  std::fclose(f);
  if (got != sizeof(hdr)) return -2;
  static const unsigned char sig[8] = {137, 80, 78, 71, 13, 10, 26, 10};
  if (std::memcmp(hdr, sig, 8) != 0) return -3;
  if (read_be32(hdr + 8) != 13 || std::memcmp(hdr + 12, "IHDR", 4) != 0)
    return -4;
  *width = static_cast<int32_t>(read_be32(hdr + 16));
  *height = static_cast<int32_t>(read_be32(hdr + 20));
  int bit_depth = hdr[24], color_type = hdr[25];
  int compression = hdr[26], filter_method = hdr[27], interlace = hdr[28];
  if (bit_depth != 16 || color_type != 0 || compression != 0 ||
      filter_method != 0 || interlace != 0) return -5;
  if (*width <= 0 || *height <= 0) return -6;
  return 0;
}

// Decode a 16-bit greyscale PNG into `out` (H*W uint16, host byte order).
// Returns 0 on success.
int png16_decode(const char* path, int32_t width, int32_t height,
                 uint16_t* out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -2; }
  void* mapped = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (mapped == MAP_FAILED) return -3;
  const unsigned char* data = static_cast<const unsigned char*>(mapped);
  const int64_t size = st.st_size;

  // gather IDAT payloads
  unsigned char* compressed = static_cast<unsigned char*>(std::malloc(size));
  if (!compressed) { munmap(mapped, st.st_size); return -4; }
  int64_t comp_len = 0;
  int64_t off = 8;
  int rc = -5;
  while (off + 12 <= size) {
    uint32_t len = read_be32(data + off);
    const unsigned char* type = data + off + 4;
    if (off + 12 + static_cast<int64_t>(len) > size) break;
    if (std::memcmp(type, "IDAT", 4) == 0) {
      std::memcpy(compressed + comp_len, data + off + 8, len);
      comp_len += len;
    } else if (std::memcmp(type, "IEND", 4) == 0) {
      rc = 0;
      break;
    }
    off += 12 + len;
  }
  munmap(mapped, st.st_size);
  if (rc != 0 || comp_len == 0) { std::free(compressed); return -5; }

  const int64_t stride = static_cast<int64_t>(width) * 2;  // bytes per row
  const int64_t raw_len = (stride + 1) * height;           // +1 filter byte
  unsigned char* raw = static_cast<unsigned char*>(std::malloc(raw_len));
  if (!raw) { std::free(compressed); return -4; }
  uLongf dest_len = static_cast<uLongf>(raw_len);
  int zrc = uncompress(raw, &dest_len, compressed,
                       static_cast<uLong>(comp_len));
  std::free(compressed);
  if (zrc != Z_OK || dest_len != static_cast<uLongf>(raw_len)) {
    std::free(raw);
    return -6;
  }

  // un-filter scanlines (bpp = 2 for 16-bit grey)
  unsigned char* prev = static_cast<unsigned char*>(std::calloc(stride, 1));
  if (!prev) { std::free(raw); return -4; }
  for (int32_t r = 0; r < height; ++r) {
    unsigned char* row = raw + static_cast<int64_t>(r) * (stride + 1);
    int filter = row[0];
    unsigned char* cur = row + 1;
    for (int64_t i = 0; i < stride; ++i) {
      int a = i >= 2 ? cur[i - 2] : 0;        // left (per byte-pair)
      int b = prev[i];                        // up
      int c = i >= 2 ? prev[i - 2] : 0;       // up-left
      int x = cur[i];
      switch (filter) {
        case 0: break;
        case 1: x += a; break;
        case 2: x += b; break;
        case 3: x += (a + b) / 2; break;
        case 4: x += paeth(a, b, c); break;
        default:
          std::free(prev); std::free(raw); return -7;
      }
      cur[i] = static_cast<unsigned char>(x & 0xff);
    }
    // PNG stores 16-bit samples big-endian
    uint16_t* dst = out + static_cast<int64_t>(r) * width;
    for (int32_t i = 0; i < width; ++i) {
      dst[i] = static_cast<uint16_t>((cur[2 * i] << 8) | cur[2 * i + 1]);
    }
    std::memcpy(prev, cur, stride);
  }
  std::free(prev);
  std::free(raw);
  return 0;
}
#endif  // STEREODATA_HAVE_ZLIB

}  // extern "C"
