#!/usr/bin/env python
"""Training CLI (reference train_stereo.py:214-258, same flag surface).

Thin wrapper over the installable console entry point
(``raft_stereo_tpu.cli:_train_main`` == ``raft-stereo-train``).
"""

from raft_stereo_tpu.cli import _train_main

if __name__ == "__main__":
    _train_main()
