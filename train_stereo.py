#!/usr/bin/env python
"""Training CLI (reference train_stereo.py:214-258, same flag surface)."""

import argparse
import logging

from raft_stereo_tpu import cli
from raft_stereo_tpu.training.trainer import train


def main():
    parser = argparse.ArgumentParser(description="RAFT-Stereo TPU training")
    cli.add_train_args(parser)
    cli.add_model_args(parser)
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(filename)s:%(lineno)d %(message)s")

    model_cfg = cli.model_config(args)
    train_cfg = cli.train_config(args)
    final = train(model_cfg, train_cfg)
    print(f"final checkpoint: {final}")


if __name__ == "__main__":
    main()
