#!/usr/bin/env python
"""Evaluation CLI (reference evaluate_stereo.py:192-243, same flag surface).

Thin wrapper over the installable console entry point
(``raft_stereo_tpu.cli:_eval_main`` == ``raft-stereo-eval``).
"""

from raft_stereo_tpu.cli import _eval_main

if __name__ == "__main__":
    _eval_main()
