#!/usr/bin/env python
"""Evaluation CLI (reference evaluate_stereo.py:192-243, same flag surface)."""

import argparse
import logging

from raft_stereo_tpu import cli
from raft_stereo_tpu.eval.validate import (validate_eth3d, validate_kitti,
                                           validate_middlebury,
                                           validate_things)
from raft_stereo_tpu.inference import StereoPredictor


def main():
    parser = argparse.ArgumentParser(description="RAFT-Stereo TPU evaluation")
    parser.add_argument("--restore_ckpt", default=None,
                        help="reference .pth or orbax state dir")
    parser.add_argument("--dataset", required=True,
                        choices=["eth3d", "kitti", "things",
                                 "middlebury_F", "middlebury_H",
                                 "middlebury_Q"])
    parser.add_argument("--valid_iters", type=int, default=32,
                        help="number of refinement iterations")
    parser.add_argument("--data_root", default="datasets")
    parser.add_argument("--bucket", type=int, default=0,
                        help="pad eval images up to multiples of this size "
                             "to bound recompiles (0 = exact /32 padding)")
    cli.add_model_args(parser)
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(filename)s:%(lineno)d %(message)s")

    cfg = cli.model_config(args)
    model, variables = cli.load_variables(args.restore_ckpt, cfg)
    predictor = StereoPredictor(cfg, variables, valid_iters=args.valid_iters,
                                bucket=args.bucket)

    if args.dataset == "eth3d":
        results = validate_eth3d(predictor, args.data_root, args.valid_iters)
    elif args.dataset == "kitti":
        results = validate_kitti(predictor, args.data_root, args.valid_iters)
    elif args.dataset == "things":
        results = validate_things(predictor, args.data_root, args.valid_iters)
    else:
        split = args.dataset.split("_")[1]
        results = validate_middlebury(predictor, args.data_root,
                                      args.valid_iters, split=split)
    print(results)


if __name__ == "__main__":
    main()
