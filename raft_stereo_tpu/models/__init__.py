from raft_stereo_tpu.models.raft_stereo import (
    RAFTStereo,
    RefinementStep,
    create_model,
    init_model,
)
