"""RAFT-Stereo model: encoders + correlation + iterative GRU refinement.

TPU-native re-design of core/raft_stereo.py: NHWC, functional flax module, and
the refinement loop compiled as a single ``lax.scan`` over a ``(net, coords1,
mask)`` carry (vs. the reference's Python loop, raft_stereo.py:108-136) —
iteration count is static, the update cell is traced once, and
``stop_gradient`` on ``coords1`` mirrors the reference's per-iteration
``detach`` (raft_stereo.py:109). Mixed precision is a bf16 compute-dtype
policy (no loss scaling needed on TPU) with the correlation volume kept fp32
(reference keeps corr fp32 except under the CUDA kernels,
raft_stereo.py:92-95).
"""

from __future__ import annotations

import warnings
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.nn.encoder import BasicEncoder, MultiBasicEncoder
from raft_stereo_tpu.nn.gru import (BasicMultiUpdateBlock, numerics_taps,
                                    record_numerics_tap, tag_residual)
from raft_stereo_tpu.nn.layers import Conv, ResidualBlock
from raft_stereo_tpu.ops.corr import CorrState, corr_lookup, init_corr
from raft_stereo_tpu.ops.geometry import (
    convex_upsample_tiles,
    coords_grid,
    image_to_upsample_tiles,
    upsample_disparity_convex,
    upsample_tiles_to_image,
)

Dtype = Any

# fp32 working-set budget for the post-scan batched upsample before it is
# chunked over the iteration axis (module constant so tests can force the
# chunked path at tiny shapes)
_UPSAMPLE_TILE_BUDGET = 1024 * 1024 * 1024


# ---- shape-dependent policy selection -------------------------------------
#
# These heuristics carry hand-measured calibration constants (one 16 GB v5e
# chip, SceneFlow-recipe shapes). They are module-level pure functions of
# static shapes so tests/test_training.py can pin WHICH policy engages at
# the calibrated shapes — if an estimate drifts, the pin fails loudly
# instead of silently mistuning (VERDICT r3 weak #5).

def fold_enc_saves_auto(cfg, batch: int, height: int, width: int) -> bool:
    """Auto decision for lane-dense folded saves under
    ``remat_encoders="norms"``: fold only when the padded saved-conv set
    wouldn't fit anyway. Calibration: 24 images of 320x720 (SceneFlow b8)
    measured 14.06 GB padded — ~2.5 KB per image-pixel; folded above ~9 GB.
    Folding costs relayout copies (measured -65 ms/step at b4), so small
    shapes keep unfolded saves."""
    n_images = batch * (2 if cfg.shared_backbone else 3)
    est_padded = n_images * height * width * 2543
    return est_padded > 9_000_000_000


def refinement_save_policy_fits(cfg, iters: int, batch: int, h: int, w: int,
                                dt, fused_lookup: bool = False,
                                residual_dtype=None) -> bool:
    """Whether the selective save policy (save ``gru_zr``/``gru_q``/
    ``corr_feats`` across the refinement backward) engages, vs full remat.

    ``h, w`` are the 1/factor-resolution grid dims. Measured at the
    SceneFlow recipe (PERF.md r2): the policy is 579.9 -> 544.9 ms/step at
    batch 4 yet 1085 vs 879 ms at batch 8 — HBM pressure inverts the trade.
    The estimate sums the tagged tensors at every GRU level per slow_fast
    pre-pass in the compute dtype's width; 1.5 GB covers the measured-good
    batch-4 bf16 point (1.36 GB) while excluding unproven batch >= 6.

    ``residual_dtype`` (config.residual_dtype): saves are stored at that
    width when it is narrower than the compute dtype — bf16 residuals halve
    the estimate for fp32-compute configs, admitting the policy at shapes
    the fp32 saves priced out (the knob's whole point)."""
    per_px = 3.0 * cfg.hidden_dims[2] + cfg.corr_channels
    if cfg.n_gru_layers >= 2:
        per_px += 3.0 * cfg.hidden_dims[1] / 4
    if cfg.n_gru_layers == 3:
        per_px += 3.0 * cfg.hidden_dims[0] / 16
    if cfg.slow_fast_gru:
        if cfg.n_gru_layers == 3:
            per_px += 2 * 3.0 * cfg.hidden_dims[0] / 16
        if cfg.n_gru_layers >= 2:
            per_px += 3.0 * cfg.hidden_dims[1] / 4
    bytes_per = 2 if (dt == jnp.bfloat16
                      or residual_dtype in ("bfloat16", jnp.bfloat16)) else 4
    saved_bytes = int(iters * batch * h * w * per_px * bytes_per)
    if fused_lookup:
        # no standalone corr tensor exists on the fused path; the kernel's
        # backward recomputes from (volumes, coords) instead
        saved_bytes -= iters * batch * h * w * cfg.corr_channels * bytes_per
    return saved_bytes <= 1_500_000_000


def upsample_chunk_count(it: int, batch: int, hp: int, wp: int, factor: int,
                         budget: int | None = None) -> int:
    """Number of chunks for the post-scan batched convex upsample.

    The one-shot upsample's ``(it*B, h, w, f, f)`` fp32 intermediates are
    the train step's largest HLO temps (1.9 GB at the SceneFlow b8 shape)
    right when residual pressure peaks; chunking bounds the temp at
    ``~chunk/it`` of that. Returns 1 (one-shot) when the full temp fits;
    otherwise the smallest divisor of ``it`` that fits the budget, falling
    back to maximal chunking (``it``) when even a single-iteration chunk
    exceeds it — never the worst-memory one-shot path when memory is
    tightest."""
    if budget is None:
        budget = _UPSAMPLE_TILE_BUDGET
    tile_bytes = batch * hp * wp * (9 + 2) * factor ** 2 * 4
    nch = 1
    if it * tile_bytes > budget:
        nch = it
        for cand in range(2, it + 1):
            if it % cand:
                continue
            if (it // cand) * tile_bytes <= budget:
                nch = cand
                break
    return nch


class RefinementStep(nn.Module):
    """One GRU refinement iteration — the body of the ``lax.scan``.

    Owns the update block's params (broadcast across scan iterations). The
    epipolar constraint zeroes the y-component of every delta
    (raft_stereo.py:119-120), so lookups stay on integer rows.

    Carry layout depends on the (static) mode, because under remat the scan
    saves every iteration's carry as backward residuals — dead carry slots
    are pure HBM waste at ~22x their size:

    * train stacked: ``(net, coords1)`` — the upsample mask is consumed
      inside the iteration and never crosses iterations (measured: carrying
      the (B, H/f, W/f, 9*f^2) fp32 mask cost ~1.5 GB of residuals).
    * train fused-loss: ``(net, coords1, flow_up)`` — the final full-res
      prediction rides the carry (needed after the scan for metrics).
    * test: ``(net, coords1)`` — only the FINAL iteration computes the
      upsample mask (compute_mask=True, run unscanned on shared params);
      the scanned iterations skip the mask head and carry no mask slot
      (raft_stereo.py:126-136 uses one deferred upsample; the reference
      computes-and-discards the other iterations' masks).
    """

    cfg: RAFTStereoConfig
    test_mode: bool = False
    fused: bool = False
    deferred: bool = False
    dtype: Optional[Dtype] = None
    fused_lookup: bool = False
    # residual_dtype plumbing for the autodiff path's tagged saves, scoped
    # per tag so only tensors a policy actually KEEPS get the cast-through:
    # save_dtype covers corr_feats (kept by both the full and "corr"
    # policies), gate_save_dtype the gru_zr/gru_q tags (full policy only).
    save_dtype: Optional[Dtype] = None
    gate_save_dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, carry, corr_state: CorrState, inp_list, coords0,
                 gt_and_mask, compute_mask: bool = True, wgrad_tap=None):
        net, coords1 = carry[0], carry[1]
        coords1 = jax.lax.stop_gradient(coords1)

        flow = coords1 - coords0
        dt0 = self.dtype
        if self.fused_lookup:
            # lookup + convc1 run as one Pallas kernel inside the motion
            # encoder; no standalone corr tensor exists
            corr = None
        elif wgrad_tap is not None:
            # custom-VJP scan (ops/scan_grad.py): the tap owns save/replay
            # of the corr lookup; checkpoint tags are inert on this path
            corr = wgrad_tap.corr_site(corr_state, coords1, dt0)
        else:
            corr = corr_lookup(corr_state, coords1)
            corr = tag_residual(corr.astype(dt0) if dt0 else corr,
                                "corr_feats", self.save_dtype,
                                tap="corr_feats")

        cfg = self.cfg
        dt = self.dtype
        # Per-application tap prefixes: the slow_fast pre-iterations re-run
        # GRU levels on the SAME params, so each block application needs its
        # own residual stacks in the batched-weight-grad backward.
        tp = (wgrad_tap.scoped if wgrad_tap is not None
              else (lambda prefix: None))
        block = BasicMultiUpdateBlock(cfg, dtype=dt,
                                      save_dtype=self.gate_save_dtype,
                                      name="update_block")
        if cfg.slow_fast_gru and cfg.n_gru_layers == 3:
            net = block(net, inp_list, iter32=True, iter16=False, iter08=False,
                        update=False, wgrad_tap=tp("pre32"))
        if cfg.slow_fast_gru and cfg.n_gru_layers >= 2:
            net = block(net, inp_list, iter32=cfg.n_gru_layers == 3,
                        iter16=True, iter08=False, update=False,
                        wgrad_tap=tp("pre16"))
        net, mask, delta_flow = block(
            net, inp_list, corr, flow.astype(dt) if dt else flow,
            iter32=cfg.n_gru_layers == 3, iter16=cfg.n_gru_layers >= 2,
            corr_state=corr_state if self.fused_lookup else None,
            coords_x=coords1[..., 0] if self.fused_lookup else None,
            compute_mask=compute_mask, wgrad_tap=tp("main"))

        # stereo: project the update onto the epipolar line
        delta_flow = delta_flow.astype(jnp.float32)
        # numerics tap (inert without an armed sink): the raw flow-head
        # output is the first place an exploding refinement shows
        record_numerics_tap(delta_flow, "delta_flow")
        delta_flow = delta_flow.at[..., 1].set(0.0)
        coords1 = coords1 + delta_flow

        if self.test_mode:
            # intermediate upsampling skipped (raft_stereo.py:126-127); the
            # mask exists only on the final (compute_mask=True) iteration
            return (net, coords1), (mask.astype(jnp.float32)
                                    if compute_mask else None)
        if self.deferred:
            # deferred-upsample: emit the low-res flow and (compute-dtype)
            # mask; one batched upsample runs after the scan (and, in the
            # fused-loss case, the loss is computed there in tile layout).
            return (net, coords1), ((coords1 - coords0)[..., :1], mask)
        flow_up = upsample_disparity_convex(coords1 - coords0,
                                            mask.astype(jnp.float32),
                                            cfg.factor)
        if self.fused:
            # fused-loss path: reduce this iteration's masked L1 to a scalar
            # INSIDE the scan, so the (iters, B, H, W, 1) full-resolution
            # prediction stack (~0.7 GB at train shape) is never written to
            # HBM nor re-read in the backward pass.
            flow_gt, loss_mask = gt_and_mask
            err = jnp.abs(flow_up.astype(jnp.float32) - flow_gt)
            err_sum = jnp.sum(jnp.where(loss_mask > 0, err, 0.0))
            return (net, coords1, flow_up), err_sum
        return (net, coords1), flow_up


class RAFTStereo(nn.Module):
    """The flagship model (core/raft_stereo.py:22-141), NHWC.

    ``__call__(image1, image2)`` takes uint8-range float images ``(B, H, W, 3)``
    and returns:

    * train mode: ``(iters, B, H, W, 1)`` per-iteration upsampled disparity-flow
      predictions (the x-component; negative disparity),
    * test mode: ``(flow_lowres (B, H/f, W/f, 2), flow_up (B, H, W, 1))``.
    """

    cfg: RAFTStereoConfig
    dtype: Optional[Dtype] = None

    @property
    def compute_dtype(self):
        if self.dtype is not None:
            return self.dtype
        return jnp.bfloat16 if self.cfg.mixed_precision else None

    @nn.compact
    def __call__(self, image1, image2, iters: int = 12, flow_init=None,
                 test_mode: bool = False, flow_gt=None, loss_mask=None,
                 stage: str = "full", enc_outs=None,
                 iter_metrics: bool = False, numerics: bool = False,
                 adaptive_tau: Optional[float] = None,
                 adaptive_min_iters: int = 1):
        """``flow_gt``/``loss_mask`` (both ``(B, H, W, 1)``) switch on the
        fused-loss training path: returns ``(per_iter_err_sums (iters,),
        final flow_up (B, H, W, 1))`` instead of the stacked predictions —
        same math as sequence_loss over the stack, far less HBM traffic.

        ``stage`` exposes the forward as separately-jittable pieces (e.g.
        encode once / refine many times with warm starts, or staged
        compilation of graphs a compile service rejects whole —
        oracle-pinned in tests/test_staged_forward.py):

        * ``"full"`` (default) — the whole forward, single graph.
        * ``"encode"`` — run only the encoders; returns
          ``(cnet_list, fmap1, fmap2)`` (the raw encoder outputs, before
          the cheap tanh/relu/zqr processing, so the cross-piece cut
          carries the fewest tensors).
        * ``"refine"`` — everything after the encoders; ``enc_outs`` must
          be the ``"encode"`` stage's output.

        The staged path is the SAME traced computation — ``"full"`` is
        exactly ``refine(encode(x))`` — so parameters, outputs, and
        gradients are identical up to XLA scheduling.

        ``iter_metrics`` (test mode only): additionally return the
        per-iteration mean |delta disparity| — an in-graph aux output
        measuring how much each GRU iteration still moves the field (the
        convergence axis of the serial-floor decomposition,
        scripts/serial_floor.py). ``True`` returns the batch-mean curve
        ``(iters,)``; ``"per_sample"`` returns ``(iters, B)`` (mean over
        H, W per sample — what the convergence observatory records per
        frame/request). Computed from consecutive carries, so the scanned
        graph gains one tiny reduction per iteration and nothing else
        changes; the return becomes ``(flow_lowres, flow_up,
        delta_norms)``.

        Passing ``flow_gt`` in test mode (requires ``iter_metrics``)
        additionally returns the per-iteration low-res EPE proxy against
        the factor-pooled ground truth — ``loss_mask`` (same shape) marks
        valid GT pixels, pooled cells with no valid pixel are excluded —
        shaped like ``delta_norms``; the return becomes ``(flow_lowres,
        flow_up, delta_norms, epes)``. With ``flow_gt=None`` the graph is
        byte-identical to the plain ``iter_metrics`` one.

        ``numerics`` (test mode only; the numerics observatory,
        obs/numerics.py): additionally return a dict of per-iteration
        ``(iters, 6)`` range-statistics stacks — one per activation tap
        (corr_feats, each GRU's zr/q gates, delta_flow), keys carrying a
        trace-order prefix — appended as the LAST element of the return
        tuple. ``False`` (the default) arms no tap sink, so the traced
        program is byte-identical to the numerics-free one (the
        ``--no_numerics`` pin).

        ``adaptive_tau`` (test mode only; requires
        ``iter_metrics="per_sample"``): the in-graph early-exit mode —
        ``iters`` becomes the policy budget and a per-sample convergence
        mask freezes samples whose applied update moved the disparity
        field less than ``adaptive_tau`` (mean |Δdisparity|, low-res px,
        strict ``<``, after at least ``adaptive_min_iters`` applications);
        frozen carries pass through later iterations unchanged. The return
        gains ``iters_taken (B,)`` int32 after the residual (and EPE)
        stacks: ``(flow_lowres, flow_up, delta_norms[, epes],
        iters_taken)``. ``cfg.adaptive_mode`` selects the mechanism
        (masked fixed-trip scan vs whole-batch ``lax.while_loop``);
        ``adaptive_tau=0.0`` never freezes anything, so the flow is
        bitwise identical to the fixed-trip scan at the same budget.
        ``adaptive_tau=None`` (the default) leaves the traced program
        byte-identical to the pre-adaptive one.
        """
        cfg = self.cfg
        dt = self.compute_dtype

        if stage == "refine":
            cnet_list, fmap1, fmap2 = enc_outs
            return self._refine(cnet_list, fmap1, fmap2, iters, flow_init,
                                test_mode, flow_gt, loss_mask, iter_metrics,
                                numerics, adaptive_tau, adaptive_min_iters)

        image1 = (2.0 * (image1 / 255.0) - 1.0).astype(jnp.float32)
        image2 = (2.0 * (image2 / 255.0) - 1.0).astype(jnp.float32)

        # Optionally rematerialize the encoders in the backward pass: their
        # full-resolution activations (conv1/layer1 run at image res,
        # extractor.py:140-146) are multi-GB backward residuals at train
        # shapes. nn.remat of a (module, x) function is transparent to
        # parameter paths, so checkpoints are unaffected; the static kwargs
        # (dual_inp/num_layers) are closed over.
        def _cnet_fwd(mdl, x):
            return mdl(x, dual_inp=cfg.shared_backbone,
                       num_layers=cfg.n_gru_layers)

        def _fnet_fwd(mdl, x):
            return mdl(x)

        if cfg.remat_encoders is True:
            # prevent_cse=True (default): at the top level of a jitted
            # function XLA CSE would otherwise merge the recomputed encoder
            # with the primal one and keep the residuals alive (inside the
            # refinement scan prevent_cse=False is the correct choice; here
            # it is not).
            _cnet_fwd = nn.remat(_cnet_fwd)
            _fnet_fwd = nn.remat(_fnet_fwd)
        elif cfg.remat_encoders == "norms":
            # Save every conv output (compute dtype) + the tiny norm stats;
            # recompute the elementwise norm/relu/add glue in backward. The
            # glue's saved form dominates plain-backward residual memory
            # (24.9 GB at SceneFlow b8 — 14.1 GB fp32 norm intermediates,
            # 3.6 GB bool relu masks — vs 7.1 GB of conv outputs), while its
            # recompute is cheap bandwidth; unlike "blocks", no conv ever
            # re-runs.
            pol = jax.checkpoint_policies.save_only_these_names(
                "enc_conv", "enc_stat")
            _cnet_fwd = nn.remat(_cnet_fwd, policy=pol)
            _fnet_fwd = nn.remat(_fnet_fwd, policy=pol)
        remat_blocks = ("hires" if cfg.remat_encoders == "blocks_hires"
                        else cfg.remat_encoders == "blocks")

        # Lane-dense folded saves under the "norms" and "blocks" policies
        # (for "blocks" the fold applies to the remat boundary inputs —
        # encoder.py _Trunk). Auto: "norms" folds by the padded-size
        # estimate (fold_enc_saves_auto — its 14 GB padded save set
        # genuinely doesn't fit a 16 GB chip at SceneFlow b8); "blocks"
        # stays UNFOLDED — its padded saves fit even at b8 with the
        # one-shot/no-tail schedule, and the fold's relayout copies
        # measured -0.39 pairs/s there (9.42 vs 9.03, bench r4).
        fold_saves = False
        if cfg.remat_encoders == "norms":
            fold_saves = (cfg.fold_enc_saves if cfg.fold_enc_saves is not None
                          else fold_enc_saves_auto(cfg, image1.shape[0],
                                                   image1.shape[1],
                                                   image1.shape[2]))
        elif cfg.remat_encoders in ("blocks", "blocks_hires"):
            fold_saves = bool(cfg.fold_enc_saves)

        # Under "blocks_hires" the context encoder is saved WHOLE: its
        # layer1 internals are ~1 GB at SceneFlow b8 (a third of fnet's
        # doubled-batch set) and skipping its recompute measured +0.3%
        # (9.61 vs 9.57 pairs/s, PERF.md r4); narrowing fnet further
        # (layer1_0 only) is compile-helper-rejected. With shared_backbone
        # the cnet IS the doubled-batch trunk, so it keeps the hires remat.
        cnet_remat = remat_blocks
        if remat_blocks == "hires" and not cfg.shared_backbone:
            cnet_remat = False
        cnet = MultiBasicEncoder(
            output_dim=(cfg.hidden_dims, cfg.hidden_dims),
            norm_fn=cfg.context_norm, downsample=cfg.n_downsample, dtype=dt,
            remat_blocks=cnet_remat, fold_saves=fold_saves, name="cnet")
        if cfg.shared_backbone:
            *cnet_list, trunk = _cnet_fwd(
                cnet, jnp.concatenate([image1, image2], axis=0))
            fmaps = Conv.make(256, 3, 1, 1, dt, "conv2_out")(
                ResidualBlock(128, 128, "instance", 1, dt, name="conv2_res")(
                    trunk))
            fmap1, fmap2 = jnp.split(fmaps, 2, axis=0)
        else:
            cnet_list = _cnet_fwd(cnet, image1)
            fnet = BasicEncoder(output_dim=256, norm_fn="instance",
                                downsample=cfg.n_downsample, dtype=dt,
                                remat_blocks=remat_blocks,
                                fold_saves=fold_saves, name="fnet")
            fmaps = _fnet_fwd(fnet,
                              jnp.concatenate([image1, image2], axis=0))
            fmap1, fmap2 = jnp.split(fmaps, 2, axis=0)

        if stage == "encode":
            return tuple(cnet_list), fmap1, fmap2
        return self._refine(tuple(cnet_list), fmap1, fmap2, iters, flow_init,
                            test_mode, flow_gt, loss_mask, iter_metrics,
                            numerics, adaptive_tau, adaptive_min_iters)

    def _refine(self, cnet_list, fmap1, fmap2, iters, flow_init, test_mode,
                flow_gt, loss_mask, iter_metrics=False, numerics=False,
                adaptive_tau=None, adaptive_min_iters=1):
        """Post-encoder forward: context processing, correlation pyramid, the
        refinement scan, and the upsample/loss tail. Called from the compact
        ``__call__`` (both the monolithic and staged paths)."""
        if iters < 1:
            # The reference crashes on iters=0 too (its post-loop upsample
            # reads the in-loop mask); make the contract explicit rather
            # than returning an unrefined or once-refined field.
            raise ValueError(f"iters must be >= 1, got {iters}")
        if iter_metrics and not test_mode:
            raise ValueError("iter_metrics aux outputs exist on the "
                             "test_mode (inference) scan only")
        if test_mode and flow_gt is not None and not iter_metrics:
            raise ValueError("the test_mode iter-EPE aux rides the "
                             "iter_metrics scan outputs; pass "
                             "iter_metrics=True or 'per_sample'")
        if numerics and not test_mode:
            raise ValueError("the numerics tap aux exists on the test_mode "
                             "(inference) scan only; the training side is "
                             "the per-leaf gradient-norm vector "
                             "(training/state.py numerics=True)")
        if adaptive_tau is not None:
            if not test_mode:
                raise ValueError("adaptive early exit (adaptive_tau) exists "
                                 "on the test_mode (inference) path only")
            if iter_metrics != "per_sample":
                raise ValueError("adaptive early exit requires "
                                 "iter_metrics='per_sample' — the "
                                 "per-sample residual both drives the "
                                 "freeze mask and rides the aux")
            if numerics:
                raise ValueError("numerics taps are not supported on the "
                                 "adaptive path; record numerics on the "
                                 "fixed-trip scan")
            if adaptive_tau < 0:
                raise ValueError(f"adaptive_tau must be >= 0, got "
                                 f"{adaptive_tau}")
        cfg = self.cfg
        dt = self.compute_dtype

        net_list = [jnp.tanh(x[0]) for x in cnet_list]
        inp_list = [nn.relu(x[1]) for x in cnet_list]

        # GRU context gate biases, computed once outside the refinement loop
        # (raft_stereo.py:87-88): conv then split into (cz, cr, cq).
        inp_list = [
            tuple(jnp.split(
                Conv.make(cfg.hidden_dims[i] * 3, 3, 1, 1, dt,
                          f"context_zqr_convs_{i}")(inp), 3, axis=-1))
            for i, inp in enumerate(inp_list)
        ]

        # Volume storage precision (config.corr_storage_dtype): default
        # mirrors the reference — fp32 for reg/alt (raft_stereo.py:92-95),
        # compute dtype for the Pallas kernels (fp16 CUDA precedent).
        if cfg.corr_storage_dtype is not None:
            storage_dt = jnp.dtype(cfg.corr_storage_dtype)
        elif (cfg.corr_implementation.endswith("_pallas")
              or cfg.corr_implementation == "fused"):
            storage_dt = dt
        else:
            storage_dt = None
        corr_state = init_corr(cfg.corr_implementation, fmap1, fmap2,
                               num_levels=cfg.corr_levels,
                               radius=cfg.corr_radius,
                               storage_dtype=storage_dt,
                               block_w=cfg.fused_block_w)

        # Fused lookup+convc1 kernel: applicable only for volume-pyramid
        # implementations whose shapes fit the kernel tiling (the check is
        # static — shapes are known at trace time). Everything else keeps
        # the unfused path with identical semantics. Auto (None) = OFF:
        # the kernel is exact and compiles fast, but the r4 TPU A/B
        # measured it slower than XLA's unfused path on every surface
        # (training AND no-backward inference — config.py fused_lookup,
        # PERF.md "r4 A/B"); opt in with fused_lookup=True to re-measure.
        use_fused_lookup = False
        want_fused = (False if cfg.fused_lookup is None
                      else bool(cfg.fused_lookup))
        if want_fused and corr_state.impl in ("reg", "reg_pallas"):
            from raft_stereo_tpu.ops.pallas.lookup_kernels import (
                fused_lookup_applicable)
            use_fused_lookup = fused_lookup_applicable(corr_state.levels,
                                                       cfg.corr_radius)

        b, h, w, _ = net_list[0].shape
        coords0 = coords_grid(b, h, w)
        coords1 = coords_grid(b, h, w)
        if flow_init is not None:
            # Stereo flow is epipolar: zero any y-component of the warm-start
            # so flow's y-channel stays structurally zero through the loop
            # (the deltas' y is always zeroed, raft_stereo.py:119-120; the
            # reference's own warm starts carry y = 0 by construction).
            flow_init = flow_init.at[..., 1].set(0.0)
            coords1 = coords1 + flow_init

        if test_mode and adaptive_tau is not None:
            # The early-exit mode is a SEPARATE branch: the default path
            # below stays byte-identical when adaptive_tau is None (the
            # adaptive=False pin, tests/test_adaptive.py).
            return self._refine_adaptive(
                net_list, inp_list, corr_state, coords0, coords1, iters,
                adaptive_tau, adaptive_min_iters, flow_gt,
                loss_mask, use_fused_lookup, dt)

        fused = flow_gt is not None and not test_mode
        if fused and loss_mask is None:
            raise ValueError("the fused-loss path needs both flow_gt and "
                             "loss_mask (see training.loss.loss_mask)")
        deferred = cfg.deferred_upsample and not test_mode
        if test_mode:
            # Inference scan: only the FINAL iteration's upsample mask is
            # consumed (one deferred upsample, raft_stereo.py:126-136; the
            # reference computes and discards the other iterations' masks).
            # The first iters-1 iterations run as a lifted scan over a body
            # with compute_mask=False — a STATIC flag, so the two mask-head
            # convs are absent from the scanned graph — and the final
            # iteration runs unscanned on the SAME module instance (shared
            # params) with the mask head on. No backward exists, so no
            # remat wrapper. Measured: default-preset KITTI-res inference
            # 7.39 -> see PERF.md r4.
            refine = RefinementStep(cfg, True, False, False, dt,
                                    fused_lookup=use_fused_lookup,
                                    name="refinement")
            carry = (tuple(net_list), coords1)

            per_sample = iter_metrics == "per_sample"

            def _residual(c_new, c_old):
                # per-iteration mean |delta disparity| from consecutive
                # carries — the convergence aux of iter_metrics
                d = jnp.abs((c_new[1] - c_old[1])[..., 0])
                return jnp.mean(d, axis=(1, 2)) if per_sample else jnp.mean(d)

            # In-graph low-res EPE proxy (flow_gt): pool the full-res GT to
            # the flow grid with mask-weighted means computed ONCE outside
            # the scan; each iteration then adds a single masked reduction
            # against the current coords. Cells with no valid GT pixel are
            # excluded from both numerator and denominator.
            iter_epe = None
            if flow_gt is not None:
                f = cfg.factor
                gt = flow_gt.astype(jnp.float32)[..., 0]
                m = (jnp.ones_like(gt) if loss_mask is None
                     else loss_mask.astype(jnp.float32)[..., 0])
                gt_c = gt.reshape(b, h, f, w, f)
                m_c = m.reshape(b, h, f, w, f)
                msum = m_c.sum(axis=(2, 4))
                gt_pool = (gt_c * m_c).sum(axis=(2, 4)) / jnp.maximum(msum,
                                                                      1.0)
                cell_valid = (msum > 0).astype(jnp.float32)
                denom = jnp.maximum(cell_valid.sum(axis=(1, 2)), 1.0)

                def _epe_of(c):
                    err = jnp.abs((c[1] - coords0)[..., 0] * f - gt_pool)
                    e = jnp.sum(err * cell_valid, axis=(1, 2)) / denom
                    return e if per_sample else jnp.mean(e)

                iter_epe = _epe_of

            def scan_iter(mdl, c, _):
                # the numerics_taps sink is armed around the body trace
                # only: tag_residual/record_numerics_tap sites deposit one
                # fused stats vector each, collected into the scan's
                # stacked ys (numerics=False arms nothing and the body is
                # byte-identical to the numerics-free trace)
                if numerics:
                    with numerics_taps() as sink:
                        c2, _unused = mdl(c, corr_state, tuple(inp_list),
                                          coords0, None, compute_mask=False)
                    taps = dict(sink)
                else:
                    c2, _unused = mdl(c, corr_state, tuple(inp_list),
                                      coords0, None, compute_mask=False)
                # aux ys; None keeps the default graph byte-identical
                y = _residual(c2, c) if iter_metrics else None
                if iter_epe is not None:
                    y = (y, iter_epe(c2))
                if numerics:
                    y = (y, taps)
                return c2, y

            delta_norms = None
            scanned_epes = None
            scanned_taps = None
            if iters > 1:
                carry, scanned = nn.scan(
                    scan_iter,
                    variable_broadcast="params",
                    split_rngs={"params": False},
                    length=iters - 1,
                    unroll=cfg.scan_unroll,
                )(refine, carry, None)
                if numerics:
                    scanned, scanned_taps = scanned
                if iter_epe is not None:
                    scanned, scanned_epes = scanned
                if iter_metrics:
                    delta_norms = scanned
            pre_final = carry
            if numerics:
                with numerics_taps() as final_sink:
                    carry, mask = refine(carry, corr_state, tuple(inp_list),
                                         coords0, None)
            else:
                carry, mask = refine(carry, corr_state, tuple(inp_list),
                                     coords0, None)
            coords1 = carry[1]
            flow_up = upsample_disparity_convex(coords1 - coords0, mask,
                                                cfg.factor)
            tap_stats = None
            if numerics:
                # per-key (iters, 6) stacks: scanned iterations + the
                # final unscanned one (same body, same tap sites — the
                # mask head it adds carries no tap)
                tap_stats = {
                    k: (v[None] if scanned_taps is None
                        else jnp.concatenate([scanned_taps[k], v[None]]))
                    for k, v in final_sink.items()}
            if iter_metrics:
                final_norm = _residual(carry, pre_final)[None]
                delta_norms = (final_norm if delta_norms is None else
                               jnp.concatenate([delta_norms, final_norm]))
                if iter_epe is not None:
                    final_epe = iter_epe(carry)[None]
                    epes = (final_epe if scanned_epes is None else
                            jnp.concatenate([scanned_epes, final_epe]))
                    ret = (coords1 - coords0, flow_up, delta_norms, epes)
                else:
                    ret = (coords1 - coords0, flow_up, delta_norms)
            else:
                ret = (coords1 - coords0, flow_up)
            # the numerics tap dict is always the LAST element
            return ret if tap_stats is None else ret + (tap_stats,)
        if fused and not deferred:
            carry = (tuple(net_list), coords1,
                     jnp.zeros((b, h * cfg.factor, w * cfg.factor, 1),
                               jnp.float32))
        else:
            carry = (tuple(net_list), coords1)

        gt_and_mask = None
        if fused:
            gt_and_mask = (flow_gt.astype(jnp.float32),
                           loss_mask.astype(jnp.float32))

        # Selective-save engagement, shared by both backward paths: which
        # tagged per-iteration values stay resident across the refinement
        # backward vs being rematerialized (refinement_save_policy_fits has
        # the measurements; config.refinement_save_policy overrides).
        engage = False
        if cfg.remat_refinement:
            engage = (cfg.refinement_save_policy
                      if cfg.refinement_save_policy is not None else
                      refinement_save_policy_fits(
                          cfg, iters, b, h, w, dt,
                          fused_lookup=use_fused_lookup,
                          residual_dtype=cfg.residual_dtype))
            if engage == "corr" and use_fused_lookup:
                # no standalone corr_feats tensor exists on the fused path
                # (the kernel's backward recomputes from volumes+coords), so
                # the "corr" policy would silently save nothing — fall back
                # to full remat, loudly.
                warnings.warn(
                    "refinement_save_policy='corr' has no effect with "
                    "fused_lookup (no corr_feats tensor exists to save); "
                    "using full per-iteration remat")
                engage = False

        if bool(cfg.batched_scan_wgrad) and not self.is_initializing():
            # Custom-VJP scan (ops/scan_grad.py): the forward runs lax.scan
            # as usual; the backward runs one reverse scan computing data
            # gradients only, and the gate convs' weight gradients are
            # computed after it as single batched contractions over the
            # iters-stacked (input, cotangent) pairs — replacing 22 small
            # accumulating weight-grad convs per conv with one MXU-shaped
            # op. Init still goes through the nn.scan branch below, which
            # owns parameter creation; here the refinement params are read
            # back and threaded through the pure scan so cotangents flow.
            from raft_stereo_tpu.ops.scan_grad import refinement_scan
            params_ref = self.scope.get_variable("params", "refinement")
            if params_ref is None:
                raise ValueError(
                    "batched_scan_wgrad needs initialized 'refinement' "
                    "params (init the model before apply)")
            save_kinds = set()
            if engage == "corr":
                save_kinds = {"corr"}
            elif engage:
                save_kinds = {"zr", "q", "corr"}
            if use_fused_lookup:
                save_kinds.discard("corr")
            refine = RefinementStep(cfg, test_mode, fused, deferred, dt,
                                    fused_lookup=use_fused_lookup,
                                    parent=None)
            carry, flow_predictions = refinement_scan(
                refine, params_ref, carry,
                (corr_state, tuple(inp_list), coords0, gt_and_mask),
                length=iters, save_kinds=frozenset(save_kinds),
                residual_dtype=cfg.residual_dtype, unroll=cfg.scan_unroll)
        else:
            # Rematerialize each refinement iteration: without this, the
            # scan stores every iteration's GRU/conv activations for the
            # backward pass (~0.6 GB per conv buffer at the SceneFlow train
            # shape, 22 iters) and training OOMs on a 16 GB chip. Remat
            # recomputes them from the carry instead — the jax.checkpoint
            # FLOPs-for-HBM trade.
            if cfg.remat_refinement:
                if engage == "corr":
                    # Save ONLY the corr lookup output: ~iters*B*h*w*36
                    # values (~180 MB bf16 at SceneFlow b8 — vs ~2.7 GB for
                    # the full set), so the backward skips re-gathering the
                    # 4-level pyramid while the gate convs rematerialize.
                    body = nn.remat(
                        RefinementStep, prevent_cse=False,
                        policy=jax.checkpoint_policies.save_only_these_names(
                            "corr_feats"))
                elif engage:
                    body = nn.remat(
                        RefinementStep, prevent_cse=False,
                        policy=jax.checkpoint_policies.save_only_these_names(
                            "gru_zr", "gru_q", "corr_feats"))
                else:
                    body = nn.remat(RefinementStep, prevent_cse=False)
            else:
                body = RefinementStep
            # residual_dtype narrows the TAGGED saves only while a policy
            # actually keeps them (otherwise the cast-through would perturb
            # the forward for zero memory gain): corr_feats under both
            # policies, the gate tags under the full set only.
            save_dt = cfg.residual_dtype if engage else None
            gate_save_dt = (cfg.residual_dtype
                            if engage and engage != "corr" else None)
            step = nn.scan(
                body,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast,
                         nn.broadcast),
                out_axes=0,
                length=iters,
                unroll=cfg.scan_unroll,
            )(cfg, test_mode, fused, deferred, dt,
              fused_lookup=use_fused_lookup, save_dtype=save_dt,
              gate_save_dtype=gate_save_dt, name="refinement")
            carry, flow_predictions = step(carry, corr_state,
                                           tuple(inp_list), coords0,
                                           gt_and_mask)

        if deferred:
            lowres, masks = flow_predictions  # (it,B,h,w,1), (it,B,h,w,9f^2)
            it, bb, hp, wp = lowres.shape[:4]
            if fused:
                # loss in tile layout: |pred - gt| summed over pixels is
                # layout-invariant, so transpose the (B,H,W) GT/mask ONCE
                # instead of the (iters*B,H,W) prediction stack, and emit
                # only per-iteration masked L1 sums + the final prediction.
                gt_t = image_to_upsample_tiles(
                    flow_gt.astype(jnp.float32), cfg.factor)
                mask_t = image_to_upsample_tiles(
                    loss_mask.astype(jnp.float32), cfg.factor)

                # Chunk the iteration axis: the one-shot batched upsample's
                # (it*B, h, w, f, f) fp32 intermediates are the train step's
                # largest HLO temps (1.9 GB at the SceneFlow b8 shape) right
                # when residual pressure peaks. Upsample+reduce per chunk
                # bounds the temp at ~chunk/it of that while keeping the
                # batching win over in-scan upsampling; shapes whose full
                # temp already fits stay one-shot (chunking is lax.map
                # serialization — pure cost when memory is plentiful).
                nch = upsample_chunk_count(it, bb, hp, wp, cfg.factor,
                                           budget=cfg.upsample_tile_budget)

                # Rematerialized (config.remat_loss_tail): without the
                # checkpoint, autodiff saves the upsample's fp32 softmax
                # weights and tile products for EVERY chunk across the loss
                # backward — measured 1.93 GB (+ 3x 220 MB tile buffers) at
                # SceneFlow b8, the largest allocation in the step and the
                # difference between fitting and not fitting 16 GB (r4 AOT
                # breakdown). Recomputing the chunk from its (bf16,
                # scan-output) slices costs one extra batched upsample —
                # only in the backward.
                def chunk_err(args):
                    lr_c, mk_c = args  # (itc, B, h, w, ...)
                    itc = lr_c.shape[0]
                    t = convex_upsample_tiles(
                        lr_c.reshape(itc * bb, hp, wp, 1).astype(jnp.float32),
                        mk_c.reshape(itc * bb, hp, wp, -1).astype(jnp.float32),
                        cfg.factor)
                    e = jnp.abs(t.reshape(itc, bb, hp, wp, cfg.factor,
                                          cfg.factor) - gt_t[None])
                    e = jnp.where(mask_t[None] > 0, e, 0.0)
                    return jnp.sum(e, axis=(1, 2, 3, 4, 5))

                if cfg.remat_loss_tail:
                    chunk_err = jax.checkpoint(chunk_err)
                if nch > 1:
                    itc = it // nch
                    err_sums = jax.lax.map(chunk_err, (
                        lowres.reshape(nch, itc, bb, hp, wp, -1),
                        masks.reshape(nch, itc, bb, hp, wp, -1),
                    )).reshape(it)
                else:
                    err_sums = chunk_err((lowres, masks))
                final_tiles = convex_upsample_tiles(
                    lowres[-1].astype(jnp.float32),
                    masks[-1].astype(jnp.float32), cfg.factor)
                final_up = upsample_tiles_to_image(final_tiles)
                return err_sums, final_up
            # Rematerialized for the same reason as chunk_err above: the
            # stacked path's softmax/tile intermediates (~1.4 GB fp32 at b8)
            # otherwise persist across the whole loss backward.
            def upsample_stack(lr, mk):
                tiles = convex_upsample_tiles(
                    lr.reshape(it * bb, hp, wp, 1).astype(jnp.float32),
                    mk.reshape(it * bb, hp, wp, -1).astype(jnp.float32),
                    cfg.factor)  # (it*B, h, w, f, f)
                up = upsample_tiles_to_image(tiles)
                return up.reshape(it, bb, hp * cfg.factor, wp * cfg.factor, 1)

            if cfg.remat_loss_tail:
                upsample_stack = jax.checkpoint(upsample_stack)
            return upsample_stack(lowres, masks)
        if fused:
            return flow_predictions, carry[2]
        return flow_predictions

    def _refine_adaptive(self, net_list, inp_list, corr_state, coords0,
                         coords1, iters, tau, min_iters, flow_gt, loss_mask,
                         use_fused_lookup, dt):
        """Early-exit test-mode refinement (the ROADMAP 1(b) actuation half).

        Same per-iteration body as the fixed-trip test-mode scan in
        :meth:`_refine`; a per-sample convergence mask rides the carry.
        Once an APPLIED update moved a sample's disparity field less than
        ``tau`` (mean |Δdisparity| in low-res px, strict ``<``, after at
        least ``min_iters`` applications) the sample freezes: every later
        iteration computes the body but ``jnp.where``-discards it, so the
        carry passes through unchanged and the residual row records 0.0.
        ``iters`` is the policy budget (the trip count); ``iters_taken``
        counts applied updates per sample (final mask iteration included).

        ``cfg.adaptive_mode`` selects the mechanism: ``"masked_scan"``
        keeps the fixed-length ``nn.scan`` (static trip count — the
        AOT/serve flavor), ``"while_loop"`` wraps the same masked body in
        a ``lax.while_loop`` that exits as soon as every sample froze
        (residual/EPE rows after a whole-batch exit stay 0.0). Both end
        with the same unscanned mask-head iteration, which always runs:
        the convex-upsample mask must exist even for frozen samples, and
        its update applies only to still-active ones. ``tau=0.0`` never
        freezes anything (residuals are non-negative), so the flow is
        bitwise identical to the fixed-trip scan at the same budget.
        """
        cfg = self.cfg
        b = net_list[0].shape[0]
        budget = iters          # static python trip count (the fixed one)
        tau = jnp.float32(tau)
        refine = RefinementStep(cfg, True, False, False, dt,
                                fused_lookup=use_fused_lookup,
                                name="refinement")

        def _res_ps(c_new, c_old):
            return jnp.mean(jnp.abs((c_new[1] - c_old[1])[..., 0]),
                            axis=(1, 2))

        # Per-sample low-res EPE proxy, pooled once — same math as the
        # fixed path's iter_epe closure (per_sample variant).
        iter_epe = None
        if flow_gt is not None:
            f = cfg.factor
            h, w = net_list[0].shape[1:3]
            gt = flow_gt.astype(jnp.float32)[..., 0]
            m = (jnp.ones_like(gt) if loss_mask is None
                 else loss_mask.astype(jnp.float32)[..., 0])
            gt_c = gt.reshape(b, h, f, w, f)
            m_c = m.reshape(b, h, f, w, f)
            msum = m_c.sum(axis=(2, 4))
            gt_pool = (gt_c * m_c).sum(axis=(2, 4)) / jnp.maximum(msum, 1.0)
            cell_valid = (msum > 0).astype(jnp.float32)
            denom = jnp.maximum(cell_valid.sum(axis=(1, 2)), 1.0)

            def iter_epe(c):
                err = jnp.abs((c[1] - coords0)[..., 0] * f - gt_pool)
                return jnp.sum(err * cell_valid, axis=(1, 2)) / denom

        def _advance(cur, act, taken, c2):
            """Apply one computed step under the freeze mask: returns the
            masked carry, next-iteration mask, applied-step counts, and
            this iteration's residual row (0.0 where frozen — the applied
            delta there is zero, whatever the discarded body computed)."""
            r = _res_ps(c2, cur)
            mask = act[:, None, None, None]
            nxt = (tuple(jnp.where(mask, n2, n1)
                         for n1, n2 in zip(cur[0], c2[0])),
                   jnp.where(mask, c2[1], cur[1]))
            row = jnp.where(act, r, 0.0)
            taken = taken + act.astype(jnp.int32)
            act = act & ((r >= tau) | (taken < min_iters))
            return nxt, act, taken, row

        active = jnp.ones((b,), jnp.bool_)
        taken = jnp.zeros((b,), jnp.int32)
        cur = (tuple(net_list), coords1)
        res_rows = None
        epe_rows = None

        if (cfg.adaptive_mode == "while_loop" and budget > 1
                and not self.is_initializing()):
            # Whole-batch dynamic trip count: the cond exits the loop the
            # moment every sample froze (or the budget ran out). The body
            # is applied functionally on the scope's refinement params
            # (the batched_scan_wgrad precedent) — flax modules cannot be
            # called under lax.while_loop directly.
            params_ref = self.scope.get_variable("params", "refinement")
            if params_ref is None:
                raise ValueError(
                    "adaptive_mode='while_loop' needs initialized "
                    "'refinement' params (init the model before apply)")
            pure = RefinementStep(cfg, True, False, False, dt,
                                  fused_lookup=use_fused_lookup,
                                  parent=None)
            rbuf = jnp.zeros((budget - 1, b), jnp.float32)
            ebuf = (jnp.zeros((budget - 1, b), jnp.float32)
                    if iter_epe is not None else None)

            def cond(st):
                return jnp.logical_and(st[0] < budget - 1, jnp.any(st[3]))

            def body(st):
                if iter_epe is not None:
                    step, net, coords, act, tk, rb, eb = st
                else:
                    step, net, coords, act, tk, rb = st
                c = (net, coords)
                c2, _unused = pure.apply(
                    {"params": params_ref}, c, corr_state, tuple(inp_list),
                    coords0, None, compute_mask=False)
                nxt, act, tk, row = _advance(c, act, tk, c2)
                rb = jax.lax.dynamic_update_index_in_dim(rb, row, step, 0)
                if iter_epe is not None:
                    eb = jax.lax.dynamic_update_index_in_dim(
                        eb, iter_epe(nxt), step, 0)
                    return (step + 1, nxt[0], nxt[1], act, tk, rb, eb)
                return (step + 1, nxt[0], nxt[1], act, tk, rb)

            init = (jnp.int32(0), cur[0], cur[1], active, taken, rbuf)
            if iter_epe is not None:
                init = init + (ebuf,)
            out = jax.lax.while_loop(cond, body, init)
            cur, active, taken = (out[1], out[2]), out[3], out[4]
            res_rows = out[5]
            if iter_epe is not None:
                epe_rows = out[6]
        elif budget > 1:
            def scan_iter(mdl, c, _):
                cur, act, tk = (c[0], c[1]), c[2], c[3]
                c2, _unused = mdl(cur, corr_state, tuple(inp_list),
                                  coords0, None, compute_mask=False)
                nxt, act, tk, row = _advance(cur, act, tk, c2)
                y = (row,) if iter_epe is None else (row, iter_epe(nxt))
                return (nxt[0], nxt[1], act, tk), y

            carry4, ys = nn.scan(
                scan_iter,
                variable_broadcast="params",
                split_rngs={"params": False},
                length=budget - 1,
                unroll=cfg.scan_unroll,
            )(refine, (cur[0], cur[1], active, taken), None)
            cur, active, taken = (carry4[0], carry4[1]), carry4[2], carry4[3]
            res_rows = ys[0]
            if iter_epe is not None:
                epe_rows = ys[1]

        # Final iteration always runs unscanned with the mask head on (the
        # convex-upsample mask must exist even when every sample froze);
        # its carry update still respects the freeze mask.
        c2, up_mask = refine(cur, corr_state, tuple(inp_list), coords0, None)
        nxt, active, taken, row = _advance(cur, active, taken, c2)
        coords1 = nxt[1]
        flow_up = upsample_disparity_convex(coords1 - coords0, up_mask,
                                            cfg.factor)
        delta_norms = (row[None] if res_rows is None
                       else jnp.concatenate([res_rows, row[None]]))
        ret = (coords1 - coords0, flow_up, delta_norms)
        if iter_epe is not None:
            final_epe = iter_epe(nxt)[None]
            epes = (final_epe if epe_rows is None
                    else jnp.concatenate([epe_rows, final_epe]))
            ret = ret + (epes,)
        return ret + (taken,)


def create_model(cfg: RAFTStereoConfig, dtype: Optional[Dtype] = None) -> RAFTStereo:
    return RAFTStereo(cfg=cfg, dtype=dtype)


def init_model(rng, cfg: RAFTStereoConfig, image_shape=(1, 64, 96, 3),
               dtype: Optional[Dtype] = None):
    """Initialize model variables ({'params', 'batch_stats'}) on dummy images."""
    model = create_model(cfg, dtype)
    dummy = jnp.zeros(image_shape, jnp.float32)
    variables = model.init(rng, dummy, dummy, iters=1)
    return model, variables
