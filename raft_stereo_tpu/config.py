"""Single dataclass config for the whole framework.

The reference threads a raw ``argparse.Namespace`` into every layer (model ctor
``core/raft_stereo.py:23-25``, update block ``core/update.py:98-101``, data loader
``core/stereo_datasets.py:283-292``) and re-declares the flag surface in each entry
script (``train_stereo.py:214-249``, ``evaluate_stereo.py:192-209``, ``demo.py:55-75``).
Here a frozen dataclass is defined once and shared by model, training, eval and demo;
the public flag names are preserved because they are the reference's CLI API.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

# The plugin switch preserved from the reference (--corr_implementation,
# core/raft_stereo.py:90-100). "reg_pallas"/"alt_pallas" replace the CUDA
# extensions ("reg_cuda"/"alt_cuda") with TPU Pallas kernels; "ring" is the
# sequence-parallel variant for very wide images (W sharded over the mesh's
# 'seq' axis, fmap2 blocks ppermuted ring-style — SURVEY §5 long-context row);
# "fused" is the memoryless W2-blocked kernel (ops/pallas/corr_kernels.py):
# alt's O(W) state with a lookup whose largest transient is a
# (rows, W1, fused_block_w) VMEM sub-slab — no level's B*H*W1*W2 volume
# exists at ANY width, forward or backward (alt_pallas falls back to the
# full volume when its whole-row slab outgrows VMEM; fused shrinks its
# block instead).
CORR_IMPLEMENTATIONS = ("reg", "alt", "reg_pallas", "alt_pallas", "ring",
                        "fused")
# Aliases so reference command lines keep working. The reference points its
# high-resolution spellings at the memory-frugal path: "alt_cuda" is its
# never-shipped on-the-fly extension (core/corr.py:159-188), so it routes —
# along with the explicit "fused_cuda"/"memoryless" spellings — onto "fused",
# the implementation that actually delivers that promise.
CORR_ALIASES = {"reg_cuda": "reg_pallas", "alt_cuda": "fused",
                "fused_cuda": "fused", "memoryless": "fused"}

NORM_FNS = ("group", "batch", "instance", "none")


@dataclasses.dataclass(frozen=True)
class RAFTStereoConfig:
    """Architecture config (the reference's "Architecture choices" flag group)."""

    # Hidden state and context dims, ordered coarse->fine: hidden_dims[0] is the
    # 1/32-resolution GRU, hidden_dims[2] the 1/8-resolution GRU
    # (core/extractor.py:227-250, core/update.py:104-106).
    hidden_dims: Tuple[int, ...] = (128, 128, 128)
    corr_implementation: str = "reg"
    shared_backbone: bool = False
    corr_levels: int = 4
    corr_radius: int = 4
    n_downsample: int = 2
    context_norm: str = "batch"
    slow_fast_gru: bool = False
    n_gru_layers: int = 3
    mixed_precision: bool = False
    # Ours: rematerialize each refinement iteration in the backward pass
    # (jax.checkpoint). Without it the scan stores every iteration's conv
    # activations and SceneFlow-shape training OOMs on a 16 GB chip.
    remat_refinement: bool = True
    # Ours: correlation-volume storage precision. None = match the reference
    # (core/raft_stereo.py:92-95): fp32 for "reg"/"alt"; the compute dtype for
    # the Pallas implementations (the reference's CUDA kernels are the fp16
    # precedent, sampler_kernel.cu:126). "bfloat16" halves lookup bandwidth
    # (accumulation stays fp32 in the builders) — opt-in for training recipes.
    corr_storage_dtype: Optional[str] = None
    # Ours: in training, emit (lowres flow, mask) from the refinement scan
    # and run ONE batched convex upsample over all iterations after it,
    # instead of 22 small per-iteration upsamples inside the scan body —
    # fewer latency-bound ops, and the upsample is never rematerialized in
    # the backward pass (its inputs are saved scan outputs). Semantically
    # identical to the in-scan path (fwd+grad verified); measured -12.7%
    # step time at the SceneFlow recipe (PERF.md).
    deferred_upsample: bool = True
    # Ours: fuse the per-iteration 4-level correlation lookup + the motion
    # encoder's 1x1 conv into one Pallas kernel with a hand-written VJP
    # (ops/pallas/lookup_kernels.py) — the compile-tractable subset of the
    # r3 full lookup+motion fusion (see that module's doc for why this
    # scope). None = auto: currently OFF — the kernel compiles in seconds
    # and is numerically exact (parity-verified, PARITY.md), but the r4
    # single-chip A/B measured it SLOWER than XLA's unfused lookup+conv on
    # every surface: SceneFlow-b8 training 7.23 vs 8.72 pairs/s, KITTI-res
    # inference 6.07 vs 7.39 FPS (default) and 67.4 vs 77.3 FPS (realtime)
    # — see PERF.md "r4 A/B" for the table and the suspected cause.
    # Explicit True forces it where shapes fit (the auto-SPMD pjit path
    # still strips it — no partitioning rule for the kernel).
    fused_lookup: Optional[bool] = None
    # Ours: rematerialize the encoders in the backward pass. Their
    # full-resolution conv1/layer1 activations are multi-GB backward
    # residuals at train shapes. True = recompute both whole encoders
    # (one extra encoder forward); "blocks" = remat each trunk residual
    # block individually (saves block inputs only — most of the memory win
    # at a fraction of the recompute); "blocks_hires" = remat only the
    # blocks running entirely at post-stem resolution (layer1 at the
    # shipped presets — their internals are the ~10x saves; ~2.7 GB more
    # residency than "blocks" at SceneFlow b8 for a third of the
    # recompute); "norms" = save every conv output +
    # norm statistics and recompute only the elementwise norm/relu glue
    # (no conv re-runs — the fp32 norm intermediates and bool relu masks
    # are what dominate plain-backward residual memory).
    remat_encoders: "bool | str" = False
    # Under remat_encoders="norms"/"blocks"/"blocks_hires": save conv
    # outputs ("norms") or remat-boundary block inputs (the blocks modes,
    # "blocks_hires" resolving like "blocks") in a lane-dense folded shape
    # (64/96-channel saves are otherwise padded 2x/1.33x to the 128-lane
    # tile). None = auto, policy per remat mode: "norms" folds by estimated
    # padded size (its padded save set genuinely cannot fit a 16 GB chip at
    # SceneFlow b8 — fold_enc_saves_auto), "blocks" stays UNFOLDED (its
    # padded saves fit there, and the fold's relayout copies measured
    # -0.39 pairs/s — PERF.md r4 A/B). bool forces either way.
    fold_enc_saves: Optional[bool] = None
    # Ours: fp32 working-set budget (bytes) for the post-scan batched
    # upsample before it is chunked over the iteration axis (lax.map
    # serialization — bounds the peak temp at the cost of per-chunk
    # dispatch + stack copies). None = the model default
    # (models/raft_stereo.py _UPSAMPLE_TILE_BUDGET); with the r4
    # rematerialized loss tail the one-shot schedule's temps are transient,
    # so a larger budget trades peak memory back for speed.
    upsample_tile_budget: Optional[int] = None
    # Ours: jax.checkpoint around the post-scan upsample/loss tail. True
    # recomputes the upsample's fp32 softmax/tile intermediates in the
    # backward instead of saving them across the loss backward (measured
    # 1.4-1.9 GB at SceneFlow b8 — the difference between fitting a 16 GB
    # chip and AOT-OOM, r4). False saves them (r2's schedule): one less
    # batched upsample in the backward, for shapes/chips where the
    # residency fits. Applies to both the chunked and stacked tails.
    remat_loss_tail: bool = True
    # Ours: selective refinement-backward saves (keep gru_zr/gru_q/
    # corr_feats across the scan backward instead of full per-iteration
    # remat). None = auto by the measured-size estimate
    # (models/raft_stereo.py refinement_save_policy_fits: ON at b4-like
    # residency, OFF at b8 where HBM pressure inverted the trade in r2).
    # bool forces either way — the A/B override the bench chain uses.
    # "corr" saves ONLY corr_feats: ~180 MB bf16 at SceneFlow b8 (vs the
    # full set's ~2.7 GB), skipping the 4-level pyramid-lookup recompute
    # in the backward without the gate-conv residency that loses at b8.
    refinement_save_policy: Union[bool, str, None] = None
    # Ours: lax.scan unroll factor for the refinement loop. >1 replicates
    # the iteration body inside the while loop, amortizing per-iteration
    # dispatch overhead and letting XLA fuse across consecutive iterations
    # — at the cost of a proportionally larger graph. Semantically
    # identical. A LIVE knob (PERF.md's r2 "knob removed" note was stale —
    # reconciled r8): the r4 inference A/B re-measured it on both presets
    # and scripts/serial_floor.py's rolled-vs-unrolled decomposition depends
    # on it. Measured at SceneFlow b8 (r4): unroll=2 gave 9.23 vs 9.42
    # pairs/s — the scan body's ops are large enough that dispatch
    # overhead is not the binding cost there; smaller/lower-batch shapes
    # may differ, hence the knob.
    scan_unroll: int = 1
    # Ours: custom-VJP refinement scan with batched weight gradients
    # (ops/scan_grad.py). True restructures the training backward: one
    # reverse scan computes data gradients only, and each GRU gate conv's
    # weight gradient is computed AFTER the scan as a single contraction
    # over the (iters*B)-stacked (input, cotangent) pairs — one MXU-shaped
    # wgrad conv instead of 22 small accumulating ones (~1.1 ms/iter,
    # PERF.md roofline lever #2). The trade is residual memory: the stacks
    # are multiple GB at SceneFlow b8 (the r4 analysis that deferred this
    # lever), bounded by residual_dtype. None = auto, currently OFF: the
    # memory/throughput trade is unmeasured-on-hardware and b8's headroom
    # says it loses there; bench.py carries the ON attempt every round so
    # benchmark day banks whichever path is faster (the A/B the r8 issue
    # requires). Gradients are equivalence-pinned either way
    # (tests/test_scan_grad.py).
    batched_scan_wgrad: Optional[bool] = None
    # Ours: storage dtype for refinement-backward residual stacks — the
    # allocation class the r7 breakdown named dominant
    # ([22,B,80,180,128..144]). On the custom-VJP path this narrows every
    # stacked residual (saved carries, save-policy stacks, wgrad
    # input/cotangent stacks) WITHOUT touching forward numerics; batched
    # contractions still accumulate fp32. On the autodiff path it rounds
    # the tagged gru_zr/gru_q/corr_feats saves through this dtype while a
    # save policy is engaged (one rounding on the saved values — the
    # documented-tolerance regime, tests/test_scan_grad.py). Also feeds the
    # save-policy size estimate (refinement_save_policy_fits), so bf16
    # residuals can re-admit the policy at shapes fp32 saves priced out.
    residual_dtype: Optional[str] = None
    # Ours: mechanism for the adaptive early-exit inference mode (engaged
    # per-call via adaptive_tau, test mode only; the thresholds/budgets
    # come from a recorded iter_policy — obs/converge.py). "masked_scan"
    # keeps the fixed-trip nn.scan and freezes converged samples in the
    # carry (static shapes/trip count — the AOT/serve-cache flavor; saved
    # wall clock comes from the policy's per-bucket budget undercutting
    # the fixed valid_iters). "while_loop" exits the whole batch as soon
    # as every sample has converged (dynamic trip count — wins when a
    # whole batch settles early, but the program is not expressible as a
    # fixed-length scan).
    adaptive_mode: str = "masked_scan"
    # Ours: W2 tile width (lanes) for the memoryless "fused" correlation
    # kernel's blocked grid. Bounds the kernel's largest transient —
    # (rows, W1, fused_block_w) fp32 in VMEM — independent of image width;
    # the kernel halves it further under VMEM pressure, so this is a
    # ceiling, not a promise. 256 = two 128-lane tiles per block, trading
    # grid-step overhead against residency; sweep it on hardware via
    # --fused_block_w before trusting another value.
    fused_block_w: int = 256

    def __post_init__(self):
        impl = CORR_ALIASES.get(self.corr_implementation, self.corr_implementation)
        object.__setattr__(self, "corr_implementation", impl)
        object.__setattr__(self, "hidden_dims", tuple(self.hidden_dims))
        if impl not in CORR_IMPLEMENTATIONS:
            aliases = ", ".join(f"{a!r}->{t!r}"
                                for a, t in sorted(CORR_ALIASES.items()))
            raise ValueError(
                f"unknown corr_implementation {impl!r}; registered: "
                f"{list(CORR_IMPLEMENTATIONS)} (aliases: {aliases})")
        if self.context_norm not in NORM_FNS:
            raise ValueError(f"unknown context_norm {self.context_norm!r}")
        if not 1 <= self.n_gru_layers <= 3:
            raise ValueError("n_gru_layers must be in {1,2,3}")
        if self.remat_encoders not in (False, True, "blocks", "blocks_hires",
                                       "norms"):
            raise ValueError(
                f"remat_encoders must be False, True, 'blocks', "
                f"'blocks_hires' or 'norms', got {self.remat_encoders!r}")
        if self.refinement_save_policy not in (None, False, True, "corr"):
            raise ValueError(
                f"refinement_save_policy must be None, False, True or "
                f"'corr', got {self.refinement_save_policy!r}")
        if (self.refinement_save_policy not in (None, False)
                and not self.remat_refinement):
            # mirror the loud fused_lookup-conflict fallback in the model:
            # save policies choose which residuals the refinement REMAT
            # keeps, so without remat they select nothing
            import warnings
            warnings.warn(
                f"refinement_save_policy={self.refinement_save_policy!r} "
                "has no effect with remat_refinement=False (save policies "
                "select which residuals the refinement remat keeps); the "
                "un-rematted scan saves everything anyway")
        if self.corr_storage_dtype not in (None, "float32", "bfloat16"):
            raise ValueError(
                f"unknown corr_storage_dtype {self.corr_storage_dtype!r}; "
                "expected None, 'float32' or 'bfloat16'")
        if self.residual_dtype not in (None, "float32", "bfloat16"):
            raise ValueError(
                f"unknown residual_dtype {self.residual_dtype!r}; "
                "expected None, 'float32' or 'bfloat16'")
        if self.batched_scan_wgrad not in (None, True, False):
            raise ValueError(
                f"batched_scan_wgrad must be None (auto), True or False, "
                f"got {self.batched_scan_wgrad!r}")
        if not (isinstance(self.fused_block_w, int)
                and self.fused_block_w >= 2 * self.corr_radius + 3):
            # the blocked window slice needs 2r+3 lanes per block minimum
            raise ValueError(
                f"fused_block_w must be an int >= 2*corr_radius+3 "
                f"(= {2 * self.corr_radius + 3}), got {self.fused_block_w!r}")
        if self.adaptive_mode not in ("masked_scan", "while_loop"):
            raise ValueError(
                f"adaptive_mode must be 'masked_scan' or 'while_loop', "
                f"got {self.adaptive_mode!r}")
        if len(self.hidden_dims) != 3 or self.hidden_dims[0] != self.hidden_dims[2]:
            # The reference wires context conv i (sized hidden_dims[i]) into the
            # GRU at level i whose hidden size is hidden_dims[2-i]
            # (raft_stereo.py:32 vs update.py:104-106) — consistent only when
            # hidden_dims[0] == hidden_dims[2] (conv 1 always matches gru16).
            raise ValueError("hidden_dims must have length 3 with "
                             "hidden_dims[0] == hidden_dims[2] "
                             "(reference GRU/context cross-wiring)")

    @property
    def factor(self) -> int:
        """Resolution factor of the disparity field (2**n_downsample)."""
        return 2 ** self.n_downsample

    @property
    def corr_channels(self) -> int:
        """Channels produced by a correlation lookup (core/update.py:69)."""
        return self.corr_levels * (2 * self.corr_radius + 1)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Training loop config (reference "Training parameters", train_stereo.py:220-231)."""

    name: str = "raft-stereo"
    # Path to an orbax state dir / reference .pth, or the literal "auto":
    # scan ckpt_dir for this run's checkpoints, verify each manifest
    # (training/resilience.py), and resume from the newest VALID one —
    # truncated/corrupt/foreign checkpoints are skipped with a
    # `ckpt_integrity` event. No valid checkpoint = fresh start.
    restore_ckpt: Optional[str] = None
    batch_size: int = 6
    train_datasets: Tuple[str, ...] = ("sceneflow",)
    lr: float = 0.0002
    num_steps: int = 100000
    image_size: Tuple[int, int] = (320, 720)
    train_iters: int = 16
    valid_iters: int = 32
    wdecay: float = 1e-5
    # Data augmentation (train_stereo.py:244-248)
    img_gamma: Optional[Tuple[float, ...]] = None
    saturation_range: Optional[Tuple[float, float]] = None
    do_flip: Optional[str] = None  # False/'h'/'v'
    spatial_scale: Tuple[float, float] = (0.0, 0.0)
    noyjitter: bool = False
    # Ours: data root, seed, checkpoint dir, validation cadence, device mesh.
    data_root: str = "datasets"
    seed: int = 1234
    ckpt_dir: str = "checkpoints"
    validation_frequency: int = 10000
    num_workers: int = 4
    # Parallelism: number of data-parallel and sequence(width)-parallel shards.
    # data_parallel <= 0 means "use all available devices".
    data_parallel: int = 0
    seq_parallel: int = 1
    # Gradient accumulation: average grads over k micro-batches before each
    # optimizer update (optax.MultiSteps) — large effective batches on few
    # chips. num_steps counts micro-steps; the LR schedule advances per
    # accumulated update.
    grad_accum_steps: int = 1
    # Observability (raft_stereo_tpu/obs): the run directory root — console/TB
    # logs and the events.jsonl telemetry land under <run_dir>/<name> — and
    # the stall-watchdog deadline: a `stall` event + console warning when no
    # step completes within this many seconds (widened 10x before the first
    # step to let initial compilation through). None/0 disables the watchdog.
    run_dir: str = "runs"
    stall_deadline_s: Optional[float] = 300.0
    # Span tracing (obs/trace.py): step/data_wait/dispatch/fetch spans +
    # loader spans on the event bus, feeding `cli timeline`/`cli doctor`
    # and the flight recorder. Cheap enough to leave on (ring-buffered,
    # reuses the step loop's existing perf_counter stamps); False yields
    # the null tracer and a span-free events.jsonl.
    trace: bool = True
    # Fault tolerance (training/resilience.py). Checkpoint cadence in
    # steps; None rides validation_frequency (the pre-r11 behavior —
    # checkpoints only ever landed beside validations). A preemptible-pod
    # recipe sets this much tighter than the validation cadence: a SIGKILL
    # loses at most this many steps of work (SIGTERM/SIGINT lose none —
    # the preemption handler saves before exiting).
    checkpoint_frequency: Optional[int] = None
    # Retention over step checkpoints: keep the newest K (0 disables the
    # sweep entirely — nothing is ever deleted), sparing any checkpoint
    # whose step is a multiple of ckpt_keep_every (0 = no sparing).
    ckpt_keep_last: int = 3
    ckpt_keep_every: int = 0
    # Device-side anomaly guard (training/state.py): lax.cond skips the
    # optimizer update when the global grad norm or loss is non-finite —
    # no host sync, step counter still advances. anomaly_max_skips is the
    # host-side halt policy: after M CONSECUTIVE skipped updates the run
    # raises AnomalyHalt for rollback to the last durable checkpoint
    # (0 = never halt; isolated skips only ever cost their own batch).
    anomaly_guard: bool = True
    anomaly_max_skips: int = 10
    # Numerics observatory (obs/numerics.py): per-leaf gradient-norm
    # vector in the train step's metrics, cadence-sampled into schema-v9
    # `numerics` events every numerics_every steps (the vector itself is
    # fetched with the lagged metrics either way; the cadence only gates
    # event volume). Also arms top-k offending-leaf attribution on the
    # anomaly event. numerics=False pins the step program byte-identical
    # to the unobserved one (--no_numerics).
    numerics: bool = True
    numerics_every: int = 50
    # Fleet observatory (obs/fleet.py, schema v10): host identity stamped
    # on every telemetry record plus a clock_anchor at run_start and
    # `heartbeat` liveness beats every heartbeat_every_s seconds from the
    # trainer, so `cli fleet` can align/diagnose N training processes.
    # host_id=None resolves to RAFT_HOST_ID env or <hostname>-<pid>;
    # fleet=False (--no_fleet) pins the event stream byte-shaped like a
    # single-process run (no stamps, no anchor, no beats).
    fleet: bool = True
    heartbeat_every_s: float = 10.0
    host_id: Optional[str] = None


# --- Named presets mirroring the reference's published training commands -------------

def sceneflow_config() -> tuple[RAFTStereoConfig, TrainConfig]:
    """README.md:130 SceneFlow recipe: batch 8, 22 train iters, 200k steps, bf16."""
    return (
        # bf16 volume storage is an explicit training opt-in (measured win,
        # PERF.md); eval-time parity checks run the fp32 default.
        RAFTStereoConfig(mixed_precision=True, corr_storage_dtype="bfloat16"),
        TrainConfig(batch_size=8, train_iters=22, num_steps=200000,
                    spatial_scale=(-0.2, 0.4), saturation_range=(0.0, 1.4)),
    )


def realtime_config() -> RAFTStereoConfig:
    """README.md:105 fastest configuration (7 valid iters at 1/8 resolution)."""
    return RAFTStereoConfig(
        shared_backbone=True, n_downsample=3, n_gru_layers=2, slow_fast_gru=True,
        corr_implementation="reg_pallas", mixed_precision=True,
    )


def rvc_config() -> RAFTStereoConfig:
    """README.md:81 iRaftStereo_RVC: instance-normalized context encoder."""
    return RAFTStereoConfig(context_norm="instance")


def middlebury_finetune_config() -> tuple[RAFTStereoConfig, TrainConfig]:
    """README.md:141 Middlebury 2014 finetune: 4k steps, lr 2e-5, batch 2,
    crop 384x1000, warm-started from the SceneFlow checkpoint."""
    return (
        RAFTStereoConfig(mixed_precision=True),
        TrainConfig(train_datasets=("middlebury_2014",), num_steps=4000,
                    image_size=(384, 1000), lr=2e-5, batch_size=2,
                    train_iters=22, valid_iters=32,
                    spatial_scale=(-0.2, 0.4), saturation_range=(0.0, 1.4),
                    restore_ckpt="models/raftstereo-sceneflow.pth"),
    )


# The r4-measured fastest SceneFlow-b8 training schedule (9.42 pairs/s/chip,
# PERF.md "r4 A/B"): one-shot post-scan upsample, saved (not rematerialized)
# loss tail, unfolded blocks-remat saves. Keyed by RAFTStereoConfig field
# names; shared by bench.py's banker and scripts/profile_step.py so the
# profiled schedule can never silently drift from the benched one.
R4_BEST_SCHEDULE = {
    "upsample_tile_budget": 2_147_483_648,
    "remat_loss_tail": False,
    "fold_enc_saves": False,
}
