"""Convergence observatory: record iteration-resolved quality curves,
then answer the ROADMAP 1(b) question offline.

RAFT-Stereo's update operator is an anytime estimator: the model already
measures, in-graph, how much each GRU iteration still moves the disparity
field (``iter_metrics``, models/raft_stereo.py) and — when ground truth is
available — the per-iteration low-res EPE proxy. This module is the
recording and decision layer on top of those aux outputs:

* :func:`converge_payload` / :func:`emit` — downsample one curve (strictly
  increasing iteration indices, endpoints always kept) and put a schema-v8
  ``converge`` record on the telemetry bus: one event per evaluated frame
  or served request.
* :func:`simulate` / :func:`decision_table` — the early-exit what-if
  simulator: replay recorded curves against a grid of exit thresholds τ
  (exit at the first iteration whose residual drops to τ) × bucket
  granularities, WITHOUT re-running the model. The output is the 1(b)
  decision table: predicted iterations saved and predicted EPE delta, per
  source (validator / serve bucket) and per shape bucket.
* :func:`main` — ``cli converge <run_dir>`` over a recorded run.
* :func:`exit_percentile` — "by which iteration had p95 converged?"; the
  evidence behind the doctor's OVER_ITERATED verdict (obs/doctor.py).
* :func:`build_policy` / :func:`load_policy` / :func:`policy_digest` /
  :func:`policy_lookup` — the actuation half (r16): ``cli converge
  <run_dir> --emit-policy iter_policy.json`` distills the decision table
  into a checked-in per-bucket iteration policy (τ, budget, min_iters,
  provenance: source run + the table row that earned each entry) that the
  adaptive inference mode compiles in (models/raft_stereo.py
  ``adaptive_tau``; threaded by inference.StereoPredictor, eval
  ``--iter_policy`` and the serve adaptive cache flavors keyed on
  :func:`policy_digest`). Schema lint: obs/validate.py
  ``check_iter_policy``.

The curves are disparity-residual curves in low-res pixels: τ is "the
mean |Δdisparity| one more iteration would still apply". The serial-floor
decomposition (scripts/serial_floor.py: 342.7 ms fixed + 55.2 ms/iter at
22 iterations) prices every saved iteration; this table predicts how many
a given τ saves and what it costs in EPE.
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: stored points per curve (endpoints always kept; full curve when the
#: iteration budget is already this small)
DEFAULT_MAX_POINTS = 32

#: default early-exit threshold grid (mean |Δdisparity|, low-res px)
DEFAULT_TAUS = (0.5, 0.2, 0.1, 0.05, 0.02, 0.01)

#: the doctor's "converged" threshold (see obs/doctor.py OVER_ITERATED)
DOCTOR_TAU = 0.05


# --- recording -------------------------------------------------------------

def downsample(values: Sequence[float],
               max_points: int = DEFAULT_MAX_POINTS
               ) -> Tuple[List[int], List[float]]:
    """Pick <= max_points strictly increasing indices covering [0, n-1].

    Both endpoints are always kept (the simulator needs the final value;
    half-life needs the first). Uniform stride in between.
    """
    n = len(values)
    if n == 0:
        return [], []
    if max_points < 2:
        max_points = 2
    if n <= max_points:
        idx = list(range(n))
    else:
        idx = sorted({round(i * (n - 1) / (max_points - 1))
                      for i in range(max_points)})
    return idx, [float(values[i]) for i in idx]


def half_life(idx: Sequence[int], residual: Sequence[float]) -> Optional[int]:
    """First stored iteration index where the residual fell to half its
    initial value (None when it never did within the recorded curve)."""
    if not residual:
        return None
    target = residual[0] / 2.0
    for i, v in zip(idx, residual):
        if v <= target:
            return int(i)
    return None


def converge_payload(source: str, iters: int, residual: Sequence[float], *,
                     epe: Optional[Sequence[float]] = None,
                     bucket: Optional[str] = None,
                     max_points: int = DEFAULT_MAX_POINTS,
                     **extra: Any) -> Dict[str, Any]:
    """Build one ``converge`` event payload from a full-length curve."""
    idx, res = downsample(residual, max_points)
    payload: Dict[str, Any] = {
        "source": source, "iters": int(iters), "idx": idx, "residual": res,
    }
    if epe is not None:
        payload["epe"] = [float(epe[i]) for i in idx]
    if bucket is not None:
        payload["bucket"] = bucket
    if res:
        payload["final_residual"] = res[-1]
        hl = half_life(idx, res)
        if hl is not None:
            payload["half_life"] = hl
    payload.update(extra)
    return payload


def emit(telemetry, source: str, iters: int, residual: Sequence[float], *,
         epe: Optional[Sequence[float]] = None,
         bucket: Optional[str] = None, **extra: Any) -> None:
    """Downsample + emit one frame/request's curve on the bus (no-op
    without a telemetry sink — observability never gates the data path)."""
    if telemetry is None:
        return
    telemetry.emit("converge", **converge_payload(
        source, iters, residual, epe=epe, bucket=bucket, **extra))


# --- the early-exit simulator ----------------------------------------------

def load_records(path: str) -> List[Dict[str, Any]]:
    """All ``converge`` records from a run dir (or events.jsonl path)."""
    from raft_stereo_tpu.obs.events import read_events
    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    if not os.path.exists(path):
        return []
    return [r for r in read_events(path) if r.get("event") == "converge"]


def exit_iter(idx: Sequence[int], residual: Sequence[float],
              tau: float) -> Optional[int]:
    """Iterations an early-exit policy at threshold tau would have spent:
    idx[k]+1 at the first stored point with residual <= tau (None when the
    curve never converged within the recorded budget)."""
    for i, v in zip(idx, residual):
        if v <= tau:
            return int(i) + 1
    return None


def simulate(rec: Dict[str, Any], tau: float) -> Dict[str, Any]:
    """What exiting at tau would have done to ONE recorded curve."""
    iters = int(rec["iters"])
    used = exit_iter(rec["idx"], rec["residual"], tau)
    converged = used is not None
    used = used if converged else iters
    out = {"converged": converged, "exit_iter": used,
           "saved": iters - used, "epe_delta": None}
    epe = rec.get("epe")
    if epe:
        k = rec["idx"].index(used - 1) if converged else len(epe) - 1
        out["epe_delta"] = float(epe[k]) - float(epe[-1])
    return out


def _percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (the serve/slo.py convention)."""
    if not values:
        return float("nan")
    vals = sorted(values)
    rank = max(1, math.ceil(q / 100.0 * len(vals)))
    return vals[rank - 1]


def exit_percentile(records: Iterable[Dict[str, Any]], tau: float = DOCTOR_TAU,
                    q: float = 95.0) -> Optional[Dict[str, Any]]:
    """"By which iteration had q% of frames converged (at tau)?" — over-
    iteration evidence. Never-converged curves count as the full budget, so
    the percentile cannot claim headroom convergence didn't earn."""
    recs = list(records)
    if not recs:
        return None
    exits, n_conv = [], 0
    for r in recs:
        sim = simulate(r, tau)
        exits.append(float(sim["exit_iter"]))
        n_conv += bool(sim["converged"])
    return {"n": len(recs), "n_converged": n_conv, "tau": tau, "q": q,
            "budget": max(int(r["iters"]) for r in recs),
            "exit_iter": int(_percentile(exits, q))}


def decision_table(records: Iterable[Dict[str, Any]],
                   taus: Sequence[float] = DEFAULT_TAUS,
                   bucket_by: str = "both") -> List[Dict[str, Any]]:
    """The ROADMAP 1(b) decision table over recorded curves.

    One row per (source, bucket granularity, tau): how many curves, the
    p50/p95 exit iteration, mean predicted iterations saved, and the mean
    predicted EPE delta (None when no curve carried the EPE aux).
    ``bucket_by``: "bucket" (per shape bucket), "all" (collapsed), or
    "both".
    """
    groups: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for rec in records:
        source = str(rec.get("source", "?"))
        keys = []
        if bucket_by in ("bucket", "both"):
            keys.append((source, str(rec.get("bucket", "?"))))
        if bucket_by in ("all", "both"):
            keys.append((source, "*"))
        for key in keys:
            groups.setdefault(key, []).append(rec)
    rows: List[Dict[str, Any]] = []
    for (source, bucket) in sorted(groups):
        recs = groups[(source, bucket)]
        budget = max(int(r["iters"]) for r in recs)
        for tau in taus:
            sims = [simulate(r, tau) for r in recs]
            exits = [float(s["exit_iter"]) for s in sims]
            deltas = [s["epe_delta"] for s in sims
                      if s["epe_delta"] is not None]
            rows.append({
                "source": source, "bucket": bucket, "tau": tau,
                "n": len(recs), "budget": budget,
                "converged_frac": sum(s["converged"] for s in sims)
                / len(sims),
                "exit_p50": int(_percentile(exits, 50.0)),
                "exit_p95": int(_percentile(exits, 95.0)),
                "saved_mean": sum(s["saved"] for s in sims) / len(sims),
                "epe_delta_mean": (sum(deltas) / len(deltas)
                                   if deltas else None),
                "n_epe": len(deltas),
            })
    return rows


# --- the recorded iteration policy (the actuation half) ---------------------

#: current iter_policy.json schema version
POLICY_VERSION = 1
#: top-level marker that routes a JSON artifact to the policy lint
POLICY_KIND = "iter_policy"


def build_policy(records: Iterable[Dict[str, Any]], *,
                 tau: float = DOCTOR_TAU, min_iters: int = 1,
                 margin: int = 1, source_run: str = "?") -> Dict[str, Any]:
    """Distill recorded curves into a per-bucket iteration policy.

    One entry per shape bucket (plus a ``default`` from the collapsed
    ``"*"`` rows): exit threshold ``tau``, iteration ``budget`` =
    ``exit_p95 + margin`` clamped to the recorded budget (the p95 exit
    plus safety margin — the policy must not cost quality the table never
    predicted), and ``min_iters``. Every entry carries provenance — the
    source run and the decision-table row that earned it — so the lint
    (obs/validate.py check_iter_policy) can hold the numbers referentially
    against their origin. When several sources share a bucket the LARGEST
    candidate budget wins (the conservative merge).
    """
    recs = list(records)
    if not recs:
        raise ValueError("no converge records to build a policy from")
    rows = decision_table(recs, taus=(float(tau),), bucket_by="both")

    def entry_of(row: Dict[str, Any]) -> Dict[str, Any]:
        budget = min(int(row["budget"]), int(row["exit_p95"]) + int(margin))
        budget = max(1, budget)
        return {
            "tau": float(row["tau"]),
            "budget": budget,
            "min_iters": max(1, min(int(min_iters), budget)),
            "provenance": {"source": row["source"], "row": dict(row)},
        }

    buckets: Dict[str, Dict[str, Any]] = {}
    default: Optional[Dict[str, Any]] = None
    for row in rows:
        e = entry_of(row)
        if row["bucket"] == "*":
            if default is None or e["budget"] > default["budget"]:
                default = e
        elif row["bucket"] != "?":
            cur = buckets.get(row["bucket"])
            if cur is None or e["budget"] > cur["budget"]:
                buckets[row["bucket"]] = e
    doc: Dict[str, Any] = {
        "kind": POLICY_KIND, "version": POLICY_VERSION,
        "source_run": source_run, "buckets": buckets,
    }
    if default is not None:
        doc["default"] = default
    return doc


def policy_digest(doc: Dict[str, Any]) -> str:
    """Short stable digest of a policy doc — the serve cache-flavor key
    (serve/cache.py) and the provenance stamp on emitted events."""
    import hashlib
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def load_policy(path: str) -> Dict[str, Any]:
    """Load + lint one ``iter_policy.json``; raises ValueError with the
    first named violation — a doctored policy must fail at load, not at
    serve time."""
    with open(path) as f:
        doc = json.load(f)
    from raft_stereo_tpu.obs.validate import check_iter_policy
    errors = check_iter_policy(doc)
    if errors:
        raise ValueError(f"{path}: {errors[0]}"
                         + (f" (+{len(errors) - 1} more)"
                            if len(errors) > 1 else ""))
    return doc


def policy_lookup(doc: Dict[str, Any],
                  bucket: Optional[str]) -> Optional[Dict[str, Any]]:
    """Resolve one bucket (``"HxW"``) to its policy entry; falls back to
    the ``default`` entry, then None (caller keeps the fixed trip)."""
    if bucket is not None:
        e = doc.get("buckets", {}).get(bucket)
        if e is not None:
            return e
    return doc.get("default")


def format_table(rows: List[Dict[str, Any]]) -> str:
    """Render the decision table for the terminal."""
    header = (f"{'source':<18} {'bucket':<12} {'tau':>6} {'n':>5} "
              f"{'conv%':>6} {'p50':>4} {'p95':>4} {'saved':>6} "
              f"{'epe_delta':>10}")
    lines = [header, "-" * len(header)]
    for r in rows:
        delta = ("-" if r["epe_delta_mean"] is None
                 else f"{r['epe_delta_mean']:+.3f}")
        lines.append(
            f"{r['source']:<18} {r['bucket']:<12} {r['tau']:>6g} "
            f"{r['n']:>5} {100.0 * r['converged_frac']:>5.0f}% "
            f"{r['exit_p50']:>4} {r['exit_p95']:>4} "
            f"{r['saved_mean']:>6.1f} {delta:>10}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``cli converge <run_dir>`` — the offline early-exit simulator."""
    from raft_stereo_tpu.cli import build_converge_parser
    args = build_converge_parser().parse_args(argv)
    records = load_records(args.run_dir)
    if not records:
        print(f"no converge records under {args.run_dir} — run eval/serve "
              "with convergence telemetry on (it is the default; "
              "--no_converge disables it)", file=sys.stderr)
        return 1
    taus = tuple(args.taus) if args.taus else DEFAULT_TAUS
    rows = decision_table(records, taus=taus, bucket_by=args.bucket_by)
    doc = {"run_dir": args.run_dir, "curves": len(records),
           "taus": list(taus), "bucket_by": args.bucket_by,
           "table": rows}
    if args.emit_policy:
        ptau = DOCTOR_TAU if args.policy_tau is None else args.policy_tau
        policy = build_policy(records, tau=ptau,
                              min_iters=args.policy_min_iters,
                              margin=args.policy_margin,
                              source_run=args.run_dir)
        os.makedirs(os.path.dirname(args.emit_policy) or ".", exist_ok=True)
        with open(args.emit_policy, "w") as f:
            json.dump(policy, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"iter policy written: {args.emit_policy} "
              f"({len(policy['buckets'])} bucket(s)"
              f"{', default' if 'default' in policy else ''}, "
              f"tau={ptau:g}, digest {policy_digest(policy)})",
              file=sys.stderr)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2)
    if args.json == "-":
        # the cli compare convention: '-' streams the JSON to stdout
        # INSTEAD of the text table (converge_drill's replay leg and
        # other machine consumers parse this)
        json.dump(doc, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        if args.json:
            os.makedirs(os.path.dirname(args.json) or ".", exist_ok=True)
            with open(args.json, "w") as f:
                json.dump(doc, f, indent=2)
        budget = max(int(r["iters"]) for r in records)
        print(f"{len(records)} curves, iteration budget {budget} "
              f"({args.run_dir})")
        print(format_table(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
