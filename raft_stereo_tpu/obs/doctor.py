"""``cli doctor``: name the dominant bottleneck, with evidence.

A rule-driven diagnosis pass over a run's events + spans that replaces
eyeballing ``cli telemetry`` output. One verdict per phase present in the
log (train steps and/or served requests), each with the evidence lines
that earned it:

* **STALLED** — the watchdog fired: something wedged outright (the
  tunneled-TPU failure mode PERF.md documents). Trumps everything: rate
  analysis of a wedged run is noise.
* **COMPILE_STORM** — repeated compilations ate a large share of the
  wall clock (shape churn / cache misses); fix compilation, not the
  steady state.
* **QUEUE_SATURATED** (serve) — requests spend most of their latency
  waiting for admission into a batch: offered load exceeds service rate;
  scale out or shed harder.
* **DATA_STARVED** (train) — the step loop blocks on the loader: the
  median step's data_wait share is dominant and the prefetch queue runs
  empty. More decode workers/prefetch, not a faster model, is the fix.
* **COMPUTE_BOUND** — the device-side phases dominate; the pipeline is
  healthy and further wins come from the model/compiler (the ROADMAP's
  serial-floor work).
* **BALANCED** — nothing dominates; **UNKNOWN** only when the log holds
  no usable evidence at all.
* **OVER_ITERATED** (own phase, additive) — schema-v8 ``converge`` curves
  show p95 of frames/requests settled (residual <= obs/converge.py's
  DOCTOR_TAU) well before the configured iteration budget: the run spent
  device time refining disparities that had stopped moving. Evidence
  quotes "p95 converged by iter k of N" and points at ``cli converge``
  for the full threshold sweep.
* **STRAGGLER / DEAD_HOST / DESYNC / FLEET_OK** (``fleet`` phase) — when
  pointed at a directory of N per-host run dirs instead of one run,
  doctor routes to the schema-v10 fleet observatory (obs/fleet.py):
  clock-aligned cross-host verdicts naming the host whose step p95 blew
  past the other hosts', whose heartbeats stopped without a clean
  run_end, or whose step counter drifted from the live fleet's.
* **NONFINITE_ORIGIN / BF16_SATURATION / GRAD_EXPLOSION /
  NUMERICS_CLEAN** (own ``numerics`` phase, additive) — the schema-v9
  numerics observatory's verdicts, in that priority order: the recorded
  tap statistics name the first tap+iteration that went non-finite (NaN
  provenance), the bf16 stacks that clipped at the format rail, or the
  parameter leaf whose gradient norm exploded. Evidence points at
  ``cli numerics`` for the full per-leaf/per-tap replay.

Rules read the ``step``/``request``/``slo``/``loader``/``stall``/
``compile`` records (all pre-v7), so doctor works on old artifacts too;
v7 spans sharpen the serve phase split, v8 converge curves add the
over-iteration rule, v9 numerics records add the numerics phase, when
present.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

from raft_stereo_tpu.obs.events import read_events
from raft_stereo_tpu.obs.summarize import _percentiles

# rule thresholds (fractions of wall / latency)
COMPILE_STORM_MIN_EVENTS = 3
COMPILE_STORM_WALL_FRAC = 0.5
DATA_STARVED_FRAC = 0.4
COMPUTE_BOUND_FRAC = 0.6
QUEUE_SATURATED_FRAC = 0.5
# OVER_ITERATED: p95 exit iteration must undercut the budget by at least
# this many iterations (a 1-iteration margin is measurement noise, not a
# tuning opportunity), over at least this many curves
OVER_ITERATED_MARGIN = 2
OVER_ITERATED_MIN_CURVES = 4


def _median(xs: Sequence[float]) -> float:
    return _percentiles(list(xs))["p50"] if xs else 0.0


def _verdict(phase: str, verdict: str,
             evidence: List[str]) -> Dict[str, Any]:
    return {"phase": phase, "verdict": verdict, "evidence": evidence}


def _wall_s(records: Sequence[Dict[str, Any]]) -> float:
    ts = [float(r["t"]) for r in records if "t" in r]
    return (max(ts) - min(ts)) if len(ts) > 1 else 0.0


def _check_stall(records, phase: str) -> Optional[Dict[str, Any]]:
    stalls = [r for r in records if r.get("event") == "stall"]
    if not stalls:
        return None
    worst = max(float(r.get("seconds_since_step", 0.0)) for r in stalls)
    return _verdict(phase, "STALLED", [
        f"stall watchdog fired {len(stalls)}x; worst gap "
        f"{worst:.1f}s since the last completed step",
        "rate analysis suppressed: a wedged run's steady-state numbers "
        "are noise — check the flight-recorder dump and the device link",
    ])


def _check_compile_storm(records, phase: str,
                         wall: float) -> Optional[Dict[str, Any]]:
    compiles = [r for r in records if r.get("event") == "compile"]
    total = sum(float(r.get("duration_s", 0.0)) for r in compiles)
    if (len(compiles) >= COMPILE_STORM_MIN_EVENTS and wall > 0
            and total > COMPILE_STORM_WALL_FRAC * wall):
        return _verdict(phase, "COMPILE_STORM", [
            f"{len(compiles)} compilations totaling {total:.1f}s = "
            f"{total / wall:.0%} of the {wall:.1f}s wall clock",
            "look for shape churn (bucket spread, microbatch breaks) or "
            "a cold/invalidated compilation cache",
        ])
    return None


def _diagnose_train(records) -> Optional[Dict[str, Any]]:
    # step records in a serving log are the loadtest's per-request
    # accounting (serve/loadtest.py), not a train loop — the request
    # records carry that story; steps with in_flight are eval frames
    if any(r.get("event") == "request" for r in records):
        return None
    steps = [r for r in records
             if r.get("event") == "step" and "in_flight" not in r]
    if not steps:
        return None
    phase = "train"
    hit = _check_stall(records, phase)
    if hit:
        return hit
    wall = _wall_s(records)
    hit = _check_compile_storm(records, phase, wall)
    if hit:
        return hit
    # skip the first step: its dispatch leg carries compilation
    body = steps[1:] or steps
    waits = [float(r.get("data_wait_s", 0.0)) for r in body]
    disps = [float(r.get("dispatch_s", 0.0)) for r in body]
    fetches = [float(r.get("fetch_s", 0.0)) for r in body]
    totals = [w + d + f for w, d, f in zip(waits, disps, fetches)]
    med_total = _median(totals)
    if med_total <= 0:
        return _verdict(phase, "UNKNOWN",
                        ["step records carry no usable phase timing"])
    wait_frac = _median(waits) / med_total
    dev_frac = _median([d + f for d, f in zip(disps, fetches)]) / med_total
    if wait_frac > DATA_STARVED_FRAC:
        evidence = [
            f"median step: data_wait {_median(waits) * 1e3:.1f}ms of "
            f"{med_total * 1e3:.1f}ms ({wait_frac:.0%}) over "
            f"{len(body)} steps"]
        loaders = [r for r in records if r.get("event") == "loader"]
        if loaders:
            depths = [float(r.get("queue_depth", 0)) for r in loaders]
            evidence.append(
                f"loader prefetch queue depth: median {_median(depths):.0f}"
                f" (0 = producer cannot keep up)")
        evidence.append("raise decode workers / prefetch before touching "
                        "the model")
        return _verdict(phase, "DATA_STARVED", evidence)
    if dev_frac >= COMPUTE_BOUND_FRAC:
        return _verdict(phase, "COMPUTE_BOUND", [
            f"median step: dispatch+fetch "
            f"{_median([d + f for d, f in zip(disps, fetches)]) * 1e3:.1f}"
            f"ms of {med_total * 1e3:.1f}ms ({dev_frac:.0%}) over "
            f"{len(body)} steps",
            "the pipeline keeps the device fed; wins come from the "
            "model/compiler (serial-floor work, ROADMAP item 1)",
        ])
    return _verdict(phase, "BALANCED", [
        f"median step {med_total * 1e3:.1f}ms: data_wait {wait_frac:.0%}, "
        f"device {dev_frac:.0%} — no phase dominates",
    ])


def _diagnose_serve(records) -> Optional[Dict[str, Any]]:
    requests = [r for r in records if r.get("event") == "request"]
    if not requests:
        return None
    phase = "serve"
    hit = _check_stall(records, phase)
    if hit:
        return hit
    hit = _check_compile_storm(records, phase, _wall_s(records))
    if hit:
        return hit
    lats = [float(r.get("latency_s", 0.0)) for r in requests]
    waits = [float(r.get("queue_wait_s", 0.0)) for r in requests]
    med_lat = _median(lats)
    if med_lat <= 0:
        return _verdict(phase, "UNKNOWN",
                        ["request records carry no usable latency"])
    wait_frac = _median(waits) / med_lat
    rejected = 0
    for r in records:
        if r.get("event") in ("queue", "slo"):
            rejected = max(rejected, int(r.get("rejected", 0)))
    if wait_frac > QUEUE_SATURATED_FRAC:
        evidence = [
            f"median request: queue_wait {_median(waits) * 1e3:.1f}ms of "
            f"{med_lat * 1e3:.1f}ms latency ({wait_frac:.0%}) over "
            f"{len(requests)} requests"]
        if rejected:
            evidence.append(f"{rejected} submits shed by backpressure — "
                            f"offered load exceeds service rate")
        depths = [int(r.get("depth", 0)) for r in records
                  if r.get("event") == "queue"]
        if depths:
            evidence.append(f"admission queue depth: median "
                            f"{_median([float(d) for d in depths]):.0f}, "
                            f"max {max(depths)}")
        evidence.append("scale out, raise max_batch/window, or shed "
                        "earlier")
        return _verdict(phase, "QUEUE_SATURATED", evidence)
    return _verdict(phase, "COMPUTE_BOUND", [
        f"median request: queue_wait {wait_frac:.0%} of "
        f"{med_lat * 1e3:.1f}ms latency over {len(requests)} requests — "
        f"time goes to the device, not the queue",
        "bigger wins come from the compiled program (bucket/batch "
        "shape), not admission tuning",
    ])


def _diagnose_converge(records) -> Optional[Dict[str, Any]]:
    """OVER_ITERATED: the recorded convergence curves prove the iteration
    budget overshoots where the estimate stops moving."""
    from raft_stereo_tpu.obs.converge import DOCTOR_TAU, exit_percentile
    curves = [r for r in records if r.get("event") == "converge"]
    if len(curves) < OVER_ITERATED_MIN_CURVES:
        return None
    ev = exit_percentile(curves, tau=DOCTOR_TAU, q=95.0)
    if ev is None:
        return None
    budget, p95 = ev["budget"], ev["exit_iter"]
    if p95 > budget - OVER_ITERATED_MARGIN:
        return None
    return _verdict("converge", "OVER_ITERATED", [
        f"p95 converged by iter {p95} of {budget} (residual <= "
        f"{ev['tau']}px over {ev['n']} curves, "
        f"{ev['n_converged']}/{ev['n']} converged within budget)",
        f"the last {budget - p95} iterations refine disparities that "
        f"have stopped moving — device time with no quality return",
        "freeze the operating point into a policy with `cli converge "
        "<run_dir> --emit-policy iter_policy.json` and serve it via "
        "--iter_policy — the compiled early exit banks these savings "
        "per sample instead of lowering the budget for everyone",
    ])


def _diagnose_numerics(records) -> Optional[Dict[str, Any]]:
    """The numerics observatory's verdict, in severity order:
    NONFINITE_ORIGIN > BF16_SATURATION > GRAD_EXPLOSION > NUMERICS_CLEAN.
    None when the run recorded no numerics events (pre-v9 artifacts)."""
    from raft_stereo_tpu.obs.numerics import GRAD_ALARM_NORM, split_label
    numerics = [r for r in records if r.get("event") == "numerics"]
    if not numerics:
        return None
    phase = "numerics"
    grads = [r for r in numerics if r.get("kind") == "grad"]
    taps = [r for r in numerics if r.get("kind") == "taps"]
    # 1) non-finite provenance — a NaN origin trumps everything else
    for r in taps:
        fnf = r.get("first_nonfinite")
        if fnf:
            return _verdict(phase, "NONFINITE_ORIGIN", [
                f"first non-finite values at tap '{fnf.get('tap')}' "
                f"iteration {fnf.get('iter')} ({fnf.get('count')} "
                f"elements; source {r.get('source')})",
                "every later NaN is downstream of this site — fix the "
                "producer, not the symptoms",
                "full per-tap series: `cli numerics <run_dir>`",
            ])
    for r in grads:
        bad = [n for n, v in zip(r.get("leaves", []),
                                 r.get("grad_norm", [])) if v is None]
        if bad:
            return _verdict(phase, "NONFINITE_ORIGIN", [
                f"non-finite gradient norm at step {r.get('step')} in "
                f"{len(bad)} leaf/leaves; first: {bad[0]}",
                "the anomaly guard skips these updates; the named leaf "
                "is where the backward first blew up",
                "full per-leaf trend: `cli numerics <run_dir>`",
            ])
    # 2) bf16 rail hits — silent clipping that precedes overflow-to-inf
    sat_by_tap: Dict[str, int] = {}
    for r in taps:
        for key, series in (r.get("taps") or {}).items():
            s = sum(int(v) for v in series.get("sat", []) if v)
            if s:
                label = split_label(key)[1] if ":" in key else key
                sat_by_tap[label] = sat_by_tap.get(label, 0) + s
    if sat_by_tap:
        worst = max(sat_by_tap, key=lambda k: sat_by_tap[k])
        return _verdict(phase, "BF16_SATURATION", [
            f"{sum(sat_by_tap.values())} values at the bf16 finite rail "
            f"across {len(sat_by_tap)} tap(s); worst: '{worst}' "
            f"({sat_by_tap[worst]} hits)",
            "values at the rail clip silently and overflow to inf one "
            "scale later — rescale or lift this stack to fp32",
            "saturation leaderboard: `cli numerics <run_dir>`",
        ])
    # 3) finite but exploding gradients
    worst_leaf, worst_norm, worst_step = None, 0.0, None
    for r in grads:
        for name, v in zip(r.get("leaves", []), r.get("grad_norm", [])):
            if v is not None and float(v) > worst_norm:
                worst_leaf, worst_norm = name, float(v)
                worst_step = r.get("step")
    if worst_leaf is not None and worst_norm > GRAD_ALARM_NORM:
        return _verdict(phase, "GRAD_EXPLOSION", [
            f"leaf '{worst_leaf}' gradient norm {worst_norm:.3g} at step "
            f"{worst_step} (alarm threshold {GRAD_ALARM_NORM:g})",
            "clip harder, lower the LR, or check this leaf's input "
            "statistics before it goes non-finite",
            "per-leaf trend: `cli numerics <run_dir>`",
        ])
    n_taps = sum(len(r.get("taps") or {}) for r in taps)
    return _verdict(phase, "NUMERICS_CLEAN", [
        f"{len(grads)} grad record(s) and {len(taps)} tap record(s) "
        f"({n_taps} tap series): all finite, no bf16 rail hits, no "
        f"gradient norm above {GRAD_ALARM_NORM:g}",
    ])


def diagnose(run_dir: str) -> Dict[str, Any]:
    """Diagnose one run dir; returns ``{"run_dir", "verdicts": [...]}``.

    ``verdicts`` holds one entry per phase with evidence; a log with
    neither steps nor requests yields a single UNKNOWN verdict. A
    directory WITHOUT its own events.jsonl but holding child run dirs
    that have one is a fleet dir: the report routes to the fleet
    observatory's cross-host verdicts (obs/fleet.py).
    """
    events_path = (os.path.join(run_dir, "events.jsonl")
                   if os.path.isdir(run_dir) else run_dir)
    if os.path.isdir(run_dir) and not os.path.exists(events_path):
        from raft_stereo_tpu.obs import fleet
        if fleet.discover_runs(run_dir):
            return fleet.diagnose_fleet(run_dir)
    records = read_events(events_path)
    verdicts = [v for v in (_diagnose_train(records),
                            _diagnose_serve(records),
                            _diagnose_converge(records),
                            _diagnose_numerics(records)) if v is not None]
    if not verdicts:
        verdicts = [_verdict("run", "UNKNOWN", [
            "no step or request records — nothing to diagnose"])]
    return {"run_dir": run_dir, "verdicts": verdicts}


def format_diagnosis(report: Dict[str, Any]) -> str:
    lines = [f"doctor: {report['run_dir']}"]
    for v in report["verdicts"]:
        lines.append(f"  [{v['phase']}] {v['verdict']}")
        for e in v["evidence"]:
            lines.append(f"    - {e}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    from raft_stereo_tpu.cli import build_doctor_parser
    args = build_doctor_parser().parse_args(argv)
    try:
        report = diagnose(args.run_dir)
    except (FileNotFoundError, ValueError) as e:
        print(f"doctor: {e}")
        return 1
    if getattr(args, "json"):
        import json
        print(json.dumps(report, indent=2))
    else:
        print(format_diagnosis(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
