"""The run-scoped telemetry bus: events.jsonl writer + stall watchdog.

A :class:`Telemetry` instance owns one run directory and appends
schema-stamped records (obs/events.py) to ``<run_dir>/events.jsonl``. It is
thread-safe (the loader's producer thread and the watchdog emit concurrently
with the training loop) and fail-open: a telemetry bug must never take down
the run it observes, so emit errors are logged once and swallowed.

Three observers ride on the bus:

* **Compile hook** — ``jax.monitoring`` duration events whose key mentions
  compilation are forwarded as ``compile`` records. Registered once per
  process (listeners cannot be unregistered in current JAX) and dispatched
  to whichever instances are open. First-call latency is the complementary
  detector: the trainer stamps its first step's dispatch time as a
  ``compile`` record with ``source="first_step_latency"`` — on tunneled
  remote-compile setups the helper's time is invisible to jax.monitoring.
* **Stall watchdog** — a daemon thread that emits a ``stall`` record and a
  one-line console warning when no heartbeat (= completed step) lands within
  ``stall_deadline_s`` (the tunneled-TPU failure mode PERF.md documents).
  One warning per stall episode; a new heartbeat re-arms it. Before the
  first step the deadline is widened 10x: initial compilation legitimately
  takes minutes.
* **Device memory** — ``memory`` records via
  ``jax.local_devices()[0].memory_stats()`` where the backend provides it
  (TPU does; CPU returns nothing and the record carries ``stats: {}``).
* **Flight recorder** — the last N records (and, when a tracer is
  attached, its span ring) are mirrored in memory and dumped to
  ``<run_dir>/flightrec-<host>-<ts>.jsonl`` when something goes wrong:
  the stall watchdog firing, an ``anomaly``/``preempt`` record landing,
  the crash path (:meth:`error`), or an explicit drain. Postmortems then
  carry the last seconds at full resolution even when steady-state
  sampling is coarse; each dump leaves a ``flightrec`` record on the bus
  pointing at the side file. Rate-limited per reason so a flapping
  watchdog cannot fill the disk. The ``host_id`` in the filename keeps N
  processes sharing a run dir from clobbering each other's dumps.
* **Fleet stamping** (schema v10, obs/fleet.py) — every record gains the
  process's ``host_id``/``pid`` (and mesh ``coords`` when given), a
  ``clock_anchor`` record lands at run_start (the monotonic-to-wall
  mapping ``cli fleet`` aligns N processes' ``t`` axes with), and
  :meth:`start_heartbeat` runs liveness beats on cadence per role.
  ``fleet=False`` turns all of it off — the stream is then byte-shaped
  like a single-process run (the ``--no_fleet`` bitwise pin).
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, Optional, Sequence

from raft_stereo_tpu.obs.events import make_record, append_json_log
from raft_stereo_tpu.obs.fleet import TRACEPARENT_ENV, resolve_host_id

logger = logging.getLogger(__name__)

# Compile-episode deadline widening before the first heartbeat (see module
# doc); tests override via the Telemetry(first_step_grace=) knob.
_FIRST_STEP_GRACE = 10.0

# Flight-recorder knobs: recent-record ring capacity, and the per-reason
# dump rate limit (a wedged run re-fires the watchdog every interval;
# one dump per episode is the useful one).
_FLIGHT_RING = 256
_FLIGHT_MIN_INTERVAL_S = 30.0

# A heartbeat thread that wakes this many cadence intervals late reports
# itself: the host is wedged enough that even a daemon timer could not
# run, which is exactly what the fleet aggregator's DEAD_HOST deadline
# (obs/fleet.py DEAD_HOST_GAP_BEATS) looks for offline — the anomaly
# rides the flight-recorder trigger so the postmortem has the window.
_HEARTBEAT_GAP_FACTOR = 3.0

# --- process-global compile-hook dispatch ----------------------------------
_hook_lock = threading.Lock()
_hook_registered = False
_active_instances: "set[Telemetry]" = set()


def _compile_listener(event: str, duration: float, **_kw) -> None:
    # Only true backend compilations (plus anything compile-flavored that
    # took real time): jax traces EVERY jaxpr through this channel — a tiny
    # train run emits 1000+ sub-millisecond jaxpr_trace records otherwise.
    if "backend_compile" not in event and not (
            "compil" in event and duration >= 0.5):
        return
    for tel in list(_active_instances):
        tel._emit_compile(event, duration)


def _ensure_compile_hook() -> bool:
    global _hook_registered
    with _hook_lock:
        if _hook_registered:
            return True
        try:
            import jax.monitoring
            jax.monitoring.register_event_duration_secs_listener(
                _compile_listener)
            _hook_registered = True
        except Exception:  # jax absent / API moved: first-call latency only
            return False
    return True


class Telemetry:
    """Event bus for one run directory; safe to use as a context manager
    (exceptions inside the ``with`` are recorded as ``error`` events and
    re-raised)."""

    def __init__(self, run_dir: str, run_name: Optional[str] = None,
                 stall_deadline_s: Optional[float] = None,
                 first_step_grace: float = _FIRST_STEP_GRACE,
                 watch_interval_s: Optional[float] = None,
                 flightrec_min_interval_s: float = _FLIGHT_MIN_INTERVAL_S,
                 host_id: Optional[str] = None, fleet: bool = True,
                 coords: Optional[Sequence[int]] = None):
        self.run_dir = run_dir
        self.run_name = run_name or os.path.basename(
            os.path.normpath(run_dir)) or "run"
        self.events_path = os.path.join(run_dir, "events.jsonl")
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._closed = False
        self._emit_failed = False
        # step bookkeeping (heartbeat + throughput windows)
        self._steps = 0
        self._last_beat = self._t0
        self._window_pairs = 0
        self._window_t0 = self._t0
        self._compile_s = 0.0
        # stall watchdog
        self._deadline = stall_deadline_s
        self._grace = max(first_step_grace, 1.0)
        self._stalled = False
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None
        # fleet stamping (schema v10): host identity on every record;
        # fleet=False restores the single-process v9-shaped stream
        self.fleet = bool(fleet)
        self.host_id = resolve_host_id(host_id) if self.fleet else None
        self.coords = list(coords) if coords is not None else None
        self._heartbeats: list = []
        # flight recorder: recent-record mirror + attached tracer
        self.tracer = None
        self._recent: "deque" = deque(maxlen=_FLIGHT_RING)
        self._flight_min_interval = flightrec_min_interval_s
        self._flight_last: Dict[str, float] = {}
        os.makedirs(run_dir, exist_ok=True)
        _active_instances.add(self)
        _ensure_compile_hook()
        if stall_deadline_s and stall_deadline_s > 0:
            interval = watch_interval_s or min(
                max(stall_deadline_s / 4.0, 0.05), 10.0)
            self._watchdog = threading.Thread(
                target=self._watch, args=(interval,),
                name="telemetry-watchdog", daemon=True)
            self._watchdog.start()

    # --- core ---------------------------------------------------------------

    def emit(self, event: str, **payload: Any) -> None:
        """Append one record; never raises (fail-open, logged once)."""
        rec = make_record(event, t=time.monotonic() - self._t0, **payload)
        if self.host_id is not None:
            rec.setdefault("host_id", self.host_id)
            rec.setdefault("pid", os.getpid())
            if self.coords is not None:
                rec.setdefault("coords", self.coords)
        try:
            with self._lock:
                if self._closed:
                    return
                append_json_log(self.events_path, rec, stream=None)
                if event != "span":  # span rings live in the tracer
                    self._recent.append(rec)
        except Exception:
            # the with-block released the lock during unwinding; re-take it
            # so the once-only latch is race-free across emitting threads
            with self._lock:
                first = not self._emit_failed
                self._emit_failed = True
            if first:
                logger.exception("telemetry emit failed (disabled for run)")
            return
        # Trigger OUTSIDE the lock: flight_dump re-enters emit (for the
        # flightrec record) and snapshots the tracer under its own lock.
        if event in ("anomaly", "preempt"):
            self.flight_dump(event)

    def attach_tracer(self, tracer) -> None:
        """Bind a Tracer (obs/trace.py): its span flushes already ride this
        bus via :meth:`emit`; binding also puts its ring into flight dumps
        and has close/``__exit__`` flush it before ``run_end``."""
        self.tracer = tracer

    def flight_dump(self, reason: str) -> Optional[str]:
        """Dump the in-memory rings to ``<run_dir>/flightrec-<ts>.jsonl``.

        First line is a header (reason, counts); then the recent records
        (``kind: event``) and the tracer's span ring including still-open
        spans (``kind: span``), each with its payload nested under
        ``record`` so payload fields can never clobber the envelope. A
        ``flightrec`` record lands on the bus
        pointing at the file. Returns the path, or None when rate-limited,
        closed, or the dump failed (fail-open like everything here).
        """
        now = time.monotonic()
        with self._lock:
            if self._closed:
                return None
            last = self._flight_last.get(reason)
            if last is not None and (
                    now - last < self._flight_min_interval):
                return None
            self._flight_last[reason] = now
            events = list(self._recent)
        tracer = self.tracer
        spans = tracer.snapshot() if tracer is not None else []
        ts = time.strftime("%Y%m%dT%H%M%S")
        # host-prefixed so N processes sharing a run dir cannot clobber
        # each other's dumps (fleet=False keeps the legacy name)
        tag = "" if self.host_id is None else \
            re.sub(r"[^A-Za-z0-9_.-]+", "_", self.host_id) + "-"
        path = os.path.join(self.run_dir, f"flightrec-{tag}{ts}.jsonl")
        n = 1
        while os.path.exists(path):  # two dumps in one second
            path = os.path.join(
                self.run_dir, f"flightrec-{tag}{ts}-{n}.jsonl")
            n += 1
        try:
            with open(path, "w") as f:
                f.write(json.dumps({
                    "kind": "flightrec", "reason": reason,
                    "run": self.run_name, "host_id": self.host_id,
                    "t": round(now - self._t0, 6),
                    "events": len(events), "spans": len(spans)}) + "\n")
                # the payload rides nested: records have their own `kind`
                # fields (anomaly), which must not clobber the envelope
                for rec in events:
                    f.write(json.dumps(
                        {"kind": "event", "record": rec}) + "\n")
                for sp in spans:
                    f.write(json.dumps(
                        {"kind": "span", "record": sp}) + "\n")
        except Exception:
            logger.exception("flight-recorder dump failed")
            return None
        self.emit("flightrec", reason=reason, path=path,
                  events=len(events), spans=len(spans))
        logger.warning("flight recorder (%s): %d events + %d spans -> %s",
                       reason, len(events), len(spans), path)
        return path

    def close(self) -> None:
        tracer = self.tracer
        if tracer is not None:  # salvage buffered spans (idempotent)
            try:
                tracer.close()
            except Exception:
                logger.exception("tracer close failed")
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2.0)
        for t in self._heartbeats:
            t.join(timeout=2.0)
        _active_instances.discard(self)
        with self._lock:
            self._closed = True

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.error(exc)
        if self.tracer is not None:  # no span may land after run_end
            try:
                self.tracer.close()
            except Exception:
                logger.exception("tracer close failed")
        self.emit("run_end", steps=self._steps,
                  ok=exc is None, compile_s=round(self._compile_s, 3))
        self.close()

    @property
    def steps(self) -> int:
        """Heartbeats (completed steps) observed by this instance."""
        return self._steps

    # --- record helpers -----------------------------------------------------

    def run_start(self, config: Optional[Dict[str, Any]] = None,
                  **payload: Any) -> None:
        payload.setdefault("devices", _device_info())
        if self.host_id is not None:
            # a launcher's trace envelope (scripts/fleet_drill.py-style
            # subprocess launches) joins this run to the parent span
            envelope = os.environ.get(TRACEPARENT_ENV)
            if envelope:
                payload.setdefault("traceparent", envelope)
        self.emit("run_start", run=self.run_name,
                  config=config or {}, **payload)
        if self.host_id is not None:
            # monotonic + wall sampled back-to-back: the offset `cli
            # fleet` aligns this process's `t` axis with (wall = t +
            # (wall - monotonic))
            mono, wall = time.monotonic(), time.time()
            self.emit("clock_anchor", host_id=self.host_id,
                      monotonic=round(mono - self._t0, 6),
                      wall=round(wall, 6))

    def start_heartbeat(self, role: str, every_s: float,
                        probe=None) -> Optional[threading.Thread]:
        """Liveness beats on cadence from a daemon thread: one schema-v10
        ``heartbeat`` record per ``every_s`` seconds with a per-role
        strictly-increasing ``seq`` (the aggregator detects gaps without
        trusting wall clocks). ``probe()`` -> dict of extras riding each
        beat (e.g. a step snapshot); probe errors are swallowed —
        fail-open like the rest of the bus. No-op (returns None) when
        fleet stamping is off or the cadence is non-positive."""
        if self.host_id is None or not every_s or every_s <= 0:
            return None
        t = threading.Thread(
            target=self._beat, args=(str(role), float(every_s), probe),
            name=f"telemetry-heartbeat-{role}", daemon=True)
        t.start()
        self._heartbeats.append(t)
        return t

    def _beat(self, role: str, every_s: float, probe) -> None:
        seq = 0
        last = time.monotonic()
        while not self._stop.wait(every_s):
            now = time.monotonic()
            gap, last = now - last, now
            extras: Dict[str, Any] = {}
            if probe is not None:
                try:
                    extras = dict(probe() or {})
                except Exception:
                    extras = {}
            self.emit("heartbeat", host_id=self.host_id, role=role,
                      seq=seq, every_s=every_s, **extras)
            if seq > 0 and gap > _HEARTBEAT_GAP_FACTOR * every_s:
                # rides the anomaly -> flight-recorder trigger in emit()
                self.emit("anomaly", kind="heartbeat_gap", role=role,
                          gap_s=round(gap, 3), every_s=every_s)
            seq += 1

    def step(self, step: int, data_wait_s: float, dispatch_s: float,
             fetch_s: float, batch_size: Optional[int] = None,
             **payload: Any) -> None:
        """One completed training/eval step; doubles as the heartbeat."""
        if batch_size is not None:
            payload["batch_size"] = batch_size
            self._window_pairs += batch_size
        self.emit("step", step=int(step),
                  data_wait_s=round(data_wait_s, 6),
                  dispatch_s=round(dispatch_s, 6),
                  fetch_s=round(fetch_s, 6), **payload)
        self.heartbeat()

    def heartbeat(self) -> None:
        # under the bus lock: the watchdog thread reads these as a unit and
        # flips _stalled back the other way
        with self._lock:
            self._steps += 1
            self._last_beat = time.monotonic()
            self._stalled = False

    def checkpoint(self, step: int, path: str, **payload: Any) -> None:
        """``reason`` rides along as an extra field: "periodic" saves omit
        it; the fault-tolerance paths stamp "preempt"/"crash"/"final"
        (training/resilience.py)."""
        self.emit("checkpoint", step=int(step), path=path, **payload)
        self.memory()

    def validation(self, results: Dict[str, float],
                   dataset: Optional[str] = None) -> None:
        payload = {"dataset": dataset} if dataset else {}
        self.emit("validation",
                  results={k: float(v) for k, v in results.items()},
                  **payload)

    def throughput(self, pairs_per_sec: float, steps: int,
                   **payload: Any) -> None:
        self.emit("throughput", pairs_per_sec=round(pairs_per_sec, 4),
                  steps=int(steps), **payload)

    def window_throughput(self) -> Optional[float]:
        """Pairs/sec since the last call (or run start); emits a
        ``throughput`` record and resets the window. None when no batch-sized
        steps landed in the window."""
        now = time.monotonic()
        pairs, dt = self._window_pairs, now - self._window_t0
        self._window_pairs, self._window_t0 = 0, now
        if pairs == 0 or dt <= 0:
            return None
        pps = pairs / dt
        self.throughput(pps, steps=self._steps, window_s=round(dt, 3))
        return pps

    def memory(self) -> None:
        self.emit("memory", stats=_memory_stats())

    def loader_gauge(self, gauges: Dict[str, Any]) -> None:
        """Queue-depth/wait gauges from the data pipeline's producer thread."""
        self.emit("loader", **gauges)

    def pipeline(self, in_flight: int, **payload: Any) -> None:
        """In-flight-depth gauge from the streaming eval pipeline
        (eval/stream.py); 0 means the device queue drained."""
        self.emit("pipeline", in_flight=int(in_flight), **payload)

    def error(self, exc: BaseException) -> None:
        self.emit("error", error=f"{type(exc).__name__}: {exc}",
                  traceback="".join(traceback.format_exception(
                      type(exc), exc, exc.__traceback__))[-4000:])
        self.flight_dump("crash")

    def _emit_compile(self, source: str, duration: float) -> None:
        self._compile_s += duration
        self.emit("compile", duration_s=round(duration, 3), source=source)

    # --- watchdog -----------------------------------------------------------

    def _watch(self, interval: float) -> None:
        while not self._stop.wait(interval):
            deadline = self._deadline
            if deadline is None:
                continue
            with self._lock:
                steps = self._steps
                elapsed = time.monotonic() - self._last_beat
                fire = elapsed > (deadline * self._grace if steps == 0
                                  else deadline) and not self._stalled
                if fire:
                    self._stalled = True  # one record per episode
            if steps == 0:
                deadline = deadline * self._grace
            if fire:
                # emit/flight_dump OUTSIDE the lock: emit takes it itself
                logger.warning(
                    "STALL: no step completed in %.1fs (deadline %.1fs) — "
                    "run %s may be wedged (tunneled-TPU stall? see PERF.md); "
                    "details in %s", elapsed, deadline, self.run_name,
                    self.events_path)
                self.emit("stall", seconds_since_step=round(elapsed, 3),
                          deadline_s=deadline, steps=steps)
                self.flight_dump("stall")


def _device_info() -> Dict[str, Any]:
    try:
        import jax
        devs = jax.local_devices()
        return {"platform": devs[0].platform, "count": len(devs)}
    except Exception:
        return {}


def _memory_stats() -> Dict[str, Any]:
    try:
        import jax
        return dict(jax.local_devices()[0].memory_stats() or {})
    except Exception:
        return {}
