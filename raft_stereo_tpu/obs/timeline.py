"""``cli timeline``: one Chrome/Perfetto-loadable view of a run.

Interleaves three record sources on one clock:

* **host spans** — schema-v7 ``span`` records (obs/trace.py) become
  complete ("X") events, one Perfetto track per producing thread, so a
  step's data_wait/dispatch/fetch legs (or a request's queue_wait/
  collect_group/dispatch/retire legs) nest visually under their root;
* **point events** — stall/anomaly/compile/checkpoint/flightrec/preempt
  records become instant ("i") markers on a dedicated track;
* **device trace** — when a ``jax.profiler`` capture exists under the run
  dir, its lanes (utils/profiling.py's parser) are merged in with their
  pids remapped out of the host range and their timebase shifted so the
  earliest device op sits under the earliest host ``dispatch`` span — the
  device clock is opaque (xprof's own epoch), so "the dispatch that
  caused the first device work" is the one correlation anchor both sides
  share.

The output is the plain Chrome trace-event JSON object
(``{"traceEvents": [...]}``) — load it at ``ui.perfetto.dev`` or
``chrome://tracing``. Written to ``<run_dir>/timeline.json`` by default.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

from raft_stereo_tpu.obs.events import read_events

#: pid of the host-span process in the merged timeline; device pids are
#: remapped to _DEVICE_PID_BASE + original so the ranges never collide.
HOST_PID = 1
EVENTS_PID = 2
_DEVICE_PID_BASE = 100000

#: event types rendered as instant markers (everything with a `t` that
#: marks a moment rather than an interval and is worth seeing on a track)
_INSTANT_EVENTS = ("stall", "anomaly", "compile", "checkpoint",
                   "flightrec", "preempt", "resume", "error", "heartbeat")

#: span names that root a unit of work, for the coverage summary
ROOT_NAMES = ("step", "request")


def span_coverage(spans: Sequence[Dict[str, Any]],
                  root_names: Sequence[str] = ROOT_NAMES
                  ) -> Dict[str, Any]:
    """How much of each root span's wall time its children account for.

    Returns ``{"roots": n, "min": f, "mean": f}`` over roots with nonzero
    duration (fractions clamped to 1.0; the phase legs are designed to
    tile their root exactly, so ~1.0 is the healthy reading and the
    acceptance bar is >= 0.9). No roots -> ``{"roots": 0}``.
    """
    by_parent: Dict[str, float] = {}
    for s in spans:
        parent = s.get("parent_id")
        if parent is not None:
            by_parent[parent] = by_parent.get(parent, 0.0) + \
                float(s.get("dur_s", 0.0))
    fracs = []
    for s in spans:
        if s.get("name") not in root_names:
            continue
        dur = float(s.get("dur_s", 0.0))
        if dur <= 0:
            continue
        fracs.append(min(by_parent.get(s.get("span_id"), 0.0) / dur, 1.0))
    if not fracs:
        return {"roots": 0}
    return {"roots": len(fracs),
            "min": round(min(fracs), 4),
            "mean": round(sum(fracs) / len(fracs), 4)}


def _span_events(spans: Sequence[Dict[str, Any]], pid: int = HOST_PID,
                 process_name: str = "host spans",
                 shift_s: float = 0.0) -> List[Dict[str, Any]]:
    """Host spans -> Chrome "X" events, one tid per producing thread.

    ``pid``/``process_name``/``shift_s`` let obs/fleet.py render one
    process-group per host on a shared aligned clock; the single-run
    timeline uses the defaults.
    """
    tids: Dict[str, int] = {}
    out: List[Dict[str, Any]] = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": process_name}}]
    for s in spans:
        thread = s.get("thread", "main")
        if thread not in tids:
            tids[thread] = len(tids) + 1
            out.append({"ph": "M", "pid": pid, "tid": tids[thread],
                        "name": "thread_name", "args": {"name": thread}})
        args = {k: v for k, v in s.items()
                if k not in ("schema", "ts", "t", "event", "name",
                             "start_s", "dur_s", "thread")}
        out.append({
            "ph": "X", "pid": pid, "tid": tids[thread],
            "name": s.get("name", "?"),
            "ts": round((float(s.get("start_s", 0.0)) + shift_s) * 1e6, 3),
            "dur": round(float(s.get("dur_s", 0.0)) * 1e6, 3),
            "args": args,
        })
    return out


def _instant_events(records: Sequence[Dict[str, Any]],
                    pid: int = EVENTS_PID, process_name: str = "events",
                    shift_s: float = 0.0) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": process_name}},
        {"ph": "M", "pid": pid, "tid": 1, "name": "thread_name",
         "args": {"name": "markers"}}]
    n = 0
    for r in records:
        if r.get("event") not in _INSTANT_EVENTS or "t" not in r:
            continue
        n += 1
        args = {k: v for k, v in r.items()
                if k not in ("schema", "ts", "t", "event")}
        out.append({
            "ph": "i", "s": "g", "pid": pid, "tid": 1,
            "name": r["event"],
            "ts": round((float(r["t"]) + shift_s) * 1e6, 3),
            "args": args,
        })
    return out if n else []


def _device_events(run_dir: str, spans: Sequence[Dict[str, Any]]
                   ) -> List[Dict[str, Any]]:
    """Merge the jax.profiler capture, shifted onto the span clock.

    Alignment anchor: the earliest device op starts with the earliest
    host ``dispatch``-named span (the host call that queued the first
    device work); with no dispatch span, the earliest span of all. No
    capture -> empty list (host-only timeline).
    """
    from raft_stereo_tpu.utils.profiling import (device_lanes,
                                                 load_trace_events)
    try:
        _, events = load_trace_events(run_dir)
    except Exception:
        return []
    device_pids, _ = device_lanes(events)
    if not device_pids:
        return []
    dev = [e for e in events
           if e.get("pid") in device_pids and "ts" in e]
    if not dev:
        return []
    dev_t0 = min(float(e["ts"]) for e in dev if e.get("ph") == "X")
    anchors = [float(s.get("start_s", 0.0)) for s in spans
               if "dispatch" in str(s.get("name", ""))]
    if not anchors:
        anchors = [float(s.get("start_s", 0.0)) for s in spans]
    shift_us = (min(anchors) * 1e6 - dev_t0) if anchors else -dev_t0
    out: List[Dict[str, Any]] = []
    for e in events:
        if e.get("pid") not in device_pids:
            continue
        e = dict(e)
        e["pid"] = _DEVICE_PID_BASE + int(e["pid"])
        if "ts" in e:
            e["ts"] = round(float(e["ts"]) + shift_us, 3)
        out.append(e)
    return out


def build_timeline(run_dir: str,
                   out: Optional[str] = None) -> Dict[str, Any]:
    """Build ``<run_dir>/timeline.json``; returns a summary dict
    (path, event counts, coverage, whether a device trace merged)."""
    events_path = os.path.join(run_dir, "events.jsonl")
    records = read_events(events_path)
    spans = [r for r in records if r.get("event") == "span"]
    trace_events: List[Dict[str, Any]] = []
    trace_events.extend(_span_events(spans))
    trace_events.extend(_instant_events(records))
    device = _device_events(run_dir, spans)
    trace_events.extend(device)
    out = out or os.path.join(run_dir, "timeline.json")
    with open(out, "w") as f:
        json.dump({"traceEvents": trace_events,
                   "displayTimeUnit": "ms"}, f)
    return {
        "path": out,
        "spans": len(spans),
        "markers": sum(1 for e in trace_events if e.get("ph") == "i"),
        "device_events": len(device),
        "coverage": span_coverage(spans),
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    from raft_stereo_tpu.cli import build_timeline_parser
    args = build_timeline_parser().parse_args(argv)
    try:
        summary = build_timeline(args.run_dir, out=args.out)
    except (FileNotFoundError, ValueError) as e:
        print(f"timeline: {e}")
        return 1
    cov = summary["coverage"]
    cov_line = ("no root spans" if not cov.get("roots") else
                f"{cov['roots']} roots, child coverage min "
                f"{cov['min']:.0%} mean {cov['mean']:.0%}")
    print(f"timeline: {summary['path']}\n"
          f"  {summary['spans']} spans, {summary['markers']} markers, "
          f"{summary['device_events']} device events\n"
          f"  {cov_line}\n"
          f"  load at ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
