"""Span tracing on the telemetry bus: one correlated host timeline.

The event bus records THAT things happened (step/request/queue rows); this
layer records WHY a particular unit of work was slow, as nested spans —
``trace_id`` groups the spans of one unit of work (a train step, a served
request), ``span_id``/``parent_id`` nest them, and ``start_s``/``dur_s``
sit on the same monotonic ``t`` axis every other record uses, so
``cli timeline`` can interleave spans with events and the ``jax.profiler``
device trace on one clock (obs/timeline.py) and ``cli doctor`` can name
the dominant bottleneck per phase (obs/doctor.py).

Design constraints, in order:

* **Cheap enough to leave on.** Closed spans land in an in-memory ring and
  a flush buffer; the buffer is written to events.jsonl as additive
  schema-v7 ``span`` records once per ``flush_every`` spans (one batched
  lock acquisition per record, no syscall per span). Hot loops that
  already own ``perf_counter`` stamps (the trainer's t0..t3 split, the
  serving scheduler's submit/dispatch stamps) use :meth:`Tracer.record` —
  retroactive span construction with zero timing calls of its own.
* **Zero overhead when disabled.** :data:`NULL_TRACER` answers the whole
  API with no-ops, so call sites thread ``tracer`` unconditionally; a run
  with tracing off emits a bitwise-identical step event stream
  (tests/test_trace.py pins this).
* **Cross-thread propagation.** The current span is thread-local;
  a producer/scheduler thread continues a caller's trace by capturing
  ``tracer.current()`` in the submitting thread and passing it as
  ``parent=`` in the worker (the loader producer and serve scheduler do).
* **Referential integrity.** Parents end after their children, so
  children may flush first, but ``close()`` force-flushes everything still
  open — within one events.jsonl every ``parent_id`` resolves to a
  flushed ``span_id`` (obs/validate.py lints this; the flight recorder's
  ring additionally snapshots still-open spans marked ``open=True``).
"""

from __future__ import annotations

import contextlib
import functools
import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional

#: default ring capacity (closed spans kept for the flight recorder)
RING_SIZE = 2048
#: spans buffered before a batch flush to the telemetry bus
FLUSH_EVERY = 32


class SpanContext(NamedTuple):
    """Immutable propagation token: enough to parent a span from another
    thread (capture with :meth:`Tracer.current`, pass as ``parent=``)."""

    trace_id: str
    span_id: str


class Span:
    """One open span; ``end()`` (or the context manager) closes it."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_pc",
                 "end_pc", "attrs", "thread", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str], start_pc: float,
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_pc = start_pc
        self.end_pc: Optional[float] = None
        self.attrs = attrs
        self.thread = threading.current_thread().name

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def end(self, end_pc: Optional[float] = None) -> None:
        if self.end_pc is None:
            self.end_pc = time.perf_counter() if end_pc is None else end_pc
            self._tracer._finish(self)


class Tracer:
    """Span factory + ring buffer bound to one :class:`Telemetry` instance.

    Spans are stamped with ``time.perf_counter()`` and mapped onto the
    telemetry ``t`` axis via an offset captured at construction, so span
    times, event ``t`` stamps and (after the timeline merger's shift) the
    device trace share one clock.
    """

    enabled = True

    def __init__(self, telemetry=None, *, ring: int = RING_SIZE,
                 flush_every: int = FLUSH_EVERY):
        self.telemetry = telemetry
        self._lock = threading.RLock()
        self._ring: "deque" = deque(maxlen=max(16, ring))
        self._pending: List[Dict[str, Any]] = []
        self._flush_every = max(1, flush_every)
        self._open: Dict[str, Span] = {}
        self._n = itertools.count(1)
        self._local = threading.local()
        # perf_counter stamp that maps to t=0 on the telemetry axis
        t0 = getattr(telemetry, "_t0", None)
        self._t0_pc = time.perf_counter() - (
            (time.monotonic() - t0) if t0 is not None else 0.0)
        if telemetry is not None:
            telemetry.attach_tracer(self)

    # --- clock ---------------------------------------------------------------

    def to_t(self, pc_stamp: float) -> float:
        """Map a ``time.perf_counter()`` stamp to the telemetry ``t`` axis."""
        return pc_stamp - self._t0_pc

    # --- span construction ---------------------------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> Optional[SpanContext]:
        """The calling thread's innermost open span, as a propagation token."""
        stack = self._stack()
        return stack[-1].context if stack else None

    def _ids(self, parent: Any) -> tuple:
        """Resolve (trace_id, parent_id) from an explicit parent context,
        an open Span, or None (a new root = a new trace)."""
        if isinstance(parent, Span):
            parent = parent.context
        if isinstance(parent, SpanContext):
            return parent.trace_id, parent.span_id
        return f"t{next(self._n):06x}", None

    def start(self, name: str, parent: Any = "inherit",
              **attrs: Any) -> Span:
        """Open a span (caller owns ``end()``); prefer :meth:`span`."""
        if parent == "inherit":
            parent = self.current()
        trace_id, parent_id = self._ids(parent)
        with self._lock:
            span_id = f"s{next(self._n):06x}"
        span = Span(self, name, trace_id, span_id, parent_id,
                    time.perf_counter(), attrs)
        with self._lock:
            self._open[span_id] = span
        return span

    @contextlib.contextmanager
    def span(self, name: str, parent: Any = "inherit", **attrs: Any):
        """Context manager: open a span, push it as the thread's current
        span (children nest under it), close on exit."""
        s = self.start(name, parent=parent, **attrs)
        stack = self._stack()
        stack.append(s)
        try:
            yield s
        finally:
            if stack and stack[-1] is s:
                stack.pop()
            s.end()

    def traced(self, name: Optional[str] = None, **attrs: Any):
        """Decorator form of :meth:`span`."""
        def deco(fn):
            label = name or fn.__name__

            @functools.wraps(fn)
            def wrapper(*args, **kwargs):
                with self.span(label, **attrs):
                    return fn(*args, **kwargs)
            return wrapper
        return deco

    def record(self, name: str, start_pc: float, end_pc: float,
               parent: Any = None, **attrs: Any) -> Optional[SpanContext]:
        """Retroactively record a span from existing ``perf_counter``
        stamps — the hot-loop API: the trainer/scheduler measure their
        phases anyway; this turns the stamps into a span without a single
        extra timing call. Returns the span's context so subsequent
        ``record`` calls can parent under it."""
        trace_id, parent_id = self._ids(parent)
        with self._lock:
            span_id = f"s{next(self._n):06x}"
        span = Span(self, name, trace_id, span_id, parent_id,
                    start_pc, attrs)
        span.end_pc = end_pc
        self._finish(span)
        return SpanContext(trace_id, span_id)

    # --- ring + flush --------------------------------------------------------

    def _payload(self, span: Span, open_: bool = False) -> Dict[str, Any]:
        end = span.end_pc if span.end_pc is not None else time.perf_counter()
        payload: Dict[str, Any] = dict(
            name=span.name, span_id=span.span_id, trace_id=span.trace_id,
            start_s=round(self.to_t(span.start_pc), 6),
            dur_s=round(max(end - span.start_pc, 0.0), 6),
            thread=span.thread)
        if span.parent_id is not None:
            payload["parent_id"] = span.parent_id
        if open_:
            payload["open"] = True
        payload.update(span.attrs)
        return payload

    def _finish(self, span: Span) -> None:
        payload = self._payload(span)
        with self._lock:
            self._open.pop(span.span_id, None)
            self._ring.append(payload)
            self._pending.append(payload)
            do_flush = len(self._pending) >= self._flush_every
        if do_flush:
            self.flush()

    def flush(self) -> None:
        """Write buffered spans to the bus, in end order (children of a
        still-open parent flush first; ``close()`` flushes the parent, so
        whole-file parent_id integrity holds)."""
        with self._lock:
            batch, self._pending = self._pending, []
        if self.telemetry is not None:
            for payload in batch:
                self.telemetry.emit("span", **payload)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Ring contents (closed spans) plus still-open spans marked
        ``open=True`` — the flight recorder's span half."""
        with self._lock:
            out = list(self._ring)
            out.extend(self._payload(s, open_=True)
                       for s in self._open.values())
        return out

    def close(self) -> None:
        """End every still-open span and flush — call BEFORE the run's
        ``run_end`` record so no span lands after it."""
        with self._lock:
            still_open = list(self._open.values())
        for span in still_open:
            span.end()
        self.flush()


class _NullTracer:
    """The disabled tracer: the whole API as no-ops, so call sites thread
    a tracer unconditionally and pay nothing when tracing is off."""

    enabled = False

    @contextlib.contextmanager
    def span(self, name, parent="inherit", **attrs):
        yield None

    def traced(self, name=None, **attrs):
        return lambda fn: fn

    def start(self, name, parent="inherit", **attrs):
        raise RuntimeError("start() on the null tracer; gate on .enabled")

    def record(self, name, start_pc, end_pc, parent=None, **attrs):
        return None

    def current(self):
        return None

    def to_t(self, pc_stamp):
        return pc_stamp

    def flush(self):
        pass

    def snapshot(self):
        return []

    def close(self):
        pass


#: the shared disabled tracer (stateless, safe to share across threads)
NULL_TRACER = _NullTracer()


def tracer_for(telemetry, enabled: bool = True):
    """The call-site helper: a real :class:`Tracer` bound to ``telemetry``
    (reusing one already attached), or :data:`NULL_TRACER` when disabled
    or there is no bus to ride."""
    if not enabled or telemetry is None:
        return NULL_TRACER
    existing = getattr(telemetry, "tracer", None)
    return existing if existing is not None else Tracer(telemetry)
