"""Run-regression gate: diff two runs' event logs against thresholds.

``python -m raft_stereo_tpu.cli compare <baseline> <candidate>`` lands here.
The r5 round shipped two regressions a reviewer had to *notice* (the banked
bench number wobbling 0.7% below published figures; the multichip dryrun
timing out after its stages passed) — this gate makes them machine-detected:
each run's ``events.jsonl`` is reduced to comparable scalars and the
candidate fails (non-zero exit) when any metric moves past its threshold in
the bad direction:

* ``throughput_pairs_per_sec`` — best ``throughput`` record (the banked
  number's semantics: a bench chain logs every attempt, the best is what
  the round reports); higher is better.
* per-phase step percentiles (``data_wait/dispatch/fetch`` p50/p90) — lower
  is better.
* ``peak_memory_bytes`` — max over ``memory`` stats and ``xla_memory``
  introspection records (obs/xla.py); lower is better.
* ``compile_total_s`` — summed compile records; lower is better.

Metrics absent from either run are *skipped*, not failed (a CPU run has no
device memory stats; an eval run has no throughput record) — the gate
compares what both runs measured, and says what it skipped. A candidate
with no readable events at all is an error (exit 2), because "nothing to
compare" must never read as "no regression".
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

from raft_stereo_tpu.obs.events import read_events

_PHASES = ("data_wait_s", "dispatch_s", "fetch_s")

# Default relative thresholds, tuned to the measured noise bands: the b8
# banker wobbles ~1% run-to-run over 12 timed steps (9.55-9.64, VERDICT r5
# #2), so 3% throughput is a real move; phase percentiles and compile times
# are noisier (host scheduling, cache warmth), so their gates are looser.
DEFAULT_THRESHOLDS = {
    "throughput_drop": 0.03,    # candidate pairs/sec below baseline by >3%
    "phase_increase": 0.25,     # any phase percentile worse by >25%
    "memory_growth": 0.05,      # peak bytes above baseline by >5%
    "compile_growth": 0.50,     # total compile seconds above baseline by >50%
}


def _percentile(values: Sequence[float], q: float) -> float:
    import numpy as np
    return float(np.percentile(np.asarray(values, dtype=np.float64), q))


def extract_metrics(run_dir: str) -> Optional[Dict[str, float]]:
    """Reduce a run dir's events.jsonl to the gate's comparable scalars.

    Returns None when the run left no parseable events (the caller decides
    whether that is an error or a skip).
    """
    path = run_dir
    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    if not os.path.exists(path):
        return None
    try:
        events = read_events(path)
    except ValueError:
        return None
    if not events:
        return None
    by = lambda kind: [e for e in events if e.get("event") == kind]  # noqa: E731

    metrics: Dict[str, float] = {}
    tp = [e["pairs_per_sec"] for e in by("throughput")
          if isinstance(e.get("pairs_per_sec"), (int, float))]
    if tp:
        metrics["throughput_pairs_per_sec"] = max(tp)

    steps = by("step")
    for phase in _PHASES:
        vals = [s[phase] for s in steps
                if isinstance(s.get(phase), (int, float))]
        if vals:
            metrics[f"{phase}_p50"] = _percentile(vals, 50)
            metrics[f"{phase}_p90"] = _percentile(vals, 90)

    peaks: List[float] = []
    for e in by("memory"):
        stats = e.get("stats") or {}
        v = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
        if isinstance(v, (int, float)):
            peaks.append(float(v))
    for e in by("xla_memory"):
        if isinstance(e.get("peak_bytes"), (int, float)):
            peaks.append(float(e["peak_bytes"]))
    if peaks:
        metrics["peak_memory_bytes"] = max(peaks)

    compiles = [e.get("duration_s", 0.0) for e in by("compile")]
    if compiles:
        metrics["compile_total_s"] = float(sum(compiles))
    return metrics


def _gate(metric: str, thresholds: Dict[str, float]):
    """(threshold key, higher_is_better) for one metric name."""
    if metric == "throughput_pairs_per_sec":
        return "throughput_drop", True
    if metric == "peak_memory_bytes":
        return "memory_growth", False
    if metric == "compile_total_s":
        return "compile_growth", False
    return "phase_increase", False  # the per-phase percentiles


def compare_runs(baseline_dir: str, candidate_dir: str,
                 thresholds: Optional[Dict[str, float]] = None
                 ) -> Dict[str, Any]:
    """Build the comparison report; see module doc for semantics."""
    thr = dict(DEFAULT_THRESHOLDS)
    if thresholds:
        thr.update({k: v for k, v in thresholds.items() if v is not None})
    base = extract_metrics(baseline_dir)
    cand = extract_metrics(candidate_dir)
    report: Dict[str, Any] = {
        "baseline": baseline_dir, "candidate": candidate_dir,
        "thresholds": thr, "metrics": {}, "regressions": [], "skipped": [],
    }
    if cand is None or base is None:
        report["error"] = ("candidate has no readable events.jsonl"
                           if cand is None
                           else "baseline has no readable events.jsonl")
        report["ok"] = False
        return report
    for name in sorted(set(base) | set(cand)):
        if name not in base or name not in cand:
            report["skipped"].append(name)
            continue
        a, b = base[name], cand[name]
        key, higher_better = _gate(name, thr)
        # relative move in the BAD direction ("rel" > 0 = candidate worse)
        if a == 0:
            rel = 0.0 if b == 0 else float("inf")
        else:
            rel = (a - b) / a if higher_better else (b - a) / a
        regressed = rel > thr[key]
        report["metrics"][name] = {
            "baseline": a, "candidate": b,
            "regression_rel": round(rel, 5) if rel != float("inf") else None,
            "threshold": thr[key], "ok": not regressed,
        }
        if regressed:
            report["regressions"].append(name)
    report["ok"] = not report["regressions"]
    return report


def format_comparison(report: Dict[str, Any]) -> str:
    lines = [f"baseline:  {report['baseline']}",
             f"candidate: {report['candidate']}"]
    if report.get("error"):
        lines.append(f"ERROR: {report['error']}")
        return "\n".join(lines)
    lines.append("")
    lines.append(f"{'metric':28s} {'baseline':>14s} {'candidate':>14s} "
                 f"{'worse by':>9s}  gate")
    for name, m in report["metrics"].items():
        rel = m["regression_rel"]
        rel_s = "inf" if rel is None else f"{100 * rel:+.1f}%"
        lines.append(f"{name:28s} {m['baseline']:14.6g} "
                     f"{m['candidate']:14.6g} {rel_s:>9s}  "
                     f"{'ok' if m['ok'] else 'REGRESSED'}")
    for name in report["skipped"]:
        lines.append(f"{name:28s} {'(skipped: present in one run only)'}")
    lines.append("")
    if report["regressions"]:
        lines.append("REGRESSION: " + ", ".join(report["regressions"]))
    else:
        lines.append("ok: no metric moved past its threshold")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        description="Regression-gate two runs' events.jsonl "
                    "(exit 1 on regression, 2 on unreadable input)")
    p.add_argument("baseline", help="baseline run dir (or events.jsonl)")
    p.add_argument("candidate", help="candidate run dir (or events.jsonl)")
    p.add_argument("--max-throughput-drop", type=float, default=None,
                   help=f"relative drop tolerated "
                        f"(default {DEFAULT_THRESHOLDS['throughput_drop']})")
    p.add_argument("--max-phase-increase", type=float, default=None,
                   help=f"relative phase-percentile increase tolerated "
                        f"(default {DEFAULT_THRESHOLDS['phase_increase']})")
    p.add_argument("--max-memory-growth", type=float, default=None,
                   help=f"relative peak-memory growth tolerated "
                        f"(default {DEFAULT_THRESHOLDS['memory_growth']})")
    p.add_argument("--max-compile-growth", type=float, default=None,
                   help=f"relative compile-time growth tolerated "
                        f"(default {DEFAULT_THRESHOLDS['compile_growth']})")
    p.add_argument("--json", default=None,
                   help="write the full report to this path; '-' prints "
                        "the report JSON to stdout INSTEAD of the text "
                        "table (machine consumers — rehearse_round's "
                        "compare leg — parse this rather than scraping "
                        "the rendering)")
    args = p.parse_args(argv)
    report = compare_runs(args.baseline, args.candidate, thresholds={
        "throughput_drop": args.max_throughput_drop,
        "phase_increase": args.max_phase_increase,
        "memory_growth": args.max_memory_growth,
        "compile_growth": args.max_compile_growth,
    })
    if args.json == "-":
        json.dump(report, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        if args.json:
            os.makedirs(os.path.dirname(args.json) or ".", exist_ok=True)
            with open(args.json, "w") as f:
                json.dump(report, f, indent=1)
        print(format_comparison(report))
    if report.get("error"):
        return 2
    return 0 if report["ok"] else 1
