"""Run-report builder: events.jsonl + (optional) profiler trace, one summary.

``python -m raft_stereo_tpu.cli telemetry <run_dir>`` lands here. The report
merges the two observability artifacts a run can leave behind:

* ``<run_dir>/events.jsonl`` (obs/telemetry.py) — per-phase step timing
  percentiles, throughput trend over step windows, compile count/time,
  checkpoints, validations, stalls and errors;
* a ``jax.profiler`` trace under the run dir (``plugins/profile/...``) —
  device-op/category totals via :func:`utils.profiling.summarize_trace`.

Either half may be absent; the report says so instead of failing, because
the summarizer's job is reading partial artifacts from wedged runs.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, List, Optional, Sequence

from raft_stereo_tpu.obs.events import read_events, validate_events

_PHASES = ("data_wait_s", "dispatch_s", "fetch_s")


def _percentiles(values: Sequence[float]) -> Dict[str, float]:
    import numpy as np
    arr = np.asarray(sorted(values), dtype=np.float64)
    return {
        "p50": float(np.percentile(arr, 50)),
        "p90": float(np.percentile(arr, 90)),
        "max": float(arr[-1]),
        "total": float(arr.sum()),
    }


def _throughput_trend(steps: List[Dict[str, Any]],
                      n_windows: int = 5) -> List[Dict[str, Any]]:
    """Pairs/sec per window of consecutive step records (wall time from the
    monotonic ``t`` axis; falls back to per-phase sums when ``t`` is absent)."""
    timed = [s for s in steps if "batch_size" in s]
    if len(timed) < 2:
        return []
    per = max(len(timed) // n_windows, 1)
    trend = []
    for i in range(0, len(timed), per):
        win = timed[i:i + per]
        if len(win) >= 2 and all("t" in s for s in win):
            dt = win[-1]["t"] - win[0]["t"]
            pairs = sum(s["batch_size"] for s in win[1:])
        else:
            dt = sum(sum(s.get(p, 0.0) for p in _PHASES) for s in win)
            pairs = sum(s["batch_size"] for s in win)
        if dt > 0:
            trend.append({
                "steps": [win[0].get("step"), win[-1].get("step")],
                "pairs_per_sec": round(pairs / dt, 3),
            })
    return trend


def _pipeline_overlap(steps: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Overlap efficiency of a pipelined run: serial phase work / wall time.

    ``serial_s`` sums every step's data-wait + dispatch + fetch; ``wall_s``
    spans the monotonic ``t`` axis from the first to the last step record.
    Sequential loops land at ~1.0 (all phase work on the critical path);
    values above 1.0 mean the pipeline overlapped that much host/device
    work per unit of wall clock (eval/stream.py's whole purpose); well
    below 1.0 means time went somewhere the phase split doesn't see.
    """
    timed = [s for s in steps if "t" in s]
    if len(timed) < 2:
        return None
    wall = timed[-1]["t"] - timed[0]["t"]
    if wall <= 0:
        return None
    # the first record's phases happened before its own `t` stamp, i.e.
    # outside the [t_first, t_last] window — sum the in-window steps only
    serial = sum(sum(s.get(p, 0.0) for p in _PHASES) for s in timed[1:])
    return {"serial_s": round(serial, 4), "wall_s": round(wall, 4),
            "efficiency": round(serial / wall, 3)}


def _span_summary(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Phase percentiles + overlap efficiency from schema-v7 spans.

    The span-derived twin of :func:`_pipeline_overlap`/the step-phase
    table: per-name duration percentiles, root child-coverage, and —
    because child spans are the serial phase work while wall time spans
    first start to last end — a pipeline-overlap efficiency that needs no
    ``jax.profiler`` capture. This is what lets ``cli telemetry`` say
    something better than "trace: none" on span-carrying runs.
    """
    spans = [e for e in events if e.get("event") == "span"]
    if not spans:
        return None
    from raft_stereo_tpu.obs.timeline import span_coverage
    by_name: Dict[str, List[float]] = {}
    for s in spans:
        by_name.setdefault(str(s.get("name", "?")), []).append(
            float(s.get("dur_s", 0.0)))
    starts = [float(s.get("start_s", 0.0)) for s in spans]
    ends = [float(s.get("start_s", 0.0)) + float(s.get("dur_s", 0.0))
            for s in spans]
    wall = max(ends) - min(starts)
    serial = sum(float(s.get("dur_s", 0.0)) for s in spans
                 if s.get("parent_id") is not None)
    out: Dict[str, Any] = {
        "count": len(spans),
        "by_name": {n: {"count": len(v), **_percentiles(v)}
                    for n, v in sorted(by_name.items())},
        "coverage": span_coverage(spans),
    }
    if wall > 0 and serial > 0:
        out["overlap"] = {"serial_s": round(serial, 4),
                          "wall_s": round(wall, 4),
                          "efficiency": round(serial / wall, 3)}
    return out


def _pipeline_gauges(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    import numpy as np
    gauges = [e for e in events if e.get("event") == "pipeline"]
    if not gauges:
        return None
    depths = [g["in_flight"] for g in gauges if "in_flight" in g]
    out: Dict[str, Any] = {"gauges": len(gauges)}
    if depths:
        out["in_flight_p50"] = float(np.median(depths))
        out["in_flight_max"] = int(max(depths))
    last = gauges[-1]
    for k in ("window", "microbatch"):
        if k in last:
            out[k] = last[k]
    return out


def _xla_summary(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Headline numbers from the compiled-artifact introspection records
    (obs/xla.py): the LAST xla_memory/xla_cost event of the log — for a
    bench chain that is the final (banked) attempt's executable."""
    mems = [e for e in events if e.get("event") == "xla_memory"]
    costs = [e for e in events if e.get("event") == "xla_cost"]
    if not mems and not costs:
        return None
    out: Dict[str, Any] = {"n_memory": len(mems), "n_cost": len(costs)}
    if mems:
        m = mems[-1]
        out["source"] = m.get("source")
        for k in ("peak_bytes", "temp_bytes", "argument_bytes",
                  "output_bytes", "capacity_bytes", "headroom_bytes"):
            if k in m:
                out[k] = m[k]
    if costs:
        c = costs[-1]
        out.setdefault("source", c.get("source"))
        for k in ("flops", "bytes_accessed", "flops_per_byte"):
            if k in c:
                out[k] = c[k]
    return out


def _find_trace_dir(run_dir: str) -> Optional[str]:
    hits = glob.glob(os.path.join(run_dir, "**", "plugins", "profile"),
                     recursive=True)
    if not hits:
        return None
    # summarize_trace expects the log dir CONTAINING plugins/profile
    return os.path.dirname(os.path.dirname(sorted(hits)[0]))


def summarize_run(run_dir: str, top: int = 10) -> Dict[str, Any]:
    """Build the merged report dict for ``run_dir``."""
    report: Dict[str, Any] = {"run_dir": run_dir}

    events_path = os.path.join(run_dir, "events.jsonl")
    if os.path.exists(events_path):
        events = read_events(events_path)
        report["events"] = _summarize_events(events)
        report["schema_errors"] = validate_events(events)[:20]
    else:
        report["events"] = None

    trace_dir = _find_trace_dir(run_dir)
    if trace_dir is not None:
        from raft_stereo_tpu.utils.profiling import summarize_trace
        try:
            report["trace"] = summarize_trace(trace_dir, top=top)
        except Exception as e:  # partial/corrupt capture from a wedged run
            report["trace"] = {"error": f"{type(e).__name__}: {e}"}
    else:
        report["trace"] = None
    return report


def _summarize_events(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    by = lambda kind: [e for e in events if e.get("event") == kind]  # noqa: E731
    steps = by("step")
    out: Dict[str, Any] = {
        "n_records": len(events),
        "run": next((e.get("run") for e in by("run_start")), None),
        "steps": len(steps),
        "phases": {p: _percentiles([s[p] for s in steps if p in s])
                   for p in _PHASES if any(p in s for s in steps)},
        "throughput_trend": _throughput_trend(steps),
        "pipeline_overlap": _pipeline_overlap(steps),
        "spans": _span_summary(events),
        "pipeline": _pipeline_gauges(events),
        "xla": _xla_summary(events),
        "converge": _converge_summary(events),
        "compiles": {
            "count": len(by("compile")),
            "total_s": round(sum(e.get("duration_s", 0.0)
                                 for e in by("compile")), 3),
        },
        "checkpoints": [{"step": e.get("step"), "path": e.get("path"),
                         **({"reason": e["reason"]} if "reason" in e
                            else {})}
                        for e in by("checkpoint")],
        # fault tolerance (schema v5, training/resilience.py): preemption,
        # resume provenance, checkpoint-integrity verdicts, anomaly skips
        "preempts": [{"signal": e.get("signal"), "step": e.get("step")}
                     for e in by("preempt")],
        "resumes": [{"step": e.get("step"), "path": e.get("path")}
                    for e in by("resume")],
        "ckpt_integrity_failures": [
            {"path": e.get("path"), "reason": e.get("reason")}
            for e in by("ckpt_integrity") if not e.get("ok")],
        "anomalies": _anomaly_summary(by("anomaly")),
        "validations": [e.get("results") for e in by("validation")],
        "stalls": [{"t": e.get("t"),
                    "seconds_since_step": e.get("seconds_since_step"),
                    "deadline_s": e.get("deadline_s")}
                   for e in by("stall")],
        "errors": [e.get("error") for e in by("error")],
    }
    ends = by("run_end")
    if ends:
        out["run_end"] = {k: ends[-1].get(k) for k in ("steps", "ok", "t")}
    mems = [e for e in by("memory") if e.get("stats")]
    if mems:
        last = mems[-1]["stats"]
        out["memory_last"] = {k: last[k] for k in
                              ("bytes_in_use", "peak_bytes_in_use")
                              if k in last}
    return out


def _converge_summary(events: List[Dict[str, Any]]
                      ) -> Optional[Dict[str, Any]]:
    """Headlines from schema-v8 ``converge`` records (obs/converge.py):
    curve count by source, half-life percentiles ("by which iteration had
    the residual halved?") and final-residual percentiles — the quick read
    before replaying the full decision table with ``cli converge``."""
    curves = [e for e in events if e.get("event") == "converge"]
    if not curves:
        return None
    from raft_stereo_tpu.obs.converge import _percentile
    by_source: Dict[str, int] = {}
    for c in curves:
        src = str(c.get("source", "?"))
        by_source[src] = by_source.get(src, 0) + 1
    out: Dict[str, Any] = {
        "count": len(curves),
        "budget": max(int(c.get("iters", 0)) for c in curves),
        "by_source": by_source,
    }
    hls = [float(c["half_life"]) for c in curves if "half_life" in c]
    if hls:
        out["half_life_p50"] = int(_percentile(hls, 50.0))
        out["half_life_p95"] = int(_percentile(hls, 95.0))
        out["n_half_life"] = len(hls)
    finals = [float(c["final_residual"]) for c in curves
              if "final_residual" in c]
    if finals:
        out["final_residual_p50"] = round(_percentile(finals, 50.0), 6)
        out["final_residual_p95"] = round(_percentile(finals, 95.0), 6)
    return out


def _anomaly_summary(anomalies: List[Dict[str, Any]]
                     ) -> Optional[Dict[str, Any]]:
    if not anomalies:
        return None
    by_kind: Dict[str, int] = {}
    for e in anomalies:
        kind = e.get("kind", "?")
        by_kind[kind] = by_kind.get(kind, 0) + 1
    out: Dict[str, Any] = {"count": len(anomalies), "by_kind": by_kind}
    skips = [e for e in anomalies if e.get("kind") == "nonfinite_grad"]
    if skips:
        out["skipped_update_steps"] = [e.get("step") for e in skips]
    return out


def format_summary(report: Dict[str, Any]) -> str:
    lines: List[str] = [f"run: {report['run_dir']}"]
    ev = report.get("events")
    if ev is None:
        lines.append("events: none (no events.jsonl under the run dir)")
    else:
        lines.append(f"events: {ev['n_records']} records, "
                     f"{ev['steps']} steps"
                     + (f" (run '{ev['run']}')" if ev.get("run") else ""))
        end = ev.get("run_end")
        if end:
            lines.append(f"run_end: ok={end.get('ok')} "
                         f"steps={end.get('steps')} at t={end.get('t')}s")
        if ev["phases"]:
            lines.append("")
            lines.append("per-step phases (s):"
                         "          p50       p90       max     total")
            for p, q in ev["phases"].items():
                lines.append(f"  {p:16s} {q['p50']:12.4f} {q['p90']:9.4f} "
                             f"{q['max']:9.4f} {q['total']:9.2f}")
        if ev["throughput_trend"]:
            lines.append("")
            lines.append("throughput trend (pairs/sec):")
            for w in ev["throughput_trend"]:
                lines.append(f"  steps {w['steps'][0]}-{w['steps'][1]}: "
                             f"{w['pairs_per_sec']}")
        ov = ev.get("pipeline_overlap")
        if ov:
            lines.append("")
            lines.append(f"pipeline overlap: {ov['efficiency']}x "
                         f"({ov['serial_s']}s of phase work in "
                         f"{ov['wall_s']}s wall)")
        sp = ev.get("spans")
        if sp:
            lines.append("")
            lines.append(f"spans: {sp['count']}"
                         + (f", root child-coverage min "
                            f"{sp['coverage']['min']:.0%} mean "
                            f"{sp['coverage']['mean']:.0%}"
                            if sp["coverage"].get("roots") else ""))
            lines.append("span phases (s):   count"
                         "       p50       p90       max     total")
            for name, q in sp["by_name"].items():
                lines.append(f"  {name:16s} {q['count']:5d} "
                             f"{q['p50']:9.4f} {q['p90']:9.4f} "
                             f"{q['max']:9.4f} {q['total']:9.2f}")
        pg = ev.get("pipeline")
        if pg:
            depth = (f"in-flight p50 {pg['in_flight_p50']} "
                     f"max {pg['in_flight_max']}"
                     if "in_flight_p50" in pg else "no depth samples")
            extras = ", ".join(f"{k}={pg[k]}" for k in ("window", "microbatch")
                               if k in pg)
            lines.append(f"pipeline gauges: {pg['gauges']} ({depth}"
                         + (f", {extras}" if extras else "") + ")")
        xl = ev.get("xla")
        if xl:
            gib = 1024 ** 3
            parts = []
            if "peak_bytes" in xl:
                peak = f"peak {xl['peak_bytes'] / gib:.2f} GiB"
                if "capacity_bytes" in xl:
                    peak += (f" of {xl['capacity_bytes'] / gib:.1f} GiB "
                             f"(headroom "
                             f"{xl['headroom_bytes'] / gib:.2f} GiB)")
                if "temp_bytes" in xl:
                    peak += f", temps {xl['temp_bytes'] / gib:.2f} GiB"
                parts.append(peak)
            if "flops" in xl:
                cost = f"{xl['flops']:.3g} flops"
                if "flops_per_byte" in xl:
                    cost += f", {xl['flops_per_byte']} flops/byte"
                parts.append(cost)
            lines.append("")
            lines.append(f"xla executable ({xl.get('source')}): "
                         + "; ".join(parts))
        cv = ev.get("converge")
        if cv:
            lines.append("")
            srcs = ", ".join(f"{s}:{n}" for s, n in
                             sorted(cv["by_source"].items()))
            lines.append(f"convergence curves: {cv['count']} "
                         f"(budget {cv['budget']} iters; {srcs})")
            if "half_life_p50" in cv:
                lines.append(f"  residual half-life: p50 iter "
                             f"{cv['half_life_p50']}, p95 iter "
                             f"{cv['half_life_p95']} "
                             f"(n={cv['n_half_life']})")
            if "final_residual_p50" in cv:
                lines.append(f"  final residual: p50 "
                             f"{cv['final_residual_p50']} px, p95 "
                             f"{cv['final_residual_p95']} px — replay "
                             f"exit thresholds with `cli converge`")
        c = ev["compiles"]
        lines.append("")
        lines.append(f"compiles: {c['count']} ({c['total_s']} s)")
        lines.append(f"checkpoints: {len(ev['checkpoints'])}"
                     + ("".join(
                         f"\n  step {k['step']}"
                         + (f" [{k['reason']}]" if "reason" in k else "")
                         + f": {k['path']}"
                         for k in ev["checkpoints"][-3:])))
        for p in ev.get("preempts", []):
            lines.append(f"PREEMPT: {p['signal']} at step {p['step']} "
                         f"(saved; resume with --restore_ckpt auto)")
        for r in ev.get("resumes", []):
            lines.append(f"resumed: step {r['step']} from {r['path']}")
        for f_ in ev.get("ckpt_integrity_failures", []):
            lines.append(f"CKPT INTEGRITY: skipped {f_['path']} "
                         f"({f_['reason']})")
        an = ev.get("anomalies")
        if an:
            lines.append(f"ANOMALIES: {an['count']} ({an['by_kind']})"
                         + (f", skipped updates at steps "
                            f"{an['skipped_update_steps']}"
                            if "skipped_update_steps" in an else ""))
        for v in ev["validations"]:
            lines.append(f"validation: {v}")
        if "memory_last" in ev:
            lines.append(f"device memory (last): {ev['memory_last']}")
        if ev["stalls"]:
            lines.append(f"STALLS: {len(ev['stalls'])}")
            for s in ev["stalls"]:
                lines.append(f"  t={s['t']}s: no step for "
                             f"{s['seconds_since_step']}s "
                             f"(deadline {s['deadline_s']}s)")
        else:
            lines.append("stalls: none")
        for e in ev["errors"]:
            lines.append(f"ERROR: {e}")
        if ev.get("schema_errors") or report.get("schema_errors"):
            for e in report.get("schema_errors", []):
                lines.append(f"schema violation: {e}")

    tr = report.get("trace")
    lines.append("")
    if tr is None:
        sp = (ev or {}).get("spans") if ev else None
        if sp and sp.get("overlap"):
            o = sp["overlap"]
            lines.append(
                f"trace: no jax.profiler capture; span-derived pipeline "
                f"efficiency {o['efficiency']}x ({o['serial_s']}s of span "
                f"work in {o['wall_s']}s wall)")
        else:
            lines.append(
                "trace: none (no jax.profiler capture under the run dir)")
    elif "error" in tr:
        lines.append(f"trace: unreadable ({tr['error']})")
    else:
        from raft_stereo_tpu.utils.profiling import format_report
        lines.append("profiler trace:")
        lines.append(format_report(tr))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        description="Summarize a run directory's telemetry "
                    "(events.jsonl + optional profiler trace)")
    p.add_argument("run_dir")
    p.add_argument("--top", type=int, default=10,
                   help="top device ops to show from the trace")
    args = p.parse_args(argv)
    print(format_summary(summarize_run(args.run_dir, top=args.top)))
    return 0
