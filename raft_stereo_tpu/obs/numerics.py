"""Numerics observatory: record in-graph numeric health, then attribute
failures offline.

The observability stack covers time (spans, v7) and quality (converge
curves, v8) but was blind on *numerics*: the PR-7 anomaly guard could only
say "a gradient somewhere was non-finite". This module is the recording
and attribution layer for schema-v9 ``numerics`` events:

* **grad records** (``kind="grad"``) — the train step computes one fused
  L2 reduction per parameter leaf (training/state.py ``numerics=True``,
  no host sync); the trainer emits the vector on the lagged metrics fetch
  every ``--numerics_every`` steps. :func:`grad_leaf_names` recovers the
  leaf names in the SAME flatten order, :func:`top_leaves` ranks the
  offenders (non-finite first, then by norm) for the ``anomaly`` event's
  attribution extra.
* **tap records** (``kind="taps"``) — the refinement scan's activation
  taps (nn/gru.py ``tag_residual`` riding a scan-body sink, models/
  raft_stereo.py ``numerics=True``) yield per-iteration
  min/max/absmean/nonfinite/sat/underflow series per tap.
  :func:`taps_payload` turns the fetched ``(iters, 6)`` stacks into one
  event with NaN provenance: ``first_nonfinite = {tap, iter}`` names the
  dataflow-earliest tap of the earliest poisoned iteration.
* **consumers** — :func:`emit` puts records on the bus and fires the
  flight recorder on the first numerics alarm; :func:`main` is
  ``cli numerics <run_dir>`` (per-leaf/per-tap trend tables, saturation
  leaderboard, first-nonfinite report); obs/doctor.py reads the same
  records for the NONFINITE_ORIGIN / BF16_SATURATION / GRAD_EXPLOSION
  verdicts.

bf16 counter semantics (computed in-graph against bfloat16 regardless of
the tensor's own dtype, because the ``residual_dtype="bfloat16"`` stacks
and the bf16 corr-policy channel cast through it): **saturation** counts
values whose magnitude reaches the bf16 max finite (|x| >=
:data:`BF16_MAX_FINITE` — the value clamps to the top of the bf16 range;
finite fp32 never rounds to bf16 inf, so "at the rail" IS the overflow
signal), **underflow** counts nonzero magnitudes below the smallest normal
bf16 (|x| < :data:`BF16_MIN_NORMAL`, tested on the raw fp32 bit pattern —
bf16 hardware flushes that regime to zero, and an integer compare is the
only test XLA's own denormals-are-zero float compares cannot lie about).
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

#: per-tap per-iteration statistics vector, in order (nn/gru.py
#: ``_tap_stats`` produces exactly this layout; keep the two in sync via
#: this one tuple)
STAT_FIELDS = ("min", "max", "absmean", "nonfinite", "sat", "underflow")

#: largest finite bfloat16 value (0x7F7F); the saturation-counter rail
BF16_MAX_FINITE = 3.3895313892515355e38

#: smallest normal bfloat16 (2**-126); nonzero magnitudes below it live in
#: the flush-to-zero regime of bf16 hardware — the underflow-counter rail
BF16_MIN_NORMAL = 1.1754943508222875e-38

#: per-leaf gradient norm past this is a GRAD_EXPLOSION alarm (well above
#: anything a clip-1.0 schedule should ever see pre-clip on a healthy run)
GRAD_ALARM_NORM = 1e3

#: leaves quoted in anomaly attribution / doctor evidence
TOP_K = 5


# --- leaf naming (train side) ------------------------------------------------

def grad_leaf_names(params: Any) -> List[str]:
    """Flattened param-leaf names, in ``jax.tree.leaves`` order — the same
    order training/state.py stacks the per-leaf norm vector in, so
    ``names[i]`` labels ``leaf_grad_norms[i]``."""
    from jax import tree_util

    paths = tree_util.tree_flatten_with_path(params)[0]
    names = []
    for key_path, _leaf in paths:
        parts = []
        for k in key_path:
            part = getattr(k, "key", None)
            if part is None:
                part = getattr(k, "idx", None)
            parts.append(str(k) if part is None else str(part))
        names.append("/".join(parts))
    return names


def _clean(v: Any) -> Optional[float]:
    """float(v), with non-finite collapsed to None (the only NaN marker a
    strict-JSON consumer can round-trip)."""
    f = float(v)
    return f if math.isfinite(f) else None


def top_leaves(names: Sequence[str], norms: Sequence[Any],
               k: int = TOP_K) -> List[Tuple[str, Optional[float]]]:
    """Top-k offending leaves: non-finite norms first (the poisoned ones),
    then by descending norm — the anomaly event's attribution extra."""
    pairs = [(str(n), _clean(v)) for n, v in zip(names, norms)]
    pairs.sort(key=lambda p: (0, 0.0) if p[1] is None else (1, -p[1]))
    return pairs[:k]


def grad_payload(step: int, names: Sequence[str], norms: Sequence[Any],
                 source: str = "train", **extra: Any) -> Dict[str, Any]:
    """One ``kind="grad"`` numerics payload from the fetched per-leaf
    norm vector (non-finite norms become null — NaN provenance survives
    JSON)."""
    cleaned = [_clean(v) for v in norms]
    payload: Dict[str, Any] = {
        "source": source, "kind": "grad", "step": int(step),
        "leaves": [str(n) for n in names], "grad_norm": cleaned,
        "top": [[n, v] for n, v in top_leaves(names, norms)],
    }
    payload.update(extra)
    return payload


# --- tap payloads (eval/serve side) ------------------------------------------

def split_label(key: str) -> Tuple[int, str]:
    """Sink keys are ``"<order>:<label>"`` (trace order survives the
    pytree key sort jit applies to dict outputs); returns (order, label).
    Unprefixed keys sort last, in name order."""
    head, sep, tail = key.partition(":")
    if sep and head.isdigit():
        return int(head), tail
    return 1 << 30, key


def taps_payload(source: str, taps: Dict[str, Any], *,
                 bucket: Optional[str] = None,
                 **extra: Any) -> Optional[Dict[str, Any]]:
    """One ``kind="taps"`` numerics payload from fetched per-tap
    ``(iters, len(STAT_FIELDS))`` stat stacks (None on an empty dict).

    Series values are cleaned to null where non-finite (an all-NaN
    iteration has no finite min/max). ``first_nonfinite`` is the earliest
    poisoned iteration; ties go to the dataflow-earliest tap.
    """
    if not taps:
        return None
    ordered = sorted(taps.items(), key=lambda kv: split_label(kv[0]))
    out_taps: Dict[str, Dict[str, List[Optional[float]]]] = {}
    iters = 0
    sat_total = 0
    underflow_total = 0
    first_nf: Optional[Dict[str, Any]] = None
    for key, arr in ordered:
        label = split_label(key)[1]
        a = np.asarray(arr, dtype=np.float64)
        if a.ndim == 1:
            a = a[None]
        iters = max(iters, a.shape[0])
        series = {name: [_clean(v) for v in a[:, i]]
                  for i, name in enumerate(STAT_FIELDS)}
        # counters are counts: non-finite would mean the reduction itself
        # was poisoned — surface as 0 in the rollup, the nonfinite series
        # still tells the story
        nf = [0 if v is None else int(v) for v in series["nonfinite"]]
        sat_total += sum(0 if v is None else int(v)
                         for v in series["sat"])
        underflow_total += sum(0 if v is None else int(v)
                               for v in series["underflow"])
        for it, count in enumerate(nf):
            if count > 0:
                if first_nf is None or it < first_nf["iter"]:
                    first_nf = {"tap": label, "iter": it, "count": count}
                break
        out_taps[label] = series
    payload: Dict[str, Any] = {
        "source": source, "kind": "taps", "iters": int(iters),
        "taps": out_taps, "sat_total": int(sat_total),
        "underflow_total": int(underflow_total),
        "first_nonfinite": first_nf,
    }
    if bucket is not None:
        payload["bucket"] = bucket
    payload.update(extra)
    return payload


# --- the bus + the alarm -----------------------------------------------------

def alarm(payload: Dict[str, Any]) -> Optional[str]:
    """The numerics-alarm predicate: the reason string that should fire a
    flight-recorder dump, or None when the record is healthy."""
    if payload.get("kind") == "grad":
        norms = payload.get("grad_norm") or []
        if any(v is None for v in norms):
            return "nonfinite_grad_leaf"
        if any(v is not None and v > GRAD_ALARM_NORM for v in norms):
            return "grad_explosion"
        return None
    if payload.get("first_nonfinite") is not None:
        return "nonfinite_tap"
    if payload.get("sat_total", 0) > 0:
        return "bf16_saturation"
    return None


def emit(telemetry, payload: Optional[Dict[str, Any]]) -> None:
    """Put one numerics record on the bus; the FIRST alarming record also
    banks a flight-recorder dump (telemetry's per-reason rate limit makes
    repeats cheap). No-op without a sink or payload — observability never
    gates the data path."""
    if telemetry is None or payload is None:
        return
    telemetry.emit("numerics", **payload)
    reason = alarm(payload)
    if reason is not None:
        dump = getattr(telemetry, "flight_dump", None)
        if dump is not None:
            dump("numerics")


# --- the offline report (cli numerics) ---------------------------------------

def load_records(path: str) -> List[Dict[str, Any]]:
    """All ``numerics`` records from a run dir (or events.jsonl path)."""
    from raft_stereo_tpu.obs.events import read_events
    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    if not os.path.exists(path):
        return []
    return [r for r in read_events(path) if r.get("event") == "numerics"]


def leaf_trend(records: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-leaf gradient-norm trend over the run's grad records: first /
    last / max norm, growth ratio, and whether the leaf ever went
    non-finite. Sorted worst-first (non-finite, then by last norm)."""
    series: Dict[str, List[Tuple[int, Optional[float]]]] = {}
    for r in records:
        if r.get("kind") != "grad":
            continue
        step = int(r.get("step", 0))
        for name, v in zip(r.get("leaves") or [], r.get("grad_norm") or []):
            series.setdefault(str(name), []).append((step, v))
    rows = []
    for name, pts in series.items():
        pts.sort(key=lambda p: p[0])
        finite = [v for _, v in pts if v is not None]
        nonfinite_steps = [s for s, v in pts if v is None]
        first = finite[0] if finite else None
        last = next((v for _, v in reversed(pts) if v is not None), None)
        rows.append({
            "leaf": name, "n": len(pts),
            "first": first, "last": last,
            "max": max(finite) if finite else None,
            "growth": (last / first if first and last is not None
                       else None),
            "nonfinite_steps": nonfinite_steps,
        })
    rows.sort(key=lambda r: (0, 0.0) if r["nonfinite_steps"]
              else (1, -(r["last"] or 0.0)))
    return rows


def tap_trend(records: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-tap aggregate over the run's tap records: value envelope,
    mean absmean, and the counter totals. Trace order preserved."""
    agg: Dict[str, Dict[str, Any]] = {}
    order: List[str] = []
    for r in records:
        if r.get("kind") != "taps":
            continue
        for label, series in (r.get("taps") or {}).items():
            row = agg.get(label)
            if row is None:
                row = agg[label] = {
                    "tap": label, "events": 0, "min": None, "max": None,
                    "absmean_sum": 0.0, "absmean_n": 0,
                    "nonfinite": 0, "sat": 0, "underflow": 0}
                order.append(label)
            row["events"] += 1
            mins = [v for v in series.get("min", []) if v is not None]
            maxs = [v for v in series.get("max", []) if v is not None]
            if mins:
                row["min"] = (min(mins) if row["min"] is None
                              else min(row["min"], min(mins)))
            if maxs:
                row["max"] = (max(maxs) if row["max"] is None
                              else max(row["max"], max(maxs)))
            for v in series.get("absmean", []):
                if v is not None:
                    row["absmean_sum"] += v
                    row["absmean_n"] += 1
            for field in ("nonfinite", "sat", "underflow"):
                row[field] += sum(int(v) for v in series.get(field, [])
                                  if v is not None)
    rows = []
    for label in order:
        row = agg[label]
        rows.append({
            "tap": label, "events": row["events"],
            "min": row["min"], "max": row["max"],
            "absmean": (row["absmean_sum"] / row["absmean_n"]
                        if row["absmean_n"] else None),
            "nonfinite": row["nonfinite"], "sat": row["sat"],
            "underflow": row["underflow"],
        })
    return rows


def saturation_leaderboard(tap_rows: Sequence[Dict[str, Any]]
                           ) -> List[Dict[str, Any]]:
    """Taps that tripped the bf16 counters, worst first (the range-
    pressure ranking the bf16 kernel rewrites will watch)."""
    hot = [r for r in tap_rows if r["sat"] or r["underflow"]]
    hot.sort(key=lambda r: (-r["sat"], -r["underflow"]))
    return hot


def first_nonfinite_report(records: Iterable[Dict[str, Any]]
                           ) -> List[Dict[str, Any]]:
    """Every recorded NaN origin: tap records with ``first_nonfinite``
    and grad records with null per-leaf norms."""
    out = []
    for r in records:
        if r.get("kind") == "taps" and r.get("first_nonfinite"):
            fn = r["first_nonfinite"]
            out.append({"source": r.get("source"), "kind": "taps",
                        "tap": fn.get("tap"), "iter": fn.get("iter"),
                        "frame": r.get("frame"), "id": r.get("id"),
                        "bucket": r.get("bucket")})
        elif r.get("kind") == "grad":
            bad = [n for n, v in zip(r.get("leaves") or [],
                                     r.get("grad_norm") or [])
                   if v is None]
            if bad:
                out.append({"source": r.get("source"), "kind": "grad",
                            "step": r.get("step"), "leaves": bad[:TOP_K],
                            "n_leaves": len(bad)})
    return out


def build_report(run_dir: str,
                 records: Sequence[Dict[str, Any]],
                 top: int = 10) -> Dict[str, Any]:
    """The ``cli numerics`` report document (the ``--json`` payload)."""
    leaves = leaf_trend(records)
    taps = tap_trend(records)
    return {
        "run_dir": run_dir,
        "grad_events": sum(1 for r in records if r.get("kind") == "grad"),
        "tap_events": sum(1 for r in records if r.get("kind") == "taps"),
        "leaves": leaves[:top],
        "n_leaves": len(leaves),
        "taps": taps,
        "saturation": saturation_leaderboard(taps),
        "first_nonfinite": first_nonfinite_report(records),
    }


def _fmt(v: Optional[float], spec: str = ".3g") -> str:
    return "-" if v is None else format(v, spec)


def format_report(doc: Dict[str, Any]) -> str:
    """Render the report for the terminal."""
    lines = [f"{doc['grad_events']} grad + {doc['tap_events']} tap "
             f"numerics records ({doc['run_dir']})"]
    if doc["leaves"]:
        lines.append("")
        lines.append(f"per-leaf gradient norms (worst {len(doc['leaves'])} "
                     f"of {doc['n_leaves']}):")
        header = (f"  {'leaf':<44} {'first':>9} {'last':>9} {'max':>9} "
                  f"{'growth':>7} {'nonfin':>6}")
        lines += [header, "  " + "-" * (len(header) - 2)]
        for r in doc["leaves"]:
            lines.append(
                f"  {r['leaf'][:44]:<44} {_fmt(r['first']):>9} "
                f"{_fmt(r['last']):>9} {_fmt(r['max']):>9} "
                f"{_fmt(r['growth'], '.2f'):>7} "
                f"{len(r['nonfinite_steps']):>6}")
    if doc["taps"]:
        lines.append("")
        lines.append("activation taps (refinement scan, trace order):")
        header = (f"  {'tap':<24} {'events':>6} {'min':>10} {'max':>10} "
                  f"{'absmean':>9} {'nonfin':>6} {'sat':>5} {'uflow':>6}")
        lines += [header, "  " + "-" * (len(header) - 2)]
        for r in doc["taps"]:
            lines.append(
                f"  {r['tap'][:24]:<24} {r['events']:>6} "
                f"{_fmt(r['min']):>10} {_fmt(r['max']):>10} "
                f"{_fmt(r['absmean']):>9} {r['nonfinite']:>6} "
                f"{r['sat']:>5} {r['underflow']:>6}")
    if doc["saturation"]:
        lines.append("")
        lines.append("bf16 saturation leaderboard:")
        for r in doc["saturation"]:
            lines.append(f"  {r['tap']}: sat={r['sat']} "
                         f"underflow={r['underflow']} "
                         f"(|max|={_fmt(r['max'])})")
    if doc["first_nonfinite"]:
        lines.append("")
        lines.append("first-nonfinite provenance:")
        for r in doc["first_nonfinite"]:
            if r["kind"] == "taps":
                where = f"frame={r['frame']}" if r.get("frame") is not None \
                    else f"id={r.get('id')}"
                lines.append(
                    f"  [{r['source']}] tap {r['tap']!r} at refinement "
                    f"iteration {r['iter']} ({where})")
            else:
                lines.append(
                    f"  [{r['source']}] step {r['step']}: {r['n_leaves']} "
                    f"non-finite grad leaves, first {r['leaves']}")
    elif doc["grad_events"] or doc["tap_events"]:
        lines.append("")
        lines.append("no non-finite values recorded")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``cli numerics <run_dir>`` — the offline numerics report."""
    from raft_stereo_tpu.cli import build_numerics_parser
    args = build_numerics_parser().parse_args(argv)
    records = load_records(args.run_dir)
    if not records:
        print(f"no numerics records under {args.run_dir} — run train/eval "
              "with numerics telemetry on (the default; --no_numerics "
              "disables it) or serve with --numerics", file=sys.stderr)
        return 1
    doc = build_report(args.run_dir, records, top=args.top)
    if args.json:
        # the cli compare convention: '-' streams JSON to stdout INSTEAD
        # of the text report; any other value is an output path
        if args.json == "-":
            json.dump(doc, sys.stdout, indent=1)
            print()
        else:
            os.makedirs(os.path.dirname(args.json) or ".", exist_ok=True)
            with open(args.json, "w") as f:
                json.dump(doc, f, indent=2)
            print(f"numerics report written to {args.json}")
    else:
        print(format_report(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
