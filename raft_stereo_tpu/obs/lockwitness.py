"""Dynamic lock-order witness (graftlint engine 4's runtime half).

Opt-in via ``RAFT_LOCK_WITNESS=<dump path>``: the threading lock
factories are patched so every ``threading.Lock()`` / ``RLock()``
*created from package code* is wrapped in a recording proxy. Each
acquisition while another witnessed lock is held records an order edge
(held -> acquired) keyed by the same canonical lock ids the static
topology uses (``analysis/concurrency_rules.py``), so the dump can be
held directly against ``.graftlint-threads.json``:

    RAFT_LOCK_WITNESS=/tmp/w.json python -m raft_stereo_tpu.cli loadtest ...
    python -m raft_stereo_tpu.cli lint --concurrency --witness /tmp/w.json

A witnessed edge that contradicts the static acquisition order — or
that closes a cycle the static pass missed — fails the lint gate; the
serve/fleet drills are the interleavings that make the evidence real
(scripts/load_drill.py's ``witness`` drill banks it under ``runs/``).

Design notes: only *creation* is intercepted, and only for locks whose
creating frame lives under ``raft_stereo_tpu/`` — stdlib-internal locks
(logging, queue.Queue's, bare ``Condition()`` backing locks) are never
wrapped, so the overhead lands exclusively on the package's own
synchronization. ``Condition(wrapped_lock)`` works unchanged: the
proxies expose ``_release_save``/``_acquire_restore``/``_is_owned`` so
``wait()``'s full release/reacquire is witnessed too.
"""

from __future__ import annotations

import atexit
import json
import linecache
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_DIR)
_SELF = os.path.abspath(__file__)

# the real factories, captured at import — the registry's own mutex and
# any stdlib use keep going through these
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

ENV_VAR = "RAFT_LOCK_WITNESS"
WITNESS_VERSION = 1

_ASSIGN_SELF = re.compile(r"\s*self\.(\w+)\s*[:=]")
_ASSIGN_NAME = re.compile(r"\s*(\w+)\s*[:=]")


def _lock_id_from_frame(frame) -> Optional[str]:
    """Canonical lock id for a factory call frame, or None when the lock
    was created outside the package (stdlib, tests, third-party)."""
    path = os.path.abspath(frame.f_code.co_filename)
    if not path.startswith(_PKG_DIR + os.sep) or path == _SELF:
        return None
    rel = os.path.relpath(path, _REPO_ROOT)
    qual = getattr(frame.f_code, "co_qualname", None)  # 3.11+
    if qual is not None:
        qual = qual.replace(".<locals>", "")
    line = linecache.getline(path, frame.f_lineno)
    if "Lock(" not in line:
        # a C-extension (numpy Generator, etc.) creating its own lock
        # pushes no Python frame, so the call attributes to the package
        # caller's line; only wrap literal Lock()/RLock() creation sites
        return None
    m = _ASSIGN_SELF.match(line)
    if m:
        # self._x = threading.Lock() in a method: the owning class, to
        # match the static `{rel}::{Class}.{attr}` canonical id
        if qual and "." in qual:
            cls = qual.rsplit(".", 2)[-2]
        else:
            slf = frame.f_locals.get("self")
            cls = type(slf).__name__ if slf is not None \
                else frame.f_code.co_name
        return f"{rel}::{cls}.{m.group(1)}"
    qual = qual or frame.f_code.co_name
    m = _ASSIGN_NAME.match(line)
    if m:
        if frame.f_code.co_name == "<module>":
            return f"{rel}::{m.group(1)}"
        return f"{rel}::{qual}.{m.group(1)}"
    return f"{rel}::{qual}.L{frame.f_lineno}"


class _Registry:
    """Per-thread held stacks + the global witnessed order-edge counts."""

    def __init__(self) -> None:
        self._mu = _ORIG_LOCK()
        self._tls = threading.local()
        self.edges: Dict[Tuple[str, str], int] = {}
        self.locks: Dict[str, str] = {}

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def note_acquire(self, lock_id: str) -> None:
        held = self._held()
        if lock_id not in held:  # re-entrant RLock levels add no edge
            if held:
                edge = (held[-1], lock_id)
                with self._mu:
                    self.edges[edge] = self.edges.get(edge, 0) + 1
        held.append(lock_id)

    def note_release(self, lock_id: str) -> None:
        held = self._held()
        # release order need not mirror acquire order; drop the deepest
        # occurrence so outer levels keep witnessing
        for i in range(len(held) - 1, -1, -1):
            if held[i] == lock_id:
                del held[i]
                break

    def register(self, lock_id: str, kind: str) -> None:
        with self._mu:
            self.locks.setdefault(lock_id, kind)

    def dump(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "version": WITNESS_VERSION,
                "locks": dict(sorted(self.locks.items())),
                "edges": sorted([a, b, n] for (a, b), n
                                in self.edges.items()),
            }


class _LockProxy:
    """Witnessing wrapper over a primitive lock; Condition-compatible."""

    def __init__(self, inner, lock_id: str, registry: _Registry) -> None:
        self._inner = inner
        self._witness_id = lock_id
        self._reg = registry

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._reg.note_acquire(self._witness_id)
        return got

    def release(self) -> None:
        self._reg.note_release(self._witness_id)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<witnessed {self._inner!r} as {self._witness_id}>"

    # Condition(lock) protocol: a primitive Lock releases one level
    def _release_save(self):
        self.release()

    def _acquire_restore(self, state) -> None:
        self.acquire()

    def _is_owned(self) -> bool:
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


class _RLockProxy(_LockProxy):
    """RLock flavor: ``_release_save`` drops ALL levels (Condition.wait's
    contract), and the witness held-stack mirrors that."""

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._reg.note_acquire(self._witness_id)
        return got

    def _release_save(self):
        state = self._inner._release_save()
        self._reg.note_release(self._witness_id)
        return state

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        self._reg.note_acquire(self._witness_id)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


_installed: Optional[_Registry] = None


def install(dump_path: str) -> _Registry:
    """Patch the lock factories; idempotent. The dump lands at exit (or
    call :func:`dump_now` explicitly — the drills do, so a SIGKILL'd
    subprocess still banks what it saw up to the last checkpoint)."""
    global _installed
    if _installed is not None:
        return _installed
    reg = _Registry()

    def make_lock():
        inner = _ORIG_LOCK()
        frame = _caller_frame()
        lid = _lock_id_from_frame(frame) if frame is not None else None
        if lid is None:
            return inner
        reg.register(lid, "Lock")
        return _LockProxy(inner, lid, reg)

    def make_rlock():
        inner = _ORIG_RLOCK()
        frame = _caller_frame()
        lid = _lock_id_from_frame(frame) if frame is not None else None
        if lid is None:
            return inner
        reg.register(lid, "RLock")
        return _RLockProxy(inner, lid, reg)

    threading.Lock = make_lock  # type: ignore[assignment]
    threading.RLock = make_rlock  # type: ignore[assignment]
    _installed = reg
    atexit.register(lambda: dump_now(dump_path))
    return reg


def _caller_frame():
    import sys
    f = sys._getframe(1)  # make_lock / make_rlock
    return f.f_back


def dump_now(path: str) -> None:
    if _installed is None:
        return
    doc = _installed.dump()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)


def maybe_install() -> Optional[_Registry]:
    """Install when ``RAFT_LOCK_WITNESS`` names a dump path — the cli
    entry point calls this before dispatch, so any serve/train/loadtest
    leg can witness without code changes."""
    path = os.environ.get(ENV_VAR)
    if not path:
        return None
    return install(path)
