"""Event schema + the shared JSONL sink.

One record = one JSON object on one line. Every record carries:

* ``schema`` — integer schema version (:data:`SCHEMA_VERSION`); bump it when
  a record's required fields change so downstream summarizers fail loudly
  instead of misreading old artifacts (scripts/check_events.py lints this),
* ``ts`` — ISO-8601 wall-clock timestamp,
* ``t`` — seconds since the run's telemetry was opened (monotonic clock;
  the axis summarizers sort and window on, immune to NTP jumps),
* ``event`` — one of :data:`EVENT_TYPES`' keys, plus that type's required
  payload fields (extra fields are always allowed).

The sink, :func:`append_json_log`, is the one copy of the dated
JSON-line-append protocol used by ``runs/<name>/events.jsonl``, bench.py's
attempt log and the measurement harnesses (scripts/bank_monolith.py,
scripts/batch_frontier.py). It creates parent directories — including the
degenerate "bare filename" case whose empty dirname used to crash the
bench.py copy — and mirrors each line to a stream for live consumption.
"""

from __future__ import annotations

import datetime
import json
import os
import sys
from typing import Any, Dict, Iterable, List, Optional

SCHEMA_VERSION = 10

# Back-compat: every schema version whose artifacts are still readable.
# v1 -> v2 (the xla_memory/xla_cost introspection events), v2 -> v3 (the
# op_counts jaxpr profile event), v3 -> v4 (the graftlint `lint` report
# event), v4 -> v5 (the fault-tolerance events: preempt/resume/
# ckpt_integrity/anomaly), v5 -> v6 (the serving events: request/queue/
# slo), v6 -> v7 (the tracing events: span/flightrec), v7 -> v8 (the
# convergence-observatory `converge` event; the `slo` quality fields ride
# as optional extras) and v8 -> v9 (the numerics-observatory `numerics`
# event; the `anomaly` top-leaf attribution and the `slo` output-range
# gauges ride as optional extras) and v9 -> v10 (the fleet-observatory
# events: `heartbeat` liveness beats and the `clock_anchor`
# monotonic-to-wall mapping; host identity — host_id/pid/mesh — rides on
# every record as optional extras stamped by the Telemetry bus) were
# purely ADDITIVE — no earlier event changed its required fields — so
# pre-existing runs/*/events.jsonl lint clean: an older record is
# validated against its own surface (it just may not use events
# introduced later).
SUPPORTED_SCHEMA_VERSIONS = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10)

# Events introduced after schema v1; a record stamped with an older schema
# than its event's introduction is drift (a writer forgot the bump).
_EVENT_MIN_VERSION: Dict[str, int] = {
    "xla_memory": 2,
    "xla_cost": 2,
    "op_counts": 3,
    "lint": 4,
    "preempt": 5,
    "resume": 5,
    "ckpt_integrity": 5,
    "anomaly": 5,
    "request": 6,
    "queue": 6,
    "slo": 6,
    "span": 7,
    "flightrec": 7,
    "converge": 8,
    "numerics": 9,
    "heartbeat": 10,
    "clock_anchor": 10,
}

# event type -> payload fields REQUIRED at this schema version. Extra fields
# are fine; missing ones are schema drift (caught by validate_record and the
# scripts/check_events.py lint).
EVENT_TYPES: Dict[str, tuple] = {
    "run_start": ("run",),
    # Step timing split by phase (seconds): host wait on the data pipeline,
    # device dispatch (the jitted call; synchronous compile lands here on
    # first execution), and the host fetch of executable outputs — the real
    # device-completion sync point on tunneled TPUs (see bench.py).
    "step": ("step", "data_wait_s", "dispatch_s", "fetch_s"),
    "compile": ("duration_s", "source"),
    "checkpoint": ("step", "path"),
    "validation": ("results",),
    "throughput": ("pairs_per_sec", "steps"),
    "memory": ("stats",),
    "loader": ("queue_depth",),
    # Streaming-eval pipeline gauge (eval/stream.py): device dispatches
    # currently in flight; `window`/`microbatch` ride along as extras.
    "pipeline": ("in_flight",),
    # Compiled-artifact introspection (obs/xla.py), one record per
    # lower().compile() site: executable memory footprint from XLA's
    # memory_analysis (peak_bytes = arguments + outputs + temps + generated
    # code - aliased; capacity/headroom ride along where the backend
    # reports a bytes_limit) and the HLO cost model (flops, bytes
    # accessed, flops_per_byte).
    "xla_memory": ("source", "peak_bytes"),
    "xla_cost": ("source", "flops"),
    # Jaxpr-level conv placement profile (obs/xla.py conv_op_profile):
    # convs per scan body vs outside any scan — the structural evidence for
    # scheduling claims like the batched-weight-grad scan's "22 per-
    # iteration wgrad convs replaced by post-scan contractions"
    # (scripts/scan_wgrad_evidence.py).
    "op_counts": ("source", "conv_total"),
    # Static-analysis report (raft_stereo_tpu/analysis, schema v4): one
    # record per `cli lint` invocation — total findings plus the
    # error/warning/suppressed split and the rules that ran; the JSON
    # report carries the per-finding detail.
    "lint": ("source", "findings"),
    "stall": ("seconds_since_step", "deadline_s"),
    "error": ("error",),
    # Fault tolerance (training/resilience.py, schema v5). `preempt`: a
    # SIGTERM/SIGINT triggered the save-and-exit path (`signal` is the
    # name, `step` where training stopped; the matching `checkpoint` event
    # carries reason="preempt"). `resume`: a restore positioned the run at
    # `step` from checkpoint `path` (auto-resume or explicit
    # --restore_ckpt). `ckpt_integrity`: one verification verdict per
    # candidate scanned by `--restore_ckpt auto` (`ok` bool; `reason` rides
    # along on failure — truncated file, crc mismatch, config-digest
    # mismatch). `anomaly`: non-finite-gradient skips
    # (kind="nonfinite_grad", with step/grad_norm/consecutive),
    # the halt decision after M consecutive skips (kind="halt"), loader
    # quarantines (kind="loader_quarantine", with epoch/index/substitute)
    # and a non-finite state blocking an emergency save
    # (kind="nonfinite_state").
    "preempt": ("signal", "step"),
    "resume": ("step", "path"),
    "ckpt_integrity": ("path", "ok"),
    "anomaly": ("kind",),
    # Serving (raft_stereo_tpu/serve, schema v6). `request`: one terminal
    # record per served request — `status` is "ok" or "error"; latency,
    # queue wait, bucket/batch and (on failure) the captured error +
    # traceback tail ride along (per-request fault isolation's paper
    # trail). `queue`: admission-side gauge — request-queue `depth`, with
    # in-flight dispatches and admitted/completed/failed/rejected
    # counters as extras. `slo`: the rolling headline every N
    # retirements — p50/p99 end-to-end latency (ms), sustained
    # `pairs_per_sec` over the sample window, and `in_flight` depth.
    "request": ("id", "status"),
    "queue": ("depth",),
    "slo": ("p50_ms", "p99_ms", "pairs_per_sec", "in_flight"),
    # Tracing (obs/trace.py, schema v7). `span`: one closed span of the
    # unified host timeline — `trace_id` groups the spans of one unit of
    # work (a train step, a served request), `span_id` is unique within
    # the run, `parent_id` (optional) nests it under another span of the
    # same file (referential integrity is linted by obs/validate.py), and
    # `start_s`/`dur_s` sit on the same monotonic `t` axis every other
    # record uses, so `cli timeline` can interleave spans with events and
    # the jax.profiler device trace on one clock. `thread` and arbitrary
    # attrs ride along. `flightrec`: a flight-recorder dump happened —
    # `reason` is what fired it (stall/anomaly/crash/preempt/drain),
    # `path` the dumped ``flightrec-<ts>.jsonl`` carrying the in-memory
    # event/span rings at full resolution.
    "span": ("name", "span_id", "trace_id", "start_s", "dur_s"),
    "flightrec": ("reason", "path"),
    # Convergence observatory (obs/converge.py, schema v8). `converge`:
    # one record per evaluated frame / served request carrying its
    # iteration-resolved convergence curve — `source` names the producer
    # ("eval:<validator>" or "serve:<bucket>"), `iters` the iteration
    # budget the curve covers, `idx` the strictly-increasing downsampled
    # 0-based iteration indices (last one == iters-1), `residual` the mean
    # |delta disparity| at each stored index. An `epe` curve (the in-graph
    # low-res EPE proxy, recorded when GT was available), `bucket`
    # ("HxW"), `id`/`frame`, `half_life` and `final_residual` ride along
    # as extras. Consistency (lengths/monotonicity/finiteness) is linted
    # by obs/validate.py check_converge_integrity. The v8 `slo` records
    # additionally carry an optional `quality` extra: rolling per-bucket
    # final-residual percentiles (serve quality-drift monitoring).
    "converge": ("source", "iters", "idx", "residual"),
    # Numerics observatory (obs/numerics.py, schema v9). `numerics`: one
    # record per train cadence window / eval frame dispatch / served batch
    # carrying in-graph numeric health statistics. `source` names the
    # producer ("train", "eval:<validator>", "serve:<bucket>"), `kind`
    # selects the payload shape: "grad" records carry `step`, `leaves`
    # (flattened param-leaf names) and `grad_norm` (per-leaf L2 norms,
    # null where non-finite — the NaN marker JSON can carry) from the
    # train step's fused per-leaf reduction; "taps" records carry `iters`
    # and `taps` — per activation-tap {min,max,absmean,nonfinite,sat,
    # underflow} series over the refinement iterations (bf16 saturation =
    # |x| at/above the bf16 max finite, underflow = nonzero fp32 flushed
    # to bf16 zero), plus `first_nonfinite` {tap, iter} NaN provenance,
    # `sat_total`/`underflow_total` rollups and `bucket`/`frame`/`id`
    # extras. Consistency is linted by obs/validate.py
    # check_numerics_integrity. The v9 `anomaly` records additionally
    # carry an optional `top_leaves` extra (top-k offending-leaf
    # attribution) and the v9 `slo` quality gauges optional per-bucket
    # output-range percentiles (serve output drift).
    "numerics": ("source", "kind"),
    # Fleet observatory (obs/fleet.py, schema v10). `heartbeat`: a
    # liveness beat on cadence from each long-lived role in a process
    # (`role` is "trainer"/"loader"/"serve"/...), `seq` a per-role
    # strictly-increasing counter so the aggregator can detect gaps
    # without trusting wall clocks; `every_s` (the configured cadence)
    # and a `step` snapshot ride along as extras. `clock_anchor`: the
    # monotonic-to-wall mapping sampled at one instant during run_start —
    # `monotonic` is the record's own `t` (seconds since telemetry
    # opened), `wall` the epoch seconds read back-to-back with it — so
    # `cli fleet` can place N processes' `t` axes on one aligned clock
    # offline. Both carry `host_id` as a required field; ALL records
    # additionally gain optional `host_id`/`pid` (and mesh `coords`)
    # extras stamped by the Telemetry bus when fleet stamping is on.
    # Cross-file cadence/anchor integrity is linted by obs/validate.py
    # check_fleet_integrity.
    "heartbeat": ("host_id", "role", "seq"),
    "clock_anchor": ("host_id", "monotonic", "wall"),
    "run_end": ("steps",),
}


def make_record(event: str, t: Optional[float] = None,
                **payload: Any) -> Dict[str, Any]:
    """Build a schema-stamped record (validation is the writer's job)."""
    rec: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "ts": datetime.datetime.now().isoformat(timespec="milliseconds"),
        "event": event,
    }
    if t is not None:
        rec["t"] = round(float(t), 6)
    rec.update(payload)
    return rec


def validate_record(rec: Any) -> List[str]:
    """Return a list of schema violations (empty = valid)."""
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    errors: List[str] = []
    ver = rec.get("schema")
    if ver not in SUPPORTED_SCHEMA_VERSIONS:
        errors.append(f"schema {ver!r} not in supported versions "
                      f"{SUPPORTED_SCHEMA_VERSIONS}")
    if not isinstance(rec.get("ts"), str):
        errors.append("missing/non-string ts")
    event = rec.get("event")
    if event not in EVENT_TYPES:
        errors.append(f"unknown event {event!r}")
        return errors
    if (isinstance(ver, int)
            and ver < _EVENT_MIN_VERSION.get(event, 1)):
        errors.append(f"{event}: introduced in schema "
                      f"{_EVENT_MIN_VERSION[event]}, record claims {ver}")
    for field in EVENT_TYPES[event]:
        if field not in rec:
            errors.append(f"{event}: missing required field {field!r}")
    return errors


def append_json_log(path: str, entry: Dict[str, Any],
                    stream=sys.stdout) -> Dict[str, Any]:
    """Dated JSON-line append; returns the entry (with ``ts`` stamped).

    ``stream`` mirrors the line for live consumption (pass ``sys.stderr`` —
    or ``None`` to silence — where stdout is a parsed protocol, e.g.
    bench.py's attempt chain).
    """
    entry = dict(entry)
    entry.setdefault(
        "ts", datetime.datetime.now().isoformat(timespec="seconds"))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    line = json.dumps(entry)
    with open(path, "a") as f:
        f.write(line + "\n")
    if stream is not None:
        print(line, file=stream, flush=True)
    return entry


def read_events(path: str) -> List[Dict[str, Any]]:
    """Parse an events.jsonl; raises ValueError on unparseable lines."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: unparseable record: {e}")
    return out


def validate_events(records: Iterable[Dict[str, Any]]) -> List[str]:
    """Validate a record stream; returns ["#<idx>: <violation>", ...]."""
    errors: List[str] = []
    for i, rec in enumerate(records):
        errors.extend(f"#{i}: {e}" for e in validate_record(rec))
    return errors
