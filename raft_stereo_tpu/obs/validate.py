"""Artifact-level event validation — the one copy of the check logic.

scripts/check_events.py (the CLI), scripts/rehearse_round.py's ``events``
leg and the analysis test fixtures all validate the same way: resolve a
path (file or run directory) to its ``events.jsonl``, parse it, and hold
every record against the schema (obs/events.py). Before this module the
path-resolution/empty-log/unparseable handling lived in the script only,
so library callers re-implemented it; now the script is a thin CLI over
:func:`check_path`.
"""

from __future__ import annotations

import os
import sys
from typing import Iterable, List, Optional, Sequence

from raft_stereo_tpu.obs.events import read_events, validate_events


def check_span_integrity(records: Iterable[dict]) -> List[str]:
    """Referential integrity of schema-v7 ``span`` records within one file.

    A tracer flush may interleave traces, but by end-of-file every
    ``parent_id`` must resolve to a flushed ``span_id`` (obs/trace.py's
    ``close()`` guarantees this by force-flushing open spans) and span ids
    must be unique — an orphan parent or a duplicate id means a writer
    dropped or double-emitted part of a trace. The one exemption:
    spans marked ``remote_parent`` inherited their parent across a
    process boundary (a traceparent header / RAFT_TRACEPARENT envelope,
    obs/fleet.py), so the parent legitimately lives in ANOTHER host's
    file — ``cli fleet`` resolves those joins across the fleet dir.
    """
    spans = [r for r in records
             if isinstance(r, dict) and r.get("event") == "span"]
    errors: List[str] = []
    seen: set = set()
    for s in spans:
        sid = s.get("span_id")
        if sid in seen:
            errors.append(f"span: duplicate span_id {sid!r}")
        seen.add(sid)
    for s in spans:
        parent = s.get("parent_id")
        if parent is not None and parent not in seen \
                and not s.get("remote_parent"):
            errors.append(
                f"span {s.get('span_id')!r} ({s.get('name')!r}): orphan "
                f"parent_id {parent!r} — no such span in this file")
        trace = s.get("trace_id")
        if not isinstance(trace, str) or not trace:
            errors.append(
                f"span {s.get('span_id')!r}: missing/empty trace_id")
    return errors


def check_converge_integrity(records: Iterable[dict]) -> List[str]:
    """Consistency of schema-v8 ``converge`` records (obs/converge.py).

    The downsampled curve must be internally coherent or the early-exit
    simulator silently lies: indices strictly increasing within the
    iteration budget and ending on the final iteration, curves no longer
    than the budget, residual/epe the same length as the index list, and
    every value finite (a NaN residual means the aux read garbage).
    """
    import math
    recs = [r for r in records
            if isinstance(r, dict) and r.get("event") == "converge"]
    errors: List[str] = []
    for n, r in enumerate(recs):
        tag = f"converge #{n} ({r.get('source')!r})"
        idx, residual = r.get("idx"), r.get("residual")
        iters = r.get("iters")
        if not isinstance(idx, list) or not isinstance(residual, list) \
                or not isinstance(iters, int):
            errors.append(f"{tag}: idx/residual/iters malformed")
            continue
        if len(idx) != len(residual):
            errors.append(f"{tag}: {len(idx)} indices vs "
                          f"{len(residual)} residual values")
        if len(idx) > iters:
            errors.append(f"{tag}: {len(idx)} stored points exceed the "
                          f"iteration budget {iters}")
        if any(b <= a for a, b in zip(idx, idx[1:])):
            errors.append(f"{tag}: downsample indices not strictly "
                          f"increasing: {idx}")
        if idx and (idx[0] < 0 or idx[-1] != iters - 1):
            errors.append(f"{tag}: indices must cover [0, iters-1]; got "
                          f"first={idx[0]} last={idx[-1]} iters={iters}")
        epe = r.get("epe")
        if epe is not None and (not isinstance(epe, list)
                                or len(epe) != len(idx)):
            errors.append(f"{tag}: epe curve length mismatch")
        for name in ("residual", "epe"):
            vals = r.get(name)
            if isinstance(vals, list) and not all(
                    isinstance(v, (int, float)) and math.isfinite(v)
                    for v in vals):
                errors.append(f"{tag}: non-finite {name} value")
    return errors


def check_numerics_integrity(records: Iterable[dict]) -> List[str]:
    """Consistency of schema-v9 ``numerics`` records (obs/numerics.py).

    grad records must keep ``leaves`` and ``grad_norm`` parallel (the
    attribution hinges on index alignment), with every norm a number or
    null (null IS the NaN marker — a NaN literal would not round-trip
    strict JSON). taps records must keep every stat series the same
    length as the advertised iteration count, counters non-negative, and
    the ``first_nonfinite`` pointer referentially valid: it must name a
    recorded tap and an in-range iteration whose nonfinite counter is
    actually positive.
    """
    from raft_stereo_tpu.obs.numerics import STAT_FIELDS
    recs = [r for r in records
            if isinstance(r, dict) and r.get("event") == "numerics"]
    errors: List[str] = []
    for n, r in enumerate(recs):
        kind = r.get("kind")
        tag = f"numerics #{n} ({r.get('source')!r}, kind={kind!r})"
        if kind == "grad":
            leaves, norms = r.get("leaves"), r.get("grad_norm")
            if not isinstance(leaves, list) or not isinstance(norms, list):
                errors.append(f"{tag}: leaves/grad_norm malformed")
                continue
            if len(leaves) != len(norms):
                errors.append(f"{tag}: {len(leaves)} leaves vs "
                              f"{len(norms)} grad_norm values")
            if not all(v is None or isinstance(v, (int, float))
                       for v in norms):
                errors.append(f"{tag}: grad_norm values must be numbers "
                              "or null")
        elif kind == "taps":
            taps, iters = r.get("taps"), r.get("iters")
            if not isinstance(taps, dict) or not isinstance(iters, int):
                errors.append(f"{tag}: taps/iters malformed")
                continue
            for label, series in taps.items():
                if not isinstance(series, dict):
                    errors.append(f"{tag}: tap {label!r} series malformed")
                    continue
                for field in STAT_FIELDS:
                    vals = series.get(field)
                    if not isinstance(vals, list) or len(vals) != iters:
                        errors.append(f"{tag}: tap {label!r} {field} "
                                      f"series is not length iters={iters}")
                    elif field in ("nonfinite", "sat", "underflow") \
                            and any(isinstance(v, (int, float)) and v < 0
                                    for v in vals):
                        errors.append(f"{tag}: tap {label!r} negative "
                                      f"{field} counter")
            fn = r.get("first_nonfinite")
            if fn is not None:
                if not isinstance(fn, dict):
                    errors.append(f"{tag}: first_nonfinite malformed")
                elif fn.get("tap") not in taps:
                    errors.append(f"{tag}: first_nonfinite names unknown "
                                  f"tap {fn.get('tap')!r}")
                elif not isinstance(fn.get("iter"), int) \
                        or not 0 <= fn["iter"] < iters:
                    errors.append(f"{tag}: first_nonfinite iter "
                                  f"{fn.get('iter')!r} outside "
                                  f"[0, {iters})")
                else:
                    series = taps[fn["tap"]].get("nonfinite")
                    if isinstance(series, list) and len(series) > fn["iter"] \
                            and not (isinstance(series[fn["iter"]],
                                                (int, float))
                                     and series[fn["iter"]] > 0):
                        errors.append(
                            f"{tag}: first_nonfinite points at tap "
                            f"{fn['tap']!r} iter {fn['iter']} but its "
                            "nonfinite counter is not positive there")
        else:
            errors.append(f"{tag}: unknown kind (expected grad|taps)")
    return errors


def check_fleet_integrity(records: Iterable[dict]) -> List[str]:
    """Consistency of the schema-v10 fleet records (obs/fleet.py).

    Host identity must be coherent or the offline clock alignment
    attributes evidence to the wrong process: every stamped ``host_id``
    non-empty and identical within a process segment (a ``run_start``
    opens a new segment — an auto-resumed run legitimately appends a
    second process's records, and pids differ, but two host identities
    INSIDE one segment mean two writers share a log), ``heartbeat``
    sequence numbers strictly increasing per (host, role) with a
    non-decreasing ``t`` axis within a segment, and at most one
    ``clock_anchor`` per host per segment — present whenever heartbeats
    are (beats without an anchor cannot be placed on the fleet clock).
    v1–v9 artifacts carry none of these records and no stamps, so they
    lint clean (additive).
    """
    recs = [r for r in records if isinstance(r, dict)]
    errors: List[str] = []
    hosts: set = set()
    anchors: dict = {}
    last_seq: dict = {}
    last_t: dict = {}
    have_beats = False
    have_anchor = False
    for n, r in enumerate(recs):
        if r.get("event") == "run_start":  # a new process segment begins
            hosts, anchors = set(), {}
            last_seq, last_t = {}, {}
        if "host_id" in r:
            hid = r.get("host_id")
            if not isinstance(hid, str) or not hid:
                errors.append(f"#{n} ({r.get('event')!r}): empty/"
                              f"non-string host_id {hid!r}")
            else:
                hosts.add(hid)
                if len(hosts) > 1:
                    errors.append(
                        f"#{n}: host_id inconsistent within one process "
                        f"segment: {sorted(hosts)} (one segment = one "
                        f"process)")
                    hosts = {hid}
        if r.get("event") == "clock_anchor":
            have_anchor = True
            hid = r.get("host_id")
            anchors[hid] = anchors.get(hid, 0) + 1
            if anchors[hid] > 1:
                errors.append(f"#{n}: clock_anchor repeated for host "
                              f"{hid!r} (must be present once per "
                              f"segment)")
        if r.get("event") == "heartbeat":
            have_beats = True
            key = (r.get("host_id"), r.get("role"))
            seq = r.get("seq")
            if not isinstance(seq, int) or seq < 0:
                errors.append(f"heartbeat #{n}: seq must be a "
                              f"non-negative int, got {seq!r}")
                continue
            if key in last_seq and seq <= last_seq[key]:
                errors.append(
                    f"heartbeat #{n} ({key[0]!r}/{key[1]!r}): seq {seq} "
                    f"not after {last_seq[key]} — cadence not monotonic")
            last_seq[key] = seq
            t = r.get("t")
            if isinstance(t, (int, float)):
                if key in last_t and t < last_t[key]:
                    errors.append(
                        f"heartbeat #{n} ({key[0]!r}/{key[1]!r}): t {t} "
                        f"rewound below {last_t[key]}")
                last_t[key] = t
    if have_beats and not have_anchor:
        errors.append("heartbeat records present but no clock_anchor — "
                      "beats cannot be placed on the fleet clock")
    return errors


def check_iter_policy(doc: dict) -> List[str]:
    """Schema + referential lint of one ``iter_policy.json`` document
    (obs/converge.py ``build_policy``) — the artifact the adaptive
    inference mode compiles in, so a doctored one must fail loudly with a
    named reason, never silently mis-budget the graph.

    Checks: version/kind, bucket coverage (at least one bucket or a
    default, bucket keys shaped ``HxW``), τ > 0 per entry (τ=0 is the
    parity-test value, never a production policy), integer budgets with
    ``1 <= min_iters <= budget``, provenance present (source run + table
    row), and referential consistency of each entry against its
    provenance row: the row's τ must match the entry's, and the entry's
    budget must not exceed the recorded iteration budget (the row's
    ``budget`` — the valid_iters the curves were recorded at).
    """
    errors: List[str] = []
    if not isinstance(doc, dict):
        return ["iter_policy: not a JSON object"]
    if doc.get("kind") != "iter_policy":
        errors.append(f"iter_policy: kind {doc.get('kind')!r} != "
                      "'iter_policy'")
    if doc.get("version") != 1:
        errors.append(f"iter_policy: unsupported version "
                      f"{doc.get('version')!r}")
    if not isinstance(doc.get("source_run"), str) or not doc.get("source_run"):
        errors.append("iter_policy: missing source_run provenance")
    buckets = doc.get("buckets")
    if not isinstance(buckets, dict):
        errors.append("iter_policy: buckets must be an object")
        buckets = {}
    entries = [(f"bucket {k!r}", v) for k, v in sorted(buckets.items())]
    if "default" in doc:
        entries.append(("default", doc["default"]))
    if not entries:
        errors.append("iter_policy: no bucket coverage — neither a bucket "
                      "entry nor a default")
    for key in buckets:
        parts = str(key).split("x")
        if len(parts) != 2 or not all(p.isdigit() and int(p) > 0
                                      for p in parts):
            errors.append(f"iter_policy: bucket key {key!r} is not 'HxW'")
    for tag, e in entries:
        if not isinstance(e, dict):
            errors.append(f"iter_policy {tag}: entry malformed")
            continue
        tau, budget = e.get("tau"), e.get("budget")
        min_iters = e.get("min_iters")
        if not isinstance(tau, (int, float)) or not tau > 0:
            errors.append(f"iter_policy {tag}: tau must be > 0, got {tau!r}")
        if not isinstance(budget, int) or budget < 1:
            errors.append(f"iter_policy {tag}: budget must be an int >= 1, "
                          f"got {budget!r}")
        if not isinstance(min_iters, int) or min_iters < 1 \
                or (isinstance(budget, int) and min_iters > budget):
            errors.append(f"iter_policy {tag}: min_iters must be in "
                          f"[1, budget], got {min_iters!r}")
        prov = e.get("provenance")
        if not isinstance(prov, dict) or not isinstance(prov.get("source"),
                                                        str) \
                or not isinstance(prov.get("row"), dict):
            errors.append(f"iter_policy {tag}: provenance (source + table "
                          "row) missing")
            continue
        row = prov["row"]
        row_tau = row.get("tau")
        if isinstance(row_tau, (int, float)) and isinstance(tau, (int, float)) \
                and float(row_tau) != float(tau):
            errors.append(f"iter_policy {tag}: entry tau {tau!r} != "
                          f"provenance row tau {row_tau!r}")
        row_budget = row.get("budget")
        if isinstance(row_budget, int) and isinstance(budget, int) \
                and budget > row_budget:
            errors.append(f"iter_policy {tag}: budget {budget} exceeds the "
                          f"recorded iteration budget {row_budget} "
                          "(valid_iters the curves were recorded at)")
    return errors


def check_policy_path(path: str) -> List[str]:
    """Validate one ``iter_policy.json`` file path."""
    import json
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable policy JSON: {e}"]
    return [f"{path}: {e}" for e in check_iter_policy(doc)]


def _looks_like_policy(path: str) -> bool:
    """A .json artifact routed to the policy lint: either its top-level
    ``kind`` says so, or it cannot be parsed at all (in which case the
    policy checker reports the parse failure for .json paths)."""
    import json
    if not path.endswith(".json"):
        return False
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return True
    return isinstance(doc, dict) and doc.get("kind") == "iter_policy"


def check_path(path: str) -> List[str]:
    """Validate one ``events.jsonl`` (or a run directory containing one),
    or — for ``*.json`` artifacts whose ``kind`` is ``iter_policy`` — the
    iteration-policy schema (:func:`check_iter_policy`).

    Returns ``["<path>: <violation>", ...]`` — empty means the artifact
    conforms. A missing file and an empty log are violations: an artifact
    that silently vanished is exactly what a lint must not bless.
    """
    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    if not os.path.exists(path):
        return [f"{path}: missing"]
    if _looks_like_policy(path):
        return check_policy_path(path)
    try:
        records = read_events(path)
    except ValueError as e:
        return [str(e)]
    if not records:
        return [f"{path}: empty event log"]
    errors = validate_events(records)
    errors.extend(check_span_integrity(records))
    errors.extend(check_converge_integrity(records))
    errors.extend(check_numerics_integrity(records))
    errors.extend(check_fleet_integrity(records))
    return [f"{path}: {e}" for e in errors]


def check_paths(paths: Iterable[str]) -> List[str]:
    """Validate several artifacts; concatenated :func:`check_path` output."""
    errors: List[str] = []
    for path in paths:
        errors.extend(check_path(path))
    return errors


def main(argv: Optional[Sequence[str]] = None,
         doc: Optional[str] = None) -> int:
    """The check-events CLI body: lint each argument, report, exit 1 on any
    violation. ``doc`` is the usage text printed when no paths are given."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print((doc or __doc__).strip(), file=sys.stderr)
        return 2
    errors = check_paths(argv)
    for e in errors:
        print(e, file=sys.stderr)
    if not errors:
        print(f"ok: {len(argv)} artifact(s) conform to the event schema")
    return 1 if errors else 0
