"""Compiled-artifact introspection: XLA memory/cost analysis as events.

The profiler traces (utils/profiling.py) answer "where did device time go";
this module answers the *other* two device-side questions a run leaves open:

* **How much memory does the executable need, and how close is that to the
  chip?** ``jax.stages.Compiled.memory_analysis()`` reports the executable's
  argument / output / temp / generated-code footprint at buffer-assignment
  time — BEFORE anything runs, so an AOT-OOM recipe can be diagnosed without
  surviving it, and a "spill regime" claim can be checked against the
  actual temp residency instead of hypothesized.
* **What does the compiled graph cost?** ``cost_analysis()`` exposes XLA's
  HLO cost model (flops, bytes accessed): flops/byte is the executable's
  arithmetic intensity — the number that says whether a recipe is compute-
  or bandwidth-bound before a profiler ever attaches.

:func:`introspect_compiled` turns both into schema events (``xla_memory`` /
``xla_cost``, obs/events.py v2) on a run's ``events.jsonl``, so every
``lower().compile()`` site (bench.py's attempt chain, the trainer's first
step, scripts/profile_step.py, scripts/batch_frontier.py rows) leaves a
machine-readable record the summarizer and the compare gate can read.

For *naming* buffers (which allocation dominates the temp footprint — the
question VERDICT r5 weak #4 asks about the b10 collapse), XLA's
buffer-assignment dump is the ground truth: run the compile in a process
with ``XLA_FLAGS=--xla_dump_to=<dir>`` and feed the resulting
``*buffer-assignment.txt`` to :func:`summarize_buffer_assignment`
(scripts/alloc_breakdown.py drives this end to end). The analyses are
backend-generic — on CPU the "device" numbers describe host buffers, which
is still the same HLO module and buffer shapes as the TPU executable; only
layouts and the capacity line differ. Everything here is fail-open: an
introspection API moving under a jax upgrade must never take down the run
it observes.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Any, Dict, List, Optional

# CompiledMemoryStats attribute -> short event-field name. host_* mirrors
# (CPU-offload sizes) are folded in only when non-zero.
_MEMORY_FIELDS = {
    "argument_size_in_bytes": "argument_bytes",
    "output_size_in_bytes": "output_bytes",
    "temp_size_in_bytes": "temp_bytes",
    "alias_size_in_bytes": "alias_bytes",
    "generated_code_size_in_bytes": "generated_code_bytes",
}


def memory_analysis_dict(compiled) -> Optional[Dict[str, int]]:
    """``compiled.memory_analysis()`` as a plain dict, or None.

    Adds ``peak_bytes`` — the executable's device residency while it runs:
    arguments + outputs + temps + generated code, minus buffers aliased
    into arguments (donation). This is the number to hold against the
    chip's ``bytes_limit``.
    """
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out: Dict[str, int] = {}
    for attr, name in _MEMORY_FIELDS.items():
        v = getattr(ma, attr, None)
        if v is not None:
            out[name] = int(v)
    if not out:
        return None
    out["peak_bytes"] = (out.get("argument_bytes", 0)
                         + out.get("output_bytes", 0)
                         + out.get("temp_bytes", 0)
                         + out.get("generated_code_bytes", 0)
                         - out.get("alias_bytes", 0))
    return out


def cost_analysis_dict(compiled) -> Optional[Dict[str, float]]:
    """``compiled.cost_analysis()`` flattened to scalar properties, or None.

    Keeps the module-level totals (``flops``, ``bytes accessed``,
    ``transcendentals``, ``optimal_seconds``) and derives ``flops_per_byte``
    (arithmetic intensity); the per-operand keys XLA also emits
    (``bytes accessed0{}`` ...) are dropped.
    """
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: Dict[str, float] = {}
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals"),
                      ("optimal_seconds", "optimal_seconds")):
        v = ca.get(key)
        if isinstance(v, (int, float)) and v == v:  # drop NaN sentinels
            out[name] = float(v)
    if "flops" not in out:
        return None
    if out.get("bytes_accessed"):
        out["flops_per_byte"] = round(out["flops"] / out["bytes_accessed"], 4)
    return out


def device_capacity_bytes(device=None) -> Optional[int]:
    """The backend's per-device memory capacity (``bytes_limit``), or None
    where the backend doesn't report one (XLA-CPU)."""
    try:
        import jax
        dev = device if device is not None else jax.local_devices()[0]
        stats = dev.memory_stats() or {}
    except Exception:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None


def introspect_compiled(compiled, telemetry=None, source: str = "compiled",
                        extra: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, Optional[Dict[str, Any]]]:
    """Extract memory + cost analyses; emit ``xla_memory``/``xla_cost``.

    Returns ``{"memory": ..., "cost": ...}`` (either half None where the
    backend provides nothing). When ``telemetry`` is given, each available
    half becomes one schema event with ``source`` naming the compile site;
    ``extra`` fields (batch, recipe tag, ...) ride along on both.
    """
    mem = memory_analysis_dict(compiled)
    cost = cost_analysis_dict(compiled)
    if mem is not None:
        cap = device_capacity_bytes()
        if cap:
            mem["capacity_bytes"] = cap
            mem["headroom_bytes"] = cap - mem["peak_bytes"]
    if telemetry is not None:
        if mem is not None:
            telemetry.emit("xla_memory", source=source, **mem,
                           **(extra or {}))
        if cost is not None:
            telemetry.emit("xla_cost", source=source, **cost,
                           **(extra or {}))
    return {"memory": mem, "cost": cost}


def compact_xla_summary(analysis: Dict[str, Optional[Dict[str, Any]]]
                        ) -> Optional[Dict[str, Any]]:
    """The two headline numbers (peak bytes, flops/byte) for result JSONs."""
    mem, cost = analysis.get("memory"), analysis.get("cost")
    out: Dict[str, Any] = {}
    if mem:
        out["peak_bytes"] = mem["peak_bytes"]
        out["temp_bytes"] = mem.get("temp_bytes")
        if "headroom_bytes" in mem:
            out["headroom_bytes"] = mem["headroom_bytes"]
    if cost:
        out["flops"] = cost["flops"]
        if "flops_per_byte" in cost:
            out["flops_per_byte"] = cost["flops_per_byte"]
    return out or None


# --- jaxpr op profiles -------------------------------------------------------
#
# Backend-independent structural evidence for scheduling claims: where do the
# convolutions LIVE — inside a scan's while-loop body (executed once per
# iteration) or at the top level (executed once per step)? The batched-
# weight-grad scan (ops/scan_grad.py) claims to move the per-iteration
# weight-grad convs out of the backward loop; this profile is the artifact
# that shows it (scripts/scan_wgrad_evidence.py, `op_counts` events), without
# needing a TPU or even an XLA compile.

def iter_subjaxprs(params):
    """Every sub-jaxpr held by one equation's params (pjit/remat/custom_vjp/
    cond/while/scan bodies), unwrapped to plain ``Jaxpr``s."""
    import jax.core as jcore
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            if isinstance(item, jcore.ClosedJaxpr):
                yield item.jaxpr
            elif isinstance(item, jcore.Jaxpr):
                yield item


_iter_subjaxprs = iter_subjaxprs  # back-compat alias


def iter_eqns(jaxpr, path: str = "top"):
    """Depth-first ``(eqn, path)`` walk of a jaxpr and every sub-jaxpr.

    ``path`` names the nesting chain with primitive names — scan bodies are
    indexed (``top/scan[0]/...``) in jaxpr order so a rule finding anchored
    to a path is stable across unrelated edits. This is the generic walker
    the analysis/ graph rules share with the conv profilers below."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    scan_i = 0
    for eqn in jaxpr.eqns:
        yield eqn, path
        if eqn.primitive.name == "scan":
            sub_path = f"{path}/scan[{scan_i}]"
            scan_i += 1
        else:
            sub_path = f"{path}/{eqn.primitive.name}"
        for sub in iter_subjaxprs(eqn.params):
            yield from iter_eqns(sub, sub_path)


def _count_convs(jaxpr) -> int:
    """Total conv_general_dilated eqns in a jaxpr, recursing through every
    sub-jaxpr (pjit/remat/custom_vjp/cond/while/scan bodies)."""
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "conv_general_dilated":
            n += 1
        for sub in _iter_subjaxprs(eqn.params):
            n += _count_convs(sub)
    return n


def conv_op_profile(closed_jaxpr) -> Dict[str, Any]:
    """Profile conv placement: per-scan body counts vs everything outside.

    Returns ``{"outside_scans": N, "scans": [{"length", "convs",
    "convs_per_step"}...], "total": N}`` where ``convs_per_step`` counts the
    convs one loop iteration executes and ``total`` weights each scan body
    by 1 (static op count). Scans are listed in jaxpr order: for a
    ``value_and_grad`` train step the forward refinement scan comes first
    and the backward (reverse) scan last — the one whose per-step conv
    count the batched-weight-grad path shrinks."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    scans: List[Dict[str, Any]] = []

    def walk(jxp) -> int:
        outside = 0
        for eqn in jxp.eqns:
            if eqn.primitive.name == "conv_general_dilated":
                outside += 1
            elif eqn.primitive.name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                convs = _count_convs(body)
                scans.append({"length": int(eqn.params.get("length", 0)),
                              "convs_per_step": convs, "convs": convs})
            else:
                for sub in _iter_subjaxprs(eqn.params):
                    outside += walk(sub)
        return outside

    outside = walk(jaxpr)
    return {"outside_scans": outside, "scans": scans,
            "total": outside + sum(s["convs"] for s in scans)}


def emit_op_counts(profile: Dict[str, Any], telemetry=None,
                   source: str = "op_profile",
                   extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Flatten a :func:`conv_op_profile` into one ``op_counts`` event."""
    rec = {
        "conv_total": profile["total"],
        "conv_outside_scans": profile["outside_scans"],
        "scan_convs_per_step": [s["convs_per_step"]
                                for s in profile["scans"]],
        "scan_lengths": [s["length"] for s in profile["scans"]],
    }
    if telemetry is not None:
        telemetry.emit("op_counts", source=source, **rec, **(extra or {}))
    return rec


# --- collective walkers ------------------------------------------------------
#
# The SPMD engine (analysis/spmd_rules.py) and the fingerprint gate
# (analysis/fingerprint.py) both ask the same two questions of a sharded
# program: WHICH collectives does it run, and do any of them live inside the
# refinement scan's loop body (executed per iteration, serialized against the
# scan's dependence chain)? The jaxpr walk answers for the traced program;
# the HLO walk answers for the compiled executable after SPMD partitioning,
# where XLA's propagation may have inserted collectives the trace never wrote.

COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather",
    "all_gather_invariant",
    # shard_map's replication-rule rewrite (check_rep/check_vma=True)
    # re-spells psum; pbroadcast is deliberately NOT here — it marks a
    # replication-type change, no bytes move
    "psum2",
})


def collective_axis_names(eqn) -> tuple:
    """Mesh axis names a collective eqn operates over (``axes`` on the psum
    family, ``axis_name`` on ppermute/all_gather; positional vmap axes are
    dropped — only named mesh axes matter to the SPMD contracts)."""
    p = eqn.params
    v = p.get("axes", p.get("axis_name", ()))
    if not isinstance(v, (tuple, list)):
        v = (v,)
    return tuple(a for a in v if isinstance(a, str))


def collective_profile(closed_jaxpr, path: str = "top") -> Dict[str, Any]:
    """Jaxpr-level collective placement profile.

    Returns ``{"total", "by_kind": {prim: n}, "in_loop": {prim: n},
    "outside": {prim: n}, "axes": {prim: [axis...]}}`` where ``in_loop``
    counts collectives whose walk path crosses a scan body — the ones a
    sharded program executes once per loop iteration.
    """
    by_kind: Dict[str, int] = {}
    in_loop: Dict[str, int] = {}
    outside: Dict[str, int] = {}
    axes: Dict[str, set] = {}
    for eqn, epath in iter_eqns(closed_jaxpr, path=path):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMITIVES:
            continue
        by_kind[name] = by_kind.get(name, 0) + 1
        bucket = in_loop if "/scan[" in epath else outside
        bucket[name] = bucket.get(name, 0) + 1
        axes.setdefault(name, set()).update(collective_axis_names(eqn))
    return {"total": sum(by_kind.values()), "by_kind": by_kind,
            "in_loop": in_loop, "outside": outside,
            "axes": {k: sorted(v) for k, v in axes.items()}}


# Optimized-HLO line shapes (any backend, post SPMD partitioning):
#   %all-reduce.1 = f32[128]{0} all-reduce(f32[128]{0} %x), replica_groups=...
#   ROOT %tuple.2 = (f32[2,8,12,64]{...}) tuple(%y)
#   %while.3 = (...) while(%t), condition=%cond.1, body=%body.1
_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*([a-z0-9]+)\[([\d,]*)\]"
    r"(?:\{[^}]*\})?\s+([\w\-]+)\(")
# tuple-typed instructions (while/optimization-barrier/...): no single array
# shape; still needed for the call graph (a while's body= edge lives here)
_HLO_TUPLE_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*\(.*?\)\s+([\w\-]+)\(")
# computation header: `%region_0.12_spmd (param: (s32[], f32[1,16])) ->
# (s32[], f32[1,16]) {` — the param list nests parens, so the name is
# matched alone and the header shape (`... -> ... {`, no `=` before the
# params) is checked separately in parse_hlo_instructions
_HLO_COMP_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\(")
# called-computation attrs: either a single ref (`body=%region_0.12`) or a
# braced list (`branch_computations={%a, %b}`); an unanchored comma-list
# would swallow the NEXT attr's key (`condition=%x, body=%y` -> "x, body")
_HLO_CALLED_RE = re.compile(
    r"(?:body|condition|calls|to_apply|branch_computations)="
    r"(\{[^}]*\}|%?[\w.\-]+)")

HLO_COLLECTIVE_OPS = ("all-reduce", "all-gather", "collective-permute",
                      "all-to-all", "reduce-scatter", "collective-broadcast",
                      "all-reduce-start", "all-gather-start",
                      "collective-permute-start")

_HLO_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1, "s4": 1,
    "u64": 8, "u32": 4, "u16": 2, "u8": 1, "u4": 1,
    "pred": 1, "c64": 8, "c128": 16,
}


def _hlo_bytes(dtype: str, dims: str) -> Optional[int]:
    itemsize = _HLO_DTYPE_BYTES.get(dtype)
    if itemsize is None:
        return None
    n = 1
    for d in filter(None, dims.split(",")):
        n *= int(d)
    return n * itemsize


def parse_hlo_instructions(hlo_text: str) -> List[Dict[str, Any]]:
    """Flat instruction list from an HLO module text: ``{"name", "op",
    "dtype", "shape", "bytes", "computation", "called"}`` per array-typed
    instruction (tuple-typed aggregates — while/parameter tuples — are
    skipped; their leaves appear individually)."""
    out: List[Dict[str, Any]] = []
    comp = "<module>"
    for line in hlo_text.splitlines():
        stripped = line.rstrip()
        if stripped.endswith("{") and "->" in stripped \
                and "=" not in stripped.split("(", 1)[0]:
            mc = _HLO_COMP_RE.match(line)
            if mc:
                comp = mc.group(1)
                continue
        m = _HLO_INSTR_RE.match(line)
        if m:
            name, dtype, dims, op = m.groups()
        else:
            mt = _HLO_TUPLE_INSTR_RE.match(line)
            if not mt:
                continue
            name, op = mt.groups()
            dtype, dims = None, None
        called: List[str] = []
        for mm in _HLO_CALLED_RE.finditer(line):
            called.extend(c.strip().lstrip("%")
                          for c in mm.group(1).strip("{}").split(",")
                          if c.strip())
        out.append({"name": name, "op": op, "dtype": dtype,
                    "shape": ([int(d) for d in filter(None, dims.split(","))]
                              if dims is not None else None),
                    "bytes": (_hlo_bytes(dtype, dims)
                              if dtype is not None else None),
                    "computation": comp, "called": called})
    return out


def hlo_collective_profile(hlo_text: str) -> Dict[str, Any]:
    """Collectives in a compiled (post-partitioning) HLO module.

    Returns ``{"total", "by_kind": {op: n}, "in_loop": {op: n}}`` where
    ``in_loop`` counts collectives living in a computation reachable from a
    ``while`` op's body — the compiled mirror of
    :func:`collective_profile`'s scan-body bucket.
    """
    instrs = parse_hlo_instructions(hlo_text)
    # computation -> computations it calls (one edge set; whiles contribute
    # their body+condition, fusions/calls their callees)
    edges: Dict[str, set] = {}
    loop_roots: set = set()
    for ins in instrs:
        if ins["called"]:
            edges.setdefault(ins["computation"], set()).update(ins["called"])
        if ins["op"] == "while":
            loop_roots.update(ins["called"])
    in_loop_comps: set = set()
    frontier = set(loop_roots)
    while frontier:
        nxt = set()
        for c in frontier:
            if c in in_loop_comps:
                continue
            in_loop_comps.add(c)
            nxt.update(edges.get(c, ()))
        frontier = nxt - in_loop_comps
    by_kind: Dict[str, int] = {}
    in_loop: Dict[str, int] = {}
    for ins in instrs:
        if ins["op"] not in HLO_COLLECTIVE_OPS:
            continue
        op = ins["op"].replace("-start", "")
        by_kind[op] = by_kind.get(op, 0) + 1
        if ins["computation"] in in_loop_comps:
            in_loop[op] = in_loop.get(op, 0) + 1
    return {"total": sum(by_kind.values()), "by_kind": by_kind,
            "in_loop": in_loop}


# Aggregate/bookkeeping ops whose "output" is an alias or an input copy, not
# a buffer the partitioner materialized.
_HLO_NONMATERIALIZING = frozenset({
    "parameter", "get-tuple-element", "tuple", "bitcast", "constant",
})


def hlo_large_instructions(hlo_text: str, min_bytes: int,
                           top: int = 8) -> List[Dict[str, Any]]:
    """Array-materializing instructions whose per-device output buffer is at
    least ``min_bytes``, largest first — in a post-partitioning module these
    are the tensors each device actually holds, so a "sharded" intermediate
    showing up here at its full global size is replication made visible."""
    hits = [ins for ins in parse_hlo_instructions(hlo_text)
            if ins["bytes"] is not None and ins["bytes"] >= min_bytes
            and ins["op"] not in _HLO_NONMATERIALIZING]
    return sorted(hits, key=lambda i: -i["bytes"])[:top]


# --- buffer-assignment dumps ------------------------------------------------
#
# Line shapes in an XLA *buffer-assignment.txt (any backend):
#   allocation 6: size 16452, preallocated-temp:
#    value: <9 dot.4 @0> (size=16384,offset=0): f32[64,64]{1,0}
#   Total bytes used: 49236 (48.1KiB)

_ALLOC_RE = re.compile(r"^allocation (\d+): size (\d+), (.+?):?$")
_VALUE_RE = re.compile(
    r"^\s+value: <\d+ (\S+) @\S+> \(size=(\d+),offset=(\d+)\): (\S+)")
_TOTAL_RE = re.compile(r"^Total bytes used: (\d+)")


def _alloc_kind(desc: str) -> str:
    for kind in ("preallocated-temp", "parameter", "constant",
                 "thread-local"):
        if kind in desc:
            return "temp" if kind == "preallocated-temp" else kind
    return desc.split(",")[0].strip()


def parse_buffer_assignment(text: str) -> Dict[str, Any]:
    """Parse XLA's ``*buffer-assignment.txt`` dump into allocations.

    Returns ``{"total_bytes", "allocations": [{"index", "size", "kind",
    "maybe_live_out", "values": [{"instruction", "size", "offset",
    "shape"}]}]}``. Only the leading BufferAssignment section is read (the
    "Used values" tail repeats every value with its uses).
    """
    allocations: List[Dict[str, Any]] = []
    total = None
    cur: Optional[Dict[str, Any]] = None
    for line in text.splitlines():
        m = _TOTAL_RE.match(line)
        if m:
            total = int(m.group(1))
            break  # end of the assignment section
        m = _ALLOC_RE.match(line)
        if m:
            desc = m.group(3)
            cur = {"index": int(m.group(1)), "size": int(m.group(2)),
                   "kind": _alloc_kind(desc),
                   "maybe_live_out": "maybe-live-out" in desc,
                   "values": []}
            allocations.append(cur)
            continue
        m = _VALUE_RE.match(line)
        if m and cur is not None:
            cur["values"].append({"instruction": m.group(1),
                                  "size": int(m.group(2)),
                                  "offset": int(m.group(3)),
                                  "shape": m.group(4)})
    return {"total_bytes": total, "allocations": allocations}


def summarize_buffer_assignment(text: str, top: int = 8) -> Dict[str, Any]:
    """Name the buffers that matter: top allocations by size, and inside the
    dominant temp allocation the largest values (HLO instruction + shape) —
    the answer to "WHICH buffer is the big one", which the aggregate
    ``memory_analysis`` totals cannot give."""
    parsed = parse_buffer_assignment(text)
    allocs = sorted(parsed["allocations"], key=lambda a: -a["size"])
    temp_allocs = [a for a in allocs if a["kind"] == "temp"]
    dominant = None
    if temp_allocs:
        biggest = temp_allocs[0]
        values = sorted(biggest["values"], key=lambda v: -v["size"])[:top]
        dominant = {
            "allocation": biggest["index"],
            "size": biggest["size"],
            "top_values": [{"instruction": v["instruction"],
                            "shape": v["shape"], "size": v["size"]}
                           for v in values],
        }
    return {
        "total_bytes": parsed["total_bytes"],
        "temp_bytes": sum(a["size"] for a in temp_allocs),
        "top_allocations": [
            {"index": a["index"], "size": a["size"], "kind": a["kind"],
             "n_values": len(a["values"])}
            for a in allocs[:top]
        ],
        "dominant_temp": dominant,
    }


_SHAPE_DIMS_RE = re.compile(r"\[([0-9,]*)\]")


def volume_class_summary(text: str, w1: int, h1: int, num_levels: int = 4,
                         top: int = 4, min_width: int = 16
                         ) -> Dict[str, Any]:
    """The correlation-volume allocation class, by name.

    Scans EVERY value in a buffer-assignment dump (not just the top-N the
    summary keeps) for shapes trailing in ``(W1, W2_level)`` — the all-pairs
    volume and its pooled pyramid/scan-stacked descendants, ``W2_level``
    walking the floor-halving pool chain from ``W1``. Leading dims must
    cover at least ``h1`` rows (``h1`` = the feature-map height): the class
    is the per-IMAGE O(H*W^2) residency, not any bounded per-block slab
    (e.g. the fused kernel's (rows<=8, W1, block) interpret-mode transient).
    Pool levels at or below ``min_width`` lanes are excluded: those levels
    are linear-in-W small, and their widths collide with the (2r+2)-lane
    tap stacks every on-the-fly lookup legitimately builds — the class
    names the QUADRATIC residency, which lives in the wide levels.
    This is the class the r7 breakdown named dominant and the memoryless
    ``fused`` lookup deletes: under ``fused`` the count must be ZERO, which
    aggregate ``memory_analysis`` totals can suggest but never prove.
    """
    widths = set()
    w = int(w1)
    for _ in range(num_levels):
        if w > min_width:
            widths.add(w)
        w //= 2
    parsed = parse_buffer_assignment(text)
    hits = []
    for a in parsed["allocations"]:
        for v in a["values"]:
            m = _SHAPE_DIMS_RE.search(v["shape"])
            if not m or not m.group(1):
                continue
            dims = [int(x) for x in m.group(1).split(",") if x]
            lead = 1
            for x in dims[:-2]:
                lead *= x
            if (len(dims) >= 3 and dims[-2] == w1 and dims[-1] in widths
                    and lead >= h1):
                hits.append({**v, "allocation": a["index"],
                             "kind": a["kind"]})
    hits.sort(key=lambda v: -v["size"])
    return {
        "w1": int(w1), "h1": int(h1),
        "pool_widths": sorted(widths, reverse=True),
        "count": len(hits),
        "bytes": sum(v["size"] for v in hits),
        "largest": [{"instruction": v["instruction"], "shape": v["shape"],
                     "size": v["size"], "kind": v["kind"]}
                    for v in hits[:top]],
    }


def find_buffer_assignment(dump_dir: str) -> Optional[str]:
    """Pick the main module's buffer-assignment file from an
    ``--xla_dump_to`` directory (the largest one — jit wrapper modules for
    convert/broadcast ops dump alongside the real graph)."""
    paths = glob.glob(os.path.join(dump_dir, "*buffer-assignment.txt"))
    if not paths:
        return None
    return max(paths, key=os.path.getsize)
