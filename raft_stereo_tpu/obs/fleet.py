"""Fleet observatory (schema v10): N processes, one aligned story.

Every observatory before this one (spans/doctor, converge, numerics) was
single-process: one ``events.jsonl``, one monotonic clock, trace context
that died at the HTTP boundary. This module is the multi-process half:

* **Host identity** — :func:`resolve_host_id` names a process (explicit >
  ``RAFT_HOST_ID`` env > ``<hostname>-<pid>``); the Telemetry bus stamps
  it (plus ``pid`` and optional mesh ``coords``) on every record it
  writes, and emits a ``clock_anchor`` record at run_start: the
  monotonic-to-wall mapping sampled at one instant, so N processes' ``t``
  axes can be aligned offline (``wall = t + offset`` with
  ``offset = anchor.wall - anchor.monotonic``).
* **Trace propagation** — :func:`format_traceparent` /
  :func:`parse_traceparent` carry a span context across process
  boundaries as a W3C-traceparent-style header
  (``00-<trace_id>-<span_id>-01`` with the repo's short ids): the serve
  HTTP front accepts/echoes it, the loadtest client sends it, and the
  same envelope rides subprocess launches via the ``RAFT_TRACEPARENT``
  env var, so a request's client-side span and the server's
  queue_wait/collect_group/dispatch/retire spans join one ``trace_id``.
* **The aggregator** — ``cli fleet <dir-with-N-run-dirs>`` merges per-host
  event logs into one clock-aligned rollup (per-host step-time /
  throughput distributions, skew table, heartbeat gaps, cross-host trace
  joins; :func:`aggregate_fleet`) and one Perfetto timeline with a
  process-group per host on a single aligned clock
  (:func:`build_fleet_timeline`).
* **Fleet verdicts** — :func:`diagnose_fleet` (routed from ``cli doctor``
  when pointed at a fleet dir) names STRAGGLER (one host's step p95 well
  past the other hosts' median, evidence quoting both), DEAD_HOST (a host
  without a clean ``run_end`` whose heartbeat gap blew past the deadline)
  and DESYNC (live hosts' step counters diverge), or FLEET_OK.

Logs are read leniently here (:func:`read_events_lenient`): a SIGKILL'd
host's final line is legitimately truncated mid-write, and the aggregator
must still tell its story — the strict lint (obs/validate.py) stays
strict.

Proof: ``scripts/fleet_drill.py`` — a real 3-process CPU drill with an
injected sleep-straggler and a SIGKILL'd host, banked as the ``fleet``
leg of scripts/rehearse_round.py.
"""

from __future__ import annotations

import datetime
import json
import os
import socket
from typing import Any, Dict, List, Optional, Sequence

#: explicit host identity for a launched process (beats the hostname-pid
#: default; the fleet drill names its children host0/host1/host2 with it)
HOST_ID_ENV = "RAFT_HOST_ID"
#: cross-process trace envelope for subprocess launches: a traceparent
#: header value; the child's run_start records it so the launcher's span
#: and the child's run join offline
TRACEPARENT_ENV = "RAFT_TRACEPARENT"

# --- fleet verdict thresholds ----------------------------------------------
#: STRAGGLER: a host's step p95 must reach this multiple of the median of
#: the OTHER hosts' p95 (median-of-others, not fleet median, so one slow
#: host cannot drag the reference toward itself in a small fleet)
STRAGGLER_FACTOR = 2.0
#: ... over at least this many post-compile steps (one step is noise)
STRAGGLER_MIN_STEPS = 2
#: DEAD_HOST: a heartbeat gap (tail or internal) past this many cadence
#: intervals on a host that never wrote a clean run_end
DEAD_HOST_GAP_BEATS = 3.0
#: DESYNC: live hosts' max step counters may differ by this many steps
#: (barrier-free loops legitimately skew by a step or two)
DESYNC_STEP_MARGIN = 2


def resolve_host_id(explicit: Optional[str] = None) -> str:
    """Name this process for fleet stamping: explicit > RAFT_HOST_ID env >
    ``<short-hostname>-<pid>`` (unique per process on one machine)."""
    if explicit:
        return str(explicit)
    env = os.environ.get(HOST_ID_ENV)
    if env:
        return env
    host = socket.gethostname().split(".")[0] or "host"
    return f"{host}-{os.getpid()}"


def format_traceparent(ctx) -> str:
    """SpanContext -> ``00-<trace_id>-<span_id>-01`` (W3C traceparent
    shape with the repo's short ids, which never contain dashes)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(header: Optional[str]):
    """Header value -> SpanContext, or None for anything malformed (a
    broken header must degrade to "no remote parent", never error)."""
    from raft_stereo_tpu.obs.trace import SpanContext
    if not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) != 4 or not parts[1] or not parts[2]:
        return None
    return SpanContext(trace_id=parts[1], span_id=parts[2])


def read_events_lenient(path: str) -> List[Dict[str, Any]]:
    """Parse an events.jsonl, skipping unparseable lines: a SIGKILL'd
    writer truncates its final line mid-write, and the aggregator must
    still read the rest (the strict reader is obs/events.read_events)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def discover_runs(fleet_dir: str) -> List[str]:
    """Child run dirs (those holding an ``events.jsonl``), sorted."""
    if not os.path.isdir(fleet_dir):
        raise FileNotFoundError(f"{fleet_dir}: not a directory")
    out = []
    for name in sorted(os.listdir(fleet_dir)):
        child = os.path.join(fleet_dir, name)
        if os.path.isfile(os.path.join(child, "events.jsonl")):
            out.append(child)
    return out


def load_host(run_dir: str) -> Dict[str, Any]:
    """One host's log + its clock offset (``wall = t + offset``).

    The offset comes from the schema-v10 ``clock_anchor`` record; logs
    predating v10 fall back to the first record's wall-clock ``ts`` minus
    its monotonic ``t`` (coarser — ``ts`` has millisecond resolution and
    is stamped a hair after ``t`` — but enough to place an old log on the
    fleet axis). ``anchored`` says which one was used.
    """
    records = read_events_lenient(os.path.join(run_dir, "events.jsonl"))
    host_id, anchor = None, None
    for r in records:
        if anchor is None and r.get("event") == "clock_anchor":
            anchor = r
        if host_id is None and isinstance(r.get("host_id"), str) \
                and r["host_id"]:
            host_id = r["host_id"]
        if host_id is not None and anchor is not None:
            break
    if host_id is None:
        host_id = os.path.basename(os.path.normpath(run_dir)) or "host"
    offset = None
    if anchor is not None:
        try:
            offset = float(anchor["wall"]) - float(anchor["monotonic"])
        except (KeyError, TypeError, ValueError):
            offset = None
    if offset is None:
        for r in records:
            if "t" in r and isinstance(r.get("ts"), str):
                try:
                    wall = datetime.datetime.fromisoformat(
                        r["ts"]).timestamp()
                    offset = wall - float(r["t"])
                except (ValueError, TypeError):
                    continue
                break
    return {"run_dir": run_dir, "host_id": host_id, "records": records,
            "offset": offset if offset is not None else 0.0,
            "anchored": anchor is not None}


def _percentile(xs: Sequence[float], q: float) -> float:
    xs = sorted(xs)
    if not xs:
        return 0.0
    k = (len(xs) - 1) * q / 100.0
    lo = int(k)
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (k - lo)


def _step_stats(records: Sequence[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Train-step timing distribution for one host; None for hosts that
    run no train loop (a serve host's ``step`` records — the loadtest's
    per-request accounting — are excluded the way doctor excludes them:
    any ``request`` record means this is a serving log)."""
    if any(r.get("event") == "request" for r in records):
        return None
    steps = [r for r in records
             if r.get("event") == "step" and "in_flight" not in r]
    if not steps:
        return None
    body = steps[1:] or steps  # first step's dispatch carries compile
    totals = [float(r.get("data_wait_s", 0.0))
              + float(r.get("dispatch_s", 0.0))
              + float(r.get("fetch_s", 0.0)) for r in body]
    pairs = sum(int(r["batch_size"]) for r in body if "batch_size" in r)
    ts = [float(r["t"]) for r in body if "t" in r]
    dt = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    return {
        "n": len(steps),
        "step_max": max(int(r.get("step", 0)) for r in steps),
        "p50_s": round(_percentile(totals, 50.0), 6),
        "p95_s": round(_percentile(totals, 95.0), 6),
        "pairs_per_sec": round(pairs / dt, 4) if dt > 0 and pairs else None,
    }


def _heartbeat_stats(records: Sequence[Dict[str, Any]]
                     ) -> Dict[str, Dict[str, Any]]:
    """Per-role beat bookkeeping: count, cadence (the ``every_s`` extra,
    else the median inter-beat delta), worst internal gap, last beat's
    monotonic ``t``. Gap-vs-deadline judgment happens fleet-side where
    the aligned end time is known."""
    by_role: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        if r.get("event") == "heartbeat":
            by_role.setdefault(str(r.get("role", "?")), []).append(r)
    out: Dict[str, Dict[str, Any]] = {}
    for role, beats in by_role.items():
        ts = sorted(float(b["t"]) for b in beats if "t" in b)
        cadence = None
        for b in beats:
            if isinstance(b.get("every_s"), (int, float)) \
                    and b["every_s"] > 0:
                cadence = float(b["every_s"])
                break
        deltas = [b - a for a, b in zip(ts, ts[1:])]
        if cadence is None and deltas:
            cadence = _percentile(deltas, 50.0)
        out[role] = {
            "beats": len(beats),
            "every_s": cadence,
            "max_gap_s": round(max(deltas), 3) if deltas else 0.0,
            "last_t": ts[-1] if ts else None,
        }
    return out


def _cross_host_traces(hosts: Sequence[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """Traces whose spans land in more than one host's log, with the
    count of remote parent links (a span whose ``parent_id`` resolves in
    a DIFFERENT host's file — the propagated-context join)."""
    span_host: Dict[str, str] = {}   # span_id -> host_id
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for h in hosts:
        for r in h["records"]:
            if r.get("event") != "span":
                continue
            sid = r.get("span_id")
            if isinstance(sid, str):
                span_host.setdefault(sid, h["host_id"])
            tid = r.get("trace_id")
            if isinstance(tid, str):
                by_trace.setdefault(tid, []).append(
                    dict(r, _host=h["host_id"]))
    joins: List[Dict[str, Any]] = []
    for tid, spans in sorted(by_trace.items()):
        host_ids = sorted({s["_host"] for s in spans})
        if len(host_ids) < 2:
            continue
        remote_links = []
        for s in spans:
            parent = s.get("parent_id")
            owner = span_host.get(parent) if isinstance(parent, str) else None
            if owner is not None and owner != s["_host"]:
                remote_links.append({
                    "child": s.get("name"), "child_host": s["_host"],
                    "parent_host": owner})
        joins.append({"trace_id": tid, "hosts": host_ids,
                      "spans": len(spans), "remote_links": remote_links})
    return joins


def aggregate_fleet(fleet_dir: str) -> Dict[str, Any]:
    """Merge N per-host logs into one clock-aligned rollup.

    Per host: identity, clock offset, aligned start/end (epoch seconds),
    clean-exit flag, step-time distribution, heartbeat bookkeeping with
    the tail gap measured against the FLEET's aligned end (a host whose
    beats stop while the rest of the fleet runs on is the dead-host
    signal). Fleet-wide: the skew table (each host's p95 vs the median of
    the others') and cross-host trace joins.
    """
    run_dirs = discover_runs(fleet_dir)
    if not run_dirs:
        raise ValueError(
            f"{fleet_dir}: no run dirs with an events.jsonl underneath")
    hosts = [load_host(d) for d in run_dirs]
    for h in hosts:
        ts = [float(r["t"]) for r in h["records"] if "t" in r]
        h["aligned_start"] = (min(ts) + h["offset"]) if ts else None
        h["aligned_end"] = (max(ts) + h["offset"]) if ts else None
        h["clean_exit"] = any(
            r.get("event") == "run_end" for r in h["records"])
        h["steps"] = _step_stats(h["records"])
        h["heartbeats"] = _heartbeat_stats(h["records"])
    fleet_end = max((h["aligned_end"] for h in hosts
                     if h["aligned_end"] is not None), default=None)
    fleet_start = min((h["aligned_start"] for h in hosts
                       if h["aligned_start"] is not None), default=None)
    for h in hosts:
        for hb in h["heartbeats"].values():
            tail = None
            if hb["last_t"] is not None and fleet_end is not None:
                tail = fleet_end - (hb["last_t"] + h["offset"])
            hb["tail_gap_s"] = round(tail, 3) if tail is not None else None
    # skew table: each stepping host's p95 against the median of the rest
    stepping = [h for h in hosts if h["steps"]]
    skew = []
    for h in stepping:
        others = [o["steps"]["p95_s"] for o in stepping if o is not h]
        ref = _percentile(others, 50.0) if others else None
        skew.append({
            "host_id": h["host_id"],
            "p50_ms": round(h["steps"]["p50_s"] * 1e3, 2),
            "p95_ms": round(h["steps"]["p95_s"] * 1e3, 2),
            "others_p95_ms": round(ref * 1e3, 2) if ref else None,
            "vs_others": (round(h["steps"]["p95_s"] / ref, 2)
                          if ref else None),
        })
    return {
        "fleet_dir": fleet_dir,
        "n_hosts": len(hosts),
        "wall_s": (round(fleet_end - fleet_start, 3)
                   if fleet_end is not None and fleet_start is not None
                   else None),
        "hosts": [{k: v for k, v in h.items() if k != "records"}
                  for h in hosts],
        "skew": skew,
        "cross_host_traces": _cross_host_traces(hosts),
    }


def _verdict(phase: str, verdict: str, evidence: List[str],
             **extra: Any) -> Dict[str, Any]:
    return dict({"phase": phase, "verdict": verdict,
                 "evidence": evidence}, **extra)


def fleet_verdicts(rollup: Dict[str, Any]) -> List[Dict[str, Any]]:
    """STRAGGLER / DEAD_HOST / DESYNC over an :func:`aggregate_fleet`
    rollup; FLEET_OK when nothing fires. Each verdict carries the
    offending ``host`` (machine-checkable attribution) plus evidence
    lines quoting both the host's and the fleet's numbers."""
    verdicts: List[Dict[str, Any]] = []
    dead: set = set()
    # DEAD_HOST first: a dead host's step counter trivially desyncs, so
    # DESYNC must be judged over the survivors only
    for h in rollup["hosts"]:
        if h["clean_exit"]:
            continue  # a clean run_end is an exit, not a death
        for role, hb in sorted(h["heartbeats"].items()):
            if not hb["every_s"]:
                continue
            deadline = DEAD_HOST_GAP_BEATS * hb["every_s"]
            gaps = [g for g in (hb["tail_gap_s"], hb["max_gap_s"])
                    if g is not None]
            worst = max(gaps) if gaps else 0.0
            if worst > deadline:
                dead.add(h["host_id"])
                verdicts.append(_verdict("fleet", "DEAD_HOST", [
                    f"host {h['host_id']} ({role}): last heartbeat "
                    f"{hb['tail_gap_s']}s before the fleet's aligned end "
                    f"(deadline {deadline:.1f}s = "
                    f"{DEAD_HOST_GAP_BEATS:g}x the {hb['every_s']:.1f}s "
                    f"cadence, worst gap {worst:.1f}s)",
                    f"no run_end in its log after {hb['beats']} beat(s) — "
                    f"the process died, it did not exit",
                ], host=h["host_id"]))
                break
    for row in rollup["skew"]:
        if row["vs_others"] is None:
            continue
        steps = next(h["steps"] for h in rollup["hosts"]
                     if h["host_id"] == row["host_id"])
        if steps["n"] - 1 < STRAGGLER_MIN_STEPS:
            continue
        if row["vs_others"] >= STRAGGLER_FACTOR:
            verdicts.append(_verdict("fleet", "STRAGGLER", [
                f"host {row['host_id']}: step p95 {row['p95_ms']}ms = "
                f"{row['vs_others']:.1f}x the other hosts' median p95 "
                f"{row['others_p95_ms']}ms (threshold "
                f"{STRAGGLER_FACTOR:g}x, {steps['n']} steps)",
                "every synchronized collective waits for the slowest "
                "host — fix this one before scaling out",
            ], host=row["host_id"]))
    live = [h for h in rollup["hosts"]
            if h["steps"] and h["host_id"] not in dead]
    if len(live) >= 2:
        lo = min(live, key=lambda h: h["steps"]["step_max"])
        hi = max(live, key=lambda h: h["steps"]["step_max"])
        spread = hi["steps"]["step_max"] - lo["steps"]["step_max"]
        if spread > DESYNC_STEP_MARGIN:
            verdicts.append(_verdict("fleet", "DESYNC", [
                f"live hosts' step counters diverge by {spread}: "
                f"{hi['host_id']} at step {hi['steps']['step_max']} vs "
                f"{lo['host_id']} at step {lo['steps']['step_max']} "
                f"(margin {DESYNC_STEP_MARGIN})",
                "replicas drifting apart means a lost barrier or "
                "divergent data feed — dead hosts are judged separately",
            ], host=lo["host_id"]))
    if not verdicts:
        n = rollup["n_hosts"]
        verdicts.append(_verdict("fleet", "FLEET_OK", [
            f"{n} host(s) aligned: no straggler past "
            f"{STRAGGLER_FACTOR:g}x, no heartbeat gap past "
            f"{DEAD_HOST_GAP_BEATS:g}x cadence, step counters within "
            f"{DESYNC_STEP_MARGIN}",
        ]))
    return verdicts


def diagnose_fleet(fleet_dir: str) -> Dict[str, Any]:
    """The ``cli doctor`` entry for a fleet dir: same report shape as
    obs/doctor.diagnose (``{"run_dir", "verdicts"}``)."""
    return {"run_dir": fleet_dir,
            "verdicts": fleet_verdicts(aggregate_fleet(fleet_dir))}


def build_fleet_timeline(fleet_dir: str,
                         out: Optional[str] = None) -> Dict[str, Any]:
    """One Perfetto timeline for N hosts: a process-group per host (spans
    + an instant-marker track each), every track shifted onto the shared
    aligned clock (zero = the fleet's earliest aligned record)."""
    run_dirs = discover_runs(fleet_dir)
    if not run_dirs:
        raise ValueError(
            f"{fleet_dir}: no run dirs with an events.jsonl underneath")
    from raft_stereo_tpu.obs.timeline import _instant_events, _span_events
    hosts = [load_host(d) for d in run_dirs]
    starts = []
    for h in hosts:
        ts = [float(r["t"]) for r in h["records"] if "t" in r]
        if ts:
            starts.append(min(ts) + h["offset"])
    fleet_t0 = min(starts) if starts else 0.0
    trace_events: List[Dict[str, Any]] = []
    n_spans = 0
    for i, h in enumerate(hosts):
        pid = 10 * (i + 1)  # spans at pid, markers at pid+1, per host
        shift = h["offset"] - fleet_t0
        spans = [r for r in h["records"] if r.get("event") == "span"]
        n_spans += len(spans)
        trace_events.extend(_span_events(
            spans, pid=pid, process_name=f"{h['host_id']} spans",
            shift_s=shift))
        trace_events.extend(_instant_events(
            h["records"], pid=pid + 1,
            process_name=f"{h['host_id']} events", shift_s=shift))
    out = out or os.path.join(fleet_dir, "fleet_timeline.json")
    with open(out, "w") as f:
        json.dump({"traceEvents": trace_events,
                   "displayTimeUnit": "ms"}, f)
    return {"path": out, "hosts": len(hosts), "spans": n_spans,
            "markers": sum(1 for e in trace_events if e.get("ph") == "i")}


def format_rollup(rollup: Dict[str, Any],
                  verdicts: Optional[List[Dict[str, Any]]] = None) -> str:
    lines = [f"fleet: {rollup['fleet_dir']} — {rollup['n_hosts']} host(s)"
             + (f", {rollup['wall_s']}s aligned wall"
                if rollup["wall_s"] is not None else "")]
    for h in rollup["hosts"]:
        bits = [f"  {h['host_id']}:"]
        s = h["steps"]
        if s:
            pps = f", {s['pairs_per_sec']} pairs/s" \
                if s["pairs_per_sec"] else ""
            bits.append(f"{s['n']} steps (p50 {s['p50_s'] * 1e3:.1f}ms, "
                        f"p95 {s['p95_s'] * 1e3:.1f}ms{pps})")
        for role, hb in sorted(h["heartbeats"].items()):
            tail = f", tail gap {hb['tail_gap_s']}s" \
                if hb["tail_gap_s"] is not None else ""
            bits.append(f"{role} beats {hb['beats']} "
                        f"(max gap {hb['max_gap_s']}s{tail})")
        bits.append("clean exit" if h["clean_exit"] else "NO run_end")
        if not h["anchored"]:
            bits.append("[unanchored: ts-derived offset]")
        lines.append(" ".join(bits))
    if rollup["skew"]:
        lines.append("  skew (p95 vs median of other hosts):")
        for row in rollup["skew"]:
            vs = f"{row['vs_others']:.2f}x" if row["vs_others"] else "n/a"
            lines.append(f"    {row['host_id']}: {row['p95_ms']}ms vs "
                         f"{row['others_p95_ms']}ms -> {vs}")
    joins = rollup["cross_host_traces"]
    lines.append(f"  cross-host traces: {len(joins)}")
    for j in joins:
        links = "; ".join(
            f"{l['child']}@{l['child_host']} <- {l['parent_host']}"
            for l in j["remote_links"]) or "no resolved remote parent"
        lines.append(f"    {j['trace_id']}: {j['spans']} spans across "
                     f"{'/'.join(j['hosts'])} ({links})")
    for v in verdicts or []:
        lines.append(f"  [{v['phase']}] {v['verdict']}")
        for e in v["evidence"]:
            lines.append(f"    - {e}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    from raft_stereo_tpu.cli import build_fleet_parser
    args = build_fleet_parser().parse_args(argv)
    try:
        rollup = aggregate_fleet(args.fleet_dir)
        timeline = build_fleet_timeline(args.fleet_dir, out=args.out)
    except (FileNotFoundError, ValueError) as e:
        print(f"fleet: {e}")
        return 1
    verdicts = fleet_verdicts(rollup)
    report = dict(rollup, verdicts=verdicts, timeline=timeline)
    rollup_path = os.path.join(args.fleet_dir, "fleet_rollup.json")
    with open(rollup_path, "w") as f:
        json.dump(report, f, indent=2)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(format_rollup(rollup, verdicts))
        print(f"  rollup: {rollup_path}\n  timeline: {timeline['path']} "
              f"({timeline['hosts']} process-groups, {timeline['spans']} "
              "spans) — load at ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
