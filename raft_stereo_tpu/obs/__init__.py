"""Run-scoped observability: JSONL event bus, stall watchdog, run summarizer.

The three pieces every entry point shares:

* :class:`Telemetry` (obs/telemetry.py) — the event bus; one instance per
  run directory, writing schema-versioned records to
  ``<run_dir>/events.jsonl``;
* the schema + shared sink (obs/events.py) — :func:`append_json_log` is the
  one dated JSON-line-append used by training telemetry, bench.py's attempt
  log and the measurement harnesses;
* the summarizer (obs/summarize.py) — ``python -m raft_stereo_tpu.cli
  telemetry <run_dir>`` merges events.jsonl with a ``jax.profiler`` trace
  into one report.
"""

from raft_stereo_tpu.obs.events import (EVENT_TYPES, SCHEMA_VERSION,
                                        append_json_log, make_record,
                                        read_events, validate_events,
                                        validate_record)
from raft_stereo_tpu.obs.telemetry import Telemetry
from raft_stereo_tpu.obs.summarize import format_summary, summarize_run

__all__ = [
    "EVENT_TYPES", "SCHEMA_VERSION", "append_json_log", "make_record",
    "read_events", "validate_events", "validate_record", "Telemetry",
    "format_summary", "summarize_run",
]
