"""Run-scoped observability: JSONL event bus, stall watchdog, run summarizer.

The three pieces every entry point shares:

* :class:`Telemetry` (obs/telemetry.py) — the event bus; one instance per
  run directory, writing schema-versioned records to
  ``<run_dir>/events.jsonl``;
* the schema + shared sink (obs/events.py) — :func:`append_json_log` is the
  one dated JSON-line-append used by training telemetry, bench.py's attempt
  log and the measurement harnesses;
* the summarizer (obs/summarize.py) — ``python -m raft_stereo_tpu.cli
  telemetry <run_dir>`` merges events.jsonl with a ``jax.profiler`` trace
  into one report;
* compiled-artifact introspection (obs/xla.py) —
  :func:`introspect_compiled` turns every ``lower().compile()`` site's
  memory/cost analyses into ``xla_memory``/``xla_cost`` events;
* the regression gate (obs/compare.py) — ``python -m raft_stereo_tpu.cli
  compare <baseline> <candidate>`` diffs two runs' event logs against
  thresholds and exits non-zero on regression;
* span tracing (obs/trace.py) — :class:`Tracer` rides the event bus with
  schema-v7 ``span`` records (trainer step phases, loader produce legs,
  eval frames, serve request lifecycle); consumed by ``cli timeline``
  (obs/timeline.py), ``cli doctor`` (obs/doctor.py) and the telemetry
  flight recorder;
* the fleet observatory (obs/fleet.py) — schema-v10 host identity on
  every record, ``clock_anchor``/``heartbeat`` events, traceparent-style
  cross-process trace propagation, and ``cli fleet`` merging N per-host
  run dirs into one clock-aligned rollup + Perfetto timeline; ``cli
  doctor`` grows the STRAGGLER/DEAD_HOST/DESYNC fleet verdicts.
"""

from raft_stereo_tpu.obs.events import (EVENT_TYPES, SCHEMA_VERSION,
                                        SUPPORTED_SCHEMA_VERSIONS,
                                        append_json_log, make_record,
                                        read_events, validate_events,
                                        validate_record)
from raft_stereo_tpu.obs.fleet import (HOST_ID_ENV, TRACEPARENT_ENV,
                                       aggregate_fleet, diagnose_fleet,
                                       format_traceparent, parse_traceparent,
                                       resolve_host_id)
from raft_stereo_tpu.obs.telemetry import Telemetry
from raft_stereo_tpu.obs.trace import (NULL_TRACER, Span, Tracer,
                                       tracer_for)
from raft_stereo_tpu.obs.validate import check_path, check_paths
from raft_stereo_tpu.obs.summarize import format_summary, summarize_run
from raft_stereo_tpu.obs.xla import (compact_xla_summary,
                                     introspect_compiled)
from raft_stereo_tpu.obs.compare import compare_runs

__all__ = [
    "EVENT_TYPES", "SCHEMA_VERSION", "SUPPORTED_SCHEMA_VERSIONS",
    "append_json_log", "make_record", "read_events", "validate_events",
    "validate_record", "check_path", "check_paths", "Telemetry",
    "NULL_TRACER", "Span", "Tracer", "tracer_for",
    "HOST_ID_ENV", "TRACEPARENT_ENV", "aggregate_fleet", "diagnose_fleet",
    "format_traceparent", "parse_traceparent", "resolve_host_id",
    "format_summary", "summarize_run",
    "introspect_compiled", "compact_xla_summary", "compare_runs",
]
