"""Full training state (params + frozen batch stats + optimizer + step).

Unlike the reference, which checkpoints weights only and restarts the schedule
on resume (train_stereo.py:184-186; SURVEY §5 checkpoint row), the state here
carries everything needed for exact resume.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import optax
from flax import struct

from raft_stereo_tpu.training.loss import (loss_mask, sequence_loss,
                                           sequence_loss_fused)


class TrainState(struct.PyTreeNode):
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, variables: Dict, tx: optax.GradientTransformation):
        params = variables["params"]
        return cls(params=params,
                   batch_stats=variables.get("batch_stats", {}),
                   opt_state=tx.init(params),
                   step=jax.numpy.zeros((), jax.numpy.int32))

    @property
    def variables(self):
        return {"params": self.params, "batch_stats": self.batch_stats}


def make_train_step(model, tx: optax.GradientTransformation, train_iters: int,
                    axis_name=None, fused_loss: bool = False,
                    anomaly_guard: bool = True, numerics: bool = False):
    """Build the jittable training step.

    ``batch``: dict with ``image1``/``image2`` ``(B,H,W,3)`` float images,
    ``flow`` ``(B,H,W,1)``, ``valid`` ``(B,H,W)``. When ``axis_name`` is given
    (shard_map data parallelism) gradients and metrics are ``psum``-reduced
    over the mesh axis.

    ``fused_loss`` switches to the in-scan reduced loss (the model sums each
    iteration's masked L1 inside its refinement scan instead of stacking the
    full-resolution predictions) — same math, different HBM profile; the
    stacked default measured faster under full remat.

    ``anomaly_guard`` (the device-side half of the fault-tolerance story,
    training/resilience.py): a ``lax.cond`` on the finiteness of the global
    gradient norm AND the loss skips the optimizer update entirely — params
    and optimizer state pass through untouched — so one NaN/Inf batch
    cannot poison the remaining 100k steps of the schedule. The predicate
    is computed on device and never concretized on the host (graftlint's
    ``host-sync``/``tracer-unsafe`` rules stay green over this path; the
    naive ``float(grad_norm)``-per-step alternative is the seeded-violation
    fixture in tests/test_resilience.py). The step counter still advances
    on a skipped update — it counts consumed batches, which is what the
    loader's exact-resume repositioning needs. Metrics gain ``grad_norm``
    and ``skipped_updates`` (0/1 this step); the host-side
    :class:`~raft_stereo_tpu.training.resilience.AnomalyPolicy` reads them
    off the lagged metrics fetch and halts after M consecutive skips.
    Under ``shard_map`` the predicate is computed from the psum'd gradients
    and loss, so every device takes the same branch.

    ``numerics`` (the numerics observatory, obs/numerics.py): metrics gain
    ``leaf_grad_norms`` — one L2 norm per parameter leaf, in
    ``jax.tree.leaves`` order (``grad_leaf_names`` recovers the labels),
    computed as one fused square-sum reduction per leaf with a single
    vectorized sqrt at the end. Same no-host-sync, ``lax.cond``-free
    discipline as the guard: the vector stays on device until the lagged
    metrics fetch, where the trainer cadence-samples it into ``numerics``
    events and hands the top offenders to the ``anomaly`` attribution.
    Off (the default) adds zero operations — the program is byte-identical
    to the unobserved step.
    """
    import jax.numpy as jnp

    def train_step(state: TrainState, batch):
        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            if fused_loss:
                mask = loss_mask(batch["flow"], batch["valid"])
                err_sums, final_flow = model.apply(
                    variables, batch["image1"], batch["image2"],
                    iters=train_iters, flow_gt=batch["flow"],
                    loss_mask=mask)
                return sequence_loss_fused(err_sums, final_flow,
                                           batch["flow"], mask,
                                           axis_name=axis_name)
            preds = model.apply(
                variables, batch["image1"], batch["image2"],
                iters=train_iters)
            return sequence_loss(preds, batch["flow"], batch["valid"],
                                 axis_name=axis_name)

        (loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        if axis_name is not None:
            grads = jax.lax.psum(grads, axis_name)
        if numerics:
            # per-leaf L2 norms: one fused sum-of-squares per leaf, one
            # vectorized sqrt over the stacked vector — NaN/Inf propagate
            # into the affected slot (that IS the provenance signal)
            leaf_sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
                       for g in jax.tree_util.tree_leaves(grads)]
            leaf_grad_norms = jnp.sqrt(jnp.stack(leaf_sq))
        if anomaly_guard:
            grad_norm = optax.global_norm(grads)
            finite = jnp.isfinite(grad_norm) & jnp.isfinite(loss)

            def _apply(operand):
                g, opt_state_, params_ = operand
                updates, new_opt = tx.update(g, opt_state_, params_)
                return optax.apply_updates(params_, updates), new_opt

            def _skip(operand):
                _g, opt_state_, params_ = operand
                return params_, opt_state_

            params, opt_state = jax.lax.cond(
                finite, _apply, _skip,
                (grads, state.opt_state, state.params))
            metrics = dict(metrics, loss=loss, grad_norm=grad_norm,
                           skipped_updates=1.0
                           - finite.astype(jnp.float32))
        else:
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            metrics = dict(metrics, loss=loss)
        if numerics:
            metrics = dict(metrics, leaf_grad_norms=leaf_grad_norms)
        new_state = state.replace(params=params, opt_state=opt_state,
                                  step=state.step + 1)
        return new_state, metrics

    return train_step
