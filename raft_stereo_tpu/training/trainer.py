"""The training loop (train_stereo.py:132-211, rebuilt for the JAX stack).

One function, :func:`train`, wires together: device mesh + sharded train step
(:mod:`raft_stereo_tpu.parallel`), the deterministic threaded loader, the
OneCycle/AdamW optimizer, step-windowed logging, periodic full-state
checkpoints, and the validate-on-Things hook every ``validation_frequency``
steps (train_stereo.py:183-190). Differences from the reference, by design:

* full-state checkpoints (exact resume, incl. schedule position) via orbax;
  ``--restore_ckpt`` also accepts reference ``.pth`` files (weights-only)
  and the literal ``auto`` (resume from the newest manifest-valid
  checkpoint — training/resilience.py),
* no GradScaler: bf16 needs no loss scaling; grad-clip 1.0 is kept,
* BatchNorm is frozen structurally (nn/layers.py) — no ``freeze_bn`` dance.

Fault tolerance (the r11 layer; proven by scripts/fault_drill.py):

* checkpoints are atomic (temp dir + fsync + rename, integrity manifest)
  and decoupled from validation via ``cfg.checkpoint_frequency``;
* SIGTERM/SIGINT trigger a save-and-exit path (``preempt`` event + a
  checkpoint with ``reason="preempt"``) instead of losing the work since
  the last periodic save; a crash (the ``except BaseException`` path)
  writes a best-effort emergency checkpoint, skipped with a logged warning
  when the state is non-finite;
* the train step's device-side anomaly guard (training/state.py) skips the
  optimizer update on non-finite grad-norm/loss without host sync; the
  host-side :class:`~raft_stereo_tpu.training.resilience.AnomalyPolicy`
  reads ``skipped_updates`` off the lagged metrics fetch and halts (for
  rollback to the last durable checkpoint) after M consecutive skips.

Step telemetry is emitted on the SAME one-step lag as the metrics fetch:
the ``step`` event for step *i* lands while step *i+1* runs on device and
carries ``loss``/``grad_norm``/``skipped_updates`` — so a run's event
stream is a replayable record of its loss trajectory (what the fault
drill's oracle comparison diffs), without adding a host sync per step.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Optional

import jax
import numpy as np

import dataclasses

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.data.datasets import fetch_dataloader
from raft_stereo_tpu.data.loader import infinite_batches
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.obs import Telemetry
from raft_stereo_tpu.obs.trace import tracer_for
from raft_stereo_tpu.parallel.data_parallel import make_pjit_train_step
from raft_stereo_tpu.parallel.mesh import make_mesh, replicated, shard_batch
from raft_stereo_tpu.training import resilience
from raft_stereo_tpu.training.checkpoint import (restore_train_state,
                                                 save_train_state)
from raft_stereo_tpu.training.logger import Logger
from raft_stereo_tpu.training.optim import fetch_optimizer, fetch_schedule
from raft_stereo_tpu.training.state import TrainState

logger = logging.getLogger(__name__)


def _restore(path: str, state: TrainState, model_cfg: RAFTStereoConfig,
             variables) -> TrainState:
    """Restore either a full orbax state dir or a reference .pth (weights)."""
    if path.endswith((".pth", ".pth.gz")):
        from raft_stereo_tpu.utils.checkpoint_convert import (
            load_reference_checkpoint, validate_against_variables)
        converted = load_reference_checkpoint(path)
        converted = validate_against_variables(converted, variables)
        logger.info("restored reference weights from %s", path)
        return state.replace(params=converted["params"],
                             batch_stats=converted["batch_stats"])
    restored = restore_train_state(path, jax.device_get(state))
    logger.info("restored full train state from %s (step %s)",
                path, int(restored.step))
    return restored


def _compile_step_introspected(step_fn, state, placed, tel):
    """AOT-compile the train step and record its XLA memory/cost analyses.

    ``lower().compile()`` builds the SAME executable (and persistent-cache
    key) the first jitted dispatch would, but hands back the compiled
    object, whose ``memory_analysis()``/``cost_analysis()`` become
    ``xla_memory``/``xla_cost`` events — peak-HBM headroom and flops/byte
    are on the run record before the first step executes. An ``op_counts``
    event (conv placement: per-scan-body vs outside — the refinement
    backward's structure, obs/xla.py) rides along so a run permanently
    records WHICH scan backward it trained with. Fail-open: any
    AOT/introspection failure falls back to the plain jitted callable (one
    logged warning), because observability must never take down the run.
    """
    try:
        compiled = step_fn.lower(state, placed).compile()
        from raft_stereo_tpu.obs.xla import introspect_compiled
        introspect_compiled(compiled, tel, source="train_step")
    except Exception:
        logger.warning("AOT step introspection failed; falling back to "
                       "jit dispatch", exc_info=True)
        return step_fn
    try:
        from raft_stereo_tpu.obs.xla import conv_op_profile, emit_op_counts
        emit_op_counts(conv_op_profile(jax.make_jaxpr(step_fn)(state, placed)),
                       tel, source="train_step")
    except Exception:
        logger.warning("op-count introspection failed (continuing)",
                       exc_info=True)
    return compiled


def _emergency_checkpoint(exc: BaseException, state, cfg: TrainConfig,
                          tel, global_step: int,
                          run_digest: Optional[str]) -> Optional[str]:
    """Best-effort crash-path checkpoint (the ``except BaseException``
    satellite): save the in-flight state with ``reason="crash"`` so a
    crash costs zero steps — UNLESS the state is non-finite (warn + emit
    ``anomaly kind=nonfinite_state``; the rollback target is then the
    last periodic checkpoint) or the exception is an
    :class:`~raft_stereo_tpu.training.resilience.AnomalyHalt` (which
    rolls back *by design* — saving would defeat it). Never raises."""
    if isinstance(exc, resilience.AnomalyHalt):
        return None
    try:
        if resilience.state_is_finite(state):
            path = save_train_state(
                cfg.ckpt_dir, cfg.name, state, step=global_step,
                config_digest=run_digest, reason="crash")
            logger.warning("emergency checkpoint after %s: %s",
                           type(exc).__name__, path)
            tel.checkpoint(global_step, path, reason="crash")
            return path
        logger.warning(
            "NOT saving emergency checkpoint: state is non-finite "
            "(resume from the last periodic checkpoint instead)")
        tel.emit("anomaly", kind="nonfinite_state", step=global_step)
    except Exception:
        logger.warning("emergency checkpoint failed", exc_info=True)
    return None


def train(model_cfg: RAFTStereoConfig, cfg: TrainConfig,
          validate_every: Optional[int] = None) -> str:
    """Run training to ``cfg.num_steps``; returns the final checkpoint path
    (on preemption: the preempt checkpoint's path)."""
    validation_frequency = validate_every or cfg.validation_frequency
    ckpt_frequency = cfg.checkpoint_frequency or validation_frequency
    os.makedirs(cfg.ckpt_dir, exist_ok=True)

    mesh = make_mesh(cfg.data_parallel, cfg.seq_parallel)
    n_dev = mesh.devices.size
    if cfg.batch_size % max(mesh.shape["data"], 1):
        raise ValueError(f"batch_size {cfg.batch_size} not divisible by "
                         f"data-parallel size {mesh.shape['data']}")
    logger.info("mesh: %s devices (%s)", n_dev, dict(mesh.shape))

    h, w = cfg.image_size
    model, variables = init_model(jax.random.PRNGKey(cfg.seed), model_cfg,
                                  (1, h, w, 3))
    n_params = sum(np.prod(p.shape) for p in jax.tree.leaves(
        variables["params"]))
    logger.info("parameter count: %d", n_params)

    tx = fetch_optimizer(cfg)
    state = TrainState.create(variables, tx)
    # the run-identity stamp: clobber protection + auto-resume filtering
    run_digest = resilience.config_digest(model_cfg, cfg)
    integrity_reports = []
    resume_from = None
    if cfg.restore_ckpt == "auto":
        best, integrity_reports = resilience.find_latest_valid(
            cfg.ckpt_dir, cfg.name, config_digest=run_digest,
            tree_hash=resilience.tree_structure_hash(jax.device_get(state)))
        if best is not None:
            state = _restore(best, state, model_cfg, variables)
            resume_from = best
        else:
            logger.info("--restore_ckpt auto: no valid checkpoint for %r "
                        "under %s; starting fresh", cfg.name, cfg.ckpt_dir)
    elif cfg.restore_ckpt:
        state = _restore(cfg.restore_ckpt, state, model_cfg, variables)
        resume_from = cfg.restore_ckpt

    loader = fetch_dataloader(cfg)
    accum_k = max(cfg.grad_accum_steps, 1)
    if int(state.step):
        # reposition the data stream to the restored step EXACTLY: epoch via
        # integer division, intra-epoch position via Loader.start_batch (the
        # Philox-keyed stream makes the skip bit-reproducible — a resumed run
        # sees the same remaining batches as one that never stopped)
        loader.epoch = int(state.step) // max(len(loader), 1)
        loader.start_batch = int(state.step) % max(len(loader), 1)
    # the exact schedule fetch_optimizer applies (shared, cannot desync)
    schedule = fetch_schedule(cfg)

    run_dir = os.path.join(cfg.run_dir, cfg.name)
    tel = Telemetry(run_dir, run_name=cfg.name,
                    stall_deadline_s=cfg.stall_deadline_s,
                    host_id=cfg.host_id, fleet=cfg.fleet)
    tel.run_start(config={"model": dataclasses.asdict(model_cfg),
                          "train": dataclasses.asdict(cfg)},
                  n_params=int(n_params), resumed_step=int(state.step),
                  config_digest=run_digest)
    for report in integrity_reports:
        tel.emit("ckpt_integrity", **report)
    if resume_from is not None:
        tel.emit("resume", step=int(state.step), path=resume_from)
    # span tracing (obs/trace.py): the step loop's existing perf_counter
    # stamps become step/data_wait/dispatch/fetch spans; cfg.trace=False
    # yields the null tracer and an events.jsonl with no span records.
    tracer = tracer_for(tel, enabled=cfg.trace)
    loader.gauge_hook = tel.loader_gauge
    loader.quarantine_hook = lambda info: tel.emit(
        "anomaly", kind="loader_quarantine", **info)
    loader.tracer = tracer
    policy = resilience.AnomalyPolicy(
        cfg.anomaly_max_skips if cfg.anomaly_guard else 0, telemetry=tel)
    nan_step = resilience.injected_nan_step()
    fault_sleep_s = resilience.injected_sleep_s()
    # fleet liveness: heartbeat records on cadence from a daemon thread
    # (no-op when fleet stamping is off or the cadence is 0)
    tel.start_heartbeat("trainer", cfg.heartbeat_every_s)
    # numerics observatory (obs/numerics.py): leaf names are recovered
    # once — same flatten order as the in-step per-leaf norm vector
    if cfg.numerics:
        from raft_stereo_tpu.obs import numerics as obs_numerics
        leaf_names = obs_numerics.grad_leaf_names(variables["params"])
    else:
        obs_numerics, leaf_names = None, None

    with mesh:
        state = jax.device_put(state, replicated(mesh))
        step_fn = make_pjit_train_step(model, tx, cfg.train_iters, mesh,
                                       anomaly_guard=cfg.anomaly_guard,
                                       numerics=cfg.numerics)

        # console/TB logging rides the run dir telemetry owns; write_dict
        # mirrors validation results onto the event bus
        log = Logger(log_dir=run_dir, total_steps=int(state.step),
                     telemetry=tel)
        validation_predictor = None  # built lazily, reused across validations
        global_step = start_step = int(state.step)
        # lagged metrics fetch: (step, metrics, timing) for step i is
        # synced — and its `step` event emitted — while step i+1 runs
        pending = None
        batches = infinite_batches(loader)
        step_impl = None  # AOT-compiled on the first batch (shapes known)
        preempted = False

        def flush_pending():
            nonlocal pending
            if pending is None:
                return
            step_i, metrics, timing = pending
            pending = None
            metrics = dict(metrics)
            # the per-leaf norm vector is NOT a logging scalar: pop it
            # before the float() sweep, cadence-sample it onto the bus
            leaf_norms = metrics.pop("leaf_grad_norms", None)
            vals = {k: float(v) for k, v in metrics.items()}
            top = None
            if leaf_norms is not None:
                norms = np.asarray(leaf_norms)
                top = obs_numerics.top_leaves(leaf_names, norms)
                # a poisoned vector always emits — cadence must never
                # hide the step that carries the provenance
                if (step_i % max(cfg.numerics_every, 1) == 0
                        or not np.all(np.isfinite(norms))):
                    obs_numerics.emit(tel, obs_numerics.grad_payload(
                        step_i, leaf_names, norms))
            log.push(vals, lr=float(schedule((step_i - 1) // accum_k)))
            extras = {k: vals[k]
                      for k in ("loss", "grad_norm", "skipped_updates")
                      if k in vals}
            tel.step(step_i, batch_size=cfg.batch_size, **timing, **extras)
            policy.observe(bool(vals.get("skipped_updates", 0.0)), step_i,
                           grad_norm=vals.get("grad_norm"), top_leaves=top)

        with resilience.SignalGuard() as guard:
            try:
                while global_step < cfg.num_steps:
                    if guard.requested:
                        preempted = True
                        break
                    t0 = time.perf_counter()
                    batch = next(batches)
                    t1 = time.perf_counter()
                    if fault_sleep_s is not None:
                        # scripts/fleet_drill.py's straggler hook: stretch
                        # this host's dispatch leg so the fleet rollup has
                        # a deterministic STRAGGLER to attribute
                        time.sleep(fault_sleep_s)
                    if nan_step is not None and global_step + 1 == nan_step:
                        # scripts/fault_drill.py's injection hook: prove the
                        # device guard survives a poisoned batch
                        logger.warning("fault injection: NaN batch at "
                                       "step %d", nan_step)
                        batch = dict(batch, image1=np.full_like(
                            batch["image1"], np.nan))
                    placed = shard_batch(mesh, batch)
                    if step_impl is None:
                        step_impl = _compile_step_introspected(
                            step_fn, state, placed, tel)
                    state, metrics = step_impl(state, placed)
                    t2 = time.perf_counter()
                    flush_pending()  # sync step i-1 while step i runs
                    t3 = time.perf_counter()
                    pending = (global_step + 1, metrics,
                               {"data_wait_s": t1 - t0,
                                "dispatch_s": t2 - t1,
                                "fetch_s": t3 - t2})
                    # retroactive spans from the stamps just taken: the
                    # t0..t3 legs tile the step root exactly (100% child
                    # coverage for cli timeline / cli doctor)
                    root = tracer.record("step", t0, t3,
                                         step=global_step + 1)
                    tracer.record("data_wait", t0, t1, parent=root)
                    tracer.record("dispatch", t1, t2, parent=root)
                    tracer.record("fetch", t2, t3, parent=root)
                    global_step += 1
                    if global_step == start_step + 1:
                        # first-call latency: the pjit dispatch above compiled
                        # synchronously (remote-helper time included —
                        # invisible to the jax.monitoring compile hook)
                        tel.emit("compile", duration_s=round(t2 - t1, 3),
                                 source="first_step_latency")

                    do_ckpt = global_step % ckpt_frequency == 0
                    do_val = global_step % validation_frequency == 0
                    if do_ckpt or do_val or guard.requested:
                        # flush the in-flight metrics first so validation
                        # scalars and the checkpoint agree on the step axis
                        flush_pending()
                    if guard.requested:
                        preempted = True
                        break
                    if do_ckpt:
                        ckpt = save_train_state(
                            cfg.ckpt_dir, cfg.name, state, step=global_step,
                            config_digest=run_digest,
                            keep_last=cfg.ckpt_keep_last,
                            keep_every=cfg.ckpt_keep_every)
                        logger.info("saved %s", ckpt)
                        tel.checkpoint(global_step, ckpt)
                    if do_val:
                        variables_host = jax.device_get(state.variables)
                        if validation_predictor is None:
                            from raft_stereo_tpu.inference import (
                                StereoPredictor)
                            validation_predictor = StereoPredictor(
                                model_cfg, variables_host,
                                valid_iters=cfg.valid_iters)
                        else:  # keep the jit cache, refresh only the weights
                            validation_predictor.variables = variables_host
                        results = _maybe_validate_things(
                            validation_predictor, cfg)
                        if results:
                            log.write_dict(results)
                        pps = tel.window_throughput()
                        if pps is not None:
                            logger.info("throughput: %.2f pairs/sec over "
                                        "last window", pps)

                flush_pending()
                if preempted:
                    final = save_train_state(
                        cfg.ckpt_dir, cfg.name, state, step=global_step,
                        config_digest=run_digest,
                        keep_last=cfg.ckpt_keep_last,
                        keep_every=cfg.ckpt_keep_every, reason="preempt")
                    logger.warning(
                        "preempted by %s at step %d: saved %s — resume "
                        "with --restore_ckpt auto", guard.signame,
                        global_step, final)
                    tel.emit("preempt", signal=guard.signame,
                             step=global_step)
                    tel.checkpoint(global_step, final, reason="preempt")
                else:
                    final = save_train_state(
                        cfg.ckpt_dir, cfg.name, state,
                        config_digest=run_digest, reason="final")
                    tel.checkpoint(global_step, final, reason="final")
            except BaseException as e:
                tel.error(e)  # also fires the flight recorder ("crash")
                _emergency_checkpoint(e, state, cfg, tel, global_step,
                                      run_digest)
                tracer.close()  # flush spans before run_end
                tel.emit("run_end", steps=global_step - start_step,
                         ok=False, step=global_step)
                tel.close()
                raise
            finally:
                log.close()
    tel.window_throughput()
    tracer.close()  # flush spans before run_end
    tel.emit("run_end", steps=global_step - start_step, ok=True,
             step=global_step,
             **({"reason": "preempt"} if preempted else {}))
    tel.close()
    logger.info("training done: %s (telemetry: %s)", final, tel.events_path)
    return final


def _maybe_validate_things(predictor, cfg: TrainConfig) -> Dict[str, float]:
    """validate-on-Things hook (train_stereo.py:188); skipped when the
    FlyingThings TEST data is not on disk."""
    import os.path as osp
    if not osp.isdir(osp.join(cfg.data_root, "FlyingThings3D")):
        logger.info("FlyingThings3D not found under %s; skipping validation",
                    cfg.data_root)
        return {}
    from raft_stereo_tpu.eval.validate import validate_things
    try:
        return validate_things(predictor, root=cfg.data_root,
                               iters=cfg.valid_iters)
    except ValueError as e:  # e.g. TEST split not downloaded
        logger.info("skipping validation: %s", e)
        return {}
