"""The training loop (train_stereo.py:132-211, rebuilt for the JAX stack).

One function, :func:`train`, wires together: device mesh + sharded train step
(:mod:`raft_stereo_tpu.parallel`), the deterministic threaded loader, the
OneCycle/AdamW optimizer, step-windowed logging, periodic full-state
checkpoints, and the validate-on-Things hook every ``validation_frequency``
steps (train_stereo.py:183-190). Differences from the reference, by design:

* full-state checkpoints (exact resume, incl. schedule position) via orbax;
  ``--restore_ckpt`` also accepts reference ``.pth`` files (weights-only),
* no GradScaler: bf16 needs no loss scaling; grad-clip 1.0 is kept,
* BatchNorm is frozen structurally (nn/layers.py) — no ``freeze_bn`` dance.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Optional

import jax
import numpy as np

import dataclasses

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.data.datasets import fetch_dataloader
from raft_stereo_tpu.data.loader import infinite_batches
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.obs import Telemetry
from raft_stereo_tpu.parallel.data_parallel import make_pjit_train_step
from raft_stereo_tpu.parallel.mesh import make_mesh, replicated, shard_batch
from raft_stereo_tpu.training.checkpoint import (restore_train_state,
                                                 save_train_state)
from raft_stereo_tpu.training.logger import Logger
from raft_stereo_tpu.training.optim import fetch_optimizer, fetch_schedule
from raft_stereo_tpu.training.state import TrainState

logger = logging.getLogger(__name__)


def _restore(path: str, state: TrainState, model_cfg: RAFTStereoConfig,
             variables) -> TrainState:
    """Restore either a full orbax state dir or a reference .pth (weights)."""
    if path.endswith((".pth", ".pth.gz")):
        from raft_stereo_tpu.utils.checkpoint_convert import (
            load_reference_checkpoint, validate_against_variables)
        converted = load_reference_checkpoint(path)
        converted = validate_against_variables(converted, variables)
        logger.info("restored reference weights from %s", path)
        return state.replace(params=converted["params"],
                             batch_stats=converted["batch_stats"])
    restored = restore_train_state(path, jax.device_get(state))
    logger.info("restored full train state from %s (step %s)",
                path, int(restored.step))
    return restored


def _compile_step_introspected(step_fn, state, placed, tel):
    """AOT-compile the train step and record its XLA memory/cost analyses.

    ``lower().compile()`` builds the SAME executable (and persistent-cache
    key) the first jitted dispatch would, but hands back the compiled
    object, whose ``memory_analysis()``/``cost_analysis()`` become
    ``xla_memory``/``xla_cost`` events — peak-HBM headroom and flops/byte
    are on the run record before the first step executes. An ``op_counts``
    event (conv placement: per-scan-body vs outside — the refinement
    backward's structure, obs/xla.py) rides along so a run permanently
    records WHICH scan backward it trained with. Fail-open: any
    AOT/introspection failure falls back to the plain jitted callable (one
    logged warning), because observability must never take down the run.
    """
    try:
        compiled = step_fn.lower(state, placed).compile()
        from raft_stereo_tpu.obs.xla import introspect_compiled
        introspect_compiled(compiled, tel, source="train_step")
    except Exception:
        logger.warning("AOT step introspection failed; falling back to "
                       "jit dispatch", exc_info=True)
        return step_fn
    try:
        from raft_stereo_tpu.obs.xla import conv_op_profile, emit_op_counts
        emit_op_counts(conv_op_profile(jax.make_jaxpr(step_fn)(state, placed)),
                       tel, source="train_step")
    except Exception:
        logger.warning("op-count introspection failed (continuing)",
                       exc_info=True)
    return compiled


def train(model_cfg: RAFTStereoConfig, cfg: TrainConfig,
          validate_every: Optional[int] = None) -> str:
    """Run training to ``cfg.num_steps``; returns the final checkpoint path."""
    validation_frequency = validate_every or cfg.validation_frequency
    os.makedirs(cfg.ckpt_dir, exist_ok=True)

    mesh = make_mesh(cfg.data_parallel, cfg.seq_parallel)
    n_dev = mesh.devices.size
    if cfg.batch_size % max(mesh.shape["data"], 1):
        raise ValueError(f"batch_size {cfg.batch_size} not divisible by "
                         f"data-parallel size {mesh.shape['data']}")
    logger.info("mesh: %s devices (%s)", n_dev, dict(mesh.shape))

    h, w = cfg.image_size
    model, variables = init_model(jax.random.PRNGKey(cfg.seed), model_cfg,
                                  (1, h, w, 3))
    n_params = sum(np.prod(p.shape) for p in jax.tree.leaves(
        variables["params"]))
    logger.info("parameter count: %d", n_params)

    tx = fetch_optimizer(cfg)
    state = TrainState.create(variables, tx)
    if cfg.restore_ckpt:
        state = _restore(cfg.restore_ckpt, state, model_cfg, variables)

    loader = fetch_dataloader(cfg)
    accum_k = max(cfg.grad_accum_steps, 1)
    if int(state.step):
        # reposition the data stream to the restored step EXACTLY: epoch via
        # integer division, intra-epoch position via Loader.start_batch (the
        # Philox-keyed stream makes the skip bit-reproducible — a resumed run
        # sees the same remaining batches as one that never stopped)
        loader.epoch = int(state.step) // max(len(loader), 1)
        loader.start_batch = int(state.step) % max(len(loader), 1)
    # the exact schedule fetch_optimizer applies (shared, cannot desync)
    schedule = fetch_schedule(cfg)

    run_dir = os.path.join(cfg.run_dir, cfg.name)
    tel = Telemetry(run_dir, run_name=cfg.name,
                    stall_deadline_s=cfg.stall_deadline_s)
    tel.run_start(config={"model": dataclasses.asdict(model_cfg),
                          "train": dataclasses.asdict(cfg)},
                  n_params=int(n_params), resumed_step=int(state.step))
    loader.gauge_hook = tel.loader_gauge

    with mesh:
        state = jax.device_put(state, replicated(mesh))
        step_fn = make_pjit_train_step(model, tx, cfg.train_iters, mesh)

        # console/TB logging rides the run dir telemetry owns; write_dict
        # mirrors validation results onto the event bus
        log = Logger(log_dir=run_dir, total_steps=int(state.step),
                     telemetry=tel)
        validation_predictor = None  # built lazily, reused across validations
        global_step = start_step = int(state.step)
        pending = None  # lagged metrics fetch: sync step i-1 while i runs
        batches = infinite_batches(loader)
        step_impl = None  # AOT-compiled on the first batch (shapes known)
        try:
            while global_step < cfg.num_steps:
                t0 = time.perf_counter()
                batch = next(batches)
                t1 = time.perf_counter()
                placed = shard_batch(mesh, batch)
                if step_impl is None:
                    step_impl = _compile_step_introspected(
                        step_fn, state, placed, tel)
                state, metrics = step_impl(state, placed)
                t2 = time.perf_counter()
                if pending is not None:
                    log.push({k: float(v) for k, v in pending.items()},
                             lr=float(schedule((global_step - 1) // accum_k)))
                t3 = time.perf_counter()
                pending = metrics
                global_step += 1
                if global_step == start_step + 1:
                    # first-call latency: the pjit dispatch above compiled
                    # synchronously (remote-helper time included — invisible
                    # to the jax.monitoring compile hook)
                    tel.emit("compile", duration_s=round(t2 - t1, 3),
                             source="first_step_latency")
                tel.step(global_step, data_wait_s=t1 - t0,
                         dispatch_s=t2 - t1, fetch_s=t3 - t2,
                         batch_size=cfg.batch_size)

                if global_step % validation_frequency == 0:
                    # flush the in-flight metrics first so validation scalars
                    # and the checkpoint agree on the step axis
                    if pending is not None:
                        log.push(
                            {k: float(v) for k, v in pending.items()},
                            lr=float(schedule((global_step - 1) // accum_k)))
                        pending = None
                    ckpt = save_train_state(cfg.ckpt_dir, cfg.name, state,
                                            step=global_step)
                    logger.info("saved %s", ckpt)
                    tel.checkpoint(global_step, ckpt)
                    variables_host = jax.device_get(state.variables)
                    if validation_predictor is None:
                        from raft_stereo_tpu.inference import StereoPredictor
                        validation_predictor = StereoPredictor(
                            model_cfg, variables_host,
                            valid_iters=cfg.valid_iters)
                    else:  # keep the jit cache, refresh only the weights
                        validation_predictor.variables = variables_host
                    results = _maybe_validate_things(validation_predictor, cfg)
                    if results:
                        log.write_dict(results)
                    pps = tel.window_throughput()
                    if pps is not None:
                        logger.info(
                            "throughput: %.2f pairs/sec over last window", pps)

            if pending is not None:
                log.push({k: float(v) for k, v in pending.items()},
                         lr=float(schedule((global_step - 1) // accum_k)))
            final = save_train_state(cfg.ckpt_dir, cfg.name, state)
            tel.checkpoint(global_step, final)
        except BaseException as e:
            tel.error(e)
            tel.emit("run_end", steps=global_step - start_step, ok=False,
                     step=global_step)
            tel.close()
            raise
        finally:
            log.close()
    tel.window_throughput()
    tel.emit("run_end", steps=global_step - start_step, ok=True,
             step=global_step)
    tel.close()
    logger.info("training done: %s (telemetry: %s)", final, tel.events_path)
    return final


def _maybe_validate_things(predictor, cfg: TrainConfig) -> Dict[str, float]:
    """validate-on-Things hook (train_stereo.py:188); skipped when the
    FlyingThings TEST data is not on disk."""
    import os.path as osp
    if not osp.isdir(osp.join(cfg.data_root, "FlyingThings3D")):
        logger.info("FlyingThings3D not found under %s; skipping validation",
                    cfg.data_root)
        return {}
    from raft_stereo_tpu.eval.validate import validate_things
    try:
        return validate_things(predictor, root=cfg.data_root,
                               iters=cfg.valid_iters)
    except ValueError as e:  # e.g. TEST split not downloaded
        logger.info("skipping validation: %s", e)
        return {}
