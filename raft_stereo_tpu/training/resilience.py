"""Fault tolerance for the 100k+-step schedule: atomic checkpoints with
integrity manifests, auto-resume, preemption signals, and the host side of
the anomaly guard.

RAFT-Stereo's published recipes are 100k-200k step runs (PAPER.md; the same
one-cycle schedule as RAFT, arXiv 2003.12039). On preemptible TPU pods such
a run *will* be killed — and before this module the exact-resume story was a
docstring claim: checkpoints were non-atomic ``force=True`` overwrites (a
kill mid-save leaves a half-written dir that poisons the next restore), a
crash lost up to ``validation_frequency`` steps, and nothing verified that a
checkpoint on disk was actually restorable. The protocol here makes the
claim mechanical:

* **Atomic writes** — the state is saved into a hidden temp dir *next to*
  the final path, a ``MANIFEST.json`` (step, config digest, pytree-structure
  hash, per-file size+crc32) is written beside it, everything is fsynced,
  and one ``os.rename`` publishes the checkpoint. A reader can never observe
  a partially written checkpoint under its final name.
* **Integrity verification** — :func:`verify_checkpoint` re-walks the files
  against the manifest (existence, size, crc32) and checks the digest/
  structure hashes, so ``--restore_ckpt auto`` (:func:`find_latest_valid`)
  resumes from the newest checkpoint that is actually *valid*, skipping
  truncated/corrupt/foreign ones with a recorded reason
  (``ckpt_integrity`` events, obs/events.py schema v5).
* **Retention** — keep the last K step checkpoints plus every one whose
  step is a multiple of N (:func:`apply_retention`); the final stepless
  checkpoint and ``.bak`` rotations are never swept.
* **Clobber protection** — a new run named like an old one no longer
  destroys the old run's checkpoint: a mismatched (or missing) config
  digest rotates the existing target to ``<name>.bak`` instead of deleting
  it (the satellite fix for the old ``force=True`` overwrite).
* **Preemption** — :class:`SignalGuard` converts SIGTERM/SIGINT into a
  cooperative "save and exit" flag the trainer polls once per step; the
  drill (scripts/fault_drill.py) proves the resulting resume is bitwise
  identical to an uninterrupted run.
* **Anomaly policy** — the device-side guard (training/state.py) skips the
  optimizer update on a non-finite global grad norm/loss without any host
  sync; :class:`AnomalyPolicy` is the host half: it counts *consecutive*
  skipped updates from the step metrics and halts the run
  (:class:`AnomalyHalt`) after M in a row, so auto-resume rolls back to the
  last durable checkpoint instead of burning the schedule on a poisoned
  stream.

Everything here is host-side, crash-path or once-per-checkpoint code — none
of it is jit-reachable (graftlint's tracer-safety engine lints this module
like any other; the guard that IS jit-reachable lives in training/state.py
as a ``lax.cond``).
"""

from __future__ import annotations

import dataclasses
import datetime
import hashlib
import json
import logging
import os
import re
import shutil
import signal
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MANIFEST_NAME = "MANIFEST.json"
STATE_SUBDIR = "state"
MANIFEST_FORMAT = 1

#: TrainConfig fields that identify "the same training run" for the clobber
#: and auto-resume digests: the ones that shape the state pytree, the
#: optimizer trajectory, or the deterministic data stream. Cosmetic fields
#: (name, run_dir, ckpt_dir, validation cadence, worker counts) are
#: excluded on purpose — changing them must not orphan a run's checkpoints.
_DIGEST_TRAIN_FIELDS = (
    "batch_size", "train_datasets", "lr", "num_steps", "image_size",
    "train_iters", "wdecay", "seed", "grad_accum_steps", "spatial_scale",
    "saturation_range", "img_gamma", "do_flip", "noyjitter",
)


# --- identity: config digest + pytree structure hash -------------------------

def config_digest(model_cfg: Any, train_cfg: Any = None) -> str:
    """Stable 16-hex digest of the model config (and the stream/optimizer-
    defining train fields) — the checkpoint's run-identity stamp."""
    doc: Dict[str, Any] = {"model": dataclasses.asdict(model_cfg)}
    if train_cfg is not None:
        t = dataclasses.asdict(train_cfg)
        doc["train"] = {k: t[k] for k in _DIGEST_TRAIN_FIELDS if k in t}
    blob = json.dumps(doc, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def tree_structure_hash(state: Any) -> str:
    """16-hex digest of the state pytree's treedef + per-leaf shape/dtype.

    Shape/dtype metadata only — no device transfer. A restore against a
    target with a different hash would fail (or worse, silently mis-map),
    so the manifest records it and auto-resume filters on it."""
    import jax

    leaves, treedef = jax.tree.flatten(state)
    desc = [f"{tuple(getattr(l, 'shape', ()))}:{getattr(l, 'dtype', type(l))}"
            for l in leaves]
    desc.append(str(treedef))
    return hashlib.sha256("\n".join(desc).encode()).hexdigest()[:16]


# --- atomic checkpoint protocol ----------------------------------------------

def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _file_inventory(root: str) -> Dict[str, Dict[str, int]]:
    """relpath -> {bytes, crc32} for every file under ``root`` (sorted)."""
    out: Dict[str, Dict[str, int]] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fname in sorted(filenames):
            path = os.path.join(dirpath, fname)
            crc = 0
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
            out[os.path.relpath(path, root)] = {
                "bytes": os.path.getsize(path), "crc32": crc}
    return out


def _fsync_tree(root: str) -> None:
    """fsync every file and directory under ``root`` (then ``root`` itself)
    so the subsequent rename publishes fully durable bytes."""
    for dirpath, _dirnames, filenames in os.walk(root, topdown=False):
        for fname in filenames:
            try:
                fd = os.open(os.path.join(dirpath, fname), os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
            except OSError:
                pass
        try:
            fd = os.open(dirpath, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def load_manifest(ckpt_path: str) -> Optional[Dict[str, Any]]:
    """Parse a checkpoint's manifest; None when absent/unreadable (a legacy
    pre-manifest checkpoint or a corrupt one)."""
    try:
        with open(os.path.join(ckpt_path, MANIFEST_NAME)) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


def checkpoint_state_dir(ckpt_path: str) -> str:
    """The orbax tree inside a checkpoint: ``<path>/state`` under the
    manifest layout, the path itself for legacy checkpoints."""
    state = os.path.join(ckpt_path, STATE_SUBDIR)
    return state if os.path.isdir(state) else ckpt_path


def atomic_save_train_state(ckpt_dir: str, name: str, state: Any,
                            step: Optional[int] = None, *,
                            config_digest: Optional[str] = None,
                            keep_last: int = 0, keep_every: int = 0,
                            reason: str = "periodic") -> str:
    """Write ``<ckpt_dir>/<step>_<name>`` (or ``<ckpt_dir>/<name>`` when
    ``step`` is None) atomically: temp dir -> orbax save -> manifest ->
    fsync -> rename. Returns the published path.

    When the final target already exists: a matching ``config_digest``
    (same run, e.g. the final save of a resumed run) is replaced in place;
    a mismatched or missing one rotates the stranger to ``<target>.bak``
    instead of destroying it. ``keep_last``/``keep_every`` run the
    retention sweep after a successful publish (step checkpoints only).
    """
    import jax

    ckpt_dir = os.path.abspath(ckpt_dir)
    os.makedirs(ckpt_dir, exist_ok=True)
    tag = name if step is None else f"{step}_{name}"
    final = os.path.join(ckpt_dir, tag)
    tmp = os.path.join(ckpt_dir, f".{tag}.tmp.{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)

    state_host = jax.device_get(state)
    try:
        _checkpointer().save(os.path.join(tmp, STATE_SUBDIR), state_host)
        if step is not None:
            step_val = int(step)
        else:
            counter = getattr(state_host, "step",
                              state_host.get("step")
                              if isinstance(state_host, dict) else None)
            step_val = -1 if counter is None else int(np.asarray(counter))
        manifest = {
            "format": MANIFEST_FORMAT,
            "name": name,
            "step": step_val,
            "config_digest": config_digest,
            "tree_hash": tree_structure_hash(state_host),
            "reason": reason,
            "saved_at": datetime.datetime.now().isoformat(
                timespec="seconds"),
            "files": _file_inventory(os.path.join(tmp, STATE_SUBDIR)),
        }
        manifest_path = os.path.join(tmp, MANIFEST_NAME)
        with open(manifest_path, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        _fsync_tree(tmp)

        trash = None
        if os.path.exists(final):
            existing = load_manifest(final)
            existing_digest = (existing or {}).get("config_digest")
            if config_digest is not None and (
                    existing is None or existing_digest != config_digest):
                # a DIFFERENT run (or a pre-manifest stranger) owns this
                # name: rotate it aside instead of destroying its work
                bak = final + ".bak"
                if os.path.exists(bak):
                    shutil.rmtree(bak)
                os.rename(final, bak)
                logger.warning(
                    "checkpoint %s existed with a different config digest "
                    "(%s != %s); rotated it to %s", final,
                    existing_digest, config_digest, bak)
            else:
                # same run (digest match) or no digest to compare: replace
                trash = final + f".old.{os.getpid()}"
                if os.path.exists(trash):
                    shutil.rmtree(trash)
                os.rename(final, trash)
        os.rename(tmp, final)
        _fsync_dir(ckpt_dir)
        if trash is not None:
            shutil.rmtree(trash, ignore_errors=True)
    finally:
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)

    if step is not None and keep_last > 0:
        apply_retention(ckpt_dir, name, keep_last=keep_last,
                        keep_every=keep_every)
    return final


# --- verification + auto-resume ----------------------------------------------

def verify_checkpoint(ckpt_path: str, config_digest: Optional[str] = None,
                      tree_hash: Optional[str] = None
                      ) -> Tuple[bool, Optional[str],
                                 Optional[Dict[str, Any]]]:
    """(ok, failure reason, manifest) for one checkpoint directory.

    Checks: manifest present/parseable/known format, state dir present,
    every manifest-listed file present with matching size AND crc32 (a
    truncated or bit-flipped file fails here), and — when the caller
    supplies them — config digest and pytree-structure hash matches.
    """
    manifest = load_manifest(ckpt_path)
    if manifest is None:
        return False, "missing or unparseable manifest", None
    if manifest.get("format") != MANIFEST_FORMAT:
        return False, f"unknown manifest format {manifest.get('format')!r}", \
            manifest
    state_dir = os.path.join(ckpt_path, STATE_SUBDIR)
    if not os.path.isdir(state_dir):
        return False, "state directory missing", manifest
    files = manifest.get("files")
    if not isinstance(files, dict) or not files:
        return False, "manifest lists no files", manifest
    for rel, meta in sorted(files.items()):
        path = os.path.join(state_dir, rel)
        if not os.path.isfile(path):
            return False, f"file missing: {rel}", manifest
        size = os.path.getsize(path)
        if size != meta.get("bytes"):
            return False, (f"size mismatch: {rel} is {size} bytes, "
                           f"manifest says {meta.get('bytes')}"), manifest
        crc = 0
        with open(path, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
        if crc != meta.get("crc32"):
            return False, f"crc mismatch: {rel}", manifest
    if config_digest is not None \
            and manifest.get("config_digest") is not None \
            and manifest["config_digest"] != config_digest:
        return False, (f"config digest mismatch "
                       f"({manifest['config_digest']} != {config_digest})"), \
            manifest
    if tree_hash is not None and manifest.get("tree_hash") is not None \
            and manifest["tree_hash"] != tree_hash:
        return False, (f"pytree structure mismatch "
                       f"({manifest['tree_hash']} != {tree_hash})"), manifest
    return True, None, manifest


def scan_checkpoints(ckpt_dir: str, name: str) -> List[str]:
    """Candidate checkpoint paths for one run name, NEWEST first.

    ``<step>_<name>`` entries ordered by step descending; the stepless
    final ``<name>`` is ranked by its manifest step (legacy finals without
    a manifest sort oldest — they cannot be integrity-verified anyway).
    """
    if not os.path.isdir(ckpt_dir):
        return []
    pat = re.compile(rf"^(\d+)_{re.escape(name)}$")
    ranked: List[Tuple[int, int, str]] = []
    for entry in os.listdir(ckpt_dir):
        path = os.path.join(ckpt_dir, entry)
        if not os.path.isdir(path):
            continue
        m = pat.match(entry)
        if m:
            ranked.append((int(m.group(1)), 0, path))
        elif entry == name:
            manifest = load_manifest(path) or {}
            # the final outranks a step checkpoint AT the same step
            ranked.append((int(manifest.get("step", -1)), 1, path))
    ranked.sort(reverse=True)
    return [path for _step, _pri, path in ranked]


def find_latest_valid(ckpt_dir: str, name: str,
                      config_digest: Optional[str] = None,
                      tree_hash: Optional[str] = None
                      ) -> Tuple[Optional[str], List[Dict[str, Any]]]:
    """``--restore_ckpt auto``: newest checkpoint that verifies clean.

    Returns ``(path or None, reports)`` where each report is one
    ``ckpt_integrity`` event payload (``path``/``ok``/``step`` plus
    ``reason`` on failure). Scanning stops at the first valid candidate —
    older checkpoints are left unverified (their reports are not emitted).
    """
    reports: List[Dict[str, Any]] = []
    for path in scan_checkpoints(ckpt_dir, name):
        ok, reason, manifest = verify_checkpoint(
            path, config_digest=config_digest, tree_hash=tree_hash)
        report: Dict[str, Any] = {
            "path": path, "ok": bool(ok),
            "step": (manifest or {}).get("step")}
        if not ok:
            report["reason"] = reason
            logger.warning("skipping checkpoint %s: %s", path, reason)
        reports.append(report)
        if ok:
            return path, reports
    return None, reports


def apply_retention(ckpt_dir: str, name: str, keep_last: int,
                    keep_every: int = 0) -> List[str]:
    """Delete step checkpoints beyond the newest ``keep_last``, sparing any
    whose step is a positive multiple of ``keep_every`` (0 = no sparing).
    Final stepless checkpoints and ``.bak`` rotations are never touched.
    Returns the deleted paths."""
    if keep_last <= 0:
        return []
    pat = re.compile(rf"^(\d+)_{re.escape(name)}$")
    steps: List[Tuple[int, str]] = []
    for entry in os.listdir(ckpt_dir) if os.path.isdir(ckpt_dir) else []:
        m = pat.match(entry)
        if m and os.path.isdir(os.path.join(ckpt_dir, entry)):
            steps.append((int(m.group(1)), os.path.join(ckpt_dir, entry)))
    steps.sort(reverse=True)
    deleted: List[str] = []
    for step, path in steps[keep_last:]:
        if keep_every > 0 and step % keep_every == 0:
            continue
        shutil.rmtree(path, ignore_errors=True)
        deleted.append(path)
        logger.info("retention: removed %s", path)
    return deleted


# --- preemption --------------------------------------------------------------

class SignalGuard:
    """Cooperative SIGTERM/SIGINT handling for the training loop.

    Entering installs handlers that *record* the signal instead of killing
    the process; the trainer polls :attr:`requested` once per step and runs
    the save-and-exit path. A second SIGINT restores impatience (raises
    ``KeyboardInterrupt``) so a wedged save can still be interrupted.
    Handler installation only works in the main thread — elsewhere the
    guard degrades to an inert flag (logged once), because a worker-thread
    train() must not break.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._prev: Dict[int, Any] = {}
        self._received: Optional[int] = None
        self._lock = threading.Lock()
        self.installed = False

    def _handle(self, signum, frame):
        with self._lock:
            first = self._received is None
            if not first and signum == signal.SIGINT:
                raise KeyboardInterrupt
            self._received = signum
        if first:
            logger.warning(
                "received %s: finishing the current step, then saving a "
                "preemption checkpoint and exiting", self.signame)

    def __enter__(self) -> "SignalGuard":
        try:
            for s in self._signals:
                self._prev[s] = signal.signal(s, self._handle)
            self.installed = True
        except ValueError:
            # not the main thread: signals cannot be installed here
            self._prev.clear()
            logger.warning("SignalGuard inactive (not in main thread); "
                           "preemption signals will use default handling")
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._prev.clear()
        self.installed = False

    @property
    def requested(self) -> bool:
        return self._received is not None

    @property
    def signame(self) -> Optional[str]:
        if self._received is None:
            return None
        try:
            return signal.Signals(self._received).name
        except ValueError:
            return str(self._received)


# --- anomaly policy (host side of the device guard) --------------------------

class AnomalyHalt(RuntimeError):
    """M consecutive optimizer updates were skipped on non-finite
    gradients: the input stream or the state is systematically poisoned,
    and continuing only burns schedule. The trainer deliberately does NOT
    write an emergency checkpoint for this exception — the rollback target
    is the last durable checkpoint from before the skip streak."""


class AnomalyPolicy:
    """Counts consecutive device-side update skips and halts past the cap.

    ``observe`` is fed from the step metrics the guard surfaces
    (``skipped_updates``/``grad_norm``, training/state.py); it emits one
    ``anomaly`` event per skip and raises :class:`AnomalyHalt` when
    ``max_consecutive`` skips land in a row (0 disables halting — the
    guard still skips updates, the run just never self-terminates).
    """

    def __init__(self, max_consecutive: int = 10, telemetry=None):
        self.max_consecutive = int(max_consecutive)
        self.telemetry = telemetry
        self.consecutive = 0
        self.total = 0

    def observe(self, skipped: bool, step: int,
                grad_norm: Optional[float] = None,
                top_leaves=None) -> None:
        """``top_leaves`` (optional, v9 numerics observatory): the
        ``[[leaf_name, norm-or-None], ...]`` offender ranking from the
        per-leaf norm vector — rides the ``anomaly`` event so a skipped
        update names WHICH leaves went non-finite, not just that one did."""
        if not skipped:
            self.consecutive = 0
            return
        self.consecutive += 1
        self.total += 1
        logger.warning(
            "step %d: non-finite gradients (grad_norm=%s%s) — optimizer "
            "update skipped on device (%d consecutive, %d total)",
            step, grad_norm,
            "" if not top_leaves else f", worst leaves {top_leaves[:3]}",
            self.consecutive, self.total)
        if self.telemetry is not None:
            extra = {} if top_leaves is None else {
                "top_leaves": [[str(n), v] for n, v in top_leaves]}
            self.telemetry.emit(
                "anomaly", kind="nonfinite_grad", step=int(step),
                grad_norm=None if grad_norm is None else float(grad_norm),
                consecutive=self.consecutive, skipped_total=self.total,
                **extra)
        if 0 < self.max_consecutive <= self.consecutive:
            if self.telemetry is not None:
                self.telemetry.emit(
                    "anomaly", kind="halt", step=int(step),
                    consecutive=self.consecutive,
                    skipped_total=self.total)
            raise AnomalyHalt(
                f"{self.consecutive} consecutive non-finite-gradient steps "
                f"at step {step}: halting for rollback to the last valid "
                f"checkpoint (anomaly_max_skips={self.max_consecutive})")


def state_is_finite(state: Any) -> bool:
    """Host-side finiteness check over the float leaves of the state's
    params — the crash/preempt-path gate that keeps a poisoned state out of
    an emergency checkpoint. Never jit this; the in-step check is the
    device-side ``lax.cond`` guard."""
    import jax

    params = getattr(state, "params", state)
    for leaf in jax.tree.leaves(jax.device_get(params)):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) \
                and not np.all(np.isfinite(arr.astype(np.float32))):
            return False
    return True


# --- fault injection (the drill's hook) --------------------------------------

#: environment variable scripts/fault_drill.py sets on the child run: at
#: this (1-based) global step the trainer overwrites the batch's images
#: with NaN, forcing a non-finite loss/gradient so the drill can prove the
#: device guard skips the update and the run survives.
FAULT_NAN_STEP_ENV = "RAFT_FAULT_NAN_STEP"


def injected_nan_step() -> Optional[int]:
    val = os.environ.get(FAULT_NAN_STEP_ENV)
    if not val:
        return None
    try:
        return int(val)
    except ValueError:
        logger.warning("ignoring unparseable %s=%r", FAULT_NAN_STEP_ENV, val)
        return None


#: environment variable scripts/fleet_drill.py sets on ONE trainer of a
#: multi-host drill: sleep this many seconds inside every step's dispatch
#: leg, turning that host into a deterministic straggler the fleet rollup
#: (obs/fleet.py) must name via the STRAGGLER verdict.
FAULT_SLEEP_ENV = "RAFT_FAULT_SLEEP_S"


def injected_sleep_s() -> Optional[float]:
    val = os.environ.get(FAULT_SLEEP_ENV)
    if not val:
        return None
    try:
        return float(val)
    except ValueError:
        logger.warning("ignoring unparseable %s=%r", FAULT_SLEEP_ENV, val)
        return None
