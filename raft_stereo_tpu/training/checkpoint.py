"""Full-state checkpointing (orbax): params + opt state + step.

The reference saves weights only and silently restarts the LR schedule on
resume (train_stereo.py:184-186; SURVEY §5). Here a checkpoint restores model
params, frozen batch stats, optimizer state, and the step counter (which also
positions the OneCycle schedule and, in the trainer, repositions the loader
EXACTLY — epoch and intra-epoch batch index both; the loader's Philox-keyed
per-(epoch, index) decode makes the resumed stream identical to an
uninterrupted run's, see data/loader.py).

Saves go through the atomic protocol in
:mod:`raft_stereo_tpu.training.resilience`: temp dir -> fsync -> rename,
with a per-checkpoint ``MANIFEST.json`` (step, config digest,
pytree-structure hash, per-file size+crc32) that ``--restore_ckpt auto``
verifies before trusting a checkpoint. The old ``force=True`` overwrite —
which let a new run named like an old one destroy its final checkpoint, and
a kill mid-save leave a half-written dir — is gone; a mismatched config
digest rotates the existing target to ``<name>.bak`` instead.

Weights-only interop with reference ``.pth`` files lives in
:mod:`raft_stereo_tpu.utils.checkpoint_convert`.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from raft_stereo_tpu.training.resilience import (atomic_save_train_state,
                                                 checkpoint_state_dir)


def save_train_state(ckpt_dir: str, name: str, state: Any,
                     step: Optional[int] = None,
                     config_digest: Optional[str] = None,
                     keep_last: int = 0, keep_every: int = 0,
                     reason: str = "periodic") -> str:
    """Save the full TrainState atomically; returns the checkpoint path.

    Layout mirrors the reference naming: ``<ckpt_dir>/<step>_<name>`` for
    periodic saves, ``<ckpt_dir>/<name>`` for the final one
    (train_stereo.py:184-186, 208-209); each checkpoint dir holds the orbax
    tree under ``state/`` plus its integrity manifest. ``config_digest``
    stamps the manifest (and arms the same-name clobber protection);
    ``keep_last``/``keep_every`` run retention over step checkpoints.
    """
    return atomic_save_train_state(
        ckpt_dir, name, state, step=step, config_digest=config_digest,
        keep_last=keep_last, keep_every=keep_every, reason=reason)


def restore_train_state(path: str, target: Any) -> Any:
    """Restore a TrainState saved by :func:`save_train_state`.

    ``target`` supplies the pytree structure/dtypes (a freshly created
    state). Accepts both the manifest layout (``<path>/state``) and legacy
    bare orbax dirs.
    """
    import orbax.checkpoint as ocp

    state_dir = checkpoint_state_dir(os.path.abspath(path))
    return ocp.PyTreeCheckpointer().restore(state_dir, item=target)
