"""Full-state checkpointing (orbax): params + opt state + step.

The reference saves weights only and silently restarts the LR schedule on
resume (train_stereo.py:184-186; SURVEY §5). Here a checkpoint restores model
params, frozen batch stats, optimizer state, and the step counter (which also
positions the OneCycle schedule and, in the trainer, repositions the loader
EXACTLY — epoch and intra-epoch batch index both; the loader's Philox-keyed
per-(epoch, index) decode makes the resumed stream identical to an
uninterrupted run's, see data/loader.py).

Weights-only interop with reference ``.pth`` files lives in
:mod:`raft_stereo_tpu.utils.checkpoint_convert`.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_train_state(ckpt_dir: str, name: str, state: Any,
                     step: Optional[int] = None) -> str:
    """Save the full TrainState; returns the checkpoint path.

    Layout mirrors the reference naming: ``<ckpt_dir>/<step>_<name>`` for
    periodic saves, ``<ckpt_dir>/<name>`` for the final one
    (train_stereo.py:184-186, 208-209).
    """
    tag = name if step is None else f"{step}_{name}"
    path = os.path.abspath(os.path.join(ckpt_dir, tag))
    state = jax.device_get(state)
    _checkpointer().save(path, state, force=True)
    return path


def restore_train_state(path: str, target: Any) -> Any:
    """Restore a TrainState saved by :func:`save_train_state`.

    ``target`` supplies the pytree structure/dtypes (a freshly created state).
    """
    restored = _checkpointer().restore(os.path.abspath(path), item=target)
    return restored
