"""Optimizer + LR schedule (train_stereo.py:72-79), as optax transforms.

AdamW (eps 1e-8, torch-default betas) under a global-norm gradient clip of 1.0
(train_stereo.py:175) and torch's two-phase linear OneCycle schedule:
``pct_start=0.01`` warmup from ``peak/div_factor`` to ``peak``, then linear
anneal to ``peak/div_factor/final_div_factor``, over ``num_steps + 100`` steps
(torch defaults div_factor=25, final_div_factor=1e4). No loss scaling: bf16 on
TPU does not need a GradScaler.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from raft_stereo_tpu.config import TrainConfig


def one_cycle_lr(peak_lr: float, total_steps: int, pct_start: float = 0.01,
                 div_factor: float = 25.0, final_div_factor: float = 1e4):
    """torch OneCycleLR(anneal_strategy='linear', cycle_momentum=False) clone.

    torch's scheduler is stepped once per batch *after* the optimizer step, so
    step k uses the LR at schedule position k (initial_lr at k=0).
    """
    initial_lr = peak_lr / div_factor
    min_lr = initial_lr / final_div_factor
    warmup_steps = max(int(round(pct_start * total_steps)) - 1, 1)

    warmup = optax.linear_schedule(initial_lr, peak_lr, warmup_steps)
    anneal = optax.linear_schedule(peak_lr, min_lr,
                                   total_steps - 1 - warmup_steps)
    return optax.join_schedules([warmup, anneal], [warmup_steps])


def fetch_schedule(cfg: TrainConfig):
    """The LR schedule ``fetch_optimizer`` applies — shared with the trainer's
    logging path so the logged lr can never desync from the applied lr.

    ``cfg.num_steps`` counts micro-steps; the schedule advances once per
    APPLIED update, so its horizon is the number of updates.
    """
    k = max(getattr(cfg, "grad_accum_steps", 1), 1)
    n_updates = -(-cfg.num_steps // k)
    return one_cycle_lr(cfg.lr, n_updates + 100)


def fetch_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """AdamW + OneCycle + global-norm clip, mirroring fetch_optimizer
    (train_stereo.py:72-79). Weight decay applies to every parameter, as in
    torch (the reference does not exclude norms/biases).

    ``cfg.grad_accum_steps > 1`` wraps the transform in ``optax.MultiSteps``:
    gradients are averaged over k micro-batches per update (large effective
    batches without the activation memory).
    """
    k = max(getattr(cfg, "grad_accum_steps", 1), 1)
    schedule = fetch_schedule(cfg)
    tx = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(learning_rate=schedule, b1=0.9, b2=0.999, eps=1e-8,
                    weight_decay=cfg.wdecay),
    )
    if k > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=k)
    return tx
