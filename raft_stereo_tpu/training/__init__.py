from raft_stereo_tpu.training.loss import sequence_loss
from raft_stereo_tpu.training.optim import fetch_optimizer, one_cycle_lr
from raft_stereo_tpu.training.resilience import (AnomalyHalt, AnomalyPolicy,
                                                 SignalGuard, config_digest,
                                                 find_latest_valid,
                                                 verify_checkpoint)
from raft_stereo_tpu.training.state import TrainState, make_train_step
