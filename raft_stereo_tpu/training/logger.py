"""Step-windowed metric logging: console + TensorBoard (train_stereo.py:82-129).

Running means over ``SUM_FREQ``-step windows are flushed to the console and a
TensorBoard ``runs/`` directory, plus per-step ``live_loss``/``lr`` scalars
and validation dicts — the reference Logger's exact surface. The TensorBoard
writer is optional (torch's; guarded import) so headless training never
depends on it.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

SUM_FREQ = 100  # steps per console/TB flush (train_stereo.py:16)


def _make_writer(log_dir: str):
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(log_dir=log_dir)
    except Exception:  # tensorboard not installed / not writable
        logger.warning("TensorBoard writer unavailable; console logging only")
        return None


class Logger:
    """The reference Logger surface, optionally mirrored onto the telemetry
    bus: pass ``telemetry`` (an :class:`raft_stereo_tpu.obs.Telemetry`) and
    validation dicts become ``validation`` events while console/TB behavior
    stays byte-identical."""

    def __init__(self, log_dir: str = "runs", total_steps: int = 0,
                 telemetry=None):
        self.total_steps = total_steps
        self.running: Dict[str, float] = {}
        self.window = 0  # pushes since last flush (may be < SUM_FREQ on resume)
        self.writer = _make_writer(log_dir)
        self.telemetry = telemetry

    def _flush(self, lr: float):
        keys = sorted(self.running)
        means = {k: self.running[k] / max(self.window, 1) for k in keys}
        stats = ", ".join(f"{k}={means[k]:10.4f}" for k in keys)
        logger.info("[step %6d, lr %10.7f] %s", self.total_steps, lr, stats)
        if self.writer is not None:
            for k in keys:
                self.writer.add_scalar(k, means[k], self.total_steps)
        self.running = {}
        self.window = 0

    def push(self, metrics: Dict[str, float], lr: float = 0.0):
        """Accumulate one step's metrics; flush every SUM_FREQ steps."""
        self.total_steps += 1
        self.window += 1
        for k, v in metrics.items():
            self.running[k] = self.running.get(k, 0.0) + float(v)
        if self.writer is not None:
            if "loss" in metrics:
                self.writer.add_scalar("live_loss", float(metrics["loss"]),
                                       self.total_steps)
            self.writer.add_scalar("lr", lr, self.total_steps)
        if self.total_steps % SUM_FREQ == 0:
            self._flush(lr)

    def write_dict(self, results: Dict[str, float]):
        """Log a validation-results dict (train_stereo.py:121-126)."""
        logger.info("validation: %s", results)
        if self.writer is not None:
            for k, v in results.items():
                self.writer.add_scalar(k, float(v), self.total_steps)
        if self.telemetry is not None:
            self.telemetry.validation(results)

    def close(self):
        if self.writer is not None:
            self.writer.close()
