"""Split-compilation training step: the flagship step as three compiled pieces.

The tunneled TPU's remote compile helper rejects the monolithic batch-8
SceneFlow train-step graph (HTTP 500, helper subprocess crash — observed
every round) while strictly smaller graphs compile, forcing the benchmark
into encoder-remat fallbacks that re-run the encoders in the backward pass.
``scripts/probe_compile.py`` locates the boundary: an encoders-fwd+bwd graph
with FULL residuals compiles at batch 8, and so does the refinement scan
with the encoder outputs as inputs. This module stitches exactly those
pieces into one training step:

* **piece_enc** — encoder forward (``model.apply(..., stage="encode")``)
  that ALSO emits the backward residuals: it traces the encoder ``jax.vjp``
  to a jaxpr inside its own jit, returns the jaxpr's constants (the saved
  activations) as outputs, and stashes the jaxpr (static IR) for piece_bwd.
* **piece_main** — everything after the cut: context processing, correlation
  pyramid, refinement scan, loss — with gradients for the non-encoder params
  AND the cotangent w.r.t. the encoder outputs.
* **piece_bwd** — evaluates the captured backward jaxpr with the saved
  residuals and the cotangent: encoder parameter gradients WITHOUT
  recomputing the encoder forward (the win over ``remat_encoders``).
* **piece_opt** — the optimizer update on the merged gradient tree.

The math is the monolithic step's: ``stage="full"`` is literally
``refine(encode(x))`` (models/raft_stereo.py), the vjp jaxpr is the same
backward XLA would run in-graph, and the pieces differ only in scheduling —
equivalence is tested in tests/test_split_step.py.

The split composes with the ``remat_encoders`` residual policies: the
policy's ``nn.remat`` wrapper lives inside the encode stage, so the traced
vjp saves (= piece_enc's residual outputs) are whatever the policy keeps.
With the default (no remat) the full residual set is ~24.9 GB at SceneFlow
batch 8 — runtime-OOM on a 16 GB chip even though the pieces compile (the
r3 failure); with ``remat_encoders="norms"`` piece_enc emits only conv
outputs + norm stats (~7 GB) and piece_bwd recomputes the elementwise glue,
which is the schedule to use at batch 8. Gradients w.r.t. the
input images are not computed (the monolithic step doesn't either), and the
per-shape caches mean the first call compiles three graphs.

Reference context: the reference trains its published recipe as one
``loss.backward()`` (train_stereo.py:159-179); splitting is a TPU-side
compile-service workaround, not a semantic change.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import optax

try:  # verified present on the pinned jax (0.9.0); there is NO public
    # fallback evaluator (jax.extend.core exports ClosedJaxpr but not
    # eval_jaxpr, and jax.core.jaxpr_as_fun is gone), so absence makes the
    # split step unavailable — surfaced as a clear error at build time
    # rather than a broken import mid-step.
    from jax.core import eval_jaxpr
except ImportError:  # pragma: no cover
    eval_jaxpr = None

from raft_stereo_tpu.training.loss import (loss_mask, sequence_loss,
                                           sequence_loss_fused)
from raft_stereo_tpu.training.state import TrainState

# top-level param-tree keys owned by the encoder piece (everything the
# "encode" stage touches; conv2_res/conv2_out exist only under
# shared_backbone, fnet only without it)
_ENC_KEYS = ("cnet", "fnet", "conv2_res", "conv2_out")


def _split_params(params: Dict[str, Any]):
    enc = {k: v for k, v in params.items() if k in _ENC_KEYS}
    rest = {k: v for k, v in params.items() if k not in _ENC_KEYS}
    return enc, rest


def make_split_train_step(model, tx: optax.GradientTransformation,
                          train_iters: int, fused_loss: bool = True):
    """Build a ``step(state, batch) -> (new_state, metrics)`` callable that
    runs the training step as separately-jitted pieces (see module doc).

    Python-level composition: each call issues four device dispatches that
    queue asynchronously; the caller's metric fetch synchronizes, exactly as
    with the monolithic jitted step.

    ``batch_stats`` is threaded through the jitted pieces as a traced
    argument (not baked at first call), so reusing the returned callable with
    a different state — e.g. a restored checkpoint with real running stats —
    computes with THAT state's stats. The complementary param halves each
    piece closes over (``rest`` inside the encode stage, ``enc`` inside the
    refine stage) are structurally required by flax but computationally dead
    in their stage, so baking their first-call values is sound; the cache key
    still includes both treedefs so a structurally different state triggers a
    rebuild instead of a silent mismatch.
    """
    if eval_jaxpr is None:  # pragma: no cover
        raise RuntimeError(
            "split-compilation step unavailable: this jax version exports no "
            "jaxpr evaluator (jax.core.eval_jaxpr); use the monolithic step "
            "or remat_encoders instead")
    cache: Dict[Any, Any] = {}

    def build(state, batch):
        img_sd = jax.eval_shape(lambda b: b["image1"], batch)
        enc_params0, rest_params0 = _split_params(state.params)
        cell: Dict[str, Any] = {}

        def enc_only(enc_p, bs, img1, img2):
            variables = {"params": {**enc_p, **rest_params0},
                         "batch_stats": bs}
            return model.apply(variables, img1, img2, stage="encode")

        # cotangent example for tracing the backward jaxpr (encoder-output
        # structured zeros)
        eo_sd = jax.eval_shape(enc_only, enc_params0, state.batch_stats,
                               jnp.zeros(img_sd.shape, img_sd.dtype),
                               jnp.zeros(img_sd.shape, img_sd.dtype))
        ct_example = jtu.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), eo_sd)

        def enc_fwd(enc_p, bs, img1, img2):
            out, vjp = jax.vjp(lambda p: enc_only(p, bs, img1, img2), enc_p)
            closed = jax.make_jaxpr(vjp)(ct_example)
            # the jaxpr is static IR (no tracers) — safe to stash; its
            # constants are this trace's residual tensors, returned as
            # outputs so piece_bwd can consume them next dispatch
            cell["bwd_jaxpr"] = closed.jaxpr
            return out, tuple(closed.consts)

        piece_enc = jax.jit(enc_fwd)

        def main_grads(rest_p, bs, enc_outs, batch):
            def loss_fn(p, eo):
                variables = {"params": {**enc_params0, **p},
                             "batch_stats": bs}
                if fused_loss:
                    mask = loss_mask(batch["flow"], batch["valid"])
                    err_sums, final = model.apply(
                        variables, batch["image1"], batch["image2"],
                        iters=train_iters, flow_gt=batch["flow"],
                        loss_mask=mask, stage="refine", enc_outs=eo)
                    return sequence_loss_fused(err_sums, final,
                                               batch["flow"], mask)
                preds = model.apply(
                    variables, batch["image1"], batch["image2"],
                    iters=train_iters, stage="refine", enc_outs=eo)
                return sequence_loss(preds, batch["flow"], batch["valid"])

            (loss, metrics), (g_rest, g_eo) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(rest_p, enc_outs)
            return g_rest, g_eo, dict(metrics, loss=loss)

        piece_main = jax.jit(main_grads, donate_argnums=(2,))

        enc_tree = jtu.tree_structure((enc_params0,))

        def make_piece_bwd():
            bwd_jaxpr = cell["bwd_jaxpr"]

            def enc_bwd(consts, g_eo):
                outs = eval_jaxpr(bwd_jaxpr, list(consts),
                                  *jtu.tree_leaves(g_eo))
                (g_enc,) = jtu.tree_unflatten(enc_tree, outs)
                return g_enc

            return jax.jit(enc_bwd, donate_argnums=(0, 1))

        def opt_step(state, grads):
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(params=params, opt_state=opt_state,
                                 step=state.step + 1)

        piece_opt = jax.jit(opt_step, donate_argnums=(0,))

        entry = {"enc": piece_enc, "main": piece_main,
                 "make_bwd": make_piece_bwd, "bwd": None, "opt": piece_opt}
        return entry

    def step(state: TrainState, batch):
        key = (tuple(jnp.shape(batch[k]) for k in
                     ("image1", "image2", "flow", "valid")),
               jtu.tree_structure((state.params, state.batch_stats)))
        entry = cache.get(key)
        if entry is None:
            entry = cache[key] = build(state, batch)
        enc_p, rest_p = _split_params(state.params)
        enc_outs, consts = entry["enc"](enc_p, state.batch_stats,
                                        batch["image1"], batch["image2"])
        if entry["bwd"] is None:
            # the enc jit trace has now populated the backward jaxpr
            entry["bwd"] = entry["make_bwd"]()
        g_rest, g_eo, metrics = entry["main"](rest_p, state.batch_stats,
                                              enc_outs, batch)
        g_enc = entry["bwd"](consts, g_eo)
        grads = {**g_enc, **g_rest}
        new_state = entry["opt"](state, grads)
        return new_state, metrics

    return step
