"""Sequence loss over iterative predictions (train_stereo.py:35-69).

Exponentially-weighted L1 over every refinement iteration's upsampled
prediction, with the decay adjusted so schedules with different iteration
counts are consistent: ``gamma_adj = 0.9 ** (15 / (n - 1))`` and iteration i
weighted ``gamma_adj ** (n - 1 - i)`` (train_stereo.py:52-54). Pixels are
excluded when invalid or when |disparity| >= 700 (train_stereo.py:43-46).

Supports global normalization across a device mesh: pass ``axis_name`` inside
``shard_map`` and the valid-pixel normalizer is ``psum``-reduced so the loss
equals the single-device value regardless of how the batch is sharded (the
reference's DataParallel computes the loss on gathered outputs, which is the
same global normalization).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def sequence_loss(flow_preds: jax.Array, flow_gt: jax.Array, valid: jax.Array,
                  loss_gamma: float = 0.9, max_flow: float = 700.0,
                  axis_name: Optional[str] = None,
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Compute the weighted sequence loss and final-iteration metrics.

    Args:
      flow_preds: ``(iters, B, H, W, 1)`` per-iteration disparity-flow.
      flow_gt: ``(B, H, W, 1)`` ground truth (x-flow = -disparity).
      valid: ``(B, H, W)`` or ``(B, H, W, 1)`` validity mask.
      axis_name: optional mapped axis for cross-device normalization.

    Returns:
      ``(loss, metrics)`` with metrics ``epe``, ``1px``, ``3px``, ``5px``
      matching train_stereo.py:62-67.
    """
    n_predictions = flow_preds.shape[0]
    if valid.ndim == flow_gt.ndim - 1:
        valid = valid[..., None]

    mag = jnp.sqrt(jnp.sum(flow_gt.astype(jnp.float32) ** 2, axis=-1,
                           keepdims=True))
    mask = ((valid >= 0.5) & (mag < max_flow)).astype(jnp.float32)

    def global_sum(x):
        s = jnp.sum(x)
        if axis_name is not None:
            s = jax.lax.psum(s, axis_name)
        return s

    denom = jnp.maximum(global_sum(mask), 1.0)

    if n_predictions > 1:
        adjusted_gamma = loss_gamma ** (15.0 / (n_predictions - 1))
    else:
        adjusted_gamma = 1.0
    weights = adjusted_gamma ** jnp.arange(n_predictions - 1, -1, -1,
                                           dtype=jnp.float32)

    # Guard masked-out pixels BEFORE multiplying by the mask: a non-finite GT
    # value (e.g. inf disparity from zero depth) would otherwise poison the
    # sum as inf * 0 = nan. The reference sidesteps this with boolean
    # fancy-indexing (train_stereo.py:56), unavailable under jit.
    abs_err = jnp.abs(flow_preds.astype(jnp.float32) - flow_gt[None])
    abs_err = jnp.where(mask[None] > 0, abs_err, 0.0)
    per_iter = jnp.sum(abs_err, axis=(1, 2, 3, 4))
    if axis_name is not None:
        per_iter = jax.lax.psum(per_iter, axis_name)
    flow_loss = jnp.sum(weights * per_iter) / denom

    epe = jnp.sqrt(jnp.sum(
        (flow_preds[-1].astype(jnp.float32) - flow_gt) ** 2, axis=-1))
    m = mask[..., 0]
    epe = jnp.where(m > 0, epe, 0.0)
    epe_sum = global_sum(epe)
    metrics = {
        "epe": epe_sum / denom,
        "1px": global_sum((epe < 1.0) * m) / denom,
        "3px": global_sum((epe < 3.0) * m) / denom,
        "5px": global_sum((epe < 5.0) * m) / denom,
    }
    return flow_loss, metrics
