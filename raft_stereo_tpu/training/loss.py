"""Sequence loss over iterative predictions (train_stereo.py:35-69).

Exponentially-weighted L1 over every refinement iteration's upsampled
prediction, with the decay adjusted so schedules with different iteration
counts are consistent: ``gamma_adj = 0.9 ** (15 / (n - 1))`` and iteration i
weighted ``gamma_adj ** (n - 1 - i)`` (train_stereo.py:52-54). Pixels are
excluded when invalid or when |disparity| >= 700 (train_stereo.py:43-46).

Supports global normalization across a device mesh: pass ``axis_name`` inside
``shard_map`` and the valid-pixel normalizer is ``psum``-reduced so the loss
equals the single-device value regardless of how the batch is sharded (the
reference's DataParallel computes the loss on gathered outputs, which is the
same global normalization).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def sequence_loss(flow_preds: jax.Array, flow_gt: jax.Array, valid: jax.Array,
                  loss_gamma: float = 0.9, max_flow: float = 700.0,
                  axis_name: Optional[str] = None,
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Compute the weighted sequence loss and final-iteration metrics.

    Args:
      flow_preds: ``(iters, B, H, W, 1)`` per-iteration disparity-flow.
      flow_gt: ``(B, H, W, 1)`` ground truth (x-flow = -disparity).
      valid: ``(B, H, W)`` or ``(B, H, W, 1)`` validity mask.
      axis_name: optional mapped axis for cross-device normalization.

    Returns:
      ``(loss, metrics)`` with metrics ``epe``, ``1px``, ``3px``, ``5px``
      matching train_stereo.py:62-67.
    """
    mask = loss_mask(flow_gt, valid, max_flow)
    global_sum = _make_global_sum(axis_name)

    # Guard masked-out pixels BEFORE multiplying by the mask: a non-finite GT
    # value (e.g. inf disparity from zero depth) would otherwise poison the
    # sum as inf * 0 = nan. The reference sidesteps this with boolean
    # fancy-indexing (train_stereo.py:56), unavailable under jit.
    abs_err = jnp.abs(flow_preds.astype(jnp.float32) - flow_gt[None])
    abs_err = jnp.where(mask[None] > 0, abs_err, 0.0)
    per_iter = jnp.sum(abs_err, axis=(1, 2, 3, 4))
    if axis_name is not None:
        per_iter = jax.lax.psum(per_iter, axis_name)

    flow_loss = _weighted_loss(per_iter, mask, loss_gamma, global_sum)
    metrics = _final_metrics(flow_preds[-1], flow_gt, mask, global_sum)
    return flow_loss, metrics


def loss_mask(flow_gt: jax.Array, valid: jax.Array,
              max_flow: float = 700.0) -> jax.Array:
    """The sequence-loss validity mask (train_stereo.py:43-46), shared by the
    stacked and fused paths: valid pixels with |gt flow| < max_flow."""
    if valid.ndim == flow_gt.ndim - 1:
        valid = valid[..., None]
    mag = jnp.sqrt(jnp.sum(flow_gt.astype(jnp.float32) ** 2, axis=-1,
                           keepdims=True))
    return ((valid >= 0.5) & (mag < max_flow)).astype(jnp.float32)


def _make_global_sum(axis_name: Optional[str]):
    def global_sum(x):
        s = jnp.sum(x)
        if axis_name is not None:
            s = jax.lax.psum(s, axis_name)
        return s
    return global_sum


def _weighted_loss(per_iter_sums, mask, loss_gamma, global_sum):
    """Exponential weighting + valid-pixel normalization (train_stereo.py:50-57).

    ``per_iter_sums``: (iters,) masked L1 sums, already globally reduced by
    the caller when running under a mesh axis.
    """
    n = per_iter_sums.shape[0]
    adjusted_gamma = loss_gamma ** (15.0 / (n - 1)) if n > 1 else 1.0
    weights = adjusted_gamma ** jnp.arange(n - 1, -1, -1, dtype=jnp.float32)
    denom = jnp.maximum(global_sum(mask), 1.0)
    return jnp.sum(weights * per_iter_sums) / denom


def _final_metrics(final_flow, flow_gt, mask, global_sum):
    epe = jnp.sqrt(jnp.sum(
        (final_flow.astype(jnp.float32) - flow_gt) ** 2, axis=-1))
    m = mask[..., 0]
    epe = jnp.where(m > 0, epe, 0.0)
    denom = jnp.maximum(global_sum(mask), 1.0)
    return {
        "epe": global_sum(epe) / denom,
        "1px": global_sum((epe < 1.0) * m) / denom,
        "3px": global_sum((epe < 3.0) * m) / denom,
        "5px": global_sum((epe < 5.0) * m) / denom,
    }


def sequence_loss_fused(per_iter_err_sums: jax.Array, final_flow: jax.Array,
                        flow_gt: jax.Array, mask: jax.Array,
                        loss_gamma: float = 0.9,
                        axis_name: Optional[str] = None,
                        ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Sequence loss from in-scan reduced error sums (the fused-loss path).

    Identical math to :func:`sequence_loss`: the model already reduced each
    iteration's masked L1 to a scalar inside its scan (models/raft_stereo.py),
    so only the exponential weighting, normalization, and final-iteration
    metrics remain.
    """
    global_sum = _make_global_sum(axis_name)
    per_iter = per_iter_err_sums
    if axis_name is not None:
        per_iter = jax.lax.psum(per_iter, axis_name)
    flow_loss = _weighted_loss(per_iter, mask, loss_gamma, global_sum)
    metrics = _final_metrics(final_flow, flow_gt, mask, global_sum)
    return flow_loss, metrics
