"""Distributed training steps: explicit shard_map DP and auto-SPMD dp+sp.

Two complementary paths, both over the same :mod:`mesh`:

* :func:`make_shardmap_train_step` — per-device data parallelism written
  explicitly with ``shard_map``: each device computes gradients on its batch
  shard and the gradient/metric reduction is a visible ``psum`` over the
  ``data`` axis (the north-star's "pmap data-parallel path with psum'd
  gradients", BASELINE.json, expressed with the modern shard_map API).

* :func:`make_pjit_train_step` — the full training step jitted with sharding
  annotations over the 2-D ``(data, seq)`` mesh. Batch is sharded over
  ``data``; image width over ``seq``. XLA's SPMD partitioner inserts the conv
  halo exchanges and the all-gather for the correlation volume's W2 axis; the
  volume itself stays sharded over W1 so per-pixel lookups are local. This is
  the long-image/sequence-parallel path (the analog of context parallelism for
  this model family, SURVEY §5).

Multi-host: both paths extend across hosts by initializing
``jax.distributed`` and building the mesh from global devices; the collective
layout is unchanged (psum/halo traffic rides ICI within a slice, DCN across).

Custom-VJP refinement scan (``config.batched_scan_wgrad``): both paths
compose with it unchanged — the custom scan is standard traceable JAX
(lax.scan + convs, no custom calls), so under ``shard_map`` its eps/residual
stacks take per-shard shapes and the psum'd gradients include the batched
post-scan weight-grad contractions, and under auto-SPMD ``pjit`` the
partitioner shards the stacks' batch axis like any other activation. No
fused_lookup-style stripping is needed (that kernel is excluded for a
missing SPMD *partitioning rule*, not for being a custom VJP).
Equivalence vs the single-device custom step is pinned in
tests/test_scan_grad.py::test_shardmap_dp_matches_single_device_custom.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_stereo_tpu.parallel.compat import shard_map
from raft_stereo_tpu.parallel.mesh import (
    DATA_AXIS,
    SEQ_AXIS,
    batch_specs,
    replicated,
)
from raft_stereo_tpu.training.state import TrainState, make_train_step


def make_shardmap_train_step(model, tx, train_iters: int, mesh: Mesh,
                             fused_loss: bool = False,
                             anomaly_guard: bool = True,
                             numerics: bool = False):
    """Explicit-collective DP train step (state replicated, batch sharded on B).

    ``fused_loss`` selects the in-scan/tile-layout loss (the fastest measured
    step variant): per-shard error sums are already ``psum``-normalized
    globally inside :func:`sequence_loss_fused` via ``axis_name``, so the
    sharded step is identical math to the single-chip fused step.

    ``anomaly_guard`` (default on): the non-finite-gradient ``lax.cond``
    skip in :func:`make_train_step`. Its predicate reads the psum'd
    gradients/loss, so every shard takes the same branch — no divergence,
    no extra collective.

    ``numerics`` (obs/numerics.py): the per-leaf gradient-norm vector
    rides the metrics dict. It is computed from the psum'd gradients, so
    it is replicated across shards and the ``P()`` out_spec holds.
    """
    per_shard_step = make_train_step(model, tx, train_iters,
                                     axis_name=DATA_AXIS,
                                     fused_loss=fused_loss,
                                     anomaly_guard=anomaly_guard,
                                     numerics=numerics)

    batch_spec = {"image1": P(DATA_AXIS), "image2": P(DATA_AXIS),
                  "flow": P(DATA_AXIS), "valid": P(DATA_AXIS)}

    sharded = shard_map(
        per_shard_step, mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_pjit_train_step(model, tx, train_iters: int, mesh: Mesh,
                         fused_loss: bool = False,
                         anomaly_guard: bool = True,
                         numerics: bool = False):
    """Auto-SPMD dp+sp train step: jit with sharding-annotated inputs.

    ``fused_loss`` is written globally (no explicit collectives): the SPMD
    partitioner turns the in-scan/tile-layout error reductions into the same
    cross-device sums the stacked loss gets. ``anomaly_guard``: see
    :func:`make_shardmap_train_step` — under auto-SPMD the cond predicate
    is a replicated scalar, so the guard adds no collectives either.
    """
    import dataclasses

    if getattr(model.cfg, "fused_lookup", None):
        # The fused lookup+convc1 Pallas kernel has no SPMD partitioning
        # rule: under auto-SPMD it would force its operands replicated
        # (gathering the full volume onto every device). The explicit
        # shard_map DP path sees per-shard shapes and keeps the kernel;
        # this path forces the unfused (identical-semantics) graph even
        # when a user opted in explicitly (auto/None already resolves OFF
        # since the r4 A/B — config.py).
        model = model.clone(
            cfg=dataclasses.replace(model.cfg, fused_lookup=False))
    step = make_train_step(model, tx, train_iters, axis_name=None,
                           fused_loss=fused_loss,
                           anomaly_guard=anomaly_guard,
                           numerics=numerics)
    state_sharding = replicated(mesh)
    return jax.jit(
        step,
        in_shardings=(state_sharding, batch_specs(mesh)),
        out_shardings=(state_sharding, state_sharding),
        donate_argnums=(0,),
    )


def dryrun_train_step(n_devices: int, seq_parallel: int = 2,
                      image_size=(32, 64), batch: int = 0,
                      train_iters: int = 2, fused_loss: bool = True,
                      run_shardmap: bool = True) -> None:
    """Compile + execute ONE full dp+sp training step on an n-device mesh.

    Used by the driver's multi-chip dry run (``__graft_entry__``): builds a
    ``(n_devices/seq_parallel, seq_parallel)`` mesh, shards batch over 'data'
    and width over 'seq', and runs both the pjit auto-SPMD step and the
    explicit shard_map DP step. Both run the fused (in-scan/tile-layout) loss
    by default — the bench's primary recipe — so the sharded graph validated
    here is the one a real multi-chip run would train with; stacked-loss
    sharding is covered by the test suite.

    The default shapes are a smoke run; ``dryrun_flagship_shape`` runs the
    SceneFlow-proportioned shape (batch 8, 320x720).
    """
    import numpy as np
    import jax.numpy as jnp

    from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
    from raft_stereo_tpu.models import init_model
    from raft_stereo_tpu.parallel.mesh import make_mesh, shard_batch
    from raft_stereo_tpu.training.optim import fetch_optimizer

    devices = jax.devices()
    if len(devices) < n_devices:
        raise RuntimeError(f"need {n_devices} devices, have {len(devices)}")
    if batch <= 0:
        batch = n_devices  # divisible for both the dp-only and dp x sp meshes

    cfg = RAFTStereoConfig(mixed_precision=True)
    tcfg = TrainConfig(num_steps=100, batch_size=batch)
    h, w = image_size
    model, variables = init_model(jax.random.PRNGKey(0), cfg,
                                  (1, h, w, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)

    rng = np.random.default_rng(0)
    batch_data = {
        "image1": jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)),
                              jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)),
                              jnp.float32),
        "flow": jnp.asarray(rng.uniform(-8, 0, (batch, h, w, 1)), jnp.float32),
        "valid": jnp.ones((batch, h, w), jnp.float32),
    }

    def fresh_state():
        # deep-copy: the train steps donate their state argument, and
        # device_put to a compatible placement can alias rather than copy
        return jax.tree.map(lambda x: jnp.array(x), state)

    # Path 1: auto-SPMD over (data, seq) — width sharded, halos by XLA.
    mesh = make_mesh(n_devices // seq_parallel, seq_parallel,
                     devices=devices[:n_devices])
    with mesh:
        placed = shard_batch(mesh, batch_data)
        state_r = jax.device_put(fresh_state(), replicated(mesh))
        pjit_step = make_pjit_train_step(model, tx, train_iters, mesh,
                                         fused_loss=fused_loss)
        new_state, metrics = pjit_step(state_r, placed)
        jax.block_until_ready(metrics)
        print("pjit dp x sp step ok (fused_loss=%s):" % fused_loss,
              {k: float(v) for k, v in metrics.items()})

    if not run_shardmap:
        return

    # Path 2: explicit shard_map DP with psum'd gradients.
    mesh_dp = make_mesh(n_devices, 1, devices=devices[:n_devices])
    with mesh_dp:
        state2 = jax.device_put(fresh_state(), replicated(mesh_dp))
        dp_batch = {k: jax.device_put(
            v, NamedSharding(mesh_dp, P(DATA_AXIS)))
            for k, v in batch_data.items()}
        dp_step = make_shardmap_train_step(model, tx, train_iters, mesh_dp,
                                           fused_loss=fused_loss)
        new_state2, metrics2 = dp_step(state2, dp_batch)
        jax.block_until_ready(metrics2)
        print("shard_map dp step ok (fused_loss=%s):" % fused_loss,
              {k: float(v) for k, v in metrics2.items()})


def dryrun_flagship_shape(n_devices: int, seq_parallel: int = 2,
                          train_iters: int = 2) -> None:
    """dp x sp dry run at the SceneFlow-proportioned shape: batch 8, 320x720.

    The smoke-shape dryrun proves the sharded step compiles; this proves the
    FLAGSHIP-shaped graph does — batch 8 over 'data', the 720-px width over
    'seq' — with the fused loss, i.e. the exact recipe bench.py reports.
    ``train_iters`` stays small because refinement iterations only repeat the
    (already validated) scan body; shape-dependent sharding is what varies.
    """
    dryrun_train_step(n_devices, seq_parallel=seq_parallel,
                      image_size=(320, 720), batch=8,
                      train_iters=train_iters, fused_loss=True,
                      run_shardmap=False)


def dryrun_flagship_scaled(n_devices: int, seq_parallel: int = 2,
                           train_iters: int = 2) -> None:
    """dp x sp dry run with the flagship's FULL batch and partitioning at a
    reduced image size: batch 8 over 'data', width over 'seq', fused loss —
    identical mesh and sharding rules to :func:`dryrun_flagship_shape`, the
    image scaled (96x224) so XLA-CPU compiles inside the driver's bound even
    on a 1-core host (measured 662 s there under load; the full 320x720
    graph exceeds 70 min). This stage MUST pass: it proves the bench
    recipe's partitioning *executes* on the virtual mesh, not just the
    32x64 smoke shape (r4 review item 5).
    """
    dryrun_train_step(n_devices, seq_parallel=seq_parallel,
                      image_size=(96, 224), batch=8,
                      train_iters=train_iters, fused_loss=True,
                      run_shardmap=False)
