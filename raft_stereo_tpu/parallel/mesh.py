"""Device-mesh construction and sharding helpers.

The framework scales over a 2-D logical mesh ``('data', 'seq')``:

* ``data`` — batch (data parallelism; replaces the reference's single-process
  ``nn.DataParallel`` scatter/gather, train_stereo.py:134) with gradients
  reduced by ``psum`` over ICI.
* ``seq`` — image width. Stereo's memory-scaling axis is W (the O(H*W^2)
  correlation volume; SURVEY §5 long-context row): sharding W is this model
  family's sequence/context parallelism. XLA SPMD inserts conv halo exchanges
  and the correlation-volume collectives from sharding annotations alone.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def make_mesh(data_parallel: int = 0, seq_parallel: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Create a ``(data, seq)`` mesh. ``data_parallel<=0`` = use all devices.

    Lays ``seq`` innermost so width-sharding collectives ride the
    fastest-varying (ICI-adjacent) axis of the device order.
    """
    devices = list(devices if devices is not None else jax.devices())
    if data_parallel <= 0:
        if len(devices) % seq_parallel:
            raise ValueError(f"{len(devices)} devices not divisible by "
                             f"seq_parallel={seq_parallel}")
        data_parallel = len(devices) // seq_parallel
    n = data_parallel * seq_parallel
    if n > len(devices):
        raise ValueError(f"need {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(data_parallel, seq_parallel)
    return Mesh(grid, (DATA_AXIS, SEQ_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """NHWC batch: B over 'data', W over 'seq'."""
    return NamedSharding(mesh, P(DATA_AXIS, None, SEQ_AXIS, None))


def batch_specs(mesh: Mesh):
    """Shardings for a training batch dict (image1/image2/flow/valid)."""
    img = batch_sharding(mesh)
    valid = NamedSharding(mesh, P(DATA_AXIS, None, SEQ_AXIS))
    return {"image1": img, "image2": img, "flow": img, "valid": valid}


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch: dict) -> dict:
    """Place a host batch onto the mesh with the canonical shardings."""
    specs = batch_specs(mesh)
    return {k: jax.device_put(v, specs[k]) for k, v in batch.items()}
