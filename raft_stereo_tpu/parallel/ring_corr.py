"""Ring-sharded correlation: sequence parallelism over the disparity axis.

The W2 (disparity-search) axis is this model family's "sequence" axis: the
O(H*W^2) correlation volume is what limits resolution (SURVEY §5 long-context
row; the reference's only recourses are the slower "alt" mode and lower-res
inference, README.md:132,152). For images too wide for one chip, this module
shards BOTH feature maps over the width axis of a mesh and computes the
pyramid lookup ring-style, ring-attention-shaped but for correlation:

* each device holds one W-shard of fmap1 (its output rows) and one W-shard of
  fmap2 (one block of the disparity search range),
* at every ring step a device computes its fmap1-shard's correlation against
  the fmap2 block it currently holds (an MXU matmul) and the windowed-sample
  contribution of that block, then passes the block along the ring with
  ``ppermute`` over ICI,
* contributions are EXACT partial sums: the windowed sampler's
  equality-masked taps read zero outside the held block, and the fractional
  blend is linear, so summing per-block samples reproduces the global lookup
  bit-for-bit (up to fp addition order).

Per-device memory is O(W_local * D + W_local * r) — no volume, no gather, no
all-gather of fmap2. Compute overlaps communication in the usual ring
pipeline fashion (XLA schedules the ppermute DMA against the next block's
matmul).

This is the explicit-collective sequence-parallel path, the SP analog of
``make_shardmap_train_step``'s DP; the auto-SPMD ``(data, seq)`` pjit path
(parallel/data_parallel.py) remains the default for moderate widths.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_stereo_tpu.parallel.compat import shard_map
from raft_stereo_tpu.ops.geometry import pool_w2
from raft_stereo_tpu.ops.sampler import windowed_linear_sample
from raft_stereo_tpu.parallel.mesh import SEQ_AXIS


def ring_perm(n: int):
    """The ring pipeline's block rotation: device k hands its block to k+1.

    This is the structural signature the SPMD lint keys its whitelist off
    (:func:`is_ring_perm`): a ``ppermute`` with exactly this shape inside
    the refinement scan body is the ring-corr pipeline doing its job, while
    any other collective there is a placement bug.
    """
    return [(k, (k + 1) % n) for k in range(n)]


def is_ring_perm(perm) -> bool:
    """True when ``perm`` is a pure ring rotation over all n participants
    (every source present once, one constant non-zero step).

    Shared structure tag between :func:`ring_corr_lookup` (which builds its
    permutation through :func:`ring_perm`) and the ``collective-in-loop``
    SPMD rule (analysis/spmd_rules.py), so the whitelist cannot drift from
    the implementation: a ppermute that stops matching this shape loses its
    exemption in the same commit that changes it.
    """
    try:
        pairs = [(int(a), int(b)) for a, b in perm]
    except (TypeError, ValueError):
        return False
    n = len(pairs)
    if n < 2 or sorted(a for a, _ in pairs) != list(range(n)) \
            or sorted(b for _, b in pairs) != list(range(n)):
        return False
    step = (pairs[0][1] - pairs[0][0]) % n
    if step == 0:
        return False
    return all((b - a) % n == step for a, b in pairs)


def ring_corr_lookup(fmap1: jax.Array, fmap2: jax.Array, coords: jax.Array,
                     *, radius: int = 4, num_levels: int = 4,
                     axis_name: str = SEQ_AXIS) -> jax.Array:
    """Sharded pyramid correlation lookup; call inside ``shard_map``.

    Args (per-device shards; width axis sharded over ``axis_name``):
      fmap1: ``(B, H, W1_local, D)`` left features for this device's columns.
      fmap2: ``(B, H, W2_local, D)`` one block of right features.
      coords: ``(B, H, W1_local)`` lookup centers in GLOBAL level-0 pixels.

    Returns:
      ``(B, H, W1_local, num_levels * (2*radius+1))`` correlation features,
      identical to the unsharded "alt" lookup on the gathered maps.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    w2_local = fmap2.shape[2]
    d = fmap1.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    if w2_local % (1 << (num_levels - 1)):
        raise ValueError(f"local W2 {w2_local} must be divisible by "
                         f"2^{num_levels - 1} so pyramid pooling stays local")

    f1 = fmap1.astype(jnp.float32)

    def local_pyramid(f2):
        levels = [f2]
        for _ in range(num_levels - 1):
            levels.append(pool_w2(levels[-1]))
        return tuple(levels)

    out = None
    block = fmap2.astype(jnp.float32)
    for step in range(n):
        src = (my - step) % n  # global index of the block currently held
        contrib = []
        for i, blk in enumerate(local_pyramid(block)):
            # this block covers global level-i range [src*w2l_i, (src+1)*w2l_i)
            w2l_i = w2_local >> i
            offset = (src * w2l_i).astype(jnp.float32)
            vol = jnp.einsum("bhwd,bhvd->bhwv", f1, blk,
                             preferred_element_type=jnp.float32)
            contrib.append(windowed_linear_sample(
                vol, coords / (2 ** i) - offset, radius) * scale)
        partial = jnp.concatenate(contrib, axis=-1)
        out = partial if out is None else out + partial
        if step + 1 < n:
            block = jax.lax.ppermute(block, axis_name, perm=ring_perm(n))
    return out


def make_ring_lookup(mesh: Mesh, *, radius: int = 4, num_levels: int = 4):
    """Wrap :func:`ring_corr_lookup` in shard_map over the mesh's seq axis.

    Returns a function of GLOBAL arrays ``(fmap1, fmap2, coords) -> corr``
    whose intermediates are fully W-sharded. The batch axis is sharded over
    the mesh's ``data`` axis (if present) so the ring composes with data
    parallelism: each data-shard runs its own seq-axis ring.
    """
    from raft_stereo_tpu.parallel.mesh import DATA_AXIS

    data = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
    spec_f = P(data, None, SEQ_AXIS, None)
    spec_c = P(data, None, SEQ_AXIS)

    def lookup(fmap1, fmap2, coords):
        return ring_corr_lookup(fmap1, fmap2, coords, radius=radius,
                                num_levels=num_levels, axis_name=SEQ_AXIS)

    return shard_map(lookup, mesh=mesh,
                     in_specs=(spec_f, spec_f, spec_c),
                     out_specs=spec_c,
                     check_vma=False)
