"""Multi-host distributed setup (SURVEY §5: the comm-backend the reference
lacks — its only cluster awareness is a SLURM env var for loader workers,
stereo_datasets.py:318).

JAX's runtime owns the collectives: after :func:`initialize`, every process
sees the global device set; meshes built from it span hosts, and the SAME
``psum``/halo/``ppermute`` layout as single-host rides ICI within a slice and
DCN across slices — no NCCL/MPI analog to manage.

Data feeding follows the standard JAX multi-host recipe: each process loads
only its shard of the global batch (:func:`process_batch_slice`) and
:func:`host_local_to_global` assembles the global sharded arrays.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_stereo_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, batch_specs


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host job (no-op when single-process).

    With no arguments JAX auto-detects cluster environments (TPU pods, SLURM,
    GKE). Call before any other JAX API touches devices.
    """
    if num_processes is not None and num_processes <= 1:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh(data_parallel: int = 0, seq_parallel: int = 1) -> Mesh:
    """A ``(data, seq)`` mesh over the GLOBAL device set (all hosts).

    Device order keeps each host's local devices contiguous along ``data`` so
    gradient psums cross DCN only at slice boundaries.
    """
    from raft_stereo_tpu.parallel.mesh import make_mesh
    return make_mesh(data_parallel, seq_parallel, devices=jax.devices())


def process_batch_slice(global_batch_size: int) -> slice:
    """The half-open index range of the global batch this process must load."""
    n, i = jax.process_count(), jax.process_index()
    if global_batch_size % n:
        raise ValueError(f"global batch {global_batch_size} not divisible by "
                         f"{n} processes")
    per = global_batch_size // n
    return slice(i * per, (i + 1) * per)


def host_local_to_global(mesh: Mesh, batch: Dict[str, np.ndarray]
                         ) -> Dict[str, jax.Array]:
    """Assemble per-process batch shards into global sharded arrays.

    Single-process: equivalent to :func:`raft_stereo_tpu.parallel.shard_batch`.
    Multi-process: each host contributes its local slice of the batch axis via
    ``jax.make_array_from_process_local_data``.
    """
    specs = batch_specs(mesh)
    if jax.process_count() == 1:
        return {k: jax.device_put(v, specs[k]) for k, v in batch.items()}
    n = jax.process_count()
    out = {}
    for k, v in batch.items():
        global_shape = (v.shape[0] * n,) + v.shape[1:]
        out[k] = jax.make_array_from_process_local_data(
            specs[k], np.asarray(v), global_shape)
    return out
