"""shard_map across JAX versions.

The framework targets the stable ``jax.shard_map`` (jax >= 0.7, ``check_vma``
kwarg), but CI/sandbox images sometimes pin an older jax where the API lives
at ``jax.experimental.shard_map`` and the replication-check kwarg is named
``check_rep``. This shim exports one ``shard_map`` accepting the modern
surface so every parallel module (and everything importing them — trainer,
telemetry smoke tests) stays importable on both.
"""

from __future__ import annotations

try:  # jax >= 0.7: the stable API, used as-is
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: experimental namespace + check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(*args, check_vma: bool = True, **kwargs):
        return _shard_map(*args, check_rep=check_vma, **kwargs)

__all__ = ["shard_map"]
