from raft_stereo_tpu.parallel.data_parallel import (
    dryrun_flagship_shape,
    dryrun_train_step,
    make_pjit_train_step,
    make_shardmap_train_step,
)
from raft_stereo_tpu.parallel.ring_corr import (
    make_ring_lookup,
    ring_corr_lookup,
)
from raft_stereo_tpu.parallel.mesh import (
    DATA_AXIS,
    SEQ_AXIS,
    batch_sharding,
    batch_specs,
    make_mesh,
    replicated,
    shard_batch,
)
