"""raft_stereo_tpu — a TPU-native (JAX/XLA/Pallas) stereo-depth framework.

A from-scratch re-design of the capabilities of RAFT-Stereo (Lipson, Teed &
Deng, 3DV 2021; reference implementation studied at /root/reference): iterative
multi-level ConvGRU disparity refinement over a pluggable 1-D correlation layer,
with NHWC layout, functional params, ``lax.scan`` refinement, shard_map/pjit
parallelism and Pallas kernels on the hot path.
"""

from raft_stereo_tpu.config import (
    RAFTStereoConfig,
    TrainConfig,
    middlebury_finetune_config,
    realtime_config,
    rvc_config,
    sceneflow_config,
)

__version__ = "0.1.0"

__all__ = [
    "RAFTStereoConfig",
    "TrainConfig",
    "sceneflow_config",
    "realtime_config",
    "rvc_config",
    "middlebury_finetune_config",
    "__version__",
]
