"""Fused refinement-cell Pallas kernels: corr lookup + motion encoder.

The refinement scan's forward iteration spends its non-MXU time in the
pyramid correlation lookup (~0.9 ms/iter at the train shape) and the motion
encoder's thin convolutions + elementwise glue (core/update.py:64-85 composed
with core/corr.py:127-146); the backward iteration pays the same again as
remat recompute plus the lookup's scatter. This module fuses that whole
sub-graph — 4-level windowed lookup, ``convc1/convc2/convf1/convf2/conv``,
their biases/ReLUs and the flow concat — into ONE Pallas kernel per
direction, with all intermediates VMEM-resident:

* forward: one pass over the volume slab per row-block; emits the 128-channel
  motion features directly.
* backward (hand-written VJP): recomputes the intermediates in VMEM, walks
  the transpose convs back to ``d_corr``, scatters the lookup gradient into
  per-level ``d_volume`` (row-local, so blocks write disjoint rows), and
  accumulates the five convs' weight/bias gradients across the grid into
  resident VMEM accumulators. The model detaches ``coords1`` before the
  lookup (models/raft_stereo.py RefinementStep, mirroring the reference's
  per-iteration ``detach``, core/raft_stereo.py:109) and the flow input is
  likewise derived from detached coords, so the only tensor gradient this
  sub-graph owes is ``d_volume`` — the coords cotangent is structurally zero.

Spatial tiling is rows-only. Each grid program sees THREE consecutive
``hb``-row chunks of every input (the same array bound three times with
shifted, edge-clamped block index maps) — the middle chunk is the rows the
program owns, the outer two are its halo (the conv chain's receptive field
is 5 rows < hb). Beyond-edge chunks clamp to a valid block and are then
zeroed by the row-validity mask, which re-zeroes every activation anyway
(ReLU of a positive bias is nonzero even on zero input), so the convs'
zero-padding semantics hold without materializing padded inputs. Column
padding is zero-fill shifts inside VMEM.

On non-TPU backends the kernels run in interpreter mode, so the same code is
unit-tested on CPU (tests/test_fused_motion.py).

STATUS — experimental, opt-in only (``fused_motion=True``): the kernels are
numerically verified (forward + hand-written VJP match the module
composition to fp32 tolerance, tests/test_fused_motion*.py), but Mosaic's
compile time for the COMBINED kernel is pathological on this toolchain:
measured on v5e, a 6-conv chain at a 4320-row flat slab compiles in ~11 s
and a single pyramid level's lookup in ~5 s, yet the full fused body (4
lookup levels + 6 convs) exceeds 8+ minutes — superlinear in ops x slab
size, not a hang in this code. Until that is resolved (smaller fused
scopes, or a Mosaic fix), the default pipeline keeps the XLA lookup path;
``fused_motion=None`` (auto) therefore resolves to OFF everywhere.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.ops.pallas.corr_kernels import _interpret

# Receptive-field halo of the fused chain, in level-0 rows: the output conv
# (3x3) needs cor2/flo2 at +-1, flo2 needs flo1 at +-2, flo1 (7x7 on flow)
# needs flow at +-5; the corr branch needs corr at +-2. The halo is one full
# hb-row chunk (hb >= 8 > 5), delivered as the neighbouring input blocks.
_HALO_ROWS = 5

# VMEM working-set budget per grid program (slabs + activations + weights).
# Generous: Mosaic schedules liveness much tighter than the static estimate
# in _pick_hb; the estimate only guards against clearly-oversized shapes.
_VMEM_BUDGET = 48 * 1024 * 1024


# The conv/elementwise chain runs ENTIRELY in a flattened 2-D ``(R*W, C)``
# layout: one spatial shift is a sublane-axis slice/concat by
# ``(u-1)*W + (v-1)`` plus a column-validity mask for the horizontal part
# (a shift crossing a row boundary reads the adjacent row's edge pixel —
# the mask restores the conv's zero padding). Keeping a single 2-D layout
# end-to-end is what makes Mosaic compile this kernel: the 3-D
# shift-then-reshape formulation (a relayout per conv tap, ~54 of them)
# drove the TPU compiler into multi-minute layout assignment and was
# measured 20x slower to compile on a 3-conv probe.


def _shift2d(x, off):
    """``out[p] = x[p + off]`` along the sublane axis, zero-filled."""
    if off == 0:
        return x
    z = jnp.zeros_like(x[:abs(off)])
    return (jnp.concatenate([x[off:], z], 0) if off > 0
            else jnp.concatenate([z, x[:off]], 0))


def _conv3x3_2d(x, k, w, colmasks, dt):
    """3x3 same-padding conv on a flattened ``(R*W, Ci)`` slab."""
    n, ci = x.shape
    co = k.shape[-1]
    acc = jnp.zeros((n, co), jnp.float32)
    for u in range(3):
        for v in range(3):
            xs = _shift2d(x, (u - 1) * w + (v - 1))
            if v != 1:
                xs = xs * colmasks[v - 1].astype(xs.dtype)
            acc = acc + jax.lax.dot_general(
                xs, k[u, v].astype(dt),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    return acc


def _conv3x3_2d_transpose(g, k, w, colmasks, dt):
    """Data gradient of :func:`_conv3x3_2d`:
    ``dx[p] = sum_{u,v} g[p - off_{u,v}] k[u,v]^T`` with the column mask
    evaluated at the OUTPUT position (validity of the original read)."""
    n, co = g.shape
    ci = k.shape[2]
    acc = jnp.zeros((n, ci), jnp.float32)
    for u in range(3):
        for v in range(3):
            gs = _shift2d(g, -(u - 1) * w - (v - 1))
            if v != 1:
                gs = gs * colmasks[-(v - 1)].astype(gs.dtype)
            acc = acc + jax.lax.dot_general(
                gs, k[u, v].astype(dt),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
    return acc


def _fwd_taps3x3(x, w, colmasks):
    """The 9 shifted/masked forward operands of :func:`_conv3x3_2d` (for
    weight gradients: ``dk[u,v] = taps[u,v]^T @ g``)."""
    taps = []
    for u in range(3):
        for v in range(3):
            xs = _shift2d(x, (u - 1) * w + (v - 1))
            if v != 1:
                xs = xs * colmasks[v - 1].astype(xs.dtype)
            taps.append(xs)
    return taps


def _flow_taps49(flow, w, col):
    """The 49 shifted/masked ``(N, 1)`` taps of the 7x7 ``convf1`` on the
    flattened 1-channel flow; tap ``(u, v)`` reads ``flow[r+u-3, c+v-3]``."""
    taps = []
    for u in range(7):
        for v in range(7):
            xs = _shift2d(flow, (u - 3) * w + (v - 3))
            if v != 3:
                ok = ((col + (v - 3) >= 0) & (col + (v - 3) < w))
                xs = xs * ok.astype(xs.dtype)
            taps.append(xs)
    return taps


def _convf1_2d(taps, f1_k):
    """7x7 conv on the 1-channel flow as 49 rank-1 VPU multiply-adds
    (``(N,1) * (1,64)`` broadcasts): one input channel makes the MXU
    formulation pointless, and concatenating 49 shifted single-lane taps
    trips Mosaic's concat layout rules."""
    acc = None
    for t, xs in enumerate(taps):
        term = xs * f1_k[t][None, :]
        acc = term if acc is None else acc + term
    return acc.astype(jnp.float32)


def _rotate_left_flat(v, amount, w2):
    """Barrel rotate on the lane axis: ``v[:, i] <- v[:, (i+amount) % w2]``;
    ``v (N, W2)``, ``amount (N, 1)`` int32 (flat-layout twin of
    corr_kernels._rotate_left_by)."""
    nbits = max(1, (w2 - 1).bit_length())
    for kbit in range(nbits):
        s = (1 << kbit) % w2
        rolled = jnp.concatenate([v[:, s:], v[:, :s]], axis=1)
        bit = (amount >> kbit) & 1
        v = jnp.where(bit == 1, rolled, v)
    return v


def _extract_window_flat(vol, base, radius):
    """Taps ``g[:, j] = vol[:, base + j]`` for j in [0, 2r+2), zero outside
    [0, W2). ``vol (N, W2)``, ``base (N, 1)`` int32."""
    w2 = vol.shape[-1]
    k = 2 * radius + 1
    amount = jax.lax.rem(jax.lax.rem(base, w2) + w2, w2)
    rotated = _rotate_left_flat(vol, amount, w2)
    g = rotated[:, :k + 1]
    tap_idx = base + jax.lax.broadcasted_iota(jnp.int32,
                                              (base.shape[0], k + 1), 1)
    return jnp.where((tap_idx >= 0) & (tap_idx < w2), g,
                     jnp.zeros_like(g))


def _scatter_window_flat(dg, base, radius, w2):
    """Inverse of :func:`_extract_window_flat`: place taps ``dg[:, j]`` at
    ``out[:, base + j]`` (out-of-range taps dropped). ``dg (N, 2r+2)``."""
    k = 2 * radius + 1
    tap_idx = base + jax.lax.broadcasted_iota(jnp.int32,
                                              (base.shape[0], k + 1), 1)
    dg = jnp.where((tap_idx >= 0) & (tap_idx < w2), dg, jnp.zeros_like(dg))
    dg_wide = jnp.pad(dg, ((0, 0), (0, w2 - (k + 1))))
    amount = jax.lax.rem(jax.lax.rem(base, w2) + w2, w2)
    inv = jax.lax.rem(w2 - amount, w2)
    return _rotate_left_flat(dg_wide, inv, w2)


def _lookup_flat(coords2, vols, radius, rowmask):
    """Pyramid windowed lookup, all-flat: ``coords2 (N, 1)``, ``vols`` a list
    of ``(N, W2_i)`` slabs -> fp32 ``(N, L*(2r+1))``."""
    k = 2 * radius + 1
    outs = []
    for i, vol in enumerate(vols):
        c = coords2 / (2 ** i)
        base_f = jnp.floor(c)
        frac = c - base_f
        base = base_f.astype(jnp.int32) - radius
        g = _extract_window_flat(vol, base, radius).astype(jnp.float32)
        outs.append((1.0 - frac) * g[:, :k] + frac * g[:, 1:])
    return jnp.concatenate(outs, axis=-1) * rowmask


def _cat3(a, b, c):
    return jnp.concatenate([a[0], b[0], c[0]], axis=0)


def _slab_setup(ca, cb, cc, j, hb, h, w):
    """Common flat-slab preliminaries: coords, masks, flow — all ``(N, .)``.

    Slab position p is image (row, col) = ((j-1)*hb + p // w, p % w); edge
    chunks hold clamped duplicates that the row mask zeroes.
    """
    coords2 = _cat3(ca, cb, cc)                # (N, 1) f32
    n = coords2.shape[0]
    pid = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)
    rows = (j - 1) * hb + pid // w
    col = pid % w
    rowmask = ((rows >= 0) & (rows < h)).astype(jnp.float32)  # (N, 1)
    colmasks = {
        s: ((col + s >= 0) & (col + s < w)).astype(jnp.float32)
        for s in (-1, 1)
    }
    flow = (coords2 - col.astype(jnp.float32)) * rowmask       # (N, 1)
    return coords2, n, col, rowmask, colmasks, flow


def _fwd_kernel(radius, hb, h, w, dt, *refs):
    (ca, cb, cc,
     v0a, v0b, v0c, v1a, v1b, v1c, v2a, v2b, v2c, v3a, v3b, v3c,
     c1_k, c1_b, c2_k, c2_b, f1_k, f1_b, f2_k, f2_b, o_k, o_b,
     out_ref) = refs
    j = pl.program_id(1)
    vols = (_cat3(v0a, v0b, v0c), _cat3(v1a, v1b, v1c),
            _cat3(v2a, v2b, v2c), _cat3(v3a, v3b, v3c))
    coords2, n, col, rowmask, colmasks, flow = _slab_setup(
        ca, cb, cc, j, hb, h, w)

    corr = _lookup_flat(coords2, vols, radius, rowmask).astype(dt)

    def act(acc, bias):
        y = jax.nn.relu(acc + bias.astype(jnp.float32))
        return (y * rowmask).astype(dt)

    def mm(x, k):
        return jax.lax.dot_general(
            x, k.astype(dt), dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    cor1 = act(mm(corr, c1_k[...]), c1_b[...])
    cor2 = act(_conv3x3_2d(cor1, c2_k[...], w, colmasks, dt), c2_b[...])

    flo1 = act(_convf1_2d(_flow_taps49(flow, w, col), f1_k[...]), f1_b[...])
    flo2 = act(_conv3x3_2d(flo1, f2_k[...], w, colmasks, dt), f2_b[...])

    cat = jnp.concatenate([cor2, flo2], axis=-1)
    out126 = act(_conv3x3_2d(cat, o_k[...], w, colmasks, dt), o_b[...])

    motion = jnp.concatenate(
        [out126, flow.astype(dt), jnp.zeros((n, 1), dt)], axis=-1)
    out_ref[0] = motion[hb * w:2 * hb * w]


def _bwd_kernel(radius, hb, h, w, dt, w2s, *refs):
    (ca, cb, cc,
     v0a, v0b, v0c, v1a, v1b, v1c, v2a, v2b, v2c, v3a, v3b, v3c,
     ga, gb, gc,
     c1_k, c1_b, c2_k, c2_b, f1_k, f1_b, f2_k, f2_b, o_k, o_b,
     dv0_ref, dv1_ref, dv2_ref, dv3_ref,
     dc1_k, dc1_b, dc2_k, dc2_b, df1_k, df1_b, df2_k, df2_b,
     do_k, do_b) = refs
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when((b == 0) & (j == 0))
    def _():
        for ref in (dc1_k, dc1_b, dc2_k, dc2_b, df1_k, df1_b, df2_k, df2_b,
                    do_k, do_b):
            ref[...] = jnp.zeros_like(ref)

    vols = (_cat3(v0a, v0b, v0c), _cat3(v1a, v1b, v1c),
            _cat3(v2a, v2b, v2c), _cat3(v3a, v3b, v3c))
    coords2, n, col, rowmask, colmasks, flow = _slab_setup(
        ca, cb, cc, j, hb, h, w)
    # interior rows: the middle chunk — the rows this block owns (dW
    # partials must not double-count halo rows neighbouring blocks also see)
    pid = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)
    tloc = pid // w
    interior = (((tloc >= hb) & (tloc < 2 * hb)).astype(jnp.float32)
                * rowmask)

    # ---- forward recompute (identical to _fwd_kernel) ----
    corr = _lookup_flat(coords2, vols, radius, rowmask).astype(dt)

    def pre_act(acc, bias):
        return acc + bias.astype(jnp.float32)

    def act(pre):
        return (jax.nn.relu(pre) * rowmask).astype(dt)

    def mm(x, k):
        return jax.lax.dot_general(
            x, k.astype(dt), dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    cor1_pre = pre_act(mm(corr, c1_k[...]), c1_b[...])
    cor1 = act(cor1_pre)
    cor2_pre = pre_act(_conv3x3_2d(cor1, c2_k[...], w, colmasks, dt),
                       c2_b[...])
    cor2 = act(cor2_pre)
    taps49 = _flow_taps49(flow, w, col)
    flo1_pre = pre_act(_convf1_2d(taps49, f1_k[...]), f1_b[...])
    flo1 = act(flo1_pre)
    flo2_pre = pre_act(_conv3x3_2d(flo1, f2_k[...], w, colmasks, dt),
                       f2_b[...])
    flo2 = act(flo2_pre)
    cat = jnp.concatenate([cor2, flo2], axis=-1)
    out_pre = pre_act(_conv3x3_2d(cat, o_k[...], w, colmasks, dt), o_b[...])

    # ---- backward ----
    g = _cat3(ga, gb, gc).astype(jnp.float32)      # (N, Co+2)
    # the trailing flow channels carry no gradient obligation: flow is a
    # function of detached coords only
    co = o_k.shape[-1]
    g_out = (g[:, :co] * (out_pre > 0) * rowmask).astype(dt)
    g_out_i = (g_out.astype(jnp.float32) * interior).astype(dt)

    def wgrad3x3(x, gi, dk_ref, db_ref):
        for t, xs in enumerate(_fwd_taps3x3(x, w, colmasks)):
            dk_ref[t // 3, t % 3] += jax.lax.dot_general(
                xs, gi, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        db_ref[0] += jnp.sum(gi.astype(jnp.float32), axis=0)

    wgrad3x3(cat, g_out_i, do_k, do_b)
    d_cat = _conv3x3_2d_transpose(g_out, o_k[...], w, colmasks, dt)
    d_cor2 = (d_cat[:, :64] * (cor2_pre > 0) * rowmask).astype(dt)
    d_flo2 = (d_cat[:, 64:] * (flo2_pre > 0) * rowmask).astype(dt)
    d_cor2_i = (d_cor2.astype(jnp.float32) * interior).astype(dt)
    d_flo2_i = (d_flo2.astype(jnp.float32) * interior).astype(dt)

    wgrad3x3(cor1, d_cor2_i, dc2_k, dc2_b)
    wgrad3x3(flo1, d_flo2_i, df2_k, df2_b)

    d_cor1 = (_conv3x3_2d_transpose(d_cor2, c2_k[...], w, colmasks, dt)
              * (cor1_pre > 0) * rowmask).astype(dt)
    d_flo1 = (_conv3x3_2d_transpose(d_flo2, f2_k[...], w, colmasks, dt)
              * (flo1_pre > 0) * rowmask).astype(dt)
    d_cor1_i = (d_cor1.astype(jnp.float32) * interior).astype(dt)
    d_flo1_i = (d_flo1.astype(jnp.float32) * interior).astype(dt)

    dc1_k[...] += jax.lax.dot_general(
        corr, d_cor1_i, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dc1_b[0] += jnp.sum(d_cor1_i.astype(jnp.float32), axis=0)
    d_flo1_f = d_flo1_i.astype(jnp.float32)
    for t, xs in enumerate(taps49):
        # rank-1 weight grad: sum_p taps[t][p] * g[p, :]
        df1_k[t, :] += jnp.sum(xs * d_flo1_f, axis=0)
    df1_b[0] += jnp.sum(d_flo1_f, axis=0)

    # lookup gradient: d_corr -> per-level window scatter, interior rows only
    # (the lookup is row-local, so interior d_corr rows are complete and the
    # per-block d_volume rows are disjoint)
    d_corr = (jax.lax.dot_general(
        d_cor1, c1_k[...].astype(dt),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * interior)       # (N, L*(2r+1))
    k = 2 * radius + 1
    for i, dv_ref in enumerate((dv0_ref, dv1_ref, dv2_ref, dv3_ref)):
        c = coords2 / (2 ** i)
        base_f = jnp.floor(c)
        frac = c - base_f
        base = base_f.astype(jnp.int32) - radius
        ct = d_corr[:, i * k:(i + 1) * k]
        zeros = jnp.zeros_like(ct[:, :1])
        dg = (jnp.concatenate([(1.0 - frac) * ct, zeros], axis=-1)
              + jnp.concatenate([zeros, frac * ct], axis=-1))
        dv = _scatter_window_flat(dg, base, radius, w2s[i])
        dv_ref[0] = dv[hb * w:2 * hb * w]


def _param_tuple(params):
    return (params["c1_k"], params["c1_b"], params["c2_k"], params["c2_b"],
            params["f1_k"], params["f1_b"], params["f2_k"], params["f2_b"],
            params["o_k"], params["o_b"])


def _pick_hb(h: int, w: int, w2s, itemsize: int) -> int:
    """Largest row-block whose 3-chunk slabs + activations fit the budget."""
    import os

    def lanes(n):
        return -(-n // 128) * 128

    forced = int(os.environ.get("RAFT_FUSED_MOTION_HB", "0"))
    if forced:
        # a row block must still cover the conv chain's receptive field:
        # a forced hb <= _HALO_ROWS would silently corrupt block borders
        if h % forced == 0 and forced > _HALO_ROWS:
            return forced
        import warnings
        warnings.warn(
            f"RAFT_FUSED_MOTION_HB={forced} rejected (needs h % hb == 0 "
            f"with h={h}, and hb > {_HALO_ROWS}); fused motion disabled")
        return 0
    # hb=8 only: Mosaic's compile time grows superlinearly with the flat
    # slab's sublane count (4320 rows ~6 s, 8640 rows >150 s — measured);
    # larger row blocks hit that cliff
    for hb in (8,):
        if h % hb:
            continue
        hin = 3 * hb
        slab = hin * w * sum(lanes(x) for x in w2s) * itemsize
        # ~8 concurrently-live (hin, w, 128-lane) fp32 activation tensors
        acts = hin * w * 128 * 4 * 8
        if slab + acts <= _VMEM_BUDGET:
            return hb
    return 0


def _halo_specs(nb, shapes):
    """Three Blocked specs per array: chunks j-1, j, j+1 (edge-clamped)."""
    specs = []
    for shp in shapes:
        nd = len(shp)
        for k in (-1, 0, 1):
            specs.append(pl.BlockSpec(
                shp,
                functools.partial(
                    lambda i, j, kk, nd_: (i, jnp.clip(j + kk, 0, nb - 1))
                    + (0,) * (nd_ - 2), kk=k, nd_=nd)))
    return specs


def fused_motion_applicable(levels: Sequence[jax.Array], radius: int) -> bool:
    """Static check: shapes fit the kernel's tiling and VMEM budget (the
    backward's footprint — roughly double the forward's — is the binding
    constraint)."""
    if len(levels) != 4:
        return False
    b, h, w, _ = levels[0].shape
    w2s = tuple(v.shape[-1] for v in levels)
    if any(v.shape[:3] != (b, h, w) for v in levels):
        return False
    if any(x <= 2 * radius + 2 for x in w2s):
        return False
    return _pick_hb(h, w, w2s, 2 * levels[0].dtype.itemsize) > 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_corr_motion(levels: Tuple[jax.Array, ...], coords_x: jax.Array,
                      params: dict, radius: int, dt) -> jax.Array:
    """Fused pyramid lookup + motion encoder.

    Args:
      levels: 4-level correlation volume pyramid, each ``(B, H, W1, W2_i)``
        (the ``reg`` CorrState, ops/corr.py:59-73).
      coords_x: ``(B, H, W1)`` lookup centers in level-0 pixels (detached by
        the caller; this function returns a zero coords cotangent).
      params: dict of the five conv kernels/biases —
        ``c1_k (36, 64)``, ``c2_k (3,3,64,64)``, ``f1_k (49, 64)`` (the 7x7
        x-channel kernel, flattened taps), ``f2_k (3,3,64,64)``,
        ``o_k (3,3,128,126)`` and biases; fp32 (cast to ``dt`` in-kernel).
      dt: compute dtype (the model's mixed-precision policy).

    Returns:
      ``(B, H, W1, Co+2)`` motion features in ``dt``: channels [0, Co) are
      the encoder output, Co is the flow x-component, Co+1 is zero (the
      structurally-zero flow y, update.py:85).
    """
    return _fcm_fwd(levels, coords_x, params, radius, dt)[0]


def _fcm_fwd(levels, coords_x, params, radius, dt):
    dt = jnp.dtype(dt) if dt is not None else jnp.float32
    b, h, w, _ = levels[0].shape
    w2s = tuple(v.shape[-1] for v in levels)
    vdt = levels[0].dtype
    hb = _pick_hb(h, w, w2s, vdt.itemsize)
    if hb == 0:
        raise ValueError("fused_corr_motion: shapes unsupported; gate on "
                         "fused_motion_applicable() first")
    nb = h // hb
    pt = _param_tuple(params)
    nch = params["o_k"].shape[-1] + 2
    # flatten spatial dims OUTSIDE the kernel (free layout-compatible
    # reshapes in XLA): Mosaic rejects/struggles with in-kernel shape casts
    coords_f = coords_x.astype(jnp.float32).reshape(b, h * w, 1)
    levels_f = [lv.reshape(b, h * w, x) for lv, x in zip(levels, w2s)]
    in_specs = (_halo_specs(nb, [(1, hb * w, 1)])
                + _halo_specs(nb, [(1, hb * w, x) for x in w2s])
                + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 10)
    operands = ([coords_f] * 3
                + [v for lv in levels_f for v in (lv, lv, lv)]
                + list(pt))
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, radius, hb, h, w, dt),
        grid=(b, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, hb * w, nch), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h * w, nch), dt),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(*operands)
    return out.reshape(b, h, w, nch), (levels, coords_x, params)


def _fcm_bwd(radius, dt, res, g):
    dt = jnp.dtype(dt) if dt is not None else jnp.float32
    levels, coords_x, params = res
    b, h, w, _ = levels[0].shape
    w2s = tuple(v.shape[-1] for v in levels)
    vdt = levels[0].dtype
    # the backward additionally holds the g slab and fp32 d_vol slabs;
    # budget on twice the element size (mirrors fused_motion_applicable)
    hb = _pick_hb(h, w, w2s, 2 * vdt.itemsize)
    if hb == 0:
        raise ValueError("fused_corr_motion backward: shapes exceed the "
                         "kernel budget; gate on fused_motion_applicable() "
                         "(which checks the backward footprint) first")
    nb = h // hb
    pt = _param_tuple(params)
    nch = params["o_k"].shape[-1] + 2
    coords_f = coords_x.astype(jnp.float32).reshape(b, h * w, 1)
    levels_f = [lv.reshape(b, h * w, x) for lv, x in zip(levels, w2s)]
    g_f = g.astype(dt).reshape(b, h * w, nch)
    in_specs = (_halo_specs(nb, [(1, hb * w, 1)])
                + _halo_specs(nb, [(1, hb * w, x) for x in w2s])
                + _halo_specs(nb, [(1, hb * w, nch)])
                + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 10)
    operands = ([coords_f] * 3
                + [v for lv in levels_f for v in (lv, lv, lv)]
                + [g_f] * 3
                + list(pt))
    out_shapes = [jax.ShapeDtypeStruct((b, h * w, x), jnp.float32)
                  for x in w2s]
    pshapes = [jax.ShapeDtypeStruct(p.shape if p.ndim > 1 else (1,) + p.shape,
                                    jnp.float32) for p in pt]
    dvols_and_dps = pl.pallas_call(
        functools.partial(_bwd_kernel, radius, hb, h, w, dt, w2s),
        grid=(b, nb),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, hb * w, x), lambda i, j: (i, j, 0))
                   for x in w2s]
        + [pl.BlockSpec(s.shape, lambda i, j, n=len(s.shape): (0,) * n)
           for s in pshapes],
        out_shape=out_shapes + pshapes,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(*operands)
    dvols = [dv.reshape(b, h, w, w2s[i]).astype(levels[i].dtype)
             for i, dv in enumerate(dvols_and_dps[:4])]
    dps = list(dvols_and_dps[4:])
    names = ("c1_k", "c1_b", "c2_k", "c2_b", "f1_k", "f1_b", "f2_k", "f2_b",
             "o_k", "o_b")
    dparams = {}
    for name, dp, p in zip(names, dps, pt):
        dparams[name] = (dp.reshape(p.shape) if dp.shape != p.shape
                         else dp).astype(p.dtype)
    return (tuple(dvols), jnp.zeros_like(coords_x), dparams)


fused_corr_motion.defvjp(_fcm_fwd, _fcm_bwd)
