"""Fused pyramid-lookup (+ convc1) Pallas kernel — the compilable fused scope.

The refinement scan's per-iteration correlation lookup
(core/corr.py:127-146) composed with the motion encoder's first conv
(``convc1``, a 1x1 contraction over the 36 lookup channels,
core/update.py:67) is the scan's densest cluster of small non-MXU ops: 4
pyramid levels x (window extraction + 2-tap blend) + a thin matmul, each a
handful of XLA ops issued 22 times forward and again (as remat recompute +
scatter) in the backward scan. This module fuses that scope into ONE Pallas
kernel per direction.

Why exactly this scope: the full lookup+motion-encoder fusion (the removed
r3 ``motion_kernels.py``; see PERF.md) was numerically verified but Mosaic
compiled its body in 8+ minutes — the 3x3/7x7 convs force flat-layout
spatial shifts with halo blocks, and their combination with the lookups is
where compile time explodes (measured: a single-level lookup ~5 s, the
6-conv chain ~11 s, combined > 8 min). The lookup pyramid + the 1x1 conv
needs NO spatial halo at all — the lookup is row-local and convc1 is
pointwise — so the kernel is a barrel-shifter window extraction plus one
MXU matmul on a flat ``(rows*W, .)`` slab: the scope Mosaic compiles in
seconds.

Forward: per (batch, row-block) grid program, extract each level's 2r+2-tap
window (static-rotate barrel shifter, no gather), blend to the 2r+1 lookup
features, concatenate levels in VMEM, and run ``relu(corr @ c1_k + c1_b)``
on the MXU — emitting the 64-channel ``cor1`` activation directly; the
(B, H, W, 36) corr tensor never exists in HBM.

Backward (hand-written VJP): recompute corr in VMEM, walk the matmul/relu
back to ``d_corr``, scatter the window gradients into per-level
``d_volume`` (row-local, so blocks write disjoint rows), and accumulate the
conv's weight/bias gradients across the grid in resident VMEM. The model
detaches ``coords1`` before the lookup (mirroring the reference's
per-iteration ``detach``, core/raft_stereo.py:109), so the coords cotangent
is structurally zero.

On non-TPU backends the kernels run in interpreter mode, so the same code
is unit-tested on CPU (tests/test_fused_lookup.py).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.ops.pallas.corr_kernels import _interpret

# jax >= 0.5 renamed TPUCompilerParams -> CompilerParams; support both
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

# VMEM working-set budget per grid program (volume slabs + activations).
_VMEM_BUDGET = 32 * 1024 * 1024


def _rotate_left_flat(v, amount, w2):
    """Barrel rotate on the lane axis: ``v[:, i] <- v[:, (i+amount) % w2]``;
    ``v (N, W2)``, ``amount (N, 1)`` int32 — log2(W2) static rotates, each
    kept per row by one bit of ``amount`` (no gather)."""
    nbits = max(1, (w2 - 1).bit_length())
    for kbit in range(nbits):
        s = (1 << kbit) % w2
        rolled = jnp.concatenate([v[:, s:], v[:, :s]], axis=1)
        bit = (amount >> kbit) & 1
        v = jnp.where(bit == 1, rolled, v)
    return v


def _extract_window_flat(vol, base, radius):
    """Taps ``g[:, j] = vol[:, base + j]`` for j in [0, 2r+2), zero outside
    [0, W2). ``vol (N, W2)``, ``base (N, 1)`` int32."""
    w2 = vol.shape[-1]
    k = 2 * radius + 1
    amount = jax.lax.rem(jax.lax.rem(base, w2) + w2, w2)
    rotated = _rotate_left_flat(vol, amount, w2)
    g = rotated[:, :k + 1]
    tap_idx = base + jax.lax.broadcasted_iota(jnp.int32,
                                              (base.shape[0], k + 1), 1)
    return jnp.where((tap_idx >= 0) & (tap_idx < w2), g,
                     jnp.zeros_like(g))


def _scatter_window_flat(dg, base, radius, w2):
    """Inverse of :func:`_extract_window_flat`: place taps ``dg[:, j]`` at
    ``out[:, base + j]`` (out-of-range taps dropped). ``dg (N, 2r+2)``."""
    k = 2 * radius + 1
    tap_idx = base + jax.lax.broadcasted_iota(jnp.int32,
                                              (base.shape[0], k + 1), 1)
    dg = jnp.where((tap_idx >= 0) & (tap_idx < w2), dg, jnp.zeros_like(dg))
    dg_wide = jnp.pad(dg, ((0, 0), (0, w2 - (k + 1))))
    amount = jax.lax.rem(jax.lax.rem(base, w2) + w2, w2)
    inv = jax.lax.rem(w2 - amount, w2)
    return _rotate_left_flat(dg_wide, inv, w2)


def _level_window(coords2, vol, level, radius):
    """One level's blended (2r+1)-tap lookup + the (base, frac) it used."""
    k = 2 * radius + 1
    c = coords2 / (2 ** level)
    base_f = jnp.floor(c)
    frac = c - base_f
    base = base_f.astype(jnp.int32) - radius
    g = _extract_window_flat(vol, base, radius).astype(jnp.float32)
    return (1.0 - frac) * g[:, :k] + frac * g[:, 1:], base, frac


def _fwd_kernel(radius, dt, *refs):
    (c_ref, v0, v1, v2, v3, k_ref, b_ref, out_ref) = refs
    coords2 = c_ref[0]  # (N, 1) fp32
    corr = jnp.concatenate(
        [_level_window(coords2, v[0], i, radius)[0]
         for i, v in enumerate((v0, v1, v2, v3))], axis=-1)
    pre = jax.lax.dot_general(
        corr.astype(dt), k_ref[...].astype(dt),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b_ref[0].astype(jnp.float32)
    out_ref[0] = jax.nn.relu(pre).astype(dt)


def _bwd_kernel(radius, dt, w2s, vdt, *refs):
    (c_ref, v0, v1, v2, v3, g_ref, k_ref, b_ref,
     dv0, dv1, dv2, dv3, dk_ref, db_ref) = refs
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when((i == 0) & (j == 0))
    def _():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    coords2 = c_ref[0]
    k = 2 * radius + 1
    per_level = [_level_window(coords2, v[0], lvl, radius)
                 for lvl, v in enumerate((v0, v1, v2, v3))]
    corr = jnp.concatenate([p[0] for p in per_level], axis=-1)

    corr_dt = corr.astype(dt)
    pre = jax.lax.dot_general(
        corr_dt, k_ref[...].astype(dt),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b_ref[0].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32) * (pre > 0)      # (N, Co) fp32
    g_dt = g.astype(dt)

    dk_ref[...] += jax.lax.dot_general(
        corr_dt, g_dt, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db_ref[0] += jnp.sum(g, axis=0)

    d_corr = jax.lax.dot_general(
        g_dt, k_ref[...].astype(dt),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # (N, L*(2r+1))

    for lvl, dv_ref in enumerate((dv0, dv1, dv2, dv3)):
        _, base, frac = per_level[lvl]
        ct = d_corr[:, lvl * k:(lvl + 1) * k]
        zeros = jnp.zeros_like(ct[:, :1])
        dg = (jnp.concatenate([(1.0 - frac) * ct, zeros], axis=-1)
              + jnp.concatenate([zeros, frac * ct], axis=-1))
        # accumulation is fp32 in VMEM; only the HBM store rounds to the
        # volume's storage dtype — same rounding the unfused bf16-volume
        # path pays, and it halves the d_volume HBM buffers
        dv_ref[0] = _scatter_window_flat(dg, base, radius,
                                         w2s[lvl]).astype(vdt)


def _lanes(n: int) -> int:
    return -(-n // 128) * 128


def _pick_hb(h: int, w: int, w2s, itemsize: int) -> int:
    """Largest row-block (a divisor of h) whose slabs fit the VMEM budget."""
    for hb in (16, 8, 4, 2, 1):
        if h % hb:
            continue
        slab = hb * w * sum(_lanes(x) for x in w2s) * itemsize
        # live fp32 intermediates: cor1/grads (128-lane) plus rotate temps
        # (~4 widest-level slabs) — a deliberately loose static guard
        acts = hb * w * 128 * 4 * 6 + hb * w * _lanes(max(w2s)) * 4 * 4
        if slab + acts <= _VMEM_BUDGET:
            return hb
    return 0


def fused_lookup_applicable(levels: Sequence[jax.Array], radius: int) -> bool:
    """Static check: 4 levels, equal (B, H, W) prefixes, windows strictly
    inside each level's width, and a row-block that fits VMEM."""
    if len(levels) != 4:
        return False
    b, h, w = levels[0].shape[:3]
    w2s = tuple(v.shape[-1] for v in levels)
    if any(v.shape[:3] != (b, h, w) for v in levels):
        return False
    if any(x <= 2 * radius + 2 for x in w2s):
        return False
    return _pick_hb(h, w, w2s, 2 * levels[0].dtype.itemsize) > 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_lookup_c1(levels: Tuple[jax.Array, ...], coords_x: jax.Array,
                    kernel: jax.Array, bias: jax.Array,
                    radius: int, dt) -> jax.Array:
    """Fused 4-level pyramid lookup + 1x1 conv + ReLU.

    Args:
      levels: correlation volume pyramid, each ``(B, H, W1, W2_i)`` (the
        ``reg`` CorrState, ops/corr.py:59-73); fp32 or bf16 storage.
      coords_x: ``(B, H, W1)`` lookup centers in level-0 pixels (detached by
        the caller; this function returns a zero coords cotangent).
      kernel: ``(L*(2r+1), Co)`` fp32 — ``convc1`` flattened (1x1 conv ==
        matmul over channels).
      bias: ``(Co,)`` fp32.
      radius: lookup radius r.
      dt: compute dtype (the model's mixed-precision policy) or None (fp32).

    Returns:
      ``relu(lookup(levels, coords) @ kernel + bias)`` as ``(B, H, W1, Co)``
      in ``dt`` — the motion encoder's ``cor1`` activation.
    """
    return _flc_fwd(levels, coords_x, kernel, bias, radius, dt)[0]


def _flc_fwd(levels, coords_x, kernel, bias, radius, dt):
    dt = jnp.dtype(dt) if dt is not None else jnp.float32
    b, h, w, _ = levels[0].shape
    w2s = tuple(v.shape[-1] for v in levels)
    hb = _pick_hb(h, w, w2s, levels[0].dtype.itemsize)
    if hb == 0:
        raise ValueError("fused_lookup_c1: shapes unsupported; gate on "
                         "fused_lookup_applicable() first")
    nb = h // hb
    co = kernel.shape[-1]
    coords_f = coords_x.astype(jnp.float32).reshape(b, h * w, 1)
    levels_f = [lv.reshape(b, h * w, x) for lv, x in zip(levels, w2s)]
    bias2 = bias.reshape(1, co)
    blk = lambda x: pl.BlockSpec((1, hb * w, x), lambda i, j: (i, j, 0))
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, radius, dt),
        grid=(b, nb),
        in_specs=[blk(1)] + [blk(x) for x in w2s]
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
        out_specs=blk(co),
        out_shape=jax.ShapeDtypeStruct((b, h * w, co), dt),
        compiler_params=_CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_interpret(),
    )(coords_f, *levels_f, kernel, bias2)
    return out.reshape(b, h, w, co), (levels, coords_x, kernel, bias)


def _flc_bwd(radius, dt, res, g):
    dt = jnp.dtype(dt) if dt is not None else jnp.float32
    levels, coords_x, kernel, bias = res
    b, h, w, _ = levels[0].shape
    w2s = tuple(v.shape[-1] for v in levels)
    # d_volume slabs (volume dtype) ride along in the backward: budget on
    # the doubled element size so the applicable() check covers this kernel
    hb = _pick_hb(h, w, w2s, 2 * levels[0].dtype.itemsize)
    if hb == 0:
        raise ValueError("fused_lookup_c1 backward: shapes exceed the "
                         "kernel budget; gate on fused_lookup_applicable()")
    nb = h // hb
    co = kernel.shape[-1]
    coords_f = coords_x.astype(jnp.float32).reshape(b, h * w, 1)
    levels_f = [lv.reshape(b, h * w, x) for lv, x in zip(levels, w2s)]
    g_f = g.astype(dt).reshape(b, h * w, co)
    bias2 = bias.reshape(1, co)
    blk = lambda x: pl.BlockSpec((1, hb * w, x), lambda i, j: (i, j, 0))
    whole = lambda shp: pl.BlockSpec(shp, lambda i, j: (0,) * len(shp))
    vdt = levels[0].dtype
    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, radius, dt, w2s, vdt),
        grid=(b, nb),
        in_specs=[blk(1)] + [blk(x) for x in w2s] + [blk(co)]
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
        out_specs=[blk(x) for x in w2s]
        + [whole(kernel.shape), whole((1, co))],
        out_shape=[jax.ShapeDtypeStruct((b, h * w, x), vdt)
                   for x in w2s]
        + [jax.ShapeDtypeStruct(kernel.shape, jnp.float32),
           jax.ShapeDtypeStruct((1, co), jnp.float32)],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_interpret(),
    )(coords_f, *levels_f, g_f, kernel, bias2)
    dvols = tuple(dv.reshape(b, h, w, x)
                  for dv, x in zip(outs[:4], w2s))
    dk = outs[4].astype(kernel.dtype)
    db = outs[5].reshape(co).astype(bias.dtype)
    return (dvols, jnp.zeros_like(coords_x), dk, db)


fused_lookup_c1.defvjp(_flc_fwd, _flc_bwd)

