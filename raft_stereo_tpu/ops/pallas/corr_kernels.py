"""Pallas TPU kernels for the correlation hot path.

TPU-native equivalents of the reference's CUDA extension
(sampler/sampler_kernel.cu — see SURVEY §2.2 N1/N2):

* :func:`windowed_sample_pallas` — fused pyramid-lookup kernel. Semantics of
  the CUDA forward (sampler_kernel.cu:20-60): per output pixel, blend ``2r+2``
  integer taps around ``floor(center)-r`` with weights ``1-dx``/``dx``; taps
  outside the row read as zero. One grid program handles a block of rows with
  the volume slab resident in VMEM, so HBM sees ONE pass over the volume per
  lookup instead of the ~2r+2 masked-reduce passes the pure-JAX formulation
  costs under XLA.
* its hand-written backward (sampler_kernel.cu:63-105): the window-local
  scatter into the volume gradient, again one VMEM-resident pass; the coords
  gradient is ``sum_k ct_k * (g[k+1] - g[k])`` through the fractional weight
  (``floor`` contributes zero, matching ``coords1.detach()`` usage,
  core/corr.py:29).
* :func:`alt_windowed_corr_pallas` — the fused "alt" kernel: builds each
  row's correlation slice with an in-kernel MXU matmul (fmap1 row x fmap2
  row^T / sqrt(D)) and samples it without ever writing the O(W^2) volume to
  HBM — the capability the reference's absent ``alt_cuda_corr`` extension
  promises (core/corr.py:159-188), with O(W) HBM footprint.
* :func:`fused_windowed_corr_pallas` — the memoryless blocked kernel behind
  ``corr_implementation='fused'``: like the alt kernel it fuses the feature
  dot-product into the windowed sample, but it tiles the W2 axis into
  ``block_w``-lane blocks and ACCUMULATES the blended taps across blocks, so
  the per-program slab is (Hb, W1, Wb) — bounded by a knob, not by the image
  — and there is NO full-volume fallback at any width (the alt kernel falls
  back to materializing B*H*W1*W2 when its slab outgrows VMEM; this one
  shrinks the block instead). The hand VJP mirrors the tiling: fmap1
  cotangents accumulate across W2 blocks, fmap2 cotangents are written per
  block, and no forward-saved volume exists anywhere.

On non-TPU backends every ``pallas_call`` runs in interpreter mode, so the
same kernels are unit-testable on CPU (tests/test_pallas_corr.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# Budget for one program's resident blocks; well under the ~16 MB/core VMEM
# so inputs+outputs+double-buffering fit.
_VMEM_BUDGET_BYTES = 6 * 1024 * 1024


def _row_block(h: int, slab_bytes_per_row: int) -> int:
    """Rows per grid program sized by the actual VMEM slab footprint.

    Returns 0 when even a single row exceeds the budget — callers must fall
    back to the pure-JAX lookup (identical semantics). H-divisibility alone is
    not enough: Middlebury-F-scale widths make (hb, W1, W2) slabs tens of MB.
    """
    if slab_bytes_per_row > _VMEM_BUDGET_BYTES:
        return 0
    for hb in (8, 4, 2):
        if h % hb == 0 and hb * slab_bytes_per_row <= _VMEM_BUDGET_BYTES:
            return hb
    return 1


# --------------------------------------------------------------- reg lookup
#
# Window extraction is a barrel shifter: rotate each (VMEM-resident) volume
# row left by ``base`` lanes with log2(W2p) STATIC rotates, each kept or
# skipped per row by a select on one bit of ``base`` — after which the 2r+2
# window taps sit at lanes [0, 2r+2). Static lane rotates + per-sublane
# selects are native VPU ops; this replaces the 2r+2 full-width masked
# reductions (one per tap, each a cross-lane reduce) the pure-JAX
# formulation costs, and does no gather at all. The same trick inverts for
# the backward scatter (rotate right by ``base``).


def _num_bits(n: int) -> int:
    return max(1, (n - 1).bit_length())


def _rotate_left_by(v, amount, axis_size):
    """Barrel rotate: ``v[..., i] <- v[..., (i + amount) % axis_size]``.

    ``v``: (..., W); ``amount``: (...,) int32 in [0, axis_size). Static
    rotates selected per row by the bits of ``amount``.
    """
    for k in range(_num_bits(axis_size)):
        s = (1 << k) % axis_size
        rolled = jnp.concatenate([v[..., s:], v[..., :s]], axis=-1)
        bit = ((amount >> k) & 1)[..., None]
        v = jnp.where(bit == 1, rolled, v)
    return v


def _extract_window(vol, base, radius):
    """Taps ``g[..., j] = vol[..., base + j]`` for j in [0, 2r+2), zero
    outside [0, W2). ``vol`` (..., W2) fp32, ``base`` (...,) int32."""
    w2 = vol.shape[-1]
    k = 2 * radius + 1
    amount = jax.lax.rem(jax.lax.rem(base, w2) + w2, w2)
    rotated = _rotate_left_by(vol, amount, w2)
    g = rotated[..., :k + 1]
    tap_idx = base[..., None] + jax.lax.broadcasted_iota(
        jnp.int32, base.shape + (k + 1,), base.ndim)
    return jnp.where((tap_idx >= 0) & (tap_idx < w2), g, 0.0)


def _scatter_window(dg, base, radius, w2):
    """Inverse of :func:`_extract_window`: place taps ``dg[..., j]`` at
    ``out[..., base + j]`` (taps landing outside [0, w2) are dropped).
    ``dg`` (..., 2r+2), ``base`` (...,) int32 -> (..., w2) fp32."""
    k = 2 * radius + 1
    tap_idx = base[..., None] + jax.lax.broadcasted_iota(
        jnp.int32, base.shape + (k + 1,), base.ndim)
    dg = jnp.where((tap_idx >= 0) & (tap_idx < w2), dg, 0.0)
    dg_wide = jnp.pad(dg, [(0, 0)] * (dg.ndim - 1) + [(0, w2 - (k + 1))])
    amount = jax.lax.rem(jax.lax.rem(base, w2) + w2, w2)
    inv = jax.lax.rem(w2 - amount, w2)
    return _rotate_left_by(dg_wide, inv, w2)


def _lookup_fwd_kernel(radius, coords_ref, vol_ref, out_ref):
    c = coords_ref[...]                      # (Hb, W1)
    vol = vol_ref[...].astype(jnp.float32)   # (Hb, W1, W2)
    k = 2 * radius + 1

    base_f = jnp.floor(c)
    frac = (c - base_f)[..., None]
    base = base_f.astype(jnp.int32) - radius
    g = _extract_window(vol, base, radius)   # (Hb, W1, 2r+2)
    out_ref[...] = (1.0 - frac) * g[..., :k] + frac * g[..., 1:]


def _lookup_bwd_kernel(radius, coords_ref, vol_ref, ct_ref, dvol_ref,
                       dcoords_ref):
    c = coords_ref[...]                      # (Hb, W1)
    vol = vol_ref[...].astype(jnp.float32)   # (Hb, W1, W2)
    ct = ct_ref[...].astype(jnp.float32)     # (Hb, W1, 2r+1)
    k = 2 * radius + 1
    w2 = vol.shape[-1]

    base_f = jnp.floor(c)
    frac = (c - base_f)[..., None]
    base = base_f.astype(jnp.int32) - radius

    # dg_j = (1-f)*ct_j + f*ct_{j-1}, j in [0, 2r+1]
    zeros = jnp.zeros_like(ct[..., :1])
    dg = (jnp.concatenate([(1.0 - frac) * ct, zeros], axis=-1)
          + jnp.concatenate([zeros, frac * ct], axis=-1))
    dvol_ref[...] = _scatter_window(dg, base, radius, w2)

    # window taps again, for the coords gradient through frac
    g = _extract_window(vol, base, radius)
    dcoords_ref[...] = jnp.sum(ct * (g[..., 1:] - g[..., :k]), axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def windowed_sample_pallas(volume: jax.Array, center: jax.Array,
                           radius: int) -> jax.Array:
    """Pallas 2r+1-tap windowed linear sample along the last axis.

    Drop-in for :func:`raft_stereo_tpu.ops.sampler.windowed_linear_sample`:
    ``volume (B, H, W1, W2)``, ``center (B, H, W1)`` -> ``(B, H, W1, 2r+1)``.
    """
    return _ws_pallas_fwd(volume, center, radius)[0]


def _ws_pallas_fwd(volume, center, radius):
    b, h, w1, w2 = volume.shape
    # fwd holds vol + out; bwd additionally dvol — budget on 2x the vol slab
    hb = _row_block(h, 2 * w1 * w2 * 4)
    k = 2 * radius + 1
    if hb == 0 or w2 <= k + 1:  # slab too large for VMEM (or degenerate
        # window): identical pure-JAX semantics
        from raft_stereo_tpu.ops.sampler import windowed_linear_sample
        return windowed_linear_sample(volume, center, radius), (volume, center)
    out = pl.pallas_call(
        functools.partial(_lookup_fwd_kernel, radius),
        grid=(b, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, w1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, hb, w1, w2), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hb, w1, k), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, w1, k), jnp.float32),
        interpret=_interpret(),
    )(center.astype(jnp.float32), volume)
    return out, (volume, center)


def _ws_pallas_bwd(radius, res, ct):
    volume, center = res
    b, h, w1, w2 = volume.shape
    hb = _row_block(h, 2 * w1 * w2 * 4)
    k = 2 * radius + 1
    if hb == 0 or w2 <= k + 1:  # mirror the forward's pure-JAX fallback
        from raft_stereo_tpu.ops.sampler import windowed_linear_sample

        def f(v, c):
            return windowed_linear_sample(v, c, radius)

        _, vjp = jax.vjp(f, volume, center)
        return vjp(ct.astype(jnp.float32))
    dvol, dcoords = pl.pallas_call(
        functools.partial(_lookup_bwd_kernel, radius),
        grid=(b, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, w1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, hb, w1, w2), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, hb, w1, k), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hb, w1, w2), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, hb, w1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, w1, w2), jnp.float32),
            jax.ShapeDtypeStruct((b, h, w1), jnp.float32),
        ],
        interpret=_interpret(),
    )(center.astype(jnp.float32), volume, ct.astype(jnp.float32))
    return dvol.astype(volume.dtype), dcoords.astype(center.dtype)


windowed_sample_pallas.defvjp(_ws_pallas_fwd, _ws_pallas_bwd)


# ----------------------------------------------------- fused alt (no volume)

def _alt_fwd_kernel(radius, scale, coords_ref, f1_ref, f2_ref, out_ref):
    c = coords_ref[0]                            # (Hb, W1)
    f1 = f1_ref[0]                               # (Hb, W1, D)
    f2 = f2_ref[0]                               # (Hb, W2, D)
    k = 2 * radius + 1

    # per-row correlation slab on the MXU; never leaves VMEM
    vol = jax.lax.dot_general(
        f1, f2, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale   # (Hb, W1, W2)

    base_f = jnp.floor(c)
    frac = (c - base_f)[..., None]
    base = base_f.astype(jnp.int32) - radius
    g = _extract_window(vol, base, radius)
    out_ref[0] = (1.0 - frac) * g[..., :k] + frac * g[..., 1:]


def _alt_bwd_kernel(radius, scale, coords_ref, f1_ref, f2_ref, ct_ref,
                    df1_ref, df2_ref):
    c = coords_ref[0]
    f1 = f1_ref[0]
    f2 = f2_ref[0]
    ct = ct_ref[0].astype(jnp.float32)
    k = 2 * radius + 1
    w2 = f2.shape[1]

    base_f = jnp.floor(c)
    frac = (c - base_f)[..., None]
    base = base_f.astype(jnp.int32) - radius

    zeros = jnp.zeros_like(ct[..., :1])
    dg = (jnp.concatenate([(1.0 - frac) * ct, zeros], axis=-1)
          + jnp.concatenate([zeros, frac * ct], axis=-1))
    dvol = _scatter_window(dg, base, radius, w2) * scale

    # dvol: (Hb, W1, W2); f2: (Hb, W2, D) -> df1 (Hb, W1, D)
    df1_ref[0] = jax.lax.dot_general(
        dvol, f2.astype(jnp.float32),
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(df1_ref.dtype)
    # dvol^T contraction over W1: f1 (Hb, W1, D) -> df2 (Hb, W2, D)
    df2_ref[0] = jax.lax.dot_general(
        dvol, f1.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(df2_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def alt_windowed_corr_pallas(fmap1: jax.Array, fmap2: jax.Array,
                             center: jax.Array, radius: int) -> jax.Array:
    """Fused on-the-fly correlation lookup: ``dot + window-sample`` per row.

    ``fmap1 (B, H, W1, D)``, ``fmap2 (B, H, W2, D)``, ``center (B, H, W1)``
    -> ``(B, H, W1, 2r+1)`` with the 1/sqrt(D) scaling applied. The O(W^2)
    correlation slab exists only in VMEM (the reference "alt" semantics,
    core/corr.py:64-107, without the per-pixel grid_sample gathers).

    The coords gradient is intentionally not produced (the model detaches
    coords each iteration, raft_stereo.py:109, and the reference CUDA
    backward likewise returns None for coords, core/corr.py:29).
    """
    return _alt_pallas_fwd(fmap1, fmap2, center, radius)[0]


def _alt_pallas_fwd(fmap1, fmap2, center, radius):
    b, h, w1, d = fmap1.shape
    w2 = fmap2.shape[2]
    # resident per row: f1 (w1*d) + f2 (w2*d) + vol (w1*w2), fp32
    hb = _row_block(h, 4 * (w1 * d + w2 * d + w1 * w2))
    k = 2 * radius + 1
    scale = 1.0 / float(d) ** 0.5
    if hb == 0 or w2 <= k + 1:
        from raft_stereo_tpu.ops.sampler import windowed_linear_sample
        vol = jnp.einsum("bhwd,bhvd->bhwv", fmap1.astype(jnp.float32),
                         fmap2.astype(jnp.float32),
                         preferred_element_type=jnp.float32) * scale
        return (windowed_linear_sample(vol, center, radius),
                (fmap1, fmap2, center))
    out = pl.pallas_call(
        functools.partial(_alt_fwd_kernel, radius, scale),
        grid=(b, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, w1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, hb, w1, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, hb, w2, d), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hb, w1, k), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, w1, k), jnp.float32),
        interpret=_interpret(),
    )(center.astype(jnp.float32), fmap1, fmap2)
    return out, (fmap1, fmap2, center)


def _alt_pallas_bwd(radius, res, ct):
    fmap1, fmap2, center = res
    b, h, w1, d = fmap1.shape
    w2 = fmap2.shape[2]
    hb = _row_block(h, 4 * (2 * w1 * d + 2 * w2 * d + w1 * w2))
    k = 2 * radius + 1
    scale = 1.0 / float(d) ** 0.5
    if hb == 0 or w2 <= k + 1:
        from raft_stereo_tpu.ops.sampler import windowed_linear_sample

        def f(a, b2):
            vol = jnp.einsum("bhwd,bhvd->bhwv", a.astype(jnp.float32),
                             b2.astype(jnp.float32),
                             preferred_element_type=jnp.float32) * scale
            return windowed_linear_sample(vol, center, radius)

        _, vjp = jax.vjp(f, fmap1, fmap2)
        df1, df2 = vjp(ct.astype(jnp.float32))
        return df1, df2, None
    df1, df2 = pl.pallas_call(
        functools.partial(_alt_bwd_kernel, radius, scale),
        grid=(b, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, w1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, hb, w1, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, hb, w2, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, hb, w1, k), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hb, w1, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, hb, w2, d), lambda i, j: (i, j, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, w1, d), fmap1.dtype),
            jax.ShapeDtypeStruct((b, h, w2, d), fmap2.dtype),
        ],
        interpret=_interpret(),
    )(center.astype(jnp.float32), fmap1, fmap2, ct.astype(jnp.float32))
    return df1, df2, None


alt_windowed_corr_pallas.defvjp(_alt_pallas_fwd, _alt_pallas_bwd)


# ------------------------------------- memoryless fused corr (W2-blocked)
#
# The alt kernel above keeps one (Hb, W1, W2) slab per program, so past
# ~Middlebury widths it must fall back to materializing the full volume —
# exactly the residency this kernel exists to delete. Here W2 is tiled into
# Wb-lane blocks (grid axis v, innermost): each program builds only the
# (Hb, W1, Wb) sub-slab on the MXU, extracts the window taps that land
# INSIDE its block (the barrel-shifter mask drops the rest; every global tap
# lands in exactly one block), and accumulates the blended result into an
# output block whose index_map ignores v — the TPU revisiting guarantee
# keeps it resident across the whole W2 sweep. The blend is linear in the
# taps, so per-block blended accumulation is exact, not approximate.
#
# fmap2 is zero-padded up to a Wb multiple: a zero feature row correlates to
# zero, so padded taps contribute nothing to the forward, and the backward
# slices the padded rows back off df2 (their dvol contributions hit zero
# features, so df1 is untouched too).


def _fused_reference(fmap1, fmap2, center, radius):
    """Pure-JAX memoryless lookup: per-tap gather + dot, O(W) residency.

    Covers the degenerate pyramid levels (W2 <= 2r+2, fewer lanes than the
    window machinery needs) and any shape the blocked kernel cannot tile.
    Never builds a (W1, W2) slab: each of the 2r+2 taps is one fmap1-sized
    gather + reduce, strictly smaller than the lookup's own output.
    """
    w2 = fmap2.shape[2]
    k = 2 * radius + 1
    d = fmap1.shape[-1]
    scale = 1.0 / float(d) ** 0.5
    c = center.astype(jnp.float32)
    base_f = jnp.floor(c)
    frac = (c - base_f)[..., None]
    base = base_f.astype(jnp.int32) - radius
    f1 = fmap1.astype(jnp.float32)
    f2 = fmap2.astype(jnp.float32)
    taps = []
    for j in range(k + 1):
        idx = base + j                                   # (B, H, W1)
        valid = (idx >= 0) & (idx < w2)
        safe = jnp.clip(idx, 0, w2 - 1)
        f2_tap = jnp.take_along_axis(f2, safe[..., None], axis=2)
        tap = jnp.sum(f1 * f2_tap, axis=-1) * scale
        taps.append(jnp.where(valid, tap, 0.0))
    g = jnp.stack(taps, axis=-1)                         # (B, H, W1, 2r+2)
    return (1.0 - frac) * g[..., :k] + frac * g[..., 1:]


def _fused_tiles(h, w1, w2, d, k, block_w):
    """``(hb, wb, nv, w2p)`` tiling for the blocked kernel, or ``None``.

    ``wb`` starts at ``min(block_w, w2)`` (floored at the 2r+3 lanes the
    window slice needs) and HALVES until the per-program residency fits
    ``_VMEM_BUDGET_BYTES`` — the memoryless answer to pressure, where the
    alt kernel gives up and materializes. ``None`` only for degenerate
    windows or a single row that cannot fit at the minimum block."""
    if w2 <= k + 1:
        return None
    wb = max(min(int(block_w), w2), k + 2)
    while True:
        # fp32 residents per row: f1 + df1 (w1*d), f2 + df2 (wb*d), the
        # sub-slab + its scatter twin (w1*wb), window/tap temps (w1*(k+1)).
        hb = _row_block(h, 4 * (2 * w1 * d + 2 * wb * d
                                + 2 * w1 * wb + 2 * w1 * (k + 1)))
        if hb:
            nv = -(-w2 // wb)
            return hb, wb, nv, nv * wb
        if wb <= k + 2:
            return None
        wb = max(wb // 2, k + 2)


def _pad_w2(fmap2, w2p):
    w2 = fmap2.shape[2]
    if w2p == w2:
        return fmap2
    return jnp.pad(fmap2, ((0, 0), (0, 0), (0, w2p - w2), (0, 0)))


def _fused_fwd_kernel(radius, scale, wb, coords_ref, f1_ref, f2_ref, out_ref):
    v = pl.program_id(2)
    c = coords_ref[0]                            # (Hb, W1)
    f1 = f1_ref[0]                               # (Hb, W1, D)
    f2 = f2_ref[0]                               # (Hb, Wb, D)
    k = 2 * radius + 1

    @pl.when(v == 0)
    def _init():
        out_ref[0] = jnp.zeros(out_ref.shape[1:], out_ref.dtype)

    # this block's (Hb, W1, Wb) sub-slab on the MXU; never leaves VMEM
    vol = jax.lax.dot_general(
        f1, f2, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale

    base_f = jnp.floor(c)
    frac = (c - base_f)[..., None]
    # block-local tap base: taps outside [0, wb) are zeroed by the window
    # mask, so each global tap contributes from exactly one block
    base = base_f.astype(jnp.int32) - radius - v * wb
    g = _extract_window(vol, base, radius)
    out_ref[0] += (1.0 - frac) * g[..., :k] + frac * g[..., 1:]


def _fused_bwd_kernel(radius, scale, wb, coords_ref, f1_ref, f2_ref, ct_ref,
                      df1_ref, df2_ref):
    v = pl.program_id(2)
    c = coords_ref[0]
    f1 = f1_ref[0]
    f2 = f2_ref[0]
    ct = ct_ref[0].astype(jnp.float32)

    @pl.when(v == 0)
    def _init():
        df1_ref[0] = jnp.zeros(df1_ref.shape[1:], df1_ref.dtype)

    base_f = jnp.floor(c)
    frac = (c - base_f)[..., None]
    base = base_f.astype(jnp.int32) - radius - v * wb

    zeros = jnp.zeros_like(ct[..., :1])
    dg = (jnp.concatenate([(1.0 - frac) * ct, zeros], axis=-1)
          + jnp.concatenate([zeros, frac * ct], axis=-1))
    # taps outside this block are masked before the scatter, mirroring the
    # forward's per-block window mask
    dvol = _scatter_window(dg, base, radius, wb) * scale  # (Hb, W1, Wb)

    # df1 accumulates across the W2 sweep (fp32 accumulator, index_map
    # ignores v); df2 is per-block, written once
    df1_ref[0] += jax.lax.dot_general(
        dvol, f2.astype(jnp.float32),
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    df2_ref[0] = jax.lax.dot_general(
        dvol, f1.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(df2_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_windowed_corr_pallas(fmap1: jax.Array, fmap2: jax.Array,
                               center: jax.Array, radius: int,
                               block_w: int = 256) -> jax.Array:
    """Memoryless fused correlation lookup, W2-blocked.

    ``fmap1 (B, H, W1, D)``, ``fmap2 (B, H, W2, D)``, ``center (B, H, W1)``
    -> ``(B, H, W1, 2r+1)`` with the 1/sqrt(D) scaling applied — same
    semantics as :func:`alt_windowed_corr_pallas` and the reg volume lookup,
    but the largest transient is the (Hb, W1, min(block_w, W2)) sub-slab:
    no B*H*W1*W2 volume exists in HBM OR as a whole-row VMEM slab at any
    width, forward or backward. ``block_w`` trades VMEM residency against
    grid steps (config.fused_block_w / --fused_block_w).

    The coords gradient is intentionally not produced (the model detaches
    coords each iteration, raft_stereo.py:109, matching the reference CUDA
    backward's None, core/corr.py:29).
    """
    return _fused_pallas_fwd(fmap1, fmap2, center, radius, block_w)[0]


def _fused_pallas_fwd(fmap1, fmap2, center, radius, block_w):
    b, h, w1, d = fmap1.shape
    w2 = fmap2.shape[2]
    k = 2 * radius + 1
    tiles = _fused_tiles(h, w1, w2, d, k, block_w)
    if tiles is None:
        return (_fused_reference(fmap1, fmap2, center, radius),
                (fmap1, fmap2, center))
    hb, wb, nv, w2p = tiles
    scale = 1.0 / float(d) ** 0.5
    out = pl.pallas_call(
        functools.partial(_fused_fwd_kernel, radius, scale, wb),
        grid=(b, h // hb, nv),
        in_specs=[
            pl.BlockSpec((1, hb, w1), lambda i, j, v: (i, j, 0)),
            pl.BlockSpec((1, hb, w1, d), lambda i, j, v: (i, j, 0, 0)),
            pl.BlockSpec((1, hb, wb, d), lambda i, j, v: (i, j, v, 0)),
        ],
        out_specs=pl.BlockSpec((1, hb, w1, k), lambda i, j, v: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, w1, k), jnp.float32),
        interpret=_interpret(),
    )(center.astype(jnp.float32), fmap1, _pad_w2(fmap2, w2p))
    return out, (fmap1, fmap2, center)


def _fused_pallas_bwd(radius, block_w, res, ct):
    fmap1, fmap2, center = res
    b, h, w1, d = fmap1.shape
    w2 = fmap2.shape[2]
    k = 2 * radius + 1
    tiles = _fused_tiles(h, w1, w2, d, k, block_w)
    if tiles is None:
        _, vjp = jax.vjp(
            lambda a, b2: _fused_reference(a, b2, center, radius),
            fmap1, fmap2)
        df1, df2 = vjp(ct.astype(jnp.float32))
        return df1.astype(fmap1.dtype), df2.astype(fmap2.dtype), None
    hb, wb, nv, w2p = tiles
    scale = 1.0 / float(d) ** 0.5
    df1, df2 = pl.pallas_call(
        functools.partial(_fused_bwd_kernel, radius, scale, wb),
        grid=(b, h // hb, nv),
        in_specs=[
            pl.BlockSpec((1, hb, w1), lambda i, j, v: (i, j, 0)),
            pl.BlockSpec((1, hb, w1, d), lambda i, j, v: (i, j, 0, 0)),
            pl.BlockSpec((1, hb, wb, d), lambda i, j, v: (i, j, v, 0)),
            pl.BlockSpec((1, hb, w1, k), lambda i, j, v: (i, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hb, w1, d), lambda i, j, v: (i, j, 0, 0)),
            pl.BlockSpec((1, hb, wb, d), lambda i, j, v: (i, j, v, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, w1, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, w2p, d), fmap2.dtype),
        ],
        interpret=_interpret(),
    )(center.astype(jnp.float32), fmap1, _pad_w2(fmap2, w2p),
      ct.astype(jnp.float32))
    if w2p != w2:
        df2 = df2[:, :, :w2]
    return df1.astype(fmap1.dtype), df2, None


fused_windowed_corr_pallas.defvjp(_fused_pallas_fwd, _fused_pallas_bwd)
