"""1-D linear sampling primitives.

The reference's correlation lookup is, in every implementation, a 1-D linear
interpolation along the disparity axis:

* ``bilinear_sampler`` (core/utils/utils.py:59-74) wraps ``grid_sample`` with
  ``align_corners=True`` and zero padding; on the ``(B*H*W1, 1, 1, W2)``-shaped
  correlation volume the ``H > 1`` guard makes it exactly 1-D.
* the CUDA kernel (sampler/sampler_kernel.cu:20-60) computes ``dy`` but never
  uses it — it blends two adjacent taps along W2 with weights ``1-dx``/``dx``.

Here that semantics is one pure function on the *last* axis, expressed as a
clip-gather + mask (dynamic-slice-friendly for XLA) rather than a random-access
scatter/gather. Out-of-range coordinates contribute zero, matching
``grid_sample(padding_mode='zeros', align_corners=True)`` exactly: a tap at
coordinate ``x`` blends ``v[floor(x)]`` and ``v[floor(x)+1]``, where any index
outside ``[0, W-1]`` reads as 0.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def linear_sample_1d(values: jax.Array, x: jax.Array) -> jax.Array:
    """Linearly sample ``values`` along its last axis at pixel coordinates ``x``.

    Args:
      values: ``(..., W)`` array. Leading dims must broadcast with ``x``'s leading
        dims (all but the last axis of ``x``).
      x: ``(..., K)`` pixel coordinates in ``[0, W-1]`` (out-of-range gives 0).

    Returns:
      ``(..., K)`` sampled values, in ``values.dtype``.
    """
    w = values.shape[-1]
    x = x.astype(jnp.float32)
    x0f = jnp.floor(x)
    dx = (x - x0f).astype(values.dtype)
    i0 = x0f.astype(jnp.int32)
    i1 = i0 + 1

    def gather(idx):
        valid = (idx >= 0) & (idx < w)
        safe = jnp.clip(idx, 0, w - 1)
        v = jnp.take_along_axis(
            jnp.broadcast_to(values, x.shape[:-1] + (w,)), safe, axis=-1
        )
        return jnp.where(valid, v, jnp.zeros_like(v))

    return gather(i0) * (1 - dx) + gather(i1) * dx


def window_taps(x: jax.Array, radius: int) -> jax.Array:
    """Expand center coordinates ``x (...)`` into ``(..., 2r+1)`` taps ``x + [-r..r]``.

    Mirrors ``dx = torch.linspace(-r, r, 2r+1)`` (core/corr.py:135): taps are in
    ascending offset order, which fixes the channel order fed to the motion
    encoder's 1x1 conv (core/update.py:71).
    """
    offsets = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    return x[..., None] + offsets


def gather_window_2d(values: jax.Array, x: jax.Array) -> jax.Array:
    """Sample per-row feature vectors along W with 1-D linear interpolation.

    This is the memory-frugal "alt" primitive: rather than materializing the
    O(W^2) correlation volume, sample the right feature map at the lookup taps
    and dot with the left features (core/corr.py:72-87, where ``grid_sample``
    with exact integer y rows degenerates to per-row 1-D interpolation).

    Args:
      values: ``(B, H, W, D)`` feature map.
      x: ``(B, H, Q, K)`` pixel x-coordinates (per row), e.g. Q=W1, K=2r+1 taps.

    Returns:
      ``(B, H, Q, K, D)`` sampled features (zero outside ``[0, W-1]``).
    """
    b, h, w, d = values.shape
    q, k = x.shape[2], x.shape[3]
    x = x.astype(jnp.float32)
    x0f = jnp.floor(x)
    dx = (x - x0f).astype(values.dtype)[..., None]
    i0 = x0f.astype(jnp.int32)
    i1 = i0 + 1

    def gather(idx):
        valid = ((idx >= 0) & (idx < w))[..., None]
        safe = jnp.clip(idx, 0, w - 1).reshape(b, h, q * k)
        v = jnp.take_along_axis(values, safe[..., None], axis=2)
        return jnp.where(valid, v.reshape(b, h, q, k, d), 0)

    return gather(i0) * (1 - dx) + gather(i1) * dx
