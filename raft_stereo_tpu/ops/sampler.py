"""1-D linear sampling primitives.

The reference's correlation lookup is, in every implementation, a 1-D linear
interpolation along the disparity axis:

* ``bilinear_sampler`` (core/utils/utils.py:59-74) wraps ``grid_sample`` with
  ``align_corners=True`` and zero padding; on the ``(B*H*W1, 1, 1, W2)``-shaped
  correlation volume the ``H > 1`` guard makes it exactly 1-D.
* the CUDA kernel (sampler/sampler_kernel.cu:20-60) computes ``dy`` but never
  uses it — it blends two adjacent taps along W2 with weights ``1-dx``/``dx``.

Here that semantics is one pure function on the *last* axis, expressed as a
clip-gather + mask (dynamic-slice-friendly for XLA) rather than a random-access
scatter/gather. Out-of-range coordinates contribute zero, matching
``grid_sample(padding_mode='zeros', align_corners=True)`` exactly: a tap at
coordinate ``x`` blends ``v[floor(x)]`` and ``v[floor(x)+1]``, where any index
outside ``[0, W-1]`` reads as 0.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def linear_sample_1d(values: jax.Array, x: jax.Array) -> jax.Array:
    """Linearly sample ``values`` along its last axis at pixel coordinates ``x``.

    Args:
      values: ``(..., W)`` array. Leading dims must broadcast with ``x``'s leading
        dims (all but the last axis of ``x``).
      x: ``(..., K)`` pixel coordinates in ``[0, W-1]`` (out-of-range gives 0).

    Returns:
      ``(..., K)`` sampled values, in ``values.dtype``.
    """
    w = values.shape[-1]
    x = x.astype(jnp.float32)
    x0f = jnp.floor(x)
    dx = (x - x0f).astype(values.dtype)
    i0 = x0f.astype(jnp.int32)
    i1 = i0 + 1

    def gather(idx):
        valid = (idx >= 0) & (idx < w)
        safe = jnp.clip(idx, 0, w - 1)
        v = jnp.take_along_axis(
            jnp.broadcast_to(values, x.shape[:-1] + (w,)), safe, axis=-1
        )
        return jnp.where(valid, v, jnp.zeros_like(v))

    return gather(i0) * (1 - dx) + gather(i1) * dx


def windowed_linear_sample(values: jax.Array, center: jax.Array,
                           radius: int) -> jax.Array:
    """Sample a contiguous ``2r+1``-tap window around ``center``, TPU-fast.

    Semantically identical to ``linear_sample_1d(values, window_taps(center,
    radius))`` — every tap shares ``center``'s fractional part, so the window
    is ``(1-f) * v[base+k] + f * v[base+k+1]`` for ``k in [0, 2r]`` with
    ``base = floor(center) - r`` (the structure the reference's CUDA kernel
    exploits, sampler/sampler_kernel.cu:20-60, which loops ``2r+2`` integer
    taps and blends with ``dx``/``1-dx``).

    Implementation note (the TPU-native part): per-pixel random-access gathers
    are catastrophically slow on TPU (measured 131 ms per lookup at the
    SceneFlow train shape vs ~5 ms for the whole GRU update). Instead the
    ``2r+2`` integer taps are computed as equality-masked reductions over the
    full W axis — elementwise VPU work that XLA fuses into ~``2r+2`` passes
    over the volume, with no gather at all. Out-of-range taps reduce over an
    all-false mask and yield exactly 0, matching ``grid_sample``'s zero
    padding.

    Args:
      values: ``(..., W)`` volume row.
      center: ``(...)`` window-center coordinates (leading dims broadcast with
        ``values``' leading dims).

    XLA's automatic transpose of the masked reductions is efficient in the
    full training graph (a hand-written custom_vjp mirroring the reference's
    CUDA backward was measured end-to-end neutral and adds residual memory;
    it was removed — measure in the full step before re-adding).

    Returns:
      ``(..., 2r+1)`` sampled taps in ascending offset order, float32.
    """
    w = values.shape[-1]
    c = center.astype(jnp.float32)
    base_f = jnp.floor(c)
    frac = (c - base_f)[..., None]
    base = base_f.astype(jnp.int32) - radius
    k = 2 * radius + 1

    vals32 = values.astype(jnp.float32)
    # j-index each volume position feeds: position v contributes to tap j
    # when v == base + j
    idx = jnp.arange(w, dtype=jnp.int32) - base[..., None]  # (..., W)
    taps = [jnp.sum(jnp.where(idx == j, vals32, 0.0), axis=-1)
            for j in range(k + 1)]
    g = jnp.stack(taps, axis=-1)  # (..., 2r+2)
    return (1.0 - frac) * g[..., :k] + frac * g[..., 1:]


def window_taps(x: jax.Array, radius: int) -> jax.Array:
    """Expand center coordinates ``x (...)`` into ``(..., 2r+1)`` taps ``x + [-r..r]``.

    Mirrors ``dx = torch.linspace(-r, r, 2r+1)`` (core/corr.py:135): taps are in
    ascending offset order, which fixes the channel order fed to the motion
    encoder's 1x1 conv (core/update.py:71).
    """
    offsets = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    return x[..., None] + offsets


def gather_window_2d(values: jax.Array, x: jax.Array) -> jax.Array:
    """Sample per-row feature vectors along W with 1-D linear interpolation.

    This is the memory-frugal "alt" primitive: rather than materializing the
    O(W^2) correlation volume, sample the right feature map at the lookup taps
    and dot with the left features (core/corr.py:72-87, where ``grid_sample``
    with exact integer y rows degenerates to per-row 1-D interpolation).

    Args:
      values: ``(B, H, W, D)`` feature map.
      x: ``(B, H, Q, K)`` pixel x-coordinates (per row), e.g. Q=W1, K=2r+1 taps.

    Returns:
      ``(B, H, Q, K, D)`` sampled features (zero outside ``[0, W-1]``).
    """
    b, h, w, d = values.shape
    q, k = x.shape[2], x.shape[3]
    x = x.astype(jnp.float32)
    x0f = jnp.floor(x)
    dx = (x - x0f).astype(values.dtype)[..., None]
    i0 = x0f.astype(jnp.int32)
    i1 = i0 + 1

    def gather(idx):
        valid = ((idx >= 0) & (idx < w))[..., None]
        safe = jnp.clip(idx, 0, w - 1).reshape(b, h, q * k)
        v = jnp.take_along_axis(values, safe[..., None], axis=2)
        return jnp.where(valid, v.reshape(b, h, q, k, d), 0)

    return gather(i0) * (1 - dx) + gather(i1) * dx
