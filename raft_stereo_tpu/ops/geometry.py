"""Geometry / resampling ops (NHWC, TPU-first).

TPU-native re-design of the reference's tensor utilities (core/utils/utils.py)
and the convex-upsampling path (core/raft_stereo.py:55-67): everything is NHWC
(channel-last, the TPU-preferred layout), align-corners bilinear resizes are
expressed as two small dense interpolation matmuls (MXU-friendly, no gathers),
and convex upsampling is 9 static shifts + an einsum instead of ``F.unfold``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def coords_grid(batch: int, ht: int, wd: int, dtype=jnp.float32) -> jax.Array:
    """Pixel coordinate grid ``(B, H, W, 2)`` with channels ``(x, y)``.

    Mirrors ``coords_grid`` (core/utils/utils.py:77-80), channel-last.
    """
    ys, xs = jnp.meshgrid(jnp.arange(ht, dtype=dtype), jnp.arange(wd, dtype=dtype),
                          indexing="ij")
    grid = jnp.stack([xs, ys], axis=-1)
    return jnp.broadcast_to(grid[None], (batch, ht, wd, 2))


def avg_pool2d(x: jax.Array, window: Tuple[int, int], stride: Tuple[int, int],
               padding: Tuple[int, int] = (0, 0)) -> jax.Array:
    """NHWC average pool matching ``F.avg_pool2d(count_include_pad=True)``.

    Padded zeros count toward the divisor (the torch default), so the sum is
    always divided by ``window[0]*window[1]``. Windows that would overhang the
    input with no padding are dropped (floor semantics), as in torch.
    """
    kh, kw = window
    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add,
        window_dimensions=(1, kh, kw, 1),
        window_strides=(1, stride[0], stride[1], 1),
        padding=((0, 0), (padding[0], padding[0]), (padding[1], padding[1]), (0, 0)),
    )
    return summed / (kh * kw)


def pool2x(x: jax.Array) -> jax.Array:
    """3x3 stride-2 pad-1 average pool (core/update.py:87-88)."""
    return avg_pool2d(x, (3, 3), (2, 2), (1, 1))


def pool_w2(x: jax.Array) -> jax.Array:
    """[1,2] stride [1,2] average pool along W (corr pyramid, core/corr.py:124)."""
    return avg_pool2d(x, (1, 2), (1, 2), (0, 0))


def pool_last_axis2(x: jax.Array) -> jax.Array:
    """Stride-2 window-2 average pool along the LAST axis (floor semantics).

    Used on the ``(B, H, W1, W2)`` correlation volume, whose disparity-search
    axis W2 is the trailing axis (the reference reshapes to ``(B*H*W1,1,1,W2)``
    and pools ``[1,2]`` — core/corr.py:120-124).
    """
    ndim = x.ndim
    window = (1,) * (ndim - 1) + (2,)
    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add,
        window_dimensions=window, window_strides=window,
        padding=((0, 0),) * ndim,
    )
    return summed / 2.0


def _interp_matrix(n_out: int, n_in: int, dtype=jnp.float32) -> jax.Array:
    """Dense ``(n_out, n_in)`` align-corners linear interpolation matrix.

    Sample positions are ``linspace(0, n_in-1, n_out)`` — the align_corners=True
    grid of ``F.interpolate(mode='bilinear')``. Built with numpy at trace time
    (shapes are static under jit) so the resize becomes a single matmul.
    """
    if n_in == 1:
        return np.ones((n_out, 1), dtype=np.float32)
    pos = np.linspace(0.0, n_in - 1.0, n_out)
    i0 = np.floor(pos).astype(np.int64)
    i0 = np.clip(i0, 0, n_in - 2)
    frac = pos - i0
    m = np.zeros((n_out, n_in), dtype=np.float32)
    rows = np.arange(n_out)
    m[rows, i0] = 1.0 - frac
    m[rows, i0 + 1] = frac
    return jnp.asarray(m, dtype=dtype)


def resize_bilinear_align_corners(x: jax.Array, size: Tuple[int, int]) -> jax.Array:
    """NHWC bilinear resize with align_corners=True semantics.

    Mirrors ``interp`` (core/update.py:93-95) and the value-grid of ``upflow8``
    (core/utils/utils.py:83-85). Expressed as two dense interpolation matmuls
    (separable), which XLA maps onto the MXU instead of emitting gathers.
    """
    h_out, w_out = size
    b, h_in, w_in, c = x.shape
    if (h_in, w_in) == (h_out, w_out):
        return x
    mh = _interp_matrix(h_out, h_in, x.dtype)
    mw = _interp_matrix(w_out, w_in, x.dtype)
    x = jnp.einsum("oh,bhwc->bowc", mh, x)
    x = jnp.einsum("ow,bhwc->bhoc", mw, x)
    return x


def upflow(flow: jax.Array, factor: int = 8) -> jax.Array:
    """Upsample a flow field by ``factor`` and scale its values (utils.py:83-85)."""
    b, h, w, c = flow.shape
    return factor * resize_bilinear_align_corners(flow, (factor * h, factor * w))


def extract_3x3_patches(x: jax.Array) -> jax.Array:
    """Zero-padded 3x3 patch extraction: ``(B,H,W,C) -> (B,H,W,9,C)``.

    Patch index k = 3*dy + dx (row-major over the 3x3 window), matching the
    channel order of ``F.unfold(..., [3,3], padding=1)`` used by convex
    upsampling (core/raft_stereo.py:62).
    """
    padded = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    h, w = x.shape[1], x.shape[2]
    shifts = [padded[:, dy:dy + h, dx:dx + w, :] for dy in range(3) for dx in range(3)]
    return jnp.stack(shifts, axis=3)


def upsample_flow_convex(flow: jax.Array, mask: jax.Array, factor: int) -> jax.Array:
    """Convex-combination upsampling of flow (core/raft_stereo.py:55-67).

    Args:
      flow: ``(B, H, W, C)`` low-resolution flow (C=2).
      mask: ``(B, H, W, 9*factor*factor)`` unnormalized weights from the mask
        head; channel index decomposes as ``k*factor*factor + fy*factor + fx``
        (the reference's ``view(N, 1, 9, factor, factor, H, W)``).
      factor: upsampling factor (2**n_downsample).

    Returns:
      ``(B, factor*H, factor*W, C)`` upsampled flow; values scaled by ``factor``.
    """
    b, h, w, c = flow.shape
    mask = mask.reshape(b, h, w, 9, factor, factor)
    mask = jax.nn.softmax(mask, axis=3)
    patches = extract_3x3_patches(factor * flow)  # (B,H,W,9,C)
    # NOTE: measured on TPU in the full train step, this einsum form beats an
    # unrolled sum of broadcast multiplies by ~1.6x end-to-end — XLA fuses
    # the batched tiny contraction well in context; don't "optimize" it.
    up = jnp.einsum("bhwkyx,bhwkc->bhwyxc", mask, patches)
    # (B,H,W,fy,fx,C) -> (B, H*fy, W*fx, C)
    up = up.transpose(0, 1, 3, 2, 4, 5)
    return up.reshape(b, h * factor, w * factor, c)


def upsample_disparity_convex(flow: jax.Array, mask: jax.Array,
                              factor: int) -> jax.Array:
    """Single-channel convex upsampling — the TPU-layout-aware hot path.

    Stereo only ever keeps the x-flow channel (every call site slices
    ``[..., :1]``; the y-delta is zeroed each iteration, raft_stereo.py:120),
    so this computes :func:`upsample_flow_convex` for channel 0 alone with
    shapes chosen for the TPU: the 9-tap contraction is unrolled over
    ``(B, H, W, f*f)`` arrays (lane-friendly minor dims) instead of the
    generic ``bhwkyx,bhwkc`` einsum whose tiny batched dot + 6-D transpose
    measured ~20% of the whole train step.

    Returns ``(B, H*f, W*f, 1)``.
    """
    up = convex_upsample_tiles(flow, mask, factor)
    return upsample_tiles_to_image(up)


def convex_upsample_tiles(flow: jax.Array, mask: jax.Array,
                          factor: int) -> jax.Array:
    """Convex upsampling WITHOUT the final interleave: ``(B, h, w, f, f)``.

    The tile layout keeps the minor dims lane-friendly; losses that reduce
    over all pixels are layout-invariant, so training paths can consume the
    tiles directly (transposing the small GT once) and skip the large
    (iters*B, H*f, W*f) transpose entirely (measured ~30 ms/step of "data
    formatting" at the SceneFlow recipe shape).
    """
    b, h, w, _ = flow.shape
    f2 = factor * factor
    m = jax.nn.softmax(mask.reshape(b, h, w, 9, f2), axis=3)
    p = extract_3x3_patches(factor * flow[..., :1])[..., 0]  # (B,H,W,9)
    up = sum(m[:, :, :, k, :] * p[:, :, :, k, None] for k in range(9))
    return up.reshape(b, h, w, factor, factor)


def upsample_tiles_to_image(up: jax.Array) -> jax.Array:
    """``(B, h, w, f, f)`` tiles -> ``(B, h*f, w*f, 1)`` image."""
    b, h, w, f, _ = up.shape
    up = up.transpose(0, 1, 3, 2, 4)
    return up.reshape(b, h * f, w * f, 1)


def image_to_upsample_tiles(img: jax.Array, factor: int) -> jax.Array:
    """Inverse of :func:`upsample_tiles_to_image` for a ``(B, H, W, C<=1)``
    image: ``(B, H/f, W/f, f, f)``."""
    b, hh, ww, _ = img.shape
    h, w = hh // factor, ww // factor
    return img[..., 0].reshape(b, h, factor, w, factor).transpose(0, 1, 3, 2, 4)


class InputPadder:
    """Pads NHWC images so H, W are divisible by ``divis_by`` (utils.py:7-26).

    ``mode='sintel'`` splits padding evenly top/bottom; otherwise all height
    padding goes to the bottom. Replicate padding, exact unpad.
    """

    def __init__(self, dims: Sequence[int], mode: str = "sintel",
                 divis_by: int = 8, target: "Optional[Tuple[int, int]]" = None):
        self.ht, self.wd = dims[-3], dims[-2]  # NHWC
        if target is not None:
            # pad to an explicit (H, W) bucket >= the image, to bound the
            # number of distinct compiled shapes during evaluation
            th, tw = target
            if th < self.ht or tw < self.wd:
                raise ValueError(f"target {target} smaller than image "
                                 f"({self.ht}, {self.wd})")
            pad_ht, pad_wd = th - self.ht, tw - self.wd
        else:
            pad_ht = (((self.ht // divis_by) + 1) * divis_by - self.ht) % divis_by
            pad_wd = (((self.wd // divis_by) + 1) * divis_by - self.wd) % divis_by
        if mode == "sintel":
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2,
                         pad_ht // 2, pad_ht - pad_ht // 2]
        else:
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht]

    def pad(self, *inputs: jax.Array):
        l, r, t, b = self._pad
        out = [jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)), mode="edge")
               for x in inputs]
        return out if len(out) > 1 else out[0]

    def pad_zeros(self, *inputs: jax.Array):
        """Like :meth:`pad` but zero-filled — for ground-truth/validity
        planes, where edge replication would mark the padding as valid
        signal (the iter-EPE aux masks pooled cells on exactly this)."""
        l, r, t, b = self._pad
        out = [jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)),
                       mode="constant") for x in inputs]
        return out if len(out) > 1 else out[0]

    def unpad(self, x: jax.Array) -> jax.Array:
        l, r, t, b = self._pad
        ht, wd = x.shape[-3], x.shape[-2]
        return x[..., t:ht - b, l:wd - r, :]
