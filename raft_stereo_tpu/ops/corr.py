"""Pluggable 1-D correlation layer — the hot path.

The reference selects between four interchangeable correlation implementations
with the ``--corr_implementation`` string (core/raft_stereo.py:90-100):

* ``reg``   — materialize the all-pairs volume once, pyramid-pool it, and do a
  (2r+1)-tap linear lookup per level per iteration (core/corr.py:110-156).
* ``alt``   — never materialize the O(H*W^2) volume; per iteration, sample the
  pooled right feature map at the lookup taps and dot with the left features
  (core/corr.py:64-107). O(W) memory, for high-resolution images.
* ``reg_cuda``/``alt_cuda`` — CUDA-fused variants (sampler/sampler_kernel.cu).

This module keeps the same plugin surface, TPU-first: the volume is built with a
batched row matmul (MXU), lookups are contiguous-window gathers, and the fused
variants (``reg_pallas``/``alt_pallas``) are Pallas kernels registered here.

Because the refinement loop is a ``lax.scan``, the correlation state must be a
pytree: ``init_corr`` returns a :class:`CorrState` carrying either the pooled
volume pyramid (reg) or the feature-map pyramid (alt); ``corr_lookup`` is a pure
function of ``(state, coords)``.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from raft_stereo_tpu.ops.geometry import pool_last_axis2, pool_w2
from raft_stereo_tpu.ops.sampler import windowed_linear_sample


@struct.dataclass
class CorrState:
    """Pytree correlation state threaded through the refinement scan."""

    levels: Tuple[jax.Array, ...]  # per-level volume (reg) or fmap2 (alt/ring)
    fmap1: jax.Array | None        # left features, only for alt-style lookups
    impl: str = struct.field(pytree_node=False)
    radius: int = struct.field(pytree_node=False)
    num_levels: int = struct.field(pytree_node=False, default=4)
    # W2 block width for the memoryless 'fused' kernel (static metadata, not
    # a pytree leaf — it selects the Pallas grid, so it must be trace-static)
    block_w: int = struct.field(pytree_node=False, default=256)


def all_pairs_correlation(fmap1: jax.Array, fmap2: jax.Array) -> jax.Array:
    """All-pairs 1-D correlation volume ``(B, H, W1, W2)``, scaled by 1/sqrt(D).

    The reference's ``einsum('aijk,aijh->ajkh')`` (core/corr.py:148-156), NHWC:
    per (batch, row) this is a (W1, D) x (D, W2) matmul — large, batched, and
    MXU-shaped. Accumulates in fp32 regardless of input dtype.
    """
    d = fmap1.shape[-1]
    corr = jnp.einsum("bhwd,bhvd->bhwv", fmap1, fmap2,
                      preferred_element_type=jnp.float32)
    return corr / jnp.sqrt(jnp.float32(d))


def _build_reg(fmap1, fmap2, num_levels, radius,
               storage_dtype=None, block_w=None) -> CorrState:
    volume = all_pairs_correlation(fmap1.astype(jnp.float32),
                                   fmap2.astype(jnp.float32))
    if storage_dtype is not None:
        # bf16 volume storage halves the HBM footprint and the lookup's
        # bandwidth; taps are blended in fp32 after the read. Precedent: the
        # reference's reg_cuda path runs the whole lookup in fp16
        # (sampler_kernel.cu:126, evaluate_stereo.py:229-231).
        volume = volume.astype(storage_dtype)
    levels = [volume]
    for _ in range(num_levels - 1):
        levels.append(pool_last_axis2(levels[-1]))
    return CorrState(levels=tuple(levels), fmap1=None, impl="reg",
                     radius=radius, num_levels=num_levels)


def _build_alt(fmap1, fmap2, num_levels, radius,
               storage_dtype=None, block_w=None) -> CorrState:
    dt = storage_dtype or jnp.float32
    fmap1 = fmap1.astype(dt)
    fmap2 = fmap2.astype(dt)
    levels = [fmap2]
    for _ in range(num_levels - 1):
        levels.append(pool_w2(levels[-1]))
    return CorrState(levels=tuple(levels), fmap1=fmap1, impl="alt",
                     radius=radius, num_levels=num_levels)


def _build_fused(fmap1, fmap2, num_levels, radius,
                 storage_dtype=None, block_w=None) -> CorrState:
    """Memoryless fused state: the same O(W) pyramid as ``alt`` (pooled
    fmap2 + fmap1 — the scan carry shrinks identically), but the lookup is
    the W2-blocked Pallas kernel, which never materializes ANY level's
    (W1, W2) slab — in HBM or VMEM — at any width (``alt_pallas`` falls back
    to the full volume when its whole-row slab outgrows VMEM)."""
    dt = storage_dtype or jnp.float32
    fmap1 = fmap1.astype(dt)
    fmap2 = fmap2.astype(dt)
    levels = [fmap2]
    for _ in range(num_levels - 1):
        levels.append(pool_w2(levels[-1]))
    return CorrState(levels=tuple(levels), fmap1=fmap1, impl="fused",
                     radius=radius, num_levels=num_levels,
                     block_w=int(block_w or 256))


def _lookup_reg(state: CorrState, coords_x: jax.Array) -> jax.Array:
    """(2r+1)-tap pyramid lookup on the materialized volume.

    ``coords_x``: (B, H, W1) lookup centers in level-0 pixel units. Output
    channel order is [level0 taps -r..r, level1 taps, ...] (core/corr.py:127-146).
    """
    out = []
    for i, volume in enumerate(state.levels):
        out.append(windowed_linear_sample(volume, coords_x / (2 ** i),
                                          state.radius))
    return jnp.concatenate(out, axis=-1)


def _lookup_alt(state: CorrState, coords_x: jax.Array) -> jax.Array:
    """On-the-fly lookup (core/corr.py:72-107), TPU-first.

    Rather than gathering D-dim feature windows from fmap2 (per-pixel gathers
    are TPU-hostile), recompute each level's correlation row with a batched
    MXU matmul — ~20 GFLOP at train shapes, microseconds on the MXU — and run
    the same windowed sample as ``reg``. Persistent memory stays O(W) (only
    the pooled feature pyramid is kept); the row volume is a transient XLA
    temp. Same memory/compute trade as the reference's "alt", better-suited
    hardware mapping.
    """
    d = state.fmap1.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    out = []
    for i, fmap2 in enumerate(state.levels):
        volume = jnp.einsum("bhwd,bhvd->bhwv", state.fmap1, fmap2,
                            preferred_element_type=jnp.float32)
        out.append(windowed_linear_sample(volume, coords_x / (2 ** i),
                                          state.radius) * scale)
    return jnp.concatenate(out, axis=-1)


def _build_ring(fmap1, fmap2, num_levels, radius,
                storage_dtype=None, block_w=None) -> CorrState:
    """Ring-sharded alt: keep raw feature maps; pooling happens per ring
    block inside the lookup (parallel/ring_corr.py).

    With no ``seq``-sharded mesh in scope at trace time, degrade to a plain
    alt state HERE (pyramid pooled once at init) rather than per-lookup, so
    the fallback costs exactly what alt costs."""
    from raft_stereo_tpu.parallel.mesh import SEQ_AXIS

    mesh = _ambient_mesh()
    if (mesh is None or SEQ_AXIS not in mesh.axis_names
            or mesh.shape[SEQ_AXIS] == 1):
        import warnings
        warnings.warn(
            "corr_implementation 'ring' has no mesh with a sharded 'seq' "
            "axis in scope; falling back to the unsharded 'alt' lookup "
            "(same semantics, no width sharding). Trace under "
            "`with make_mesh(data, seq):` to enable the ring.")
        return _build_alt(fmap1, fmap2, num_levels, radius,
                          storage_dtype=storage_dtype)
    dt = storage_dtype or jnp.float32
    return CorrState(levels=(fmap2.astype(dt),), fmap1=fmap1.astype(dt),
                     impl="ring", radius=radius, num_levels=num_levels)


def _ambient_mesh():
    """The device mesh in scope at trace time, if any.

    Prefers the modern abstract mesh (``jax.sharding.use_mesh``); falls back
    to the legacy global physical mesh set by ``with mesh:`` (what the pjit
    step builders in parallel/ use).
    """
    get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract is not None:
        m = get_abstract()
        if m is not None and getattr(m, "axis_names", ()):
            return m
    try:
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            from jax.interpreters.pxla import thread_resources
        m = thread_resources.env.physical_mesh
        if m.axis_names:
            return m
    except Exception:
        pass
    return None


def _lookup_ring(state: CorrState, coords_x: jax.Array) -> jax.Array:
    """Sequence-parallel pyramid lookup: ppermute fmap2 blocks around the
    mesh's ``seq`` axis, summing exact per-block contributions (SURVEY §5
    long-context row — ring-attention-shaped, but for correlation).

    Outside any mesh (or with an unsharded ``seq`` axis) this degrades to the
    unsharded alt lookup — identical semantics, no collectives — so the
    "ring" plugin is runnable everywhere. (Normally :func:`_build_ring`
    already catches the no-mesh case at init; this branch only triggers if
    the mesh disappeared between init and lookup within one trace.)
    """
    from raft_stereo_tpu.parallel.mesh import SEQ_AXIS

    mesh = _ambient_mesh()
    if (mesh is None or SEQ_AXIS not in mesh.axis_names
            or mesh.shape[SEQ_AXIS] == 1):
        alt_state = _build_alt(state.fmap1, state.levels[0],
                               state.num_levels, state.radius)
        return _lookup_alt(alt_state, coords_x)

    # "ring" composes with the auto-SPMD paths (pjit / jit-under-mesh),
    # where make_ring_lookup's shard_map is the one manual region. Inside an
    # ALREADY-manual region (a shard_map body, e.g. make_shardmap_train_step
    # on a seq>1 mesh) nesting another shard_map fails at trace time and the
    # body's locally-built coords grid would be in the wrong (local) frame —
    # reject with an actionable error instead.
    if SEQ_AXIS in getattr(mesh, "manual_axes", ()):
        raise NotImplementedError(
            "corr_implementation='ring' cannot run inside a shard_map body "
            f"(axis {SEQ_AXIS!r} is already manual). Use the pjit data×seq "
            "path (parallel.data_parallel.make_pjit_train_step) for "
            "sequence-sharded training, or call "
            "parallel.ring_corr.ring_corr_lookup directly with per-shard "
            "maps and global coords.")

    from raft_stereo_tpu.parallel.ring_corr import make_ring_lookup
    ring = make_ring_lookup(mesh, radius=state.radius,
                            num_levels=state.num_levels)
    return ring(state.fmap1, state.levels[0], coords_x)


_BUILDERS: Dict[str, Callable] = {}
_LOOKUPS: Dict[str, Callable] = {}


def register_corr(name: str, builder: Callable, lookup: Callable) -> None:
    """Register a correlation implementation (the plugin registry).

    ``builder(fmap1, fmap2, num_levels, radius, *, storage_dtype=None,
    block_w=None) -> CorrState`` and ``lookup(state, coords_x) -> (B, H, W1,
    num_levels*(2r+1))`` features. ``storage_dtype`` requests
    reduced-precision state storage and ``block_w`` a W2 tile width for
    blocked kernels (builders may ignore either, but must accept the
    keywords). New strategies (e.g. a ring-sharded variant for very wide
    images) plug in here without touching the model.
    """
    _BUILDERS[name] = builder
    _LOOKUPS[name] = lookup


register_corr("reg", _build_reg, _lookup_reg)
register_corr("alt", _build_alt, _lookup_alt)
register_corr("ring", _build_ring, _lookup_ring)


def init_corr(impl: str, fmap1: jax.Array, fmap2: jax.Array, *,
              num_levels: int = 4, radius: int = 4,
              storage_dtype=None, block_w=None) -> CorrState:
    """Build correlation state from NHWC feature maps ``(B, H, W, D)``.

    ``storage_dtype`` (e.g. ``jnp.bfloat16``) selects reduced-precision
    storage for the volume/feature pyramid; ``None`` keeps fp32 (the
    reference's default for reg/alt, core/raft_stereo.py:92-95). Lookup
    accumulation is fp32 either way. ``block_w`` sets the W2 tile width of
    the memoryless ``fused`` kernel (config.fused_block_w; other builders
    ignore it).
    """
    if impl not in _BUILDERS and (impl.endswith("_pallas")
                                  or impl == "fused"):
        _maybe_register_pallas()
    if impl not in _BUILDERS:
        raise ValueError(f"unknown corr implementation {impl!r}; "
                         f"registered: {sorted(_BUILDERS)}")
    return _BUILDERS[impl](fmap1, fmap2, num_levels, radius,
                           storage_dtype=storage_dtype, block_w=block_w)


def corr_lookup(state: CorrState, coords: jax.Array) -> jax.Array:
    """Look up correlation features at ``coords`` ``(B, H, W, 2)`` (x, y channels).

    Only the x channel is used — disparity search is along the epipolar line
    (core/corr.py:129 ``coords[:, :1]``). Returns fp32 features
    ``(B, H, W, num_levels*(2r+1))``.
    """
    coords_x = coords[..., 0].astype(jnp.float32)
    return _LOOKUPS[state.impl](state, coords_x)


def _lookup_reg_pallas(state: CorrState, coords_x: jax.Array) -> jax.Array:
    """Fused Pallas pyramid lookup on the materialized volume (TPU kernel
    equivalent of the reference's corr_sampler CUDA extension, SURVEY N1/N2;
    interpreter mode on CPU)."""
    from raft_stereo_tpu.ops.pallas.corr_kernels import windowed_sample_pallas
    out = []
    for i, volume in enumerate(state.levels):
        out.append(windowed_sample_pallas(volume, coords_x / (2 ** i),
                                          state.radius))
    return jnp.concatenate(out, axis=-1)


def _lookup_alt_pallas(state: CorrState, coords_x: jax.Array) -> jax.Array:
    """Fused build+lookup: the O(W^2) slab never touches HBM (the working
    version of the reference's absent alt_cuda_corr, core/corr.py:159-188)."""
    from raft_stereo_tpu.ops.pallas.corr_kernels import alt_windowed_corr_pallas
    out = []
    for i, fmap2 in enumerate(state.levels):
        out.append(alt_windowed_corr_pallas(state.fmap1, fmap2,
                                            coords_x / (2 ** i), state.radius))
    return jnp.concatenate(out, axis=-1)


def _lookup_fused(state: CorrState, coords_x: jax.Array) -> jax.Array:
    """Memoryless W2-blocked lookup: per level, the largest transient is a
    (Hb, W1, block_w) VMEM sub-slab — no level's volume is ever built, in
    HBM or VMEM, at any width (ops/pallas/corr_kernels.py, the working
    version of arXiv 2505.16942's on-the-fly sampling for 1-D disparity)."""
    from raft_stereo_tpu.ops.pallas.corr_kernels import (
        fused_windowed_corr_pallas)
    out = []
    for i, fmap2 in enumerate(state.levels):
        out.append(fused_windowed_corr_pallas(
            state.fmap1, fmap2, coords_x / (2 ** i), state.radius,
            state.block_w))
    return jnp.concatenate(out, axis=-1)


def _maybe_register_pallas() -> None:
    """Lazily register the Pallas-fused implementations.

    If the Pallas kernels are unavailable on this backend, fall back to the
    pure-JAX implementations with the same semantics (mirrors the reference's
    soft import of its CUDA extensions, core/corr.py:5-14) so presets like
    realtime_config() stay runnable everywhere.
    """
    try:
        from raft_stereo_tpu.ops.pallas import corr_kernels  # noqa: F401
    except ImportError:
        import warnings
        warnings.warn("Pallas correlation kernels unavailable; "
                      "falling back to pure-JAX reg/alt implementations")
        if "reg_pallas" not in _BUILDERS:
            register_corr("reg_pallas", _build_reg, _lookup_reg)
        if "alt_pallas" not in _BUILDERS:
            register_corr("alt_pallas", _build_alt, _lookup_alt)
        if "fused" not in _BUILDERS:
            # same state pytree, alt-semantics lookup — selectable
            # everywhere, just without the memoryless guarantee
            register_corr("fused", _build_fused, _lookup_alt)
        return
    if "reg_pallas" not in _BUILDERS:
        register_corr("reg_pallas", _build_reg, _lookup_reg_pallas)
    if "alt_pallas" not in _BUILDERS:
        register_corr("alt_pallas", _build_alt, _lookup_alt_pallas)
    if "fused" not in _BUILDERS:
        register_corr("fused", _build_fused, _lookup_fused)
