from raft_stereo_tpu.ops.corr import (
    CorrState,
    all_pairs_correlation,
    corr_lookup,
    init_corr,
    register_corr,
)
from raft_stereo_tpu.ops.geometry import (
    pool_last_axis2,
    InputPadder,
    avg_pool2d,
    coords_grid,
    extract_3x3_patches,
    pool2x,
    pool_w2,
    resize_bilinear_align_corners,
    upflow,
    upsample_flow_convex,
)
from raft_stereo_tpu.ops.sampler import (
    gather_window_2d,
    linear_sample_1d,
    window_taps,
)
