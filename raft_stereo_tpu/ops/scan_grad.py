"""Custom-VJP refinement scan: batched weight gradients, lean residuals.

The refinement backward is the step's biggest bucket (~347 ms of 819 at the
r4 banker, PERF.md), and ~1.1 ms/iter of it is weight-gradient convolutions:
autodiff-through-``lax.scan`` computes each gate conv's kernel gradient once
per iteration and accumulates 22 small ``(3,3,Cin,Cout)`` contractions in the
backward while-loop. This module restructures that backward (the standard
trick for recurrent nets — Martin & Cundy, arXiv:1709.04057, applied to
RAFT's refinement GRU):

* the **forward** runs ``lax.scan`` exactly as the autodiff path does and
  additionally stacks the per-iteration carries (and, when the selective
  save policy engages, the tagged ``gru_zr``/``gru_q``/``corr_feats``
  values) as explicit residuals;
* the **backward** runs ONE reverse ``lax.scan`` computing only *data*
  gradients — the cotangent chain through the carry plus the per-iteration
  gradients of everything that is not a deferred conv weight — while
  emitting each deferred conv's ``(input parts, output cotangent)`` pair as
  stacked outputs;
* the **weight gradients** of the deferred convs (the fused z/r gate conv
  and the q conv of every ConvGRU application) are then computed OUTSIDE the
  loop as one batched contraction each over the ``(iters*B, H, W, C)``
  merged stacks — one MXU-shaped conv-wgrad per conv instead of ``iters``
  accumulating small ones.

Cotangents of the deferred conv outputs are captured with the standard
zero-perturbation trick: the backward-pass recompute adds a zeros tensor
``eps`` to each deferred conv's output and the per-step VJP is taken with
respect to ``eps`` — ``d eps`` IS the conv-output cotangent, with no change
to any primal value.

Residual precision (``config.residual_dtype``): the stacked residuals this
path materializes — carry hidden states, tap input/cotangent stacks, and
policy save-stacks — are exactly the allocation class the r7 breakdown named
dominant (``[22,B,80,180,128..144]``); storing them in bf16 halves it while
the batched contractions still accumulate in fp32
(``preferred_element_type``). The knob never changes this path's *forward*
numerics (only saved copies are rounded); on the autodiff path the same knob
rounds the tagged saves through bf16 in the forward (one rounding on the
saved tensors, ``nn/gru.py``), which is why its gradient contract is
documented-tolerance rather than exact.

Gradient contract (pinned in tests/test_scan_grad.py): with fp32 residuals
the custom VJP matches autodiff-through-``lax.scan`` to accumulation-order
tolerance (the batched contraction sums the iteration axis inside one conv
reduction instead of 22 ordered adds); with bf16 residuals it matches within
the documented bf16 tolerance. Everything here is standard traceable JAX, so
the custom VJP composes with ``jit``/``shard_map``/auto-SPMD ``pjit`` and
buffer donation unchanged.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, FrozenSet, Optional, Tuple

import jax
import jax.numpy as jnp

from raft_stereo_tpu.ops.corr import corr_lookup

_DIMNUMS = ("NHWC", "HWIO", "NHWC")


def conv_wgrad(x, g, pad: int):
    """Weight gradient of a stride-1 NHWC/HWIO conv as ONE contraction.

    ``dK[kh,kw,ci,co] = sum_{n,oh,ow} x[n,oh+kh-pad,ow+kw-pad,ci] *
    g[n,oh,ow,co]`` — the batch axis (here ``iters*B``) is contracted
    *inside* the conv, which is what turns 22 accumulating per-iteration
    wgrads into one MXU-shaped op. Accumulates fp32 regardless of the
    stack dtype."""
    return jax.lax.conv_general_dilated(
        x, g, window_strides=(1, 1), padding=((pad, pad), (pad, pad)),
        dimension_numbers=("CHWN", "IHWO", "HWNC"),
        preferred_element_type=jnp.float32)


def _conv_parts(parts, kernel, pad: int):
    """``conv(concat(parts), kernel)`` as summed per-slice convs (the
    split-input formulation of nn/gru.py, without the bias)."""
    out = None
    off = 0
    for v in parts:
        c = v.shape[-1]
        y = jax.lax.conv_general_dilated(
            v, kernel[:, :, off:off + c, :], (1, 1),
            ((pad, pad), (pad, pad)), dimension_numbers=_DIMNUMS)
        out = y if out is None else out + y
        off += c
    return out


# --- the replay op: skip a saved conv's forward recompute --------------------
#
# Mirrors ``save_only_these_names("gru_zr", "gru_q")`` semantics for the
# custom backward: the conv's output comes from the forward's save stack (so
# the MXU matmul is not recomputed), the data gradient to the input parts is
# still produced (conv is linear — its input-cotangent needs only the kernel
# and the output cotangent, never the input values), and the weight/bias
# cotangents are structurally zero because they are deferred to the batched
# post-scan contraction.

@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _replay_conv(spec, parts, kernel, eps, saved):
    del spec, parts, kernel
    return saved + eps


def _replay_conv_fwd(spec, parts, kernel, eps, saved):
    del parts
    return saved + eps, (kernel,)


def _replay_conv_bwd(spec, res, g):
    (kernel,) = res
    pad, part_specs = spec
    structs = tuple(jax.ShapeDtypeStruct(s, jnp.dtype(d))
                    for s, d in part_specs)
    (dparts,) = jax.linear_transpose(
        lambda ps: _conv_parts(ps, kernel, pad), structs)(g)
    return (tuple(dparts), jnp.zeros_like(kernel), g, jnp.zeros_like(g))


_replay_conv.defvjp(_replay_conv_fwd, _replay_conv_bwd)


@jax.custom_vjp
def _replay_value(computed, saved):
    """Use ``saved`` in place of ``computed``'s value while routing the
    cotangent back through ``computed``'s producers (the corr-lookup replay:
    the forward gather is skipped, the scatter backward into the volume
    pyramid still runs)."""
    del computed
    return saved


def _replay_value_fwd(computed, saved):
    del computed
    return saved, None


def _replay_value_bwd(_, g):
    return (g, jnp.zeros_like(g))


_replay_value.defvjp(_replay_value_fwd, _replay_value_bwd)


# --- tap objects threaded through the refinement module ----------------------

class _ScopedTap:
    """Per-application view of a tap: prefixes site keys so the slow_fast
    pre-iterations and the main update — which share module paths and
    params — get distinct residual stacks."""

    def __init__(self, tap, prefix: str):
        self._tap = tap
        self._prefix = prefix

    def gate_conv(self, path, kind, parts, kernel, bias, pad):
        key = f"{self._prefix}/{'/'.join(path)}/{kind}"
        return self._tap.gate_conv(key, tuple(path), kind, parts, kernel,
                                   bias, pad)


class _TapBase:
    """Shared traversal contract. ``gate_conv`` must return exactly what the
    plain split-input conv would (same value in probe/save modes), and every
    mode must visit sites in the same deterministic order so keys line up."""

    def scoped(self, prefix: str) -> _ScopedTap:
        return _ScopedTap(self, prefix)

    def _plain(self, parts, kernel, bias, pad):
        return _conv_parts(parts, kernel, pad) + bias


class ProbeTap(_TapBase):
    """Abstract-eval pass collecting per-site static metadata (shapes,
    dtypes, param paths) — run once under ``jax.eval_shape``."""

    def __init__(self):
        self.meta: Dict[str, Dict[str, Any]] = {}

    def gate_conv(self, key, path, kind, parts, kernel, bias, pad):
        out = self._plain(parts, kernel, bias, pad)
        self.meta[key] = dict(
            path=path, kind=kind, pad=pad,
            part_specs=tuple((tuple(p.shape), p.dtype.name) for p in parts),
            out_shape=tuple(out.shape), out_dtype=out.dtype.name)
        return out

    def corr_site(self, corr_state, coords, cast_dtype):
        corr = corr_lookup(corr_state, coords)
        if cast_dtype is not None:
            corr = corr.astype(cast_dtype)
        self.meta["corr"] = dict(kind="corr", out_shape=tuple(corr.shape),
                                 out_dtype=corr.dtype.name)
        return corr


class SaveTap(_TapBase):
    """Forward-scan tap: compute every site normally, record the outputs the
    engaged save policy keeps (they become stacked scan outputs — the
    explicit form of the autodiff path's named residual stacks)."""

    def __init__(self, save_kinds: FrozenSet[str]):
        self.save_kinds = save_kinds
        self.saves: Dict[str, jax.Array] = {}

    def gate_conv(self, key, path, kind, parts, kernel, bias, pad):
        out = self._plain(parts, kernel, bias, pad)
        if kind in self.save_kinds:
            self.saves[key] = out
        return out

    def corr_site(self, corr_state, coords, cast_dtype):
        corr = corr_lookup(corr_state, coords)
        if cast_dtype is not None:
            corr = corr.astype(cast_dtype)
        if "corr" in self.save_kinds:
            self.saves["corr"] = corr
        return corr


class BwdTap(_TapBase):
    """Backward-recompute tap: inject the ``eps`` perturbation on every
    deferred conv output (its VJP is the conv's output cotangent), collect
    the conv input parts for the batched wgrad, stop weight gradients at
    the per-step level, and substitute saved values where the policy stacked
    them in the forward."""

    def __init__(self, eps: Dict[str, jax.Array],
                 replay: Dict[str, jax.Array]):
        self.eps = eps
        self.replay = replay
        self.inputs: Dict[str, Tuple[jax.Array, ...]] = {}

    def gate_conv(self, key, path, kind, parts, kernel, bias, pad):
        parts = tuple(parts)
        self.inputs[key] = parts
        saved = self.replay.get(key)
        if saved is not None:
            spec = (pad, tuple((tuple(p.shape), p.dtype.name)
                               for p in parts))
            return _replay_conv(spec, parts, kernel, self.eps[key], saved)
        sg = jax.lax.stop_gradient
        out = _conv_parts(parts, sg(kernel), pad) + sg(bias)
        return out + self.eps[key]

    def corr_site(self, corr_state, coords, cast_dtype):
        saved = self.replay.get("corr")
        if saved is None:
            corr = corr_lookup(corr_state, coords)
            return corr.astype(cast_dtype) if cast_dtype is not None else corr

        # Keep the volume-pyramid gradient path alive while the forward
        # gather's *value* is replayed from the save stack: the computed
        # branch exists only for its cotangent (its forward output is dead
        # past _replay_value, so XLA's DCE drops the gather while the
        # scatter backward into d_volumes remains).
        corr = corr_lookup(corr_state, coords)
        if cast_dtype is not None:
            corr = corr.astype(cast_dtype)
        return _replay_value(corr, saved)


# --- residual casting --------------------------------------------------------

def _cast_carry(carry, rd):
    """Residual-dtype cast of a refinement carry for the save stack: only
    the hidden-state tuple (``carry[0]``) is narrowed — ``coords1`` (and the
    fused path's ``flow_up``) carry sub-pixel positions whose bf16 rounding
    would be a real precision loss, and they are a few channels against the
    net's hundreds."""
    if rd is None:
        return carry
    return (tuple(h.astype(rd) for h in carry[0]),) + tuple(carry[1:])


def _uncast_carry(carry, like):
    """Restore a save-stack carry to the compute dtypes of ``like``."""
    return (tuple(h.astype(l.dtype) for h, l in zip(carry[0], like[0])),) \
        + tuple(c.astype(l.dtype) for c, l in zip(carry[1:], like[1:]))


def _cast_tree(tree, rd):
    if rd is None:
        return tree
    return jax.tree.map(lambda a: a.astype(rd), tree)


def _tree_add_leaf(node, path, delta):
    """Functionally add ``delta`` at ``path`` (a tuple of dict keys) in a
    nested-dict param tree, preserving container types."""
    if not path:
        return (node + delta).astype(node.dtype)
    key = path[0]
    child = _tree_add_leaf(node[key], path[1:], delta)
    if hasattr(node, "copy") and not isinstance(node, dict):
        return node.copy({key: child})  # FrozenDict
    new = dict(node)
    new[key] = child
    return new


# --- the scan ----------------------------------------------------------------

def refinement_scan(module, params, carry, broadcasts, *, length: int,
                    save_kinds: FrozenSet[str] = frozenset(),
                    residual_dtype: Optional[Any] = None, unroll: int = 1):
    """Run ``length`` refinement iterations with the custom batched-wgrad VJP.

    Args:
      module: a detached (``parent=None``) ``RefinementStep`` whose
        ``__call__(carry, corr_state, inp_list, coords0, gt_and_mask,
        wgrad_tap=...)`` returns ``(carry, y)``.
      params: the ``refinement`` params subtree (arrays flow from the
        caller's traced params, so cotangents reach the training step).
      carry: initial scan carry ``(net_tuple, coords1[, flow_up])``.
      broadcasts: ``(corr_state, inp_list, coords0, gt_and_mask)`` —
        iteration-invariant inputs whose cotangents accumulate across the
        reverse scan (the volume pyramid's feeds the encoders).
      length: iteration count (static).
      save_kinds: subset of ``{"zr", "q", "corr"}`` — which tagged values
        the forward stacks so the backward skips recomputing them (the
        custom-path form of ``refinement_save_policy``).
      residual_dtype: optional storage dtype for every stacked residual
        this scan materializes (fp32 accumulation is preserved in the
        batched contractions).
      unroll: ``lax.scan`` unroll factor, both directions.

    Returns:
      ``(final_carry, ys)`` exactly as the ``nn.scan`` path would.
    """
    rd = jnp.dtype(residual_dtype) if residual_dtype is not None else None

    def apply_step(p, c, bc, tap):
        corr_state, inp_list, coords0, gt_and_mask = bc
        return module.apply({"params": p}, c, corr_state, inp_list, coords0,
                            gt_and_mask, wgrad_tap=tap)

    # One abstract pass collects the static site metadata (eps shapes, param
    # paths, part layouts) that the backward needs before any tracing of it.
    probe = ProbeTap()
    jax.eval_shape(lambda p, c, bc: apply_step(p, c, bc, probe),
                   params, carry, broadcasts)
    meta = probe.meta
    gate_keys = tuple(k for k, m in meta.items() if m["kind"] != "corr")

    @jax.custom_vjp
    def scan_fn(params, carry, bc):
        def body(c, _):
            c2, y = apply_step(params, c, bc, None)
            return c2, y
        return jax.lax.scan(body, carry, None, length=length, unroll=unroll)

    def scan_fwd(params, carry, bc):
        save_tap = bool(save_kinds)

        def body(c, _):
            tap = SaveTap(save_kinds) if save_tap else None
            c2, y = apply_step(params, c, bc, tap)
            saves = _cast_tree(tap.saves if save_tap else {}, rd)
            return c2, (y, _cast_carry(c, rd), saves)

        final, (ys, carries, saves) = jax.lax.scan(
            body, carry, None, length=length, unroll=unroll)
        return (final, ys), (params, bc, carry, carries, saves)

    def scan_bwd(res, cot):
        params, bc, carry0, carries, saves = res
        d_final, d_ys = cot
        eps0 = {k: jnp.zeros(meta[k]["out_shape"],
                             jnp.dtype(meta[k]["out_dtype"]))
                for k in gate_keys}

        def f(p, c, x, e, replay):
            tap = BwdTap(e, replay)
            c2, y = apply_step(p, c, x, tap)
            return (c2, y), tap.inputs

        def body(acc, xs):
            dc, dp_acc, dbc_acc = acc
            c_t, dy_t, saves_t = xs
            c_t = _uncast_carry(c_t, carry0)
            replay = {k: v.astype(jnp.dtype(meta[k]["out_dtype"]))
                      for k, v in saves_t.items()}
            _, pullback, taps_in = jax.vjp(
                lambda p, c, x, e: f(p, c, x, e, replay),
                params, c_t, bc, eps0, has_aux=True)
            dp_t, dc_t, dbc_t, deps_t = pullback((dc, dy_t))
            acc = (dc_t,
                   jax.tree.map(jnp.add, dp_acc, dp_t),
                   jax.tree.map(jnp.add, dbc_acc, dbc_t))
            return acc, (_cast_tree(taps_in, rd), _cast_tree(deps_t, rd))

        init = (d_final,
                jax.tree.map(jnp.zeros_like, params),
                jax.tree.map(jnp.zeros_like, bc))
        (dc0, dp, dbc), (x_stacks, g_stacks) = jax.lax.scan(
            body, init, (carries, d_ys, saves), reverse=True, unroll=unroll)

        # The deferred weight gradients: one batched contraction per conv
        # over the (iters*B)-merged stacks, summed across applications that
        # share parameters (slow_fast pre-iterations), then scattered into
        # the otherwise-complete accumulated param cotangents.
        contribs: Dict[Tuple[Tuple[str, ...], str],
                       Tuple[jax.Array, jax.Array]] = {}
        for key in gate_keys:
            m = meta[key]
            gs = g_stacks[key]
            gm = gs.reshape((-1,) + m["out_shape"][1:])
            dks = []
            for (shape, _dt), xs_part in zip(m["part_specs"],
                                             x_stacks[key]):
                xm = xs_part.reshape((-1,) + shape[1:])
                dks.append(conv_wgrad(xm, gm, m["pad"]))
            dk = jnp.concatenate(dks, axis=2)
            db = jnp.sum(gm.astype(jnp.float32), axis=(0, 1, 2))
            prev = contribs.get((m["path"], m["kind"]))
            if prev is not None:
                dk, db = dk + prev[0], db + prev[1]
            contribs[(m["path"], m["kind"])] = (dk, db)

        for (path, kind), (dk, db) in contribs.items():
            if kind == "zr":
                hd = dk.shape[-1] // 2
                targets = ((path + ("convz",), dk[..., :hd], db[:hd]),
                           (path + ("convr",), dk[..., hd:], db[hd:]))
            else:
                targets = ((path + ("convq",), dk, db),)
            for ppath, dkp, dbp in targets:
                dp = _tree_add_leaf(dp, ppath + ("kernel",), dkp)
                dp = _tree_add_leaf(dp, ppath + ("bias",), dbp)

        return dp, dc0, dbc

    scan_fn.defvjp(scan_fwd, scan_bwd)
    return scan_fn(params, carry, broadcasts)
