"""Stereo-to-3D visualization pipeline (the reference's fork-specific
visualize_droid_trajectory_3d.py, SURVEY §2 component 12, rebuilt as a
library).

The reference couples this pipeline to the proprietary ZED SDK (``pyzed``)
and a hard-coded checkpoint path. Here the geometry and rendering are
SDK-free and the frame source is pluggable: anything yielding left/right
numpy images works (a ZED-SVO-backed source can be added where the SDK
exists). Capabilities covered:

* disparity -> metric depth (``f*B/d``, visualize_droid_trajectory_3d.py:67-73)
* depth -> camera/world point clouds with extrinsics
  (:func:`depth_to_cloud`, reference :203-247)
* DROID trajectory parsing from ``trajectory.h5`` (:342-366; needs h5py)
* matplotlib 3-D scatter rendering of trajectory sweeps (:250-339)
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class CameraIntrinsics:
    """Pinhole intrinsics; ``baseline`` in the same units as desired depth."""

    fx: float
    fy: float
    cx: float
    cy: float
    baseline: float


def disparity_to_depth(disparity: np.ndarray, cam: CameraIntrinsics,
                       min_disp: float = 1e-3) -> np.ndarray:
    """``depth = fx * baseline / disparity`` (reference :67-73); non-positive
    disparities map to 0 depth."""
    disp = np.asarray(disparity, np.float32)
    depth = np.zeros_like(disp)
    ok = disp > min_disp
    depth[ok] = cam.fx * cam.baseline / disp[ok]
    return depth


def depth_to_cloud(depth: np.ndarray, cam: CameraIntrinsics,
                   pose: Optional[np.ndarray] = None,
                   color: Optional[np.ndarray] = None,
                   max_depth: float = np.inf,
                   stride: int = 1) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Back-project a depth map to a point cloud (reference :203-236).

    ``pose``: optional 4x4 camera-to-world matrix; ``color``: (H, W, 3) image
    sampled at the same pixels. Returns ``(points (N, 3), colors (N, 3)|None)``.
    """
    h, w = depth.shape
    ys, xs = np.mgrid[0:h:stride, 0:w:stride]
    z = depth[::stride, ::stride]
    ok = (z > 0) & (z < max_depth)
    z = z[ok]
    x = (xs[ok] - cam.cx) * z / cam.fx
    y = (ys[ok] - cam.cy) * z / cam.fy
    pts = np.stack([x, y, z], axis=-1)
    if pose is not None:
        pts = pts @ pose[:3, :3].T + pose[:3, 3]
    cols = None
    if color is not None:
        cols = color[::stride, ::stride][ok]
    return pts.astype(np.float32), cols


def load_droid_trajectory(path: str) -> np.ndarray:
    """Parse a DROID ``trajectory.h5`` into (T, 4, 4) camera-to-world poses
    (reference :346-366: translation + quaternion rows)."""
    import h5py
    from scipy.spatial.transform import Rotation

    with h5py.File(path, "r") as f:
        traj = np.asarray(f["trajectory"] if "trajectory" in f
                          else f[list(f.keys())[0]])
    poses = np.tile(np.eye(4, dtype=np.float32), (len(traj), 1, 1))
    poses[:, :3, 3] = traj[:, :3]
    poses[:, :3, :3] = Rotation.from_quat(traj[:, 3:7]).as_matrix()
    return poses


def render_clouds(clouds: Sequence[Tuple[np.ndarray, Optional[np.ndarray]]],
                  out_path: str, elev: float = -60.0, azim: float = -90.0,
                  point_size: float = 0.3) -> None:
    """Matplotlib 3-D scatter of point-cloud sweeps (reference :250-339)."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig = plt.figure(figsize=(10, 10))
    ax = fig.add_subplot(projection="3d")
    for pts, cols in clouds:
        ax.scatter(pts[:, 0], pts[:, 1], pts[:, 2], s=point_size,
                   c=None if cols is None else np.clip(cols / 255.0, 0, 1))
    ax.view_init(elev=elev, azim=azim)
    fig.savefig(out_path, dpi=150, bbox_inches="tight")
    plt.close(fig)


def process_stereo_sequence(predictor, frames: Iterable, cam: CameraIntrinsics,
                            poses: Optional[np.ndarray] = None,
                            iters: int = 32, max_depth: float = 10.0,
                            stride: int = 4):
    """RAFT depth + reprojection over a stereo sequence (reference :164-247).

    ``predictor``: a :class:`raft_stereo_tpu.inference.StereoPredictor`;
    ``frames``: iterable of ``(left_rgb, right_rgb)`` numpy pairs. Yields
    ``(points, colors)`` per frame, in world coordinates when ``poses`` given.
    """
    for t, (left, right) in enumerate(frames):
        disp = predictor.compute_disparity(left, right, iters=iters)
        depth = disparity_to_depth(disp, cam)
        pose = None if poses is None else poses[t]
        yield depth_to_cloud(depth, cam, pose=pose, color=left,
                             max_depth=max_depth, stride=stride)
