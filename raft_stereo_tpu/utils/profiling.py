"""TPU profiling harness: trace capture + device-op summaries.

The reference's only performance instrumentation is wall-clock FPS timing in
``validate_kitti`` (evaluate_stereo.py:77-81,105-107). The TPU-native
equivalent is a ``jax.profiler`` trace plus an op-level breakdown of where
device time goes — this module provides both without requiring TensorBoard:

    from raft_stereo_tpu.utils.profiling import trace, summarize_trace

    with trace("/tmp/myrun"):
        for _ in range(3):
            state, metrics = step(state, batch)
            float(metrics["loss"])          # host fetch = real sync point

    report = summarize_trace("/tmp/myrun")
    print(format_report(report))

Notes:

* On tunneled TPU devices, ``jax.block_until_ready`` can return before queued
  executions finish; fetch an output scalar per step instead (see bench.py).
* The summary parses the Chrome-trace JSON the profiler writes alongside the
  xplane protobuf, so it has no TensorBoard/tensorflow dependency.
"""

from __future__ import annotations

import collections
import contextlib
import glob
import gzip
import json
import os
from typing import Any, Dict, List, Optional


@contextlib.contextmanager
def trace(log_dir: str):
    """Context manager: capture a ``jax.profiler`` trace into ``log_dir``."""
    import jax

    with jax.profiler.trace(log_dir):
        yield log_dir


def _latest_trace_json(log_dir: str) -> Optional[str]:
    paths = sorted(glob.glob(
        os.path.join(log_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    return paths[-1] if paths else None


def load_trace_events(log_dir: str) -> tuple:
    """Load the latest Chrome-trace capture under ``log_dir``.

    Returns ``(path, events)`` — the ``traceEvents`` list of the newest
    ``plugins/profile/*/*.trace.json.gz``. Shared by :func:`summarize_trace`
    and the host/device timeline merger (obs/timeline.py). Raises
    FileNotFoundError when no capture exists.
    """
    path = _latest_trace_json(log_dir)
    if path is None:
        raise FileNotFoundError(f"no trace.json.gz under {log_dir}")
    data = json.load(gzip.open(path, "rt"))
    return path, data.get("traceEvents", [])


def device_lanes(events) -> tuple:
    """Identify the device lanes of a Chrome-trace event list.

    Returns ``(device_pids, op_tids)``: process ids whose metadata name
    mentions ``/device:`` ("/device:TPU:0" etc.) and their "XLA Ops"
    ``(pid, tid)`` lanes — the per-op device timeline. Host-only captures
    (CPU backend) return two empty sets.
    """
    device_pids = set()
    op_tids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            if "/device:" in e.get("args", {}).get("name", ""):
                device_pids.add(e["pid"])
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            if e.get("args", {}).get("name") == "XLA Ops":
                op_tids.add((e["pid"], e["tid"]))
    return device_pids, op_tids


def summarize_trace(log_dir: str, top: int = 25) -> Dict[str, Any]:
    """Aggregate device-op time from the latest trace under ``log_dir``.

    Returns ``{"trace": path, "total_device_ms": t, "by_category": [...],
    "top_ops": [...]}`` where times are totals over the captured region
    (divide by your step count for per-step numbers). Categories come from XLA
    (``convolution fusion``, ``loop fusion``, ...); ``top_ops`` carries each
    op's HLO ``long_name`` prefix so shapes are visible.
    """
    path, events = load_trace_events(log_dir)
    device_pids, op_tids = device_lanes(events)

    cat_time: collections.Counter = collections.Counter()
    op_time: collections.Counter = collections.Counter()
    op_count: collections.Counter = collections.Counter()
    op_meta: Dict[str, str] = {}
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        if op_tids and (e["pid"], e.get("tid")) not in op_tids:
            continue
        args = e.get("args", {})
        dur = e.get("dur", 0)
        cat = args.get("hlo_category", "?")
        if cat == "while":
            continue  # wrapper op; its body ops are counted individually
        name = e["name"]
        cat_time[cat] += dur
        op_time[name] += dur
        op_count[name] += 1
        total += dur
        if name not in op_meta:
            op_meta[name] = args.get("long_name", "")[:160]

    return {
        "trace": path,
        # Host-only traces (CPU backend) carry no per-op XLA device lane;
        # an empty summary with a note is the correct result there.
        "note": (None if device_pids else
                 "no XLA device lane in trace (CPU/host-only capture); "
                 "op summaries require a TPU/GPU trace"),
        "total_device_ms": total / 1e3,
        "by_category": [
            {"category": c, "ms": t / 1e3}
            for c, t in cat_time.most_common()
        ],
        "top_ops": [
            {"name": n, "ms": t / 1e3, "count": op_count[n],
             "hlo": op_meta.get(n, "")}
            for n, t in op_time.most_common(top)
        ],
    }


# --- serial-floor decomposition ---------------------------------------------
#
# The refinement loop (the lax.scan over GRU iterations — the serial hot
# path this repo exists to accelerate) contributes a batch-independent
# ~450 ms floor to the train step (PERF.md). Aggregate traces show THAT the
# scan dominates; these helpers split the floor per iteration: time the
# same graph at several iteration counts and fit wall time = fixed +
# per_iter * iters. Run the sweep twice — rolled (scan) and fully unrolled
# (scan_unroll=iters, XLA free to fuse across iteration boundaries) — and
# the rolled-minus-unrolled slope isolates the loop/layout overhead each
# iteration pays for being inside a `while` (carry relayouts, loop
# bookkeeping) from the GRU/lookup compute itself; the intercept is the
# per-call fixed work (encoders, volume build, upsample tail, host
# dispatch). scripts/serial_floor.py drives this end to end.

def fit_linear(xs: List[float], ys: List[float]) -> tuple:
    """Least-squares fit ``y = slope * x + intercept``; returns
    ``(slope, intercept)``. Needs >= 2 distinct x values."""
    import numpy as np
    x = np.asarray(xs, dtype=np.float64)
    y = np.asarray(ys, dtype=np.float64)
    if len(x) < 2 or np.ptp(x) == 0:
        raise ValueError("fit_linear needs >= 2 distinct x samples")
    slope, intercept = np.polyfit(x, y, 1)
    return float(slope), float(intercept)


def time_compiled(fn, args, repeats: int = 3, warmup: int = 1) -> float:
    """Best-of-``repeats`` wall seconds for ``fn(*args)``.

    Synchronizes by materializing every output to host (``jax.device_get``)
    — the fetch-an-output sync that works on tunneled TPUs where
    ``block_until_ready`` can return early (see module doc)."""
    import time as _time

    import jax

    for _ in range(max(warmup, 0)):
        jax.device_get(fn(*args))
    best = float("inf")
    for _ in range(max(repeats, 1)):
        t0 = _time.perf_counter()
        jax.device_get(fn(*args))
        best = min(best, _time.perf_counter() - t0)
    return best


def decompose_serial_floor(rolled: Dict[int, float],
                           unrolled: Optional[Dict[int, float]] = None
                           ) -> Dict[str, Any]:
    """Split iteration-sweep timings into fixed / compute / loop-overhead.

    ``rolled`` maps iteration count -> wall seconds for the scanned graph;
    ``unrolled`` (optional) the same for the fully-unrolled graph. Returns
    per-iteration and fixed components in seconds:

    * ``fixed_s`` — the rolled fit's intercept: per-call work independent
      of iteration count (encoders + volume build + post-scan tail + host
      dispatch);
    * ``per_iter_s`` — the rolled fit's slope: what one more GRU iteration
      costs end to end;
    * ``per_iter_compute_s`` — the unrolled slope: the iteration's
      compute with XLA free to fuse across iterations (no loop carry);
    * ``per_iter_loop_overhead_s`` — rolled minus unrolled slope: the
      layout/bookkeeping cost of living inside the ``while`` — the share
      of the floor that is NOT algorithmic serial dependency.
    """
    its = sorted(rolled)
    slope, intercept = fit_linear(its, [rolled[i] for i in its])
    out: Dict[str, Any] = {
        "samples": {str(i): round(rolled[i], 6) for i in its},
        "fixed_s": round(intercept, 6),
        "per_iter_s": round(slope, 6),
    }
    if unrolled:
        uits = sorted(unrolled)
        uslope, uintercept = fit_linear(uits, [unrolled[i] for i in uits])
        out["unrolled_samples"] = {str(i): round(unrolled[i], 6)
                                   for i in uits}
        out["unrolled_fixed_s"] = round(uintercept, 6)
        out["per_iter_compute_s"] = round(uslope, 6)
        out["per_iter_loop_overhead_s"] = round(slope - uslope, 6)
    return out


def format_report(report: Dict[str, Any]) -> str:
    lines: List[str] = [
        f"trace: {report['trace']}",
        f"total device-op time: {report['total_device_ms']:.1f} ms",
    ]
    if report.get("note"):
        lines.append(f"note: {report['note']}")
    lines += ["", "by category:"]
    for row in report["by_category"]:
        lines.append(f"  {row['ms']:9.2f} ms  {row['category']}")
    lines.append("")
    lines.append("top ops:")
    for row in report["top_ops"]:
        lines.append(f"  {row['ms']:9.2f} ms x{row['count']:<5d} "
                     f"{row['name'][:48]:48s} {row['hlo'][:70]}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="Summarize a jax.profiler trace directory")
    p.add_argument("log_dir")
    p.add_argument("--top", type=int, default=25)
    args = p.parse_args(argv)
    print(format_report(summarize_trace(args.log_dir, args.top)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
