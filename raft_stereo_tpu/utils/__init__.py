from raft_stereo_tpu.utils.checkpoint_convert import (
    convert_state_dict,
    load_reference_checkpoint,
)

__all__ = ["convert_state_dict", "load_reference_checkpoint"]
