"""Convert reference PyTorch checkpoints (.pth state_dicts) to flax variables.

The reference distributes weights-only state dicts saved from an
``nn.DataParallel`` wrapper, so every key carries a ``module.`` prefix
(train_stereo.py:184-186; evaluate_stereo.py:216-221). This module renames
those keys onto the framework's flax tree (NHWC) and:

* transposes conv weights ``(O, I, kH, kW) -> (kH, kW, I, O)``,
* maps BatchNorm running statistics into the non-trainable ``batch_stats``
  collection — the reference always runs BN in eval mode (``freeze_bn``,
  train_stereo.py:151), so the running stats are constants here by design,
* drops torch bookkeeping (``num_batches_tracked``).

Name map (torch -> flax), derived from core/raft_stereo.py:29-39,
core/extractor.py:122-300, core/update.py:97-113:

    cnet.conv1 / norm1 / layer{1-3}.{j}   -> cnet.trunk.{conv1,norm1,layer{L}_{j}}
    cnet.layer{4,5}.{j}                   -> cnet.layer{L}_{j}
    cnet.outputs{08,16}.{i}.{0,1}         -> cnet.outputs{08,16}_{i}_{res,conv}
    cnet.outputs32.{i}                    -> cnet.outputs32_{i}_conv
    fnet.conv1 / norm1 / layer{1-3}.{j}   -> fnet.trunk....   ;  fnet.conv2 -> fnet.conv2
    conv2.{0,1}        (shared backbone)  -> conv2_res / conv2_out
    context_zqr_convs.{i}                 -> context_zqr_convs_{i}
    update_block.{encoder,gru08/16/32,flow_head} -> refinement.update_block.(same)
    update_block.mask.{0,2}               -> refinement.update_block.mask_conv{1,2}
    ResidualBlock: downsample.0 -> down_conv; downsample.1 == norm3 (duplicate
    registration in the reference, extractor.py:44-45) -> norm3
"""

from __future__ import annotations

import re
from typing import Any, Dict, Mapping, Tuple

import numpy as np


def _residual_part(parts):
    """Map ResidualBlock-internal torch names to flax names."""
    if parts[0] == "downsample":
        return ("down_conv",) if parts[1] == "0" else ("norm3",)
    return (parts[0],)


def _encoder_path(parts) -> Tuple[str, ...]:
    """Path inside BasicEncoder/MultiBasicEncoder (after ``cnet.``/``fnet.``)."""
    head = parts[0]
    if head in ("conv1", "norm1"):
        return ("trunk", head)
    if head == "conv2":  # fnet 1x1 output conv (extractor.py:149)
        return ("conv2",)
    m = re.fullmatch(r"layer([1-5])", head)
    if m:
        lvl = int(m.group(1))
        block = f"layer{lvl}_{parts[1]}"
        rest = _residual_part(parts[2:])
        return (("trunk", block) if lvl <= 3 else (block,)) + rest
    m = re.fullmatch(r"outputs(08|16|32)", head)
    if m:
        scale, i = m.group(1), parts[1]
        if scale == "32":  # bare conv head (extractor.py:245-250)
            return (f"outputs32_{i}_conv",)
        if parts[2] == "0":
            return (f"outputs{scale}_{i}_res",) + _residual_part(parts[3:])
        return (f"outputs{scale}_{i}_conv",)
    raise KeyError(f"unrecognized encoder sub-path {'.'.join(parts)}")


def _module_path(parts) -> Tuple[str, ...]:
    head = parts[0]
    if head in ("cnet", "fnet"):
        return (head,) + _encoder_path(parts[1:])
    if head == "conv2":  # shared-backbone feature head (raft_stereo.py:34-37)
        if parts[1] == "0":
            return ("conv2_res",) + _residual_part(parts[2:])
        return ("conv2_out",)
    if head == "context_zqr_convs":
        return (f"context_zqr_convs_{parts[1]}",)
    if head == "update_block":
        sub = parts[1]
        if sub == "mask":
            return ("refinement", "update_block",
                    "mask_conv1" if parts[2] == "0" else "mask_conv2")
        return ("refinement", "update_block", sub) + tuple(parts[2:])
    raise KeyError(f"unrecognized top-level module {head!r}")


def _set(tree: Dict, path: Tuple[str, ...], value) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def convert_state_dict(state_dict: Mapping[str, Any]) -> Dict[str, Dict]:
    """Torch state_dict -> ``{"params": ..., "batch_stats": ...}`` pytree.

    Accepts tensors or numpy arrays; returns numpy fp32 leaves. Keys may or
    may not carry the DataParallel ``module.`` prefix.

    Leaves are COPIES, never views: ``Tensor.numpy()`` shares storage with
    the live torch parameter, and a same-dtype ``np.asarray`` keeps sharing
    it — so torch's in-place optimizer updates would silently mutate the
    "converted" pytree (found by scripts/parity_dynamics.py, where both
    frameworks must start from the same snapshot while torch keeps training).
    """
    params: Dict = {}
    batch_stats: Dict = {}
    for key, val in state_dict.items():
        if hasattr(val, "detach"):  # torch tensor
            val = val.detach().cpu().numpy()
        arr = np.array(val, dtype=np.float32)  # copy, not view
        parts = key.split(".")
        if parts[0] == "module":
            parts = parts[1:]
        leaf = parts[-1]
        if leaf == "num_batches_tracked":
            continue
        path = _module_path(parts[:-1])
        if leaf == "running_mean":
            _set(batch_stats, path + ("mean",), arr)
        elif leaf == "running_var":
            _set(batch_stats, path + ("var",), arr)
        elif leaf == "weight":
            if arr.ndim == 4:  # conv: (O, I, kH, kW) -> (kH, kW, I, O)
                _set(params, path + ("kernel",), arr.transpose(2, 3, 1, 0))
            else:  # norm affine weight
                _set(params, path + ("scale",), arr)
        elif leaf == "bias":
            _set(params, path + ("bias",), arr)
        else:
            raise KeyError(f"unrecognized leaf {leaf!r} in {key!r}")
    return {"params": params, "batch_stats": batch_stats}


def load_reference_checkpoint(path: str) -> Dict[str, Dict]:
    """Load a reference ``.pth`` / ``.pth.gz`` checkpoint and convert it.

    ``.pth.gz`` is the reference's per-epoch save format (train_stereo.py:201-204).
    """
    import torch

    if path.endswith(".gz"):
        import gzip
        with gzip.open(path, "rb") as f:
            state = torch.load(f, map_location="cpu")
    else:
        state = torch.load(path, map_location="cpu")
    if isinstance(state, dict) and "state_dict" in state:
        state = state["state_dict"]
    return convert_state_dict(state)


def convert_to_torch_state_dict(variables: Dict, *,
                                data_parallel_prefix: bool = True) -> Dict:
    """Flax variables -> a reference-compatible torch state_dict (the reverse
    of :func:`convert_state_dict`): train here, evaluate/finetune with the
    reference's own tooling.

    Keys carry the ``module.`` DataParallel prefix by default, matching how
    the reference saves and strict-loads checkpoints (train_stereo.py:142-147).
    Conv kernels transpose back ``(kH, kW, I, O) -> (O, I, kH, kW)``.
    """
    import torch

    def flatten(tree) -> Dict[str, Any]:
        out: Dict[str, Any] = {}

        def walk(node, flax_path):
            for key, val in node.items():
                if isinstance(val, Mapping):
                    walk(val, flax_path + (key,))
                else:
                    out[".".join(flax_path + (key,))] = val

        walk(tree, ())
        return out

    params_flat = flatten(variables.get("params", {}))
    stats_flat = flatten(variables.get("batch_stats", {}))

    def to_torch_key(flax_key: str, leaf: str) -> str:
        parts = flax_key.split(".")
        out = []
        i = 0
        while i < len(parts) - 1:
            p = parts[i]
            if p == "trunk":
                pass  # flattened into the encoder in torch
            elif re.fullmatch(r"layer[1-5]_[01]", p):
                lvl, j = p.split("_")
                out += [lvl, j]
            elif re.fullmatch(r"outputs(08|16|32)_\d+_(res|conv)", p):
                scale, idx, kind = re.fullmatch(
                    r"outputs(08|16|32)_(\d+)_(res|conv)", p).groups()
                if scale == "32":
                    out += [f"outputs32", idx]
                else:
                    out += [f"outputs{scale}", idx, "0" if kind == "res" else "1"]
            elif p == "down_conv":
                out += ["downsample", "0"]
            elif p == "refinement":
                pass  # scan wrapper; torch has no analog level
            elif p == "mask_conv1":
                out += ["mask", "0"]
            elif p == "mask_conv2":
                out += ["mask", "2"]
            elif p == "conv2_res":
                out += ["conv2", "0"]
            elif p == "conv2_out":
                out += ["conv2", "1"]
            elif re.fullmatch(r"context_zqr_convs_(\d+)", p):
                out += ["context_zqr_convs", p.rsplit("_", 1)[1]]
            else:
                out.append(p)
            i += 1
        return ".".join(out + [leaf])

    state: Dict[str, "torch.Tensor"] = {}
    for key, val in params_flat.items():
        leaf = key.rsplit(".", 1)[1]
        arr = np.asarray(val, np.float32)
        if leaf == "kernel":
            state[to_torch_key(key, "weight")] = torch.from_numpy(
                arr.transpose(3, 2, 0, 1).copy())
        elif leaf == "scale":
            state[to_torch_key(key, "weight")] = torch.from_numpy(arr.copy())
        else:  # bias
            state[to_torch_key(key, "bias")] = torch.from_numpy(arr.copy())
    for key, val in stats_flat.items():
        leaf = key.rsplit(".", 1)[1]
        arr = np.asarray(val, np.float32)
        torch_leaf = "running_mean" if leaf == "mean" else "running_var"
        state[to_torch_key(key, torch_leaf)] = torch.from_numpy(arr.copy())
        nbt = to_torch_key(key, "num_batches_tracked")
        state.setdefault(nbt, torch.zeros((), dtype=torch.long))

    # The reference's ResidualBlock registers norm3 twice — standalone AND as
    # downsample[1] (extractor.py:44-45) — so state_dict() emits both key
    # spellings for the same tensors; strict loading needs the duplicates.
    for key in list(state):
        if key.endswith("downsample.0.weight"):
            block = key[: -len("downsample.0.weight")]
            for k2 in list(state):
                if k2.startswith(block + "norm3."):
                    dup = block + "downsample.1." + k2[len(block + "norm3."):]
                    state[dup] = state[k2]

    if data_parallel_prefix:
        state = {f"module.{k}": v for k, v in state.items()}
    return state


def validate_against_variables(converted: Dict, variables: Dict, *,
                               allow_unused: bool = True) -> Dict[str, Dict]:
    """Check the converted tree against a model init; return the usable tree.

    The flax-side analog of the reference's ``load_state_dict(strict=True)``
    (train_stereo.py:146): missing keys and shape mismatches always raise.
    ``allow_unused`` prunes checkpoint tensors the flax model has no slot for —
    the torch reference instantiates modules it never runs (e.g. ``layer5``/
    ``outputs32`` when ``n_gru_layers < 3``, extractor.py:224-250), so their
    weights are genuinely dead and safe to drop.
    """
    import jax

    def _unflatten(d: Dict[str, Any]) -> Dict:
        tree: Dict = {}
        for key, v in d.items():
            _set(tree, tuple(key), v)
        return tree

    out: Dict[str, Dict] = {}
    for col in ("params", "batch_stats"):
        got = jax.tree_util.tree_flatten_with_path(converted.get(col, {}))[0]
        want = jax.tree_util.tree_flatten_with_path(variables.get(col, {}))[0]
        got_d = {tuple(k.key for k in p): v for p, v in got}
        want_d = {tuple(k.key for k in p): v.shape for p, v in want}
        missing = sorted(set(want_d) - set(got_d))
        unexpected = sorted(set(got_d) - set(want_d))
        bad_shape = sorted(k for k in set(got_d) & set(want_d)
                           if got_d[k].shape != want_d[k])
        if missing or bad_shape or (unexpected and not allow_unused):
            raise ValueError(
                f"checkpoint/{col} mismatch:\n"
                f"  missing: {missing[:8]}{'...' if len(missing) > 8 else ''}\n"
                f"  unexpected: {unexpected[:8]}"
                f"{'...' if len(unexpected) > 8 else ''}\n"
                f"  shape mismatch: {bad_shape[:8]}"
                f"{'...' if len(bad_shape) > 8 else ''}")
        out[col] = _unflatten({k: v for k, v in got_d.items() if k in want_d})
    return out
