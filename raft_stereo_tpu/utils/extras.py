"""Auxiliary utilities completing the reference's component inventory.

Everything here is *dead code in the reference* (never called from any entry
point — SURVEY §2 components 6 and 8) but part of its public surface, so
working equivalents are provided:

* :func:`transfer_color` — LAB-space color statistics transfer
  (core/utils/augmentor.py:18-30).
* :func:`get_middlebury_images` / :func:`get_eth3d_images` /
  :func:`get_kitti_images` — dataset image-path globs
  (core/utils/augmentor.py:33-45).
* :func:`forward_interpolate` — forward-splat a flow field onto the next
  frame's grid by nearest-scatter + griddata fill (core/utils/utils.py:28-56).
* :func:`gauss_blur` — Gaussian blur via padding + 2-D filter
  (core/utils/utils.py:87-94).
"""

from __future__ import annotations

from glob import glob

import numpy as np


def transfer_color(source: np.ndarray, target: np.ndarray) -> np.ndarray:
    """Match ``source``'s per-channel LAB mean/std to ``target``'s.

    Classic Reinhard color transfer; uint8 RGB in, float32 RGB out.
    """
    import cv2

    src = cv2.cvtColor(source.astype(np.float32) / 255.0,
                       cv2.COLOR_RGB2LAB)
    tgt = cv2.cvtColor(target.astype(np.float32) / 255.0,
                       cv2.COLOR_RGB2LAB)
    s_mean, s_std = src.reshape(-1, 3).mean(0), src.reshape(-1, 3).std(0)
    t_mean, t_std = tgt.reshape(-1, 3).mean(0), tgt.reshape(-1, 3).std(0)
    out = (src - s_mean) * (t_std / np.maximum(s_std, 1e-6)) + t_mean
    out = cv2.cvtColor(out.astype(np.float32), cv2.COLOR_LAB2RGB)
    return np.clip(out * 255.0, 0, 255).astype(np.float32)


def get_middlebury_images(root: str = "datasets/Middlebury"):
    return sorted(glob(f"{root}/MiddEval3/trainingF/*/im0.png"))


def get_eth3d_images(root: str = "datasets/ETH3D"):
    return sorted(glob(f"{root}/two_view_training/*/im0.png"))


def get_kitti_images(root: str = "datasets/KITTI"):
    return sorted(glob(f"{root}/training/image_2/*_10.png"))


def forward_interpolate(flow: np.ndarray) -> np.ndarray:
    """Forward-warp a flow field to the next frame (utils.py:28-56).

    ``flow``: (2, H, W) or (H, W, 2); returns the same layout, with holes
    filled by nearest-neighbour interpolation.
    """
    from scipy import interpolate as sp_interpolate

    chw = flow.shape[0] == 2 and flow.ndim == 3 and flow.shape[-1] != 2
    f = flow if chw else flow.transpose(2, 0, 1)
    dx, dy = f[0], f[1]
    ht, wd = dx.shape
    x0, y0 = np.meshgrid(np.arange(wd), np.arange(ht))
    x1, y1 = (x0 + dx).reshape(-1), (y0 + dy).reshape(-1)
    dx, dy = dx.reshape(-1), dy.reshape(-1)
    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    x1, y1, dx, dy = x1[valid], y1[valid], dx[valid], dy[valid]

    flow_x = sp_interpolate.griddata((x1, y1), dx, (x0, y0), method="nearest",
                                     fill_value=0)
    flow_y = sp_interpolate.griddata((x1, y1), dy, (x0, y0), method="nearest",
                                     fill_value=0)
    out = np.stack([flow_x, flow_y], axis=0).astype(np.float32)
    return out if chw else out.transpose(1, 2, 0)


def gauss_blur(img: np.ndarray, ksize: int = 5, sigma: float = 1.0
               ) -> np.ndarray:
    """Gaussian blur of an (H, W, C) image (utils.py:87-94)."""
    import cv2

    return cv2.GaussianBlur(img, (ksize, ksize), sigma)
