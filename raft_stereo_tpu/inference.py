"""Library-style inference API.

The reference demonstrates this use-case with its robotics visualizer, which
wraps the model behind ``RAFT.compute_disparity(left_np, right_np) ->
disparity_np`` (visualize_droid_trajectory_3d.py:51-65). Here it is a
first-class citizen: :class:`StereoPredictor` owns the jitted forward and a
compile cache keyed by padded input shape, so evaluation over variably-sized
images (eval pads to /32, evaluate_stereo.py:31) recompiles once per shape
bucket instead of once per image.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import create_model
from raft_stereo_tpu.ops.geometry import InputPadder

PAD_DIVIS = 32  # every reference eval call site pads to /32 (evaluate_stereo.py:31,73,123,162)


def bucket_size(n: int, divis: int, bucket: int = 0) -> int:
    """Round ``n`` up to a multiple of ``divis`` (and of ``bucket`` if given).

    Bucketing trades a little extra padding for far fewer recompiles when
    image sizes vary (e.g. Middlebury scenes all differ by a few pixels).
    """
    if bucket:
        n = -(-n // bucket) * bucket
    return -(-n // divis) * divis


class StereoPredictor:
    """Jitted stereo inference with per-shape compile caching.

    ``variables`` is a flax variable dict ({'params', 'batch_stats'}) — e.g.
    from :func:`raft_stereo_tpu.utils.load_reference_checkpoint` or an orbax
    restore.
    """

    def __init__(self, cfg: RAFTStereoConfig, variables: Dict, *,
                 valid_iters: int = 32, bucket: int = 0):
        self.cfg = cfg
        self.model = create_model(cfg)
        self.variables = variables
        self.valid_iters = valid_iters
        self.bucket = bucket
        self._compiled: Dict[Tuple[int, int, int, int], any] = {}
        # "ring" shards the width axis over every available device (sequence
        # parallelism for very wide pairs). Pad W so each device's 1/factor-
        # resolution shard still pools 2^(levels-1)-fold locally.
        self._mesh = None
        self._w_divis = PAD_DIVIS
        if cfg.corr_implementation == "ring" and len(jax.devices()) > 1:
            import math

            from raft_stereo_tpu.parallel.mesh import make_mesh
            n = len(jax.devices())
            self._mesh = make_mesh(1, n)
            # both constraints must hold: /32 model downsampling AND local
            # per-shard pyramid pooling -> lcm, not max
            self._w_divis = math.lcm(
                PAD_DIVIS, cfg.factor * n * 2 ** (cfg.corr_levels - 1))

    def _forward(self, shape: Tuple[int, int, int], iters: int):
        key = shape + (iters,)
        fn = self._compiled.get(key)
        if fn is None:
            model = self.model

            def run(variables, image1, image2):
                return model.apply(variables, image1, image2, iters=iters,
                                   test_mode=True)

            fn = jax.jit(run)
            self._compiled[key] = fn
        return fn

    def _prepared(self, image1, image2, iters):
        """Shared pad/shard/compile-lookup for the timed and untimed paths."""
        import contextlib
        iters = self.valid_iters if iters is None else iters
        image1 = jnp.asarray(image1, jnp.float32)
        image2 = jnp.asarray(image2, jnp.float32)
        b, h, w, c = image1.shape
        padder = InputPadder(
            image1.shape, divis_by=PAD_DIVIS,
            target=(bucket_size(h, PAD_DIVIS, self.bucket),
                    bucket_size(w, self._w_divis, self.bucket)))
        im1, im2 = padder.pad(image1, image2)
        ctx = self._mesh if self._mesh is not None else contextlib.nullcontext()
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from raft_stereo_tpu.parallel.mesh import SEQ_AXIS
            spec = NamedSharding(self._mesh, P(None, None, SEQ_AXIS, None))
            im1, im2 = jax.device_put(im1, spec), jax.device_put(im2, spec)
        fn = self._forward(tuple(im1.shape[:3]), iters)
        return padder, fn, im1, im2, ctx

    def __call__(self, image1: np.ndarray, image2: np.ndarray,
                 iters: Optional[int] = None) -> np.ndarray:
        """Batched NHWC uint8-range images -> flow-x ``(B, H, W, 1)`` (negative
        disparity), matching the reference's ``flow_up`` output. Untimed: one
        dispatch, one D2H fetch — the timing discipline's extra round-trips
        live only in :meth:`predict_timed`."""
        padder, fn, im1, im2, ctx = self._prepared(image1, image2, iters)
        with ctx:
            _, flow_up = fn(self.variables, im1, im2)
        return np.asarray(padder.unpad(flow_up))

    def predict_timed(self, image1: np.ndarray, image2: np.ndarray,
                      iters: Optional[int] = None
                      ) -> Tuple[np.ndarray, float]:
        """Like ``__call__`` but also returns the DEVICE-ONLY seconds of the
        jitted forward — the number comparable to the reference's model-call
        timing (evaluate_stereo.py:77-79, which brackets only
        ``model(image1, image2, ...)``, not padding or host transfer).

        Timing discipline matches scripts/bench_inference.py: inputs are
        settled on device before ``t0`` (their H2D transfer is excluded), and
        the stop is a host fetch of one output element — on tunneled TPU
        devices ``block_until_ready`` can return before queued executions
        finish, but a host transfer of an output cannot complete until its
        executable does. The full-array D2H fetch happens after ``t1``.
        """
        import time as _time
        padder, fn, im1, im2, ctx = self._prepared(image1, image2, iters)
        with ctx:
            im1, im2 = jax.block_until_ready((im1, im2))
            t0 = _time.perf_counter()
            _, flow_up = fn(self.variables, im1, im2)
            float(flow_up[0, 0, 0, 0])  # host fetch of one element = sync
            dt = _time.perf_counter() - t0
        return np.asarray(padder.unpad(flow_up)), dt

    def compute_disparity(self, left: np.ndarray, right: np.ndarray,
                          iters: Optional[int] = None) -> np.ndarray:
        """Single HWC (or HW grayscale) image pair -> positive disparity (H, W).

        The library API the reference's visualizer builds ad hoc
        (visualize_droid_trajectory_3d.py:51-65).
        """
        if left.ndim == 2:
            left = np.tile(left[..., None], (1, 1, 3))
            right = np.tile(right[..., None], (1, 1, 3))
        flow = self(left[None].astype(np.float32),
                    right[None].astype(np.float32), iters)
        return -flow[0, ..., 0]
