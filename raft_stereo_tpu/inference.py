"""Library-style inference API.

The reference demonstrates this use-case with its robotics visualizer, which
wraps the model behind ``RAFT.compute_disparity(left_np, right_np) ->
disparity_np`` (visualize_droid_trajectory_3d.py:51-65). Here it is a
first-class citizen: :class:`StereoPredictor` owns the jitted forward and a
compile cache keyed by padded input shape, so evaluation over variably-sized
images (eval pads to /32, evaluate_stereo.py:31) recompiles once per shape
bucket instead of once per image.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import create_model
from raft_stereo_tpu.ops.geometry import InputPadder

PAD_DIVIS = 32  # every reference eval call site pads to /32 (evaluate_stereo.py:31,73,123,162)


def bucket_size(n: int, divis: int, bucket: int = 0) -> int:
    """Round ``n`` up to a multiple of ``divis`` (and of ``bucket`` if given).

    Bucketing trades a little extra padding for far fewer recompiles when
    image sizes vary (e.g. Middlebury scenes all differ by a few pixels).
    """
    if bucket:
        n = -(-n // bucket) * bucket
    return -(-n // divis) * divis


class PendingPrediction:
    """Handle for an in-flight :meth:`StereoPredictor.predict_async` call.

    The device array stays on device until :meth:`result` is called — the
    D2H fetch (and, on tunneled devices, the tunnel round-trip it pays) is
    deferred so callers can keep dispatching while earlier frames compute.
    """

    def __init__(self, flow_dev, unpad: Callable, dispatch_s: float,
                 aux: Optional[Dict[str, Any]] = None):
        self._flow = flow_dev
        self._unpad = unpad
        self._result: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        # convergence aux device arrays (residual/epe curves), fetched
        # lazily by aux_result() so the deferred-D2H contract holds
        self._aux = aux
        self._aux_np: Optional[Dict[str, np.ndarray]] = None
        #: host seconds spent inside the dispatching call (async enqueue,
        #: not device time)
        self.dispatch_s = dispatch_s
        #: host seconds :meth:`result` spent blocked on the fetch
        self.fetch_s: Optional[float] = None

    def ready(self) -> bool:
        """Best-effort non-blocking completion probe (True when a fetch
        would not block; conservatively False where the backend cannot
        tell)."""
        if self._result is not None:
            return True
        is_ready = getattr(self._flow, "is_ready", None)
        try:
            return bool(is_ready()) if is_ready is not None else False
        except Exception:
            return False

    def exception(self) -> Optional[BaseException]:
        """The deferred device/fetch error this handle captured, if any
        (without re-raising). None while unfetched or on success."""
        return self._error

    def result(self) -> np.ndarray:
        """Block until the dispatch completes; unpadded ``(B, H, W, 1)``
        flow-x as numpy. Idempotent — later calls return the cached fetch.

        Because dispatch is asynchronous, a device-side execution error
        surfaces HERE, not at ``predict_async`` — it is captured once and
        re-raised on this and every later call (with the buffer released),
        so one poisoned frame fails as a per-request error the caller can
        catch instead of leaving the handle half-fetched."""
        if self._error is not None:
            raise self._error
        if self._result is None:
            t0 = time.perf_counter()
            try:
                self._result = np.asarray(self._unpad(self._flow))
            except Exception as exc:
                self._error = exc
                self._flow = None
                self.fetch_s = time.perf_counter() - t0
                raise
            self.fetch_s = time.perf_counter() - t0
            self._flow = None  # release the device buffer reference
        return self._result

    def aux_result(self) -> Optional[Dict[str, Any]]:
        """The aux outputs as numpy (``{"residual": (iters, B)``, optionally
        ``"epe": (iters, B)``, optionally ``"numerics": {tap: (iters, 6)}}``),
        or None when the predictor ran without them. Blocks like
        :meth:`result`; fetched once."""
        if self._aux is not None and self._aux_np is None:
            self._aux_np = {
                k: ({kk: np.asarray(vv) for kk, vv in v.items()}
                    if isinstance(v, dict) else np.asarray(v))
                for k, v in self._aux.items()}
            self._aux = None
        return self._aux_np


class StereoPredictor:
    """Jitted stereo inference with per-shape compile caching.

    ``variables`` is a flax variable dict ({'params', 'batch_stats'}) — e.g.
    from :func:`raft_stereo_tpu.utils.load_reference_checkpoint` or an orbax
    restore.
    """

    def __init__(self, cfg: RAFTStereoConfig, variables: Dict, *,
                 valid_iters: int = 32, bucket: int = 0,
                 converge: bool = False, iter_epe: bool = False,
                 numerics: bool = False, iter_policy=None,
                 adaptive: Optional[bool] = None):
        self.cfg = cfg
        self.model = create_model(cfg)
        self.variables = variables
        self.valid_iters = valid_iters
        self.bucket = bucket
        #: recorded iteration policy (obs/converge.py iter_policy.json):
        #: a path or a pre-loaded doc. Loading LINTS it — a doctored
        #: policy fails here, not at dispatch.
        self._policy = None
        self.policy_digest: Optional[str] = None
        if iter_policy is not None:
            from raft_stereo_tpu.obs.converge import (load_policy,
                                                      policy_digest)
            self._policy = (load_policy(iter_policy)
                            if isinstance(iter_policy, str) else iter_policy)
            self.policy_digest = policy_digest(self._policy)
        #: early-exit execution mode: per-bucket (tau, budget, min_iters)
        #: from the policy replace the fixed trip count; the aux gains
        #: iters_taken. Default None = adaptive iff a policy was given.
        self.adaptive = (bool(adaptive) if adaptive is not None
                         else self._policy is not None)
        if self.adaptive and self._policy is None:
            raise ValueError("adaptive=True needs an iter_policy (the "
                             "thresholds/budgets are compiled in from the "
                             "recorded policy — cli converge --emit-policy)")
        if self.adaptive and numerics:
            raise ValueError("numerics taps are not supported on the "
                             "adaptive path (models/raft_stereo.py); "
                             "record numerics with adaptive=False")
        if self.adaptive:
            converge = True  # the per-sample residual aux is intrinsic
        #: record per-sample convergence curves (iter_metrics="per_sample"
        #: aux — the compiled forward gains one tiny reduction per
        #: iteration); False keeps the exact prior program
        self.converge = converge
        #: additionally compute the in-graph per-iteration low-res EPE
        #: proxy when the caller supplies ground truth (implies converge)
        self.iter_epe = iter_epe
        #: record the per-iteration activation-tap range statistics
        #: (obs/numerics.py; the model's ``numerics=True`` aux — a dict of
        #: (iters, 6) stacks rides the aux LAST); False keeps the exact
        #: prior program (the --no_numerics zero-overhead pin)
        self.numerics = numerics
        if iter_epe:
            self.converge = True
        self._last_aux: Optional[Dict[str, np.ndarray]] = None
        # whether the LAST _prepared resolved an adaptive policy entry
        # (an uncovered bucket falls back to the fixed path, so the aux
        # layout is decided per dispatch, not per predictor)
        self._adaptive_used = False
        self._compiled: Dict[Tuple, Any] = {}
        # "ring" shards the width axis over every available device (sequence
        # parallelism for very wide pairs). Pad W so each device's 1/factor-
        # resolution shard still pools 2^(levels-1)-fold locally.
        self._mesh = None
        self._w_divis = PAD_DIVIS
        if cfg.corr_implementation == "ring" and len(jax.devices()) > 1:
            import math

            from raft_stereo_tpu.parallel.mesh import make_mesh
            n = len(jax.devices())
            self._mesh = make_mesh(1, n)
            # both constraints must hold: /32 model downsampling AND local
            # per-shard pyramid pooling -> lcm, not max
            self._w_divis = math.lcm(
                PAD_DIVIS, cfg.factor * n * 2 ** (cfg.corr_levels - 1))

    def _forward(self, shape: Tuple[int, int, int], iters: int,
                 with_gt: bool = False,
                 entry: Optional[Tuple[float, int, int]] = None):
        key = shape + (iters, self.converge, with_gt, self.numerics, entry)
        fn = self._compiled.get(key)
        if fn is None:
            model = self.model
            numerics = self.numerics

            if entry is not None:
                # Early-exit flavor: the policy's (tau, budget, min_iters)
                # are compile-time constants — a different policy entry is
                # a different executable (serve/cache.py keys flavors on
                # the policy digest for the same reason). ``iters`` here
                # IS the bucket budget (resolved in _prepared).
                tau, _, min_iters = entry

                if with_gt:
                    def run(variables, image1, image2, flow_gt, valid):
                        return model.apply(
                            variables, image1, image2, iters=iters,
                            test_mode=True, iter_metrics="per_sample",
                            flow_gt=flow_gt, loss_mask=valid,
                            adaptive_tau=tau, adaptive_min_iters=min_iters)
                else:
                    def run(variables, image1, image2):
                        return model.apply(
                            variables, image1, image2, iters=iters,
                            test_mode=True, iter_metrics="per_sample",
                            adaptive_tau=tau, adaptive_min_iters=min_iters)
            elif self.converge and with_gt:
                def run(variables, image1, image2, flow_gt, valid):
                    return model.apply(variables, image1, image2,
                                       iters=iters, test_mode=True,
                                       iter_metrics="per_sample",
                                       flow_gt=flow_gt, loss_mask=valid,
                                       numerics=numerics)
            elif self.converge:
                def run(variables, image1, image2):
                    return model.apply(variables, image1, image2,
                                       iters=iters, test_mode=True,
                                       iter_metrics="per_sample",
                                       numerics=numerics)
            elif numerics:
                def run(variables, image1, image2):
                    return model.apply(variables, image1, image2,
                                       iters=iters, test_mode=True,
                                       numerics=True)
            else:
                # converge+numerics off: the exact prior program (the
                # --no_converge/--no_numerics zero-overhead pins,
                # tests/test_converge.py and tests/test_numerics.py)
                def run(variables, image1, image2):
                    return model.apply(variables, image1, image2,
                                       iters=iters, test_mode=True)

            fn = jax.jit(run)
            self._compiled[key] = fn
        return fn

    def _prepared(self, image1, image2, iters, flow_gt=None, valid=None):
        """Shared pad/shard/compile-lookup for the timed and untimed paths."""
        import contextlib
        iters = self.valid_iters if iters is None else iters
        image1 = jnp.asarray(image1, jnp.float32)
        image2 = jnp.asarray(image2, jnp.float32)
        b, h, w, c = image1.shape
        padder = InputPadder(
            image1.shape, divis_by=PAD_DIVIS,
            target=(bucket_size(h, PAD_DIVIS, self.bucket),
                    bucket_size(w, self._w_divis, self.bucket)))
        im1, im2 = padder.pad(image1, image2)
        gt_args: Tuple = ()
        if self.iter_epe and flow_gt is not None:
            # GT/validity get ZERO padding: edge replication would mark the
            # padded border as valid signal, skewing the pooled-EPE aux
            gt = jnp.asarray(flow_gt, jnp.float32)
            va = (jnp.ones(gt.shape, jnp.float32) if valid is None
                  else jnp.asarray(valid, jnp.float32).reshape(gt.shape))
            gt_args = tuple(padder.pad_zeros(gt, va))
        ctx = self._mesh if self._mesh is not None else contextlib.nullcontext()
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from raft_stereo_tpu.parallel.mesh import SEQ_AXIS
            spec = NamedSharding(self._mesh, P(None, None, SEQ_AXIS, None))
            im1, im2 = jax.device_put(im1, spec), jax.device_put(im2, spec)
            if gt_args:
                gt_args = tuple(jax.device_put(x, spec) for x in gt_args)
        entry = None
        if self.adaptive:
            doc = self.policy_entry(h, w)
            if doc is not None:
                # The policy budget replaces the fixed trip count for this
                # bucket; an explicit smaller per-call ``iters`` still caps
                # it. Buckets the policy doesn't cover fall back to the
                # fixed path (no iters_taken aux for those calls).
                entry = (float(doc["tau"]), int(doc["budget"]),
                         int(doc["min_iters"]))
                iters = min(iters, entry[1]) if iters else entry[1]
        self._adaptive_used = entry is not None
        fn = self._forward(tuple(im1.shape[:3]), iters,
                           with_gt=bool(gt_args), entry=entry)
        return padder, fn, im1, im2, gt_args, ctx

    def policy_entry(self, height: int, width: int) -> Optional[Dict]:
        """The iteration-policy entry the PADDED ``(height, width)`` bucket
        resolves to (``{"tau", "budget", "min_iters", ...}``), or None when
        no policy is loaded / the bucket is uncovered and the policy has no
        default. Serve uses this to size its per-bucket iteration budget
        before dispatch (serve/server.py)."""
        if self._policy is None:
            return None
        from raft_stereo_tpu.obs.converge import policy_lookup
        bucket = "%dx%d" % (bucket_size(height, PAD_DIVIS, self.bucket),
                            bucket_size(width, self._w_divis, self.bucket))
        return policy_lookup(self._policy, bucket)

    def _aux_of(self, outs) -> Optional[Dict[str, Any]]:
        """Slot the aux outputs after (flow_lr, flow_up) into a dict.

        Layout (models/raft_stereo.py): residual, then epe when GT was
        supplied, then iters_taken on the adaptive path, then the numerics
        tap dict LAST (numerics and adaptive are mutually exclusive).
        Values stay whatever they are (device arrays here; the fetch
        points convert)."""
        if not (self.converge or self.numerics):
            return None
        rest = list(outs[2:])
        aux: Dict[str, Any] = {}
        if self.numerics:
            aux["numerics"] = rest.pop()
        if getattr(self, "_adaptive_used", False):
            aux["iters_taken"] = rest.pop()
        if self.converge:
            aux["residual"] = rest[0]
            if len(rest) > 1:
                aux["epe"] = rest[1]
        return aux

    @staticmethod
    def _aux_np(aux: Optional[Dict[str, Any]]
                ) -> Optional[Dict[str, Any]]:
        """D2H-fetch an aux dict (the numerics entry is a nested dict of
        per-tap stacks)."""
        if aux is None:
            return None
        return {k: ({kk: np.asarray(vv) for kk, vv in v.items()}
                    if isinstance(v, dict) else np.asarray(v))
                for k, v in aux.items()}

    def _stash_aux(self, outs) -> None:
        """Fetch + stash the aux of a sync call for take_aux()."""
        aux = self._aux_np(self._aux_of(outs))
        if aux is not None:
            self._last_aux = aux

    def take_aux(self) -> Optional[Dict[str, np.ndarray]]:
        """Pop the convergence aux curves of the LAST synchronous call
        (``__call__``/``predict_timed``) — ``{"residual": (iters, B)``,
        optionally ``"epe"}`` — or None when converge is off. The async
        path carries its aux on the handle instead
        (:meth:`PendingPrediction.aux_result`)."""
        aux, self._last_aux = self._last_aux, None
        return aux

    def __call__(self, image1: np.ndarray, image2: np.ndarray,
                 iters: Optional[int] = None, flow_gt=None,
                 valid=None) -> np.ndarray:
        """Batched NHWC uint8-range images -> flow-x ``(B, H, W, 1)`` (negative
        disparity), matching the reference's ``flow_up`` output. Untimed: one
        dispatch, one D2H fetch — the timing discipline's extra round-trips
        live only in :meth:`predict_timed`. ``flow_gt``/``valid`` feed the
        iter-EPE aux (only read when the predictor was built with
        ``iter_epe=True``; see :meth:`take_aux`)."""
        padder, fn, im1, im2, gt_args, ctx = self._prepared(
            image1, image2, iters, flow_gt, valid)
        with ctx:
            outs = fn(self.variables, im1, im2, *gt_args)
        self._stash_aux(outs)
        return np.asarray(padder.unpad(outs[1]))

    def predict_timed(self, image1: np.ndarray, image2: np.ndarray,
                      iters: Optional[int] = None, flow_gt=None,
                      valid=None) -> Tuple[np.ndarray, float]:
        """Like ``__call__`` but also returns the DEVICE-ONLY seconds of the
        jitted forward — the number comparable to the reference's model-call
        timing (evaluate_stereo.py:77-79, which brackets only
        ``model(image1, image2, ...)``, not padding or host transfer).

        Timing discipline matches scripts/bench_inference.py: inputs are
        settled on device before ``t0`` (their H2D transfer is excluded), and
        the stop is a host fetch of one output element — on tunneled TPU
        devices ``block_until_ready`` can return before queued executions
        finish, but a host transfer of an output cannot complete until its
        executable does. The full-array D2H fetch happens after ``t1``.
        """
        import time as _time
        padder, fn, im1, im2, gt_args, ctx = self._prepared(
            image1, image2, iters, flow_gt, valid)
        with ctx:
            im1, im2 = jax.block_until_ready((im1, im2))
            if gt_args:
                gt_args = jax.block_until_ready(gt_args)
            t0 = _time.perf_counter()
            outs = fn(self.variables, im1, im2, *gt_args)
            flow_up = outs[1]
            float(flow_up[0, 0, 0, 0])  # host fetch of one element = sync
            dt = _time.perf_counter() - t0
        self._stash_aux(outs)  # aux D2H lands after the timing stops
        return np.asarray(padder.unpad(flow_up)), dt

    def predict_async(self, image1: np.ndarray, image2: np.ndarray,
                      iters: Optional[int] = None, flow_gt=None,
                      valid=None) -> PendingPrediction:
        """Dispatch one batched forward and return immediately.

        Inputs are staged onto the device and the jitted call is enqueued
        (JAX dispatch is asynchronous); nothing blocks on device completion.
        The returned :class:`PendingPrediction` fetches the unpadded flow on
        ``result()``. With a bounded window of outstanding handles, frame
        *i*'s fetch and host post-processing overlap frames *i+1…i+K*'s
        device compute — the per-call tunnel RTT and host time amortize away
        exactly like the training loop's chained dispatch (see
        eval/stream.py, which drives this)."""
        t0 = time.perf_counter()
        padder, fn, im1, im2, gt_args, ctx = self._prepared(
            image1, image2, iters, flow_gt, valid)
        with ctx:
            outs = fn(self.variables, im1, im2, *gt_args)
        return PendingPrediction(outs[1], padder.unpad,
                                 time.perf_counter() - t0,
                                 aux=self._aux_of(outs))

    def compute_disparity(self, left: np.ndarray, right: np.ndarray,
                          iters: Optional[int] = None) -> np.ndarray:
        """Single HWC (or HW grayscale) image pair -> positive disparity (H, W).

        The library API the reference's visualizer builds ad hoc
        (visualize_droid_trajectory_3d.py:51-65).
        """
        if left.ndim == 2:
            left = np.tile(left[..., None], (1, 1, 3))
            right = np.tile(right[..., None], (1, 1, 3))
        flow = self(left[None].astype(np.float32),
                    right[None].astype(np.float32), iters)
        return -flow[0, ..., 0]
