"""Streaming evaluation driver: overlap decode / dispatch / fetch.

The four validators in eval/validate.py share one frame loop. Sequentially,
each frame pays decode + H2D + device compute + D2H + host metrics end to
end — on a tunneled chip that is ~60-75 ms of round-trip per frame that the
device spends idle (PERF.md: KITTI validator 13.28 FPS vs 83.3 FPS for the
same model with frames chained device-side). This driver pipelines the
stages the way the training loop does:

* **decode** — a small thread pool (the data/loader.py producer pattern)
  decodes frames ahead of dispatch, in index order, bounded by ``prefetch``;
* **dispatch** — frames go to ``predictor.predict_async`` and the handle is
  queued; up to ``window`` dispatches stay in flight, so the device queue
  never drains while the host fetches;
* **micro-batch** — consecutive frames whose raw shapes agree (hence pad to
  the same compiled shape) are stacked through ONE dispatch, up to
  ``microbatch``; FlyingThings' test split is a single shape, so batching
  there costs no extra compiles;
* **retire** — handles are resolved strictly in dispatch (= dataset index)
  order and the per-frame metric closure runs on the host while later
  frames compute, so aggregation semantics stay reference-exact
  (tests/test_eval_oracle.py's oracle bar).

Predictors without ``predict_async`` (e.g. the oracle tests' stubs) — or
``StreamConfig(enabled=False)`` — fall back to the sequential loop with
identical consume ordering and telemetry, so streaming is an overlay, not a
fork, of the metric path.

Telemetry: every frame emits a ``step`` record with the training loop's
data-wait / dispatch / fetch split (plus ``in_flight`` depth and
``batch_size``), and the streaming path emits a ``pipeline`` gauge every
``GAUGE_EVERY`` dispatches; obs/summarize.py turns these into the
pipeline-overlap efficiency the PERF.md evidence policy cites.
"""

from __future__ import annotations

import collections
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Union

import numpy as np

from raft_stereo_tpu.obs.trace import NULL_TRACER
from raft_stereo_tpu.serve.batching import collect_group, stack_pairs

logger = logging.getLogger(__name__)

# pipeline-gauge cadence, matching data/loader.py's producer gauges
GAUGE_EVERY = 16


@dataclass
class StreamConfig:
    """Knobs of the streaming pipeline (CLI: --stream*, --decode_workers)."""

    #: None = auto: stream when the predictor has ``predict_async``
    enabled: Optional[bool] = None
    #: max in-flight device dispatches (1 = no overlap)
    window: int = 3
    #: max consecutive same-shape frames stacked through one dispatch
    microbatch: int = 1
    #: decode threads feeding the pipeline
    decode_workers: int = 2
    #: decoded frames buffered ahead of dispatch
    prefetch: int = 8


@dataclass
class FrameTiming:
    """Per-frame phase split handed to the consume closure.

    In streaming mode the dispatch/fetch costs of a micro-batch are split
    evenly over its frames, ``device_s`` is unavailable (measuring it would
    re-serialize the pipeline), and ``e2e_s`` is the retire interval — the
    pipelined per-frame cost whose mean is the reciprocal of end-to-end
    throughput. Sequentially, ``device_s``/``e2e_s`` reproduce the timed
    validator's historical semantics (device forward / predict-call wall).
    """

    data_wait_s: float
    dispatch_s: float
    fetch_s: float
    device_s: Optional[float]
    e2e_s: float
    batch_size: int
    in_flight: int


#: consume(index, sample, flow_pred_hw1, timing) — called in index order
Consume = Callable[[int, Dict[str, np.ndarray], np.ndarray, FrameTiming],
                   None]


def resolve_stream(stream: Union[None, bool, StreamConfig]) -> StreamConfig:
    """Validator-kwarg sugar: None/bool/StreamConfig -> StreamConfig."""
    if stream is None:
        return StreamConfig()
    if isinstance(stream, bool):
        return StreamConfig(enabled=stream)
    return stream


def run_frames(predictor, dataset, consume: Consume, *, iters: int,
               stream: Union[None, bool, StreamConfig] = None,
               telemetry=None, timed: bool = False,
               source: Optional[str] = None) -> Dict[str, Any]:
    """Drive ``consume`` over every dataset frame, in index order.

    ``timed=True`` asks the sequential path for device-only timing via
    ``predictor.predict_timed`` (the KITTI validator's FPS discipline);
    other validators use the single-dispatch ``__call__``. ``source``
    labels the validator on emitted ``converge`` records (predictors built
    with ``converge=True`` yield per-frame convergence curves; see
    obs/converge.py). Returns a stats dict (mode, wall seconds,
    frames/sec) for callers that report throughput.
    """
    cfg = resolve_stream(stream)
    use_stream = (hasattr(predictor, "predict_async")
                  if cfg.enabled is None else cfg.enabled)
    if use_stream and not hasattr(predictor, "predict_async"):
        raise ValueError(
            f"stream=on but {type(predictor).__name__} has no predict_async")
    n = len(dataset)
    src = f"eval:{source or 'eval'}"
    t_run0 = time.perf_counter()
    if use_stream:
        _run_streaming(predictor, dataset, consume, iters, cfg, telemetry,
                       src)
    else:
        _run_sequential(predictor, dataset, consume, iters, telemetry, timed,
                        src)
    wall = time.perf_counter() - t_run0
    return {
        "mode": "stream" if use_stream else "sequential",
        "frames": n,
        "wall_s": wall,
        "frames_per_sec": n / wall if wall > 0 else float("inf"),
        "window": cfg.window if use_stream else 1,
        "microbatch": cfg.microbatch if use_stream else 1,
    }


def _emit_step(telemetry, index: int, timing: FrameTiming) -> None:
    if telemetry is not None:
        telemetry.step(index + 1, data_wait_s=timing.data_wait_s,
                       dispatch_s=timing.dispatch_s, fetch_s=timing.fetch_s,
                       batch_size=timing.batch_size,
                       in_flight=timing.in_flight)


def _gt_kwargs(predictor, samples) -> Dict[str, np.ndarray]:
    """GT/validity kwargs feeding the in-graph iter-EPE aux — only when the
    predictor asked for it (``iter_epe``) and every frame carries GT, so
    stub predictors and GT-less datasets never see the extra kwargs."""
    if not getattr(predictor, "iter_epe", False):
        return {}
    if not all("flow" in s for s in samples):
        return {}
    kw = {"flow_gt": np.stack([s["flow"] for s in samples])}
    if all("valid" in s for s in samples):
        kw["valid"] = np.stack([s["valid"] for s in samples])
    return kw


def _emit_numerics(telemetry, source, sample, aux, index) -> None:
    """One dispatch's ``numerics`` record. The tap statistics are reduced
    over the whole (micro-)batch in graph, so unlike the per-frame
    converge curves there is exactly one record per dispatch — ``frame``
    carries the group's first dataset index."""
    if telemetry is None or aux is None:
        return
    taps = aux.get("numerics")
    if not taps:
        return
    from raft_stereo_tpu.obs import numerics as numerics_obs
    h, w = sample["image1"].shape[:2]
    numerics_obs.emit(telemetry, numerics_obs.taps_payload(
        source, taps, bucket=f"{h}x{w}", frame=index))


def _emit_converge(telemetry, source, sample, aux, j, index) -> None:
    """One frame's ``converge`` record from a (possibly batched) aux.

    Adaptive predictors (``iter_policy=``, inference.py) add the per-sample
    ``iters_taken`` as an extra on the same record — the production loop's
    evidence that the compiled early exit actually saved iterations (and
    the doctor's OVER_ITERATED verdict input)."""
    if telemetry is None or aux is None or "residual" not in aux:
        return
    from raft_stereo_tpu.obs import converge as converge_obs
    residual = np.asarray(aux["residual"])
    res = residual[:, j] if residual.ndim == 2 else residual
    epe = aux.get("epe")
    if epe is not None:
        epe = np.asarray(epe)
        epe = epe[:, j] if epe.ndim == 2 else epe
    extra = {}
    taken = aux.get("iters_taken")
    if taken is not None:
        arr = np.asarray(taken)
        extra["iters_taken"] = int(arr[j] if arr.ndim else arr)
    h, w = sample["image1"].shape[:2]
    converge_obs.emit(telemetry, source, len(res), res, epe=epe,
                      bucket=f"{h}x{w}", frame=index, **extra)


def _run_sequential(predictor, dataset, consume, iters, telemetry, timed,
                    source):
    tracer = getattr(telemetry, "tracer", None) or NULL_TRACER
    take_aux = getattr(predictor, "take_aux", None)
    for i in range(len(dataset)):
        t_load = time.perf_counter()
        sample = dataset.sample(i)
        gt_kw = _gt_kwargs(predictor, [sample])
        t0 = time.perf_counter()
        if timed:
            flow, dt_dev = predictor.predict_timed(
                sample["image1"][None], sample["image2"][None], iters,
                **gt_kw)
        else:
            flow = predictor(sample["image1"][None], sample["image2"][None],
                             iters, **gt_kw)
            dt_dev = None
        t1 = time.perf_counter()
        root = tracer.record("eval/frame", t_load, t1, index=i)
        tracer.record("eval/decode", t_load, t0, parent=root)
        tracer.record("eval/predict", t0, t1, parent=root)
        # historical split (eval/validate.py r5 KITTI loop): dispatch is the
        # device forward where measured, fetch the pad/transfer overhead
        # around it; untimed validators can't split the single blocking call
        dispatch_s = dt_dev if dt_dev is not None else t1 - t0
        timing = FrameTiming(
            data_wait_s=t0 - t_load, dispatch_s=dispatch_s,
            fetch_s=max((t1 - t0) - dispatch_s, 0.0), device_s=dt_dev,
            e2e_s=t1 - t0, batch_size=1, in_flight=1)
        _emit_step(telemetry, i, timing)
        aux = take_aux() if take_aux is not None else None
        _emit_converge(telemetry, source, sample, aux, 0, i)
        _emit_numerics(telemetry, source, sample, aux, i)
        consume(i, sample, flow[0], timing)


def _run_streaming(predictor, dataset, consume, iters, cfg, telemetry,
                   source):
    tracer = getattr(telemetry, "tracer", None) or NULL_TRACER
    n = len(dataset)
    window = max(1, cfg.window)
    microbatch = max(1, cfg.microbatch)
    lookahead = max(cfg.prefetch, microbatch, 1)
    pool = ThreadPoolExecutor(max(1, cfg.decode_workers),
                              thread_name_prefix="eval-decode")
    pending: "collections.deque" = collections.deque()  # (idx, future)
    decoded: "collections.deque" = collections.deque()  # (idx, sample)
    in_flight: "collections.deque" = collections.deque()
    next_submit = 0
    dispatches = 0
    t_last_retire = time.perf_counter()

    def fill():
        nonlocal next_submit
        while next_submit < n and len(pending) + len(decoded) < lookahead:
            pending.append((next_submit,
                            pool.submit(dataset.sample, next_submit)))
            next_submit += 1

    def take_decoded():
        """Next decoded frame in index order; returns (idx, sample, wait_s)."""
        if decoded:
            idx, sample = decoded.popleft()
            return idx, sample, 0.0
        idx, fut = pending.popleft()
        t0 = time.perf_counter()
        sample = fut.result()
        return idx, sample, time.perf_counter() - t0

    def retire():
        nonlocal t_last_retire
        group, handle, dispatch_s, data_wait_s, stamps = in_flight.popleft()
        tr0 = time.perf_counter()
        flows = handle.result()  # (B, H, W, 1); blocks until the device is done
        aux_fn = getattr(handle, "aux_result", None)
        aux = aux_fn() if aux_fn is not None else None
        tr1 = time.perf_counter()
        fetch_s = getattr(handle, "fetch_s", None) or 0.0
        b = len(group)
        # one span tree per micro-batch group, from the first decode pull
        # to the result fetch; decode_wait is the summed future-wait
        # charged at the group's start
        tg0, td0, td1 = stamps
        root = tracer.record("eval/frames", tg0, tr1, frames=b,
                             first_index=group[0][0])
        tracer.record("eval/decode_wait", tg0, tg0 + data_wait_s,
                      parent=root)
        tracer.record("eval/dispatch", td0, td1, parent=root)
        tracer.record("eval/fetch", tr0, tr1, parent=root)
        _emit_numerics(telemetry, source, group[0][1], aux, group[0][0])
        for j, (idx, sample) in enumerate(group):
            now = time.perf_counter()
            timing = FrameTiming(
                data_wait_s=data_wait_s / b, dispatch_s=dispatch_s / b,
                fetch_s=fetch_s / b, device_s=None,
                e2e_s=now - t_last_retire, batch_size=b,
                in_flight=len(in_flight))
            t_last_retire = now
            _emit_step(telemetry, idx, timing)
            _emit_converge(telemetry, source, sample, aux, j, idx)
            consume(idx, sample, flows[j], timing)

    try:
        fill()
        while pending or decoded or next_submit < n or in_flight:
            frames_left = pending or decoded or next_submit < n
            if frames_left and len(in_flight) < window:
                tg0 = time.perf_counter()
                idx0, s0, wait = take_decoded()
                fill()
                # stack consecutive same-shape frames into one dispatch;
                # a shape break is pushed back and starts the next group
                # (serve/batching.py owns the policy, shared with the
                # serving scheduler). The decode wait of a pushed-back
                # frame is still charged to the CURRENT group — it was
                # paid while forming it.
                waits = [wait]

                def pull():
                    if not (decoded or pending):
                        return None
                    idx_k, s_k, wait_k = take_decoded()
                    fill()
                    waits.append(wait_k)
                    return (idx_k, s_k)

                group = collect_group(
                    (idx0, s0), pull, decoded.appendleft, microbatch,
                    key=lambda item: item[1]["image1"].shape)
                wait = sum(waits)
                im1, im2 = stack_pairs([s for _, s in group])
                gt_kw = _gt_kwargs(predictor, [s for _, s in group])
                t0 = time.perf_counter()
                handle = predictor.predict_async(im1, im2, iters, **gt_kw)
                t1 = time.perf_counter()
                dispatch_s = t1 - t0
                in_flight.append((group, handle, dispatch_s, wait,
                                  (tg0, t0, t1)))
                dispatches += 1
                if telemetry is not None and \
                        dispatches % GAUGE_EVERY == 1:
                    telemetry.pipeline(in_flight=len(in_flight),
                                       window=window, microbatch=microbatch)
            else:
                retire()
    finally:
        pool.shutdown(wait=False, cancel_futures=True)
