"""Dataset validators (evaluate_stereo.py:19-189, re-built on the JAX stack).

Each validator shares the reference skeleton: load pair -> pad to /32 ->
``model(test_mode=True)`` -> unpad -> EPE against GT flow, with the
dataset-specific metric definitions:

* ETH3D: bad-1px "D1", IMAGE-weighted (the reference appends each image's
  scalar D1 mean and averages those — evaluate_stereo.py:42-53)
* KITTI: bad-3px PIXEL-weighted (:97-103 concatenates per-pixel outlier
  masks), plus FPS after a warmup (:77-107)
* FlyingThings: bad-1px over pixels with ``|disp| < 192``, pixel-weighted
  (:133-143)
* Middlebury: bad-2px, image-weighted (:175-186); the reference's
  ``valid >= -0.5`` check (:173) is a NO-OP on the 0/1 nocc mask —
  replicated faithfully, so the effective filter is ``gt > -1000`` alone and
  occluded pixels are NOT excluded

EPE is the mean of per-image means in every validator. The aggregation
differences across validators are the reference's, kept so numbers are
comparable to what it prints (oracle-pinned in tests/test_eval_oracle.py,
which runs the reference's own validate_* as the oracle).

All metric arithmetic happens in numpy on the host — the device computes only
the forward pass, via :class:`raft_stereo_tpu.inference.StereoPredictor`
(which buckets shapes to bound recompiles). The frame loop itself lives in
eval/stream.py: one driver feeds all four validators, either sequentially or
as a decode/dispatch/fetch pipeline (``stream=``), with per-frame metric
closures applied in index order as results retire — so streaming changes
WHEN metrics are computed, never WHAT they aggregate to.

Frames whose validity mask is empty are skipped with a warning instead of
poisoning the aggregate: ``epe[valid].mean()`` over zero pixels is NaN (the
reference would print NaN there too — on real dataset trees the case does
not arise, so the skip never diverges from oracle numbers).
"""

from __future__ import annotations

import logging
import os.path as osp
from typing import Dict, Union

import numpy as np

from raft_stereo_tpu.data import datasets
from raft_stereo_tpu.eval.stream import StreamConfig, run_frames
from raft_stereo_tpu.inference import StereoPredictor

logger = logging.getLogger(__name__)

StreamArg = Union[None, bool, StreamConfig]


def _epe(flow_pred: np.ndarray, flow_gt: np.ndarray) -> np.ndarray:
    """Per-pixel endpoint error between (H, W, C) flows (C=1: |dx|)."""
    return np.sqrt(np.sum((flow_pred - flow_gt) ** 2, axis=-1))


def _usable(valid: np.ndarray, dataset: str, index: int) -> bool:
    """Guard the empty-valid-mask NaN: skip-and-warn instead of averaging
    a NaN into the run (see module doc)."""
    if valid.any():
        return True
    logger.warning("%s frame %d: validity mask is empty — frame skipped "
                   "(its per-image mean would be NaN)", dataset, index)
    return False


def _emit(telemetry, dataset: str, results: Dict[str, float]) -> None:
    """Mirror a validator's results onto the telemetry bus (obs/) when the
    caller runs one — eval CLI with --run_dir, or a future eval harness."""
    if telemetry is not None:
        telemetry.validation(results, dataset=dataset)


def validate_eth3d(predictor: StereoPredictor, root: str = "datasets",
                   iters: int = 32, telemetry=None,
                   stream: StreamArg = None) -> Dict[str, float]:
    """ETH3D two-view validation: EPE + bad-1px (evaluate_stereo.py:19-56)."""
    ds = datasets.ETH3D(root=osp.join(root, "ETH3D"))
    if len(ds) == 0:
        raise ValueError(f"no samples found under {root!r}")
    epe_list, out_list = [], []

    def consume(i, sample, flow_pr, timing):
        flow_gt = sample["flow"]
        valid = sample["valid"] >= 0.5
        if not _usable(valid, "eth3d", i):
            return
        epe = _epe(flow_pr, flow_gt)
        epe_list.append(epe[valid].mean().item())
        # image-weighted D1: the reference appends each image's scalar mean
        # (evaluate_stereo.py:43-47) and averages the scalars (:53)
        out_list.append((epe > 1.0)[valid].mean().item())

    run_frames(predictor, ds, consume, iters=iters, stream=stream,
               telemetry=telemetry, source="eth3d")
    epe = float(np.mean(epe_list))
    d1 = 100 * float(np.mean(out_list))
    logger.info("Validation ETH3D: EPE %f, D1 %f", epe, d1)
    results = {"eth3d-epe": epe, "eth3d-d1": d1}
    _emit(telemetry, "eth3d", results)
    return results


def validate_kitti(predictor: StereoPredictor, root: str = "datasets",
                   iters: int = 32,
                   warmup_frames: int = 50, telemetry=None,
                   stream: StreamArg = None) -> Dict[str, float]:
    """KITTI-15 training-split validation: EPE + bad-3px + FPS
    (evaluate_stereo.py:59-108).

    Sequentially, two FPS numbers are reported: ``kitti-fps`` times the
    DEVICE forward only (``StereoPredictor.predict_timed``) — the number
    comparable to the reference, which brackets only the ``model(...)`` call
    (:77-79) — and ``kitti-fps-e2e`` additionally includes padding, H2D
    transfer and the host fetch of the full disparity map. In streaming mode
    the per-frame device sync that ``kitti-fps`` needs would re-serialize
    the pipeline, so only ``kitti-fps-e2e`` is reported — computed from
    retire intervals, the pipelined throughput that converges toward the
    device-side FPS as overlap wins (PERF.md). Frames ``0..warmup_frames``
    are excluded like the reference's ``val_id > 50`` cudnn-autotune warmup
    (:81)."""
    ds = datasets.KITTI(root=osp.join(root, "KITTI"), image_set="training")
    if len(ds) == 0:
        raise ValueError(f"no samples found under {root!r}")
    epe_list, out_list, elapsed_dev, elapsed_e2e = [], [], [], []

    def consume(i, sample, flow_pr, timing):
        if i > warmup_frames:
            if timing.device_s is not None:
                elapsed_dev.append(timing.device_s)
            elapsed_e2e.append(timing.e2e_s)
        flow_gt = sample["flow"]
        valid = sample["valid"] >= 0.5
        if not _usable(valid, "kitti", i):
            return
        epe = _epe(flow_pr, flow_gt)
        epe_list.append(epe[valid].mean().item())
        # pixel-weighted D1: the reference concatenates per-pixel outlier
        # masks here (evaluate_stereo.py:97-103), unlike ETH3D/Middlebury
        out_list.append((epe > 3.0)[valid])

    run_frames(predictor, ds, consume, iters=iters, stream=stream,
               telemetry=telemetry, timed=True, source="kitti")
    epe = float(np.mean(epe_list))
    d1 = 100 * float(np.concatenate(out_list).mean())
    result = {"kitti-epe": epe, "kitti-d1": d1}
    if elapsed_dev:
        result["kitti-fps"] = 1.0 / float(np.mean(elapsed_dev))
    if elapsed_e2e:
        result["kitti-fps-e2e"] = 1.0 / float(np.mean(elapsed_e2e))
        logger.info("Validation KITTI: EPE %f, D1 %f, %s FPS (%f e2e)",
                    epe, d1, result.get("kitti-fps", "n/a (streamed)"),
                    result["kitti-fps-e2e"])
    else:
        logger.info("Validation KITTI: EPE %f, D1 %f", epe, d1)
    _emit(telemetry, "kitti", result)
    return result


def validate_things(predictor: StereoPredictor, root: str = "datasets",
                    iters: int = 32,
                    max_disp: float = 192.0, telemetry=None,
                    stream: StreamArg = None) -> Dict[str, float]:
    """FlyingThings3D TEST split: EPE + bad-1px over ``|disp| < max_disp``
    (evaluate_stereo.py:111-146). Doubles as the in-training validation hook
    (train_stereo.py:188). The test split is a single image shape, so the
    streaming path's micro-batching applies to every frame."""
    ds = datasets.SceneFlow(root=root, dstype="frames_finalpass",
                            things_test=True)
    if len(ds) == 0:
        raise ValueError(f"no samples found under {root!r}")
    epe_list, out_list = [], []

    def consume(i, sample, flow_pr, timing):
        flow_gt = sample["flow"]
        epe = _epe(flow_pr, flow_gt)
        valid = (sample["valid"] >= 0.5) & \
                (np.abs(flow_gt[..., 0]) < max_disp)
        if not _usable(valid, "things", i):
            return
        epe_list.append(epe[valid].mean().item())
        out_list.append((epe > 1.0)[valid])

    run_frames(predictor, ds, consume, iters=iters, stream=stream,
               telemetry=telemetry, source="things")
    epe = float(np.mean(epe_list))
    d1 = 100 * float(np.concatenate(out_list).mean())
    logger.info("Validation FlyingThings: EPE %f, D1 %f", epe, d1)
    results = {"things-epe": epe, "things-d1": d1}
    _emit(telemetry, "things", results)
    return results


def validate_middlebury(predictor: StereoPredictor, root: str = "datasets",
                        iters: int = 32,
                        split: str = "F", telemetry=None,
                        stream: StreamArg = None) -> Dict[str, float]:
    """Middlebury MiddEval3 validation: EPE + bad-2px (evaluate_stereo.py:149-189).

    ``split`` in {'F','H','Q'}. Mask semantics replicate the reference
    EXACTLY: its ``valid_gt >= -0.5`` check (evaluate_stereo.py:173) is a
    no-op on the 0/1 nocc mask, so the effective filter is ``gt > -1000``
    alone — occluded pixels are scored, the nocc mask is loaded but unused.
    Both EPE and D1 are image-weighted (per-image scalar means averaged,
    :176-186).
    """
    ds = datasets.Middlebury(root=osp.join(root, "Middlebury"), split=split)
    if len(ds) == 0:
        raise ValueError(f"no samples found under {root!r}")
    epe_list, out_list = [], []

    def consume(i, sample, flow_pr, timing):
        flow_gt = sample["flow"]
        valid = (sample["valid"] >= -0.5) & (flow_gt[..., 0] > -1000)
        if not _usable(valid, f"middlebury{split}", i):
            return
        epe = _epe(flow_pr, flow_gt)
        epe_list.append(epe[valid].mean().item())
        out_list.append((epe > 2.0)[valid].mean().item())

    run_frames(predictor, ds, consume, iters=iters, stream=stream,
               telemetry=telemetry, source=f"middlebury{split}")
    epe = float(np.mean(epe_list))
    d1 = 100 * float(np.mean(out_list))
    logger.info("Validation Middlebury%s: EPE %f, D1 %f", split, epe, d1)
    results = {f"middlebury{split}-epe": epe, f"middlebury{split}-d1": d1}
    _emit(telemetry, f"middlebury{split}", results)
    return results


VALIDATORS = {
    "eth3d": validate_eth3d,
    "kitti": validate_kitti,
    "things": validate_things,
    "middlebury": validate_middlebury,
}
