from raft_stereo_tpu.eval.stream import StreamConfig, run_frames
from raft_stereo_tpu.eval.validate import (
    validate_eth3d,
    validate_kitti,
    validate_middlebury,
    validate_things,
)

__all__ = ["StreamConfig", "run_frames", "validate_eth3d", "validate_kitti",
           "validate_middlebury", "validate_things"]
