"""Synthetic many-client load driver for the serving scheduler.

``cli loadtest`` (and the proof harness scripts/load_drill.py) run this:
N concurrent client threads — each a stream of same-shape requests, at
least one a *video* session riding ``flow_init`` warm starts — submit a
mixed-shape trace against one :class:`StereoServer`, after a
sequential-``predict()`` baseline over the identical trace. Both phases
write telemetry run dirs (``step`` + ``throughput`` events), so the
existing ``cli compare`` gate arbitrates served-vs-sequential throughput
with the same thresholds every other perf claim in this repo uses.

The driver is also the fault-injection rig: ``poison_at=k`` corrupts the
k-th request (global ordinal) with a NaN pixel — the per-request isolation
proof — and a mid-run SIGTERM (scripts/load_drill.py sends one) must drain
with ZERO lost admitted requests: every client tallies each submit as
exactly one of ok / failed / rejected, and ``lost`` counts admitted
requests that never produced a result.

Progress lines (``LOADTEST progress ...``) go to stdout unbuffered so a
supervising process can time its signals against real completions.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_stereo_tpu.obs.trace import NULL_TRACER
from raft_stereo_tpu.serve.server import (ServerBusy, ServerDraining,
                                          StereoServer)

#: default mixed-shape trace: three distinct /32 buckets
DEFAULT_SHAPES: Tuple[Tuple[int, int], ...] = ((48, 96), (64, 128), (96, 64))


@dataclasses.dataclass
class LoadTestConfig:
    """Trace shape/fault knobs (CLI: ``cli loadtest``)."""

    shapes: Sequence[Tuple[int, int]] = DEFAULT_SHAPES
    #: concurrent client threads (>= video_streams)
    clients: int = 8
    #: requests per client (a video client's frame count)
    requests_per_client: int = 4
    #: how many clients are video sessions (flow_init warm starts)
    video_streams: int = 1
    iters: int = 2
    #: global request ordinal to poison with a NaN pixel (None = off)
    poison_at: Optional[int] = None
    seed: int = 0
    submit_timeout_s: float = 30.0
    result_timeout_s: float = 600.0
    #: print LOADTEST progress lines to stdout
    progress: bool = True

    def trace(self) -> List[List[Dict]]:
        """Per-client request specs (shape, warm flags, poison marker)."""
        per_client = []
        for c in range(self.clients):
            video = c < self.video_streams
            # video sessions keep one shape; batch clients cycle so every
            # bucket sees traffic from several clients
            shape = self.shapes[c % len(self.shapes)]
            reqs = []
            for j in range(self.requests_per_client):
                ordinal = c * self.requests_per_client + j
                reqs.append({
                    "shape": shape, "ordinal": ordinal, "video": video,
                    "stream": f"video{c}" if video else None,
                    "poison": ordinal == self.poison_at,
                })
            per_client.append(reqs)
        return per_client


def synth_pair(rng: np.random.Generator, h: int, w: int,
               poison: bool = False) -> Tuple[np.ndarray, np.ndarray]:
    left = rng.integers(0, 255, (h, w, 3)).astype(np.float32)
    right = rng.integers(0, 255, (h, w, 3)).astype(np.float32)
    if poison:
        left[0, 0, 0] = np.nan
    return left, right


def run_baseline(predictor, lt: LoadTestConfig, telemetry=None) -> Dict:
    """Sequential ``predict()`` over the flattened trace — the throughput
    floor the served run must meet (clean inputs: the baseline's job is
    speed, the drill injects its faults only at the server)."""
    rng = np.random.default_rng(lt.seed)
    flat = [spec for client in lt.trace() for spec in client]
    t0 = time.perf_counter()
    for i, spec in enumerate(flat):
        left, right = synth_pair(rng, *spec["shape"])
        td = time.perf_counter()
        flow = predictor(left[None], right[None], lt.iters)
        dt = time.perf_counter() - td
        assert flow.shape[1:3] == spec["shape"]
        if telemetry is not None:
            telemetry.step(i, data_wait_s=0.0, dispatch_s=dt, fetch_s=0.0,
                           batch_size=1)
    wall = time.perf_counter() - t0
    pps = len(flat) / wall if wall > 0 else 0.0
    if telemetry is not None:
        telemetry.throughput(pps, steps=len(flat), phase="sequential")
    return {"requests": len(flat), "wall_s": round(wall, 3),
            "pairs_per_sec": round(pps, 4)}


def run_clients(server: StereoServer, lt: LoadTestConfig,
                telemetry=None) -> Dict:
    """Drive the trace through ``server`` with ``lt.clients`` threads;
    returns the accounting summary (ok/failed/rejected/lost per total)."""
    lock = threading.Lock()
    tally = {"submitted": 0, "ok": 0, "failed": 0, "rejected": 0,
             "lost": 0, "poisoned_failed": 0}
    done_count = [0]

    def progress(note: str) -> None:
        if lt.progress:
            with lock:
                line = (f"LOADTEST progress done={done_count[0]} "
                        f"ok={tally['ok']} failed={tally['failed']} "
                        f"rejected={tally['rejected']} {note}")
            print(line, flush=True)

    # client-side spans: each request opens a client_request span whose
    # context rides submit(parent=...), so the server's queue_wait/
    # collect_group/dispatch/retire spans join the client's trace — the
    # in-process twin of the HTTP front's traceparent header
    tracer = getattr(telemetry, "tracer", None) or NULL_TRACER

    def client(idx: int, specs: List[Dict]) -> None:
        rng = np.random.default_rng(lt.seed + 1000 + idx)
        for spec in specs:
            left, right = synth_pair(rng, *spec["shape"],
                                     poison=spec["poison"])
            with lock:
                tally["submitted"] += 1
            span = tracer.start("client_request", client=idx,
                                ordinal=spec["ordinal"]) \
                if tracer.enabled else None
            try:
                handle = server.submit(
                    left, right, iters=lt.iters, stream=spec["stream"],
                    warm_start=spec["video"],
                    timeout=lt.submit_timeout_s,
                    parent=span.context if span is not None else None)
            except ServerDraining:
                with lock:
                    tally["rejected"] += 1
                if span is not None:
                    span.set(status="rejected").end()
                progress(f"client{idx} draining")
                break  # admission closed: the rest of this client's trace
            except ServerBusy:
                with lock:
                    tally["rejected"] += 1
                if span is not None:
                    span.set(status="rejected").end()
                progress(f"client{idx} busy")
                continue
            try:
                result = handle.result(timeout=lt.result_timeout_s)
            except TimeoutError:
                with lock:
                    tally["lost"] += 1  # admitted but never retired
                if span is not None:
                    span.set(status="lost").end()
                progress(f"client{idx} LOST {handle.request_id}")
                continue
            if span is not None:
                span.set(status="ok" if result.ok else "error",
                         request_id=result.request_id).end()
            with lock:
                done_count[0] += 1
                if result.ok:
                    tally["ok"] += 1
                else:
                    tally["failed"] += 1
                    if spec["poison"]:
                        tally["poisoned_failed"] += 1
            if telemetry is not None and result.ok:
                # data_wait stays 0.0 so the seq-vs-serve phase columns
                # compare device time to device time; admission queueing
                # is its own field (and the slo rollup's p50/p99 covers
                # the end-to-end story)
                telemetry.step(
                    spec["ordinal"], data_wait_s=0.0,
                    dispatch_s=result.latency_s - result.queue_wait_s,
                    fetch_s=0.0, batch_size=1, bucket=result.bucket,
                    queue_wait_s=result.queue_wait_s,
                    served_batch=result.batch_size)
            progress(f"client{idx} {result.request_id} "
                     f"{'ok' if result.ok else 'FAILED'} b={result.batch_size}")

    threads = [threading.Thread(target=client, args=(i, specs),
                                name=f"load-client{i}", daemon=True)
               for i, specs in enumerate(lt.trace())]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    # the joins above are the happens-before edge, but take the tally lock
    # anyway: every write to the shared dict stays under the same guard
    with lock:
        served = tally["ok"] + tally["failed"]
        pps = served / wall if wall > 0 else 0.0
        tally.update(wall_s=round(wall, 3), pairs_per_sec=round(pps, 4),
                     slo=server.slo.snapshot())
    if telemetry is not None and served:
        telemetry.throughput(pps, steps=served, phase="served")
    return tally
