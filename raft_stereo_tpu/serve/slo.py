"""SLO telemetry for the serving loop (schema-v6 events).

Three records ride the existing event bus (obs/telemetry.py):

* ``request`` — one per retired request: terminal ``status`` (``ok`` /
  ``error`` / ``rejected``), queue-wait and end-to-end latency, the bucket
  and batch it rode, and — on failure — the captured error + traceback
  (per-request fault isolation's paper trail);
* ``queue`` — admission-side depth gauge (every ``gauge_every``-th
  submit): queue depth, in-flight dispatches, admitted/completed/rejected
  counters;
* ``slo`` — the serving headline every ``emit_every`` retirements: p50/p99
  end-to-end latency (ms) over a sliding sample window, current in-flight
  depth, and sustained pairs/s over the same window — the numbers a
  million-user deployment would alert on. Since schema v8 the rollup also
  carries a ``quality`` extra when the server runs with the convergence
  aux: rolling per-bucket final-residual percentiles (how settled the
  iteration actually is at retirement) — the gauge that makes quality
  drift after a hot reload visible instead of silent. Since schema v9 an
  ``output_range`` extra rides the same rollup when the numerics flavor
  is on: per-bucket rolling output-min p05 / output-max p95 of the served
  flow — the drift gauge that catches a model starting to rail its
  outputs before clients do. When requests ride the compiled early-exit
  flavors (``cli serve --iter_policy``), the ``request`` event carries
  ``iters_taken`` and the rollup an ``iters`` extra — per-bucket rolling
  iters_taken p50/p95/mean — so the deployment can see the policy's
  iteration savings (and, against the ``quality`` gauges, that they cost
  no quality) without replaying curves.

The tracker is lock-guarded (scheduler thread retires, client threads
admit) and, like every telemetry path in this repo, fail-open: with
``telemetry=None`` it still aggregates, it just emits nothing.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an ascending list (obs/compare.py's
    convention); 0.0 on empty input."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[idx])


class SLOTracker:
    def __init__(self, telemetry=None, *, window: int = 256,
                 emit_every: int = 16, gauge_every: int = 8):
        self.telemetry = telemetry
        self.window = max(1, int(window))
        self.emit_every = max(1, int(emit_every))
        self.gauge_every = max(1, int(gauge_every))
        self._lock = threading.Lock()
        # (retire wall-clock, latency seconds) per retired request
        self._samples: "deque" = deque(maxlen=self.window)
        # rolling final-residual window per bucket label (the serve
        # quality gauges; fed only when the converge aux is on)
        self._quality: Dict[str, "deque"] = {}
        # rolling (output_min, output_max) window per bucket label — the
        # output-range drift gauges; fed only when the numerics aux is on
        self._ranges: Dict[str, "deque"] = {}
        # rolling iters_taken window per bucket label — the adaptive
        # (early-exit) iteration gauges; fed only when requests ride the
        # compiled early-exit flavors (serve --iter_policy)
        self._iters: Dict[str, "deque"] = {}
        self.admitted = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self._retired_since_emit = 0

    # --- admission side ------------------------------------------------------

    def admit(self, queue_depth: int, in_flight: int) -> None:
        with self._lock:
            self.admitted += 1
            emit = self.admitted % self.gauge_every == 1 \
                or self.gauge_every == 1
            counters = self._counters()
        if emit and self.telemetry is not None:
            self.telemetry.emit("queue", depth=int(queue_depth),
                                in_flight=int(in_flight), **counters)

    def reject(self) -> None:
        with self._lock:
            self.rejected += 1

    # --- retirement side -----------------------------------------------------

    def retire(self, request_id: str, status: str, latency_s: float,
               queue_wait_s: float, bucket: str, batch_size: int,
               in_flight: int, stream: Optional[str] = None,
               error: Optional[str] = None,
               traceback_tail: Optional[str] = None,
               final_residual: Optional[float] = None,
               iters_taken: Optional[int] = None,
               output_min: Optional[float] = None,
               output_max: Optional[float] = None) -> None:
        """Record one terminal request outcome; emits the ``request`` event
        and, on cadence, the ``slo`` rollup. ``final_residual`` (mean
        |Δdisparity| of the last refinement iteration, from the converge
        aux) feeds the per-bucket rolling quality gauges;
        ``iters_taken`` (refinement iterations the compiled early-exit
        flavor actually applied) feeds the per-bucket adaptive iteration
        gauges — together they close the policy loop: iterations saved AND
        quality held; ``output_min``/``output_max`` (host range of the
        request's unpadded flow, from the numerics flavor) feed the
        per-bucket output-range drift gauges."""
        now = time.monotonic()
        with self._lock:
            if status == "ok":
                self.completed += 1
            else:
                self.failed += 1
            self._samples.append((now, float(latency_s)))
            if final_residual is not None and status == "ok":
                dq = self._quality.get(bucket)
                if dq is None:
                    dq = self._quality[bucket] = deque(maxlen=self.window)
                dq.append(float(final_residual))
            if iters_taken is not None and status == "ok":
                iq = self._iters.get(bucket)
                if iq is None:
                    iq = self._iters[bucket] = deque(maxlen=self.window)
                iq.append(int(iters_taken))
            if (output_min is not None and output_max is not None
                    and status == "ok"):
                rq = self._ranges.get(bucket)
                if rq is None:
                    rq = self._ranges[bucket] = deque(maxlen=self.window)
                rq.append((float(output_min), float(output_max)))
            self._retired_since_emit += 1
            do_slo = self._retired_since_emit >= self.emit_every
            if do_slo:
                self._retired_since_emit = 0
                slo = self._snapshot_locked(in_flight)
        if self.telemetry is not None:
            payload: Dict[str, Any] = dict(
                id=request_id, status=status,
                latency_s=round(float(latency_s), 6),
                queue_wait_s=round(float(queue_wait_s), 6),
                bucket=bucket, batch_size=int(batch_size))
            if stream is not None:
                payload["stream"] = stream
            if error is not None:
                payload["error"] = error
            if traceback_tail is not None:
                payload["traceback"] = traceback_tail[-2000:]
            if final_residual is not None:
                payload["final_residual"] = round(float(final_residual), 6)
            if iters_taken is not None:
                payload["iters_taken"] = int(iters_taken)
            if output_min is not None:
                payload["output_min"] = round(float(output_min), 4)
            if output_max is not None:
                payload["output_max"] = round(float(output_max), 4)
            self.telemetry.emit("request", **payload)
            if do_slo:
                self.telemetry.emit("slo", **slo)

    # --- rollups -------------------------------------------------------------

    def _counters(self) -> Dict[str, int]:
        return {"admitted": self.admitted, "completed": self.completed,
                "failed": self.failed, "rejected": self.rejected}

    def _snapshot_locked(self, in_flight: int) -> Dict[str, Any]:
        lats = sorted(l for _, l in self._samples)
        span = (self._samples[-1][0] - self._samples[0][0]
                if len(self._samples) > 1 else 0.0)
        pairs = len(self._samples)
        pps = pairs / span if span > 0 else 0.0
        snap = {
            "p50_ms": round(percentile(lats, 50) * 1e3, 3),
            "p99_ms": round(percentile(lats, 99) * 1e3, 3),
            "pairs_per_sec": round(pps, 4),
            "in_flight": int(in_flight),
            "window_requests": pairs,
            **self._counters(),
        }
        if self._quality:
            snap["quality"] = {
                bucket: {
                    "final_residual_p50": round(
                        percentile(sorted(dq), 50), 6),
                    "final_residual_p95": round(
                        percentile(sorted(dq), 95), 6),
                    "n": len(dq),
                }
                for bucket, dq in sorted(self._quality.items()) if dq
            }
        if self._iters:
            snap["iters"] = {
                bucket: {
                    "iters_taken_p50": round(
                        percentile(sorted(iq), 50), 2),
                    "iters_taken_p95": round(
                        percentile(sorted(iq), 95), 2),
                    "iters_taken_mean": round(sum(iq) / len(iq), 3),
                    "n": len(iq),
                }
                for bucket, iq in sorted(self._iters.items()) if iq
            }
        if self._ranges:
            snap["output_range"] = {
                bucket: {
                    "output_min_p05": round(percentile(
                        sorted(lo for lo, _ in rq), 5), 4),
                    "output_max_p95": round(percentile(
                        sorted(hi for _, hi in rq), 95), 4),
                    "n": len(rq),
                }
                for bucket, rq in sorted(self._ranges.items()) if rq
            }
        return snap

    def snapshot(self, in_flight: int = 0) -> Dict[str, Any]:
        """Current rollup (the ``/slo`` HTTP endpoint + loadtest report)."""
        with self._lock:
            return self._snapshot_locked(in_flight)

    def flush(self, in_flight: int = 0) -> None:
        """Emit a final ``slo`` rollup regardless of cadence — called at
        drain so short traces (< ``emit_every`` retirements) still leave
        the headline record in events.jsonl."""
        with self._lock:
            if not self._samples:
                return
            self._retired_since_emit = 0
            slo = self._snapshot_locked(in_flight)
        if self.telemetry is not None:
            self.telemetry.emit("slo", **slo)
