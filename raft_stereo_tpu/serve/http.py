"""Stdlib-only HTTP front for :class:`StereoServer` (``cli serve``).

No web framework (the container policy: nothing beyond the baked-in
toolchain), so this is ``http.server.ThreadingHTTPServer`` — one thread
per connection, each blocking on its request's :class:`ResultHandle`
while the scheduler batches across connections. Endpoints:

* ``POST /v1/predict`` — body is an ``.npz`` with ``left``/``right``
  HWC arrays; optional query args ``iters``, ``stream``, ``warm=1``. An
  optional ``traceparent`` request header (obs/fleet.py's
  ``00-<trace_id>-<span_id>-01`` shape) joins the server-side
  queue_wait/collect_group/dispatch/retire spans under the client's
  span — one trace across the process boundary — and is echoed back.
  200 → ``.npz`` with ``flow`` (H, W, 1) + request metadata headers;
  422 → the request retired as an error (poisoned input, etc.);
  503 → draining or queue-full backpressure. Per-request isolation means
  one client's 422 never affects another's 200.
* ``GET /healthz`` — scheduler liveness + counters (JSON); 503 once
  draining, so load balancers stop routing here during shutdown.
* ``GET /slo`` — the SLOTracker rollup (p50/p99/pairs_per_sec) as JSON.
* ``GET /metrics`` — the same rollup in Prometheus text format
  (``raft_serve_*`` gauges/counters) so external scrapers don't have to
  poll and re-shape the JSON; disable with ``make_http_server(...,
  metrics=False)`` / ``cli serve --no_metrics``.

SIGTERM/SIGINT → graceful drain via training/resilience.SignalGuard:
stop admitting, finish every admitted request, exit 0. SIGHUP → hot
model reload from the newest manifest-verified checkpoint (PR 7's
verify-before-restore, re-targeted at a live server).
"""

from __future__ import annotations

import io
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from raft_stereo_tpu.obs.fleet import parse_traceparent
from raft_stereo_tpu.serve.server import (ServerBusy, ServerDraining,
                                          StereoServer)

logger = logging.getLogger(__name__)


def _json_bytes(payload) -> bytes:
    return (json.dumps(payload, sort_keys=True) + "\n").encode()


# stats() key -> (prometheus metric name, type). Counters are monotone
# process-lifetime totals (SLOTracker counters); everything else is a
# point-in-time gauge.
_PROM_METRICS = (
    ("p50_ms", "raft_serve_latency_p50_ms", "gauge",
     "Rolling-window p50 end-to-end latency (ms)"),
    ("p99_ms", "raft_serve_latency_p99_ms", "gauge",
     "Rolling-window p99 end-to-end latency (ms)"),
    ("pairs_per_sec", "raft_serve_pairs_per_sec", "gauge",
     "Sustained throughput over the SLO sample window"),
    ("in_flight", "raft_serve_in_flight", "gauge",
     "Device dispatches currently in flight"),
    ("queue_depth", "raft_serve_queue_depth", "gauge",
     "Requests admitted but not yet collected into a batch"),
    ("window_requests", "raft_serve_window_requests", "gauge",
     "Retirements inside the current SLO sample window"),
    ("draining", "raft_serve_draining", "gauge",
     "1 once admission closed for shutdown"),
    ("executables", "raft_serve_executables", "gauge",
     "Compiled bucket programs resident in the cache"),
    ("sessions", "raft_serve_sessions", "gauge",
     "Live warm-start video sessions"),
    ("admitted", "raft_serve_requests_admitted_total", "counter",
     "Requests admitted past the bounded queue"),
    ("completed", "raft_serve_requests_completed_total", "counter",
     "Requests retired ok"),
    ("failed", "raft_serve_requests_failed_total", "counter",
     "Requests retired as errors (poisoned output / dispatch failure)"),
    ("rejected", "raft_serve_requests_rejected_total", "counter",
     "Submits shed by backpressure or drain"),
)


# per-bucket quality gauges (stats()["quality"], present when the server
# runs with the convergence aux): rendered with a bucket label — the one
# labeled metric family, so a scrape can alert on quality drift per shape
# bucket (e.g. after a hot reload) without parsing the JSON rollup.
_PROM_QUALITY = (
    ("final_residual_p50", "raft_serve_final_residual_p50",
     "Rolling p50 of the last-iteration mean |delta disparity| (px)"),
    ("final_residual_p95", "raft_serve_final_residual_p95",
     "Rolling p95 of the last-iteration mean |delta disparity| (px)"),
    ("n", "raft_serve_quality_window_requests",
     "Requests inside the rolling quality window"),
)


# per-bucket adaptive iteration gauges (stats()["iters"], present when
# requests ride the compiled early-exit flavors, ``cli serve
# --iter_policy``): rolling iters_taken percentiles per shape bucket —
# the scrapeable evidence that the recorded policy is actually saving
# iterations in production (and, against raft_serve_final_residual_*,
# that quality holds).
_PROM_ITERS = (
    ("iters_taken_p50", "raft_serve_iters_taken_p50",
     "Rolling p50 of refinement iterations applied per request"),
    ("iters_taken_p95", "raft_serve_iters_taken_p95",
     "Rolling p95 of refinement iterations applied per request"),
    ("iters_taken_mean", "raft_serve_iters_taken_mean",
     "Rolling mean of refinement iterations applied per request"),
    ("n", "raft_serve_iters_window_requests",
     "Requests inside the rolling iters_taken window"),
)


# per-bucket output-range drift gauges (stats()["output_range"], present
# when the server runs the numerics flavor, ``cli serve --numerics``):
# rolling extremes of the served flow per shape bucket — the scrapeable
# signal for a model starting to rail or collapse its outputs.
_PROM_OUTPUT_RANGE = (
    ("output_min_p05", "raft_serve_output_min_p05",
     "Rolling p05 of per-request output flow minimum (px)"),
    ("output_max_p95", "raft_serve_output_max_p95",
     "Rolling p95 of per-request output flow maximum (px)"),
    ("n", "raft_serve_output_range_window_requests",
     "Requests inside the rolling output-range window"),
)


def prometheus_metrics(stats: dict, host_id: Optional[str] = None) -> str:
    """Render a ``stats()`` dict as Prometheus text exposition format.

    ``host_id`` (``cli serve`` passes the telemetry's) adds a ``host``
    label to every sample — alongside the existing ``bucket`` label on
    the per-bucket families — so a future multi-replica scrape
    aggregates cleanly; None keeps the unlabeled single-process shape.
    """
    hl = f'host="{host_id}"' if host_id else ""
    plain = "{" + hl + "}" if hl else ""
    lines = []
    for key, name, kind, help_text in _PROM_METRICS:
        if key not in stats:
            continue
        value = stats[key]
        if isinstance(value, bool):
            value = int(value)
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{plain} {float(value):g}")
    for stats_key, families in (("quality", _PROM_QUALITY),
                                ("iters", _PROM_ITERS),
                                ("output_range", _PROM_OUTPUT_RANGE)):
        per_bucket = stats.get(stats_key) or {}
        if not per_bucket:
            continue
        for key, name, help_text in families:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} gauge")
            for bucket in sorted(per_bucket):
                value = per_bucket[bucket].get(key)
                if value is None:
                    continue
                labels = f'bucket="{bucket}"' + (f",{hl}" if hl else "")
                lines.append(f"{name}{{{labels}}} {float(value):g}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "raft-stereo-serve/1.0"
    #: set by make_http_server
    stereo: StereoServer = None  # type: ignore[assignment]
    #: /metrics exposition toggle (make_http_server(metrics=...))
    metrics: bool = True
    #: host label on /metrics samples (make_http_server(host_id=...))
    host_id: Optional[str] = None

    def log_message(self, fmt, *args):  # route to logging, not stderr
        logger.debug("http: " + fmt, *args)

    def _reply(self, code: int, body: bytes, ctype: str = "application/json",
               headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        path = urlparse(self.path).path
        if path == "/healthz":
            stats = self.stereo.stats()
            code = 503 if stats["draining"] or stats["stopped"] else 200
            self._reply(code, _json_bytes(stats))
        elif path == "/slo":
            self._reply(200, _json_bytes(self.stereo.stats()))
        elif path == "/metrics" and self.metrics:
            self._reply(200, prometheus_metrics(
                self.stereo.stats(), host_id=self.host_id).encode(),
                ctype="text/plain; version=0.0.4; charset=utf-8")
        else:
            self._reply(404, _json_bytes({"error": "not found"}))

    def do_POST(self):
        url = urlparse(self.path)
        if url.path != "/v1/predict":
            self._reply(404, _json_bytes({"error": "not found"}))
            return
        try:
            n = int(self.headers.get("Content-Length", "0"))
            with np.load(io.BytesIO(self.rfile.read(n))) as npz:
                left, right = npz["left"], npz["right"]
        except Exception as exc:
            self._reply(400, _json_bytes(
                {"error": f"bad request body: {exc}"}))
            return
        q = parse_qs(url.query)
        # cross-process trace join: a traceparent header parents the
        # server-side span tree under the client's span (malformed
        # headers degrade to "no remote parent", never an error)
        traceparent = self.headers.get("traceparent")
        parent = parse_traceparent(traceparent)
        try:
            handle = self.stereo.submit(
                left, right,
                iters=int(q["iters"][0]) if "iters" in q else None,
                stream=q["stream"][0] if "stream" in q else None,
                warm_start=q.get("warm", ["0"])[0] == "1",
                timeout=5.0, parent=parent)
        except ServerDraining:
            self._reply(503, _json_bytes({"error": "draining"}),
                        headers={"Retry-After": "never"})
            return
        except ServerBusy:
            self._reply(503, _json_bytes({"error": "queue full"}),
                        headers={"Retry-After": "1"})
            return
        except ValueError as exc:
            self._reply(400, _json_bytes({"error": str(exc)}))
            return
        result = handle.result()
        meta = {"X-Request-Id": result.request_id,
                "X-Latency-Ms": round(result.latency_s * 1e3, 3),
                "X-Batch-Size": result.batch_size,
                "X-Bucket": result.bucket}
        if parent is not None:
            meta["traceparent"] = traceparent
        if not result.ok:
            self._reply(422, _json_bytes(
                {"error": result.error, "kind": result.error_kind,
                 "request_id": result.request_id}), headers=meta)
            return
        buf = io.BytesIO()
        np.savez_compressed(buf, flow=result.flow)
        self._reply(200, buf.getvalue(),
                    ctype="application/octet-stream", headers=meta)


def make_http_server(stereo: StereoServer, host: str = "127.0.0.1",
                     port: int = 8600, metrics: bool = True,
                     host_id: Optional[str] = None) -> ThreadingHTTPServer:
    """Bind (but do not serve) the HTTP front; caller owns serve/shutdown."""
    handler = type("BoundHandler", (_Handler,),
                   {"stereo": stereo, "metrics": metrics,
                    "host_id": host_id})
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_threads = True
    return httpd


def serve_forever(stereo: StereoServer, httpd: ThreadingHTTPServer,
                  should_stop, poll_s: float = 0.25,
                  maybe_reload=None, drain_timeout_s: float = 300.0) -> int:
    """Run the HTTP loop until ``should_stop()`` (typically a
    SignalGuard's ``requested``), then drain gracefully.

    ``maybe_reload`` (optional) is polled each tick — the SIGHUP hot-reload
    hook; its exceptions are logged, never fatal (a bad reload must not
    take down a serving process). Returns the exit code (0 = clean drain).
    """
    import time
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="serve-http")
    t.start()
    logger.info("serve: listening on http://%s:%d", *httpd.server_address)
    clean = True
    try:
        while not should_stop():
            time.sleep(poll_s)
            if maybe_reload is not None:
                try:
                    maybe_reload()
                except Exception:
                    logger.exception("serve: hot reload failed; continuing "
                                     "with current weights")
    finally:
        logger.info("serve: stop requested — draining")
        httpd.shutdown()
        stereo.request_drain()
        clean = stereo.join(timeout=drain_timeout_s)
        logger.info("serve: drain %s", "complete" if clean else "TIMED OUT")
    return 0 if clean else 1
