"""The continuous-batching stereo server.

Architecture (one scheduler thread, the production shape of
eval/stream.py's ``_run_streaming``):

::

    clients --submit()--> BoundedQueue --scheduler--> ExecutableCache
                                          |  (greedy same-bucket groups,
                                          |   bounded in-flight window)
    clients <--ResultHandle-- retire <----+

* **Admission** — ``submit()`` copies nothing onto the device; it enqueues
  a request into a bounded queue (backpressure instead of backlog) and
  returns a :class:`ResultHandle` future. After ``request_drain()`` the
  queue is closed: new submits raise :class:`ServerDraining`, everything
  already admitted still completes — that is the SIGTERM contract
  (PR 7's SignalGuard semantics, re-targeted from "save and exit" to
  "stop admitting, finish in-flight, exit 0").
* **Batching** — the scheduler pulls the queue in arrival order and packs
  consecutive requests with the same ``(bucket H×W, iters, warm)`` key
  into one dispatch (serve/batching.py — the same greedy policy the
  streaming evaluator uses), optionally lingering ``linger_s`` for
  stragglers while the batch is short. Requests with different RAW shapes
  batch together whenever they pad to the same bucket; each carries its
  own padder for exact unpadding.
* **Fault isolation** — the compiled program returns a per-sample
  finiteness flag computed ON DEVICE next to the outputs. A poisoned
  request (NaN/Inf anywhere in its output) retires as an error result;
  its batchmates retire normally — one bad client cannot kill a batch,
  let alone the scheduler. A dispatch-level exception fails exactly the
  requests of that batch (captured traceback on each), and the scheduler
  keeps serving.
* **Warm starts** — ``stream_id + warm_start=True`` requests ride the
  warm program flavor: the server keeps each video session's last low-res
  flow and feeds it back as ``flow_init`` (zeros on the first frame).
  Sessions are keyed per stream and reset whenever the stream changes
  shape. Frames of one session must be submitted in order (await each
  result before the next submit — the loadtest's video client does).
* **Hot reload** — ``reload(variables)`` swaps the model weights between
  batches (ExecutableCache.reload): queued and in-flight work is never
  dropped; requests dispatched after the swap use the new weights.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
import traceback as tb_module
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.inference import PAD_DIVIS, bucket_size
from raft_stereo_tpu.obs import numerics as numerics_obs
from raft_stereo_tpu.obs.converge import emit as converge_emit
from raft_stereo_tpu.obs.trace import NULL_TRACER
from raft_stereo_tpu.ops.geometry import InputPadder
from raft_stereo_tpu.serve.batching import (BoundedQueue, QueueClosed,
                                            collect_group)
from raft_stereo_tpu.serve.cache import BucketKey, ExecutableCache
from raft_stereo_tpu.serve.slo import SLOTracker

logger = logging.getLogger(__name__)


class ServerDraining(Exception):
    """submit() after request_drain(): admission is closed for shutdown."""


class ServerBusy(Exception):
    """submit() timed out on a full queue: backpressure, try again."""


@dataclasses.dataclass
class ServeConfig:
    """Scheduler/queue knobs (CLI: ``cli serve`` / ``cli loadtest``)."""

    #: max requests stacked through one dispatch
    max_batch: int = 4
    #: bounded request-queue depth (admission backpressure past this)
    queue_depth: int = 64
    #: max dispatches in flight (the eval/stream window)
    window: int = 2
    #: refinement iterations when a request does not specify its own
    default_iters: int = 32
    #: pad buckets up to multiples of this (0 = exact /32 padding);
    #: inference.bucket_size semantics
    bucket: int = 0
    #: wait up to this long for same-bucket stragglers while a batch is
    #: below max_batch (0 = dispatch immediately)
    linger_s: float = 0.0
    #: AOT-compile bucket programs (False: jit on first call)
    aot: bool = True
    #: emit one `slo` rollup every N retired requests
    slo_every: int = 16
    #: latency sliding-window size for p50/p99 / sustained pairs/s
    slo_window: int = 256
    #: serve the converge program flavor: per-request convergence curves
    #: (`converge` events) + rolling per-bucket final-residual quality
    #: gauges in the slo rollups / Prometheus metrics. False
    #: (--no_converge) keeps the exact schema-v7 program and event stream.
    converge: bool = True
    #: serve the numerics program flavor (obs/numerics.py): per-dispatch
    #: activation-tap range statistics (`numerics` events) + per-bucket
    #: output-range drift gauges in the slo rollups / Prometheus metrics.
    #: OFF by default (opt in with --numerics): serving pays for
    #: observability only when asked, and the default program stays
    #: byte-identical to the numerics-free one.
    numerics: bool = False
    #: iteration-policy JSON path (or pre-loaded doc) from `cli converge
    #: --emit-policy`: buckets the policy covers are served by the
    #: compiled early-exit flavors — the bucket's recorded (tau, budget,
    #: min_iters) replace default_iters, per-request ``iters_taken`` rides
    #: the request/slo telemetry. Uncovered buckets keep the fixed
    #: programs.
    iter_policy: Any = None
    #: early-exit execution mode override; None = adaptive iff iter_policy
    #: is set, False ignores a loaded policy (the pre-adaptive bitwise
    #: pin), True without a policy is an error.
    adaptive: Optional[bool] = None
    #: serve buckets whose padded width reaches this via the memoryless
    #: 'fused' correlation flavor (BucketKey.impl — the per-bucket program
    #: swap for widths whose B*H*W^2 reg volume would not fit). 0 = off:
    #: every bucket keeps the server config's corr_implementation.
    fused_width: int = 0


@dataclasses.dataclass
class ServeResult:
    """Terminal outcome of one request (what :meth:`ResultHandle.result`
    returns — errors are DATA here, not exceptions: the per-request
    isolation contract)."""

    request_id: str
    ok: bool
    flow: Optional[np.ndarray] = None    # unpadded (H, W, 1) flow-x
    error: Optional[str] = None
    error_kind: Optional[str] = None     # "nonfinite_output" | "dispatch"
    traceback: Optional[str] = None
    stream: Optional[str] = None
    latency_s: float = 0.0
    queue_wait_s: float = 0.0
    batch_size: int = 0
    bucket: str = ""
    #: last-iteration mean |Δdisparity| (converge aux; None when off)
    final_residual: Optional[float] = None
    #: refinement iterations actually applied to this request by the
    #: compiled early-exit flavor (None on fixed-trip programs)
    iters_taken: Optional[int] = None
    #: host-side min/max of the unpadded output flow (numerics flavor's
    #: output-range drift gauges; None on errors or with numerics off)
    output_min: Optional[float] = None
    output_max: Optional[float] = None

    @property
    def disparity(self) -> Optional[np.ndarray]:
        """Positive disparity (H, W) — the library-API convention."""
        return None if self.flow is None else -self.flow[..., 0]


class ResultHandle:
    """Future for one admitted request; ``result()`` blocks until retired."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._done = threading.Event()
        self._result: Optional[ServeResult] = None

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._done.wait(timeout=timeout):
            raise TimeoutError(
                f"request {self.request_id} not retired within {timeout}s")
        assert self._result is not None
        return self._result

    def _set(self, result: ServeResult) -> None:
        self._result = result
        self._done.set()


@dataclasses.dataclass
class _Request:
    id: str
    image1: np.ndarray
    image2: np.ndarray
    iters: int
    warm: bool
    stream: Optional[str]
    t_submit: float
    handle: ResultHandle
    # remote trace context (obs/fleet.py traceparent propagation): the
    # client-side span this request's span tree parents under
    parent: Optional[Any] = None
    t_dispatch: float = 0.0
    # lifecycle stamps for the request's span tree (queue_wait ends when
    # the scheduler pulls the request; dispatch ends when the device call
    # returns its handles)
    t_collect: float = 0.0
    t_disp_end: float = 0.0


class StereoServer:
    """Continuous-batching inference server over one model + one device
    program cache. Thread-safe ``submit``; one scheduler thread."""

    def __init__(self, cfg: RAFTStereoConfig, variables: Dict,
                 serve: Optional[ServeConfig] = None, *, telemetry=None,
                 autostart: bool = True):
        self.cfg = cfg
        self.serve = serve or ServeConfig()
        self.telemetry = telemetry
        self.cache = ExecutableCache(cfg, variables, telemetry=telemetry,
                                     aot=self.serve.aot,
                                     converge=self.serve.converge,
                                     numerics=self.serve.numerics,
                                     iter_policy=self.serve.iter_policy,
                                     adaptive=self.serve.adaptive)
        self.slo = SLOTracker(telemetry, window=self.serve.slo_window,
                              emit_every=self.serve.slo_every)
        self._queue: BoundedQueue = BoundedQueue(self.serve.queue_depth)
        # single-owner state: only the scheduler thread mutates these
        # (graftlint engine 4 baseline names the invariant); other threads
        # may read len() for gauges but never write
        self._in_flight: "deque" = deque()
        self._sessions: Dict[str, Tuple[Tuple[int, ...], np.ndarray]] = {}
        self._pending_vars: Optional[Dict] = None
        self._reload_note: Optional[str] = None
        self._vars_lock = threading.Lock()
        self._ids = itertools.count()
        self._draining = False
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-scheduler")
        if autostart:
            self.start()

    # --- lifecycle -----------------------------------------------------------

    def start(self) -> "StereoServer":
        if not self._thread.is_alive() and not self._stopped.is_set():
            self._thread.start()
        return self

    @property
    def draining(self) -> bool:
        return self._draining

    def request_drain(self) -> None:
        """Graceful shutdown, phase 1: close admission. Already-admitted
        requests (queued or in flight) all still complete."""
        if not self._draining:
            self._draining = True
            logger.info("serve: drain requested — admission closed, "
                        "finishing %d queued + %d in-flight dispatches",
                        len(self._queue), len(self._in_flight))
        self._queue.close()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the scheduler to finish draining; True when stopped."""
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()

    def close(self, timeout: Optional[float] = None) -> bool:
        self.request_drain()
        if not self._thread.is_alive() and not self._stopped.is_set():
            # never started: drain the queue inline so admitted work is
            # still honored (the autostart=False test path)
            self._run()
            return True
        return self.join(timeout)

    # --- admission -----------------------------------------------------------

    def submit(self, left: np.ndarray, right: np.ndarray, *,
               iters: Optional[int] = None, stream: Optional[str] = None,
               warm_start: bool = False,
               timeout: Optional[float] = None,
               parent=None) -> ResultHandle:
        """Admit one HWC stereo pair; returns the request's future.

        ``parent`` is an optional span context (obs/trace.py
        ``SpanContext``, possibly parsed from a traceparent header) the
        request's span tree joins under — the cross-process trace story.

        Raises :class:`ServerDraining` once a drain started and
        :class:`ServerBusy` when the bounded queue stays full past
        ``timeout`` — both BEFORE admission: a raised submit is a rejected
        request, never a lost one."""
        if self._draining:
            self.slo.reject()
            raise ServerDraining("server is draining; submit rejected")
        left = np.asarray(left)
        right = np.asarray(right)
        if left.ndim != 3 or right.ndim != 3 or left.shape != right.shape:
            raise ValueError(
                f"expected matching HWC pairs, got {left.shape} vs "
                f"{right.shape}")
        req = _Request(
            id=f"r{next(self._ids):06d}",
            image1=left, image2=right,
            iters=int(iters) if iters is not None
            else self.serve.default_iters,
            warm=bool(warm_start and stream is not None),
            stream=stream, t_submit=time.perf_counter(),
            handle=ResultHandle(f"r?"), parent=parent)
        req.handle.request_id = req.id
        try:
            admitted = self._queue.put(req, timeout=timeout)
        except QueueClosed:
            self.slo.reject()
            raise ServerDraining("server is draining; submit rejected")
        if not admitted:
            self.slo.reject()
            raise ServerBusy(
                f"request queue full ({self.serve.queue_depth}) for "
                f"{timeout}s")
        self.slo.admit(queue_depth=len(self._queue),
                       in_flight=len(self._in_flight))
        return req.handle

    # --- hot reload ----------------------------------------------------------

    def reload(self, variables: Dict, note: Optional[str] = None) -> None:
        """Swap model weights at the next batch boundary. Queued and
        in-flight requests are untouched; later dispatches use the new
        weights. Raises (synchronously) on a pytree-structure mismatch."""
        # validate the structure NOW so a bad reload fails the caller, not
        # the scheduler thread mid-traffic
        probe_hash = self.cache._hash(variables)
        if probe_hash != self.cache._tree_hash:
            raise ValueError(
                "reload variables do not match the served pytree structure")
        with self._vars_lock:
            self._pending_vars = variables
            self._reload_note = note

    def _apply_pending_reload(self) -> None:
        with self._vars_lock:
            variables, note = self._pending_vars, self._reload_note
            self._pending_vars = None
            self._reload_note = None
        if variables is None:
            return
        self.cache.reload(variables)
        logger.info("serve: hot-reloaded model variables%s",
                    f" ({note})" if note else "")
        if self.telemetry is not None:
            self.telemetry.emit("queue", depth=len(self._queue),
                                in_flight=len(self._in_flight),
                                reload=True, note=note,
                                **self.slo._counters())

    # --- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        snap = self.slo.snapshot(in_flight=len(self._in_flight))
        snap.update(queue_depth=len(self._queue),
                    draining=self._draining,
                    stopped=self._stopped.is_set(),
                    executables=len(self.cache),
                    sessions=len(self._sessions))
        return snap

    def warmup(self, shapes, batch_sizes=(1,), iters=None,
               warm: bool = False) -> int:
        """AOT-precompile bucket programs for raw ``(H, W)`` shapes before
        admitting traffic; returns the number compiled."""
        keys = []
        for h, w in shapes:
            bh, bw = self._bucket_shape(h, w)
            it, policy = self._bucket_plan(
                bh, bw, int(iters or self.serve.default_iters))
            for b in batch_sizes:
                keys.append(BucketKey(bh, bw, int(b), it, warm, policy,
                                      self._bucket_impl(bw)))
        return self.cache.warmup(keys)

    # --- scheduler internals -------------------------------------------------

    def _bucket_shape(self, h: int, w: int) -> Tuple[int, int]:
        return (bucket_size(h, PAD_DIVIS, self.serve.bucket),
                bucket_size(w, PAD_DIVIS, self.serve.bucket))

    def _bucket_plan(self, bh: int, bw: int, iters: int) -> Tuple[int, str]:
        """(effective iters, policy digest) for a padded bucket: where the
        loaded policy covers the bucket, its recorded budget caps the trip
        count and the group rides the compiled early-exit flavor."""
        lookup = getattr(self.cache, "bucket_entry", None)
        entry = lookup(bh, bw) if lookup is not None else None
        if entry is None:
            return iters, ""
        return min(int(iters), int(entry["budget"])), self.cache.policy_digest

    def _bucket_impl(self, bw: int) -> str:
        """Correlation-impl flavor for a padded bucket width: '' keeps the
        server config's implementation; wide buckets past ``fused_width``
        ride the memoryless 'fused' program (zero volume residency)."""
        fw = int(getattr(self.serve, "fused_width", 0) or 0)
        if fw and bw >= fw and self.cfg.corr_implementation != "fused":
            return "fused"
        return ""

    def _group_key(self, req: _Request) -> Tuple:
        bh, bw = self._bucket_shape(*req.image1.shape[:2])
        iters, policy = self._bucket_plan(bh, bw, req.iters)
        return (bh, bw, iters, req.warm, policy, self._bucket_impl(bw))

    def _collect(self, first: _Request) -> List[_Request]:
        first.t_collect = first.t_collect or time.perf_counter()
        group = collect_group(
            first, self._queue.get_nowait, self._queue.push_front,
            self.serve.max_batch, key=self._group_key)
        tc = time.perf_counter()
        for req in group:
            req.t_collect = req.t_collect or tc
        deadline = time.perf_counter() + self.serve.linger_s
        k0 = self._group_key(first)
        while (len(group) < self.serve.max_batch
               and self.serve.linger_s > 0):
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            item = self._queue.get(timeout=remaining)
            if item is None:
                break
            if self._group_key(item) != k0:
                self._queue.push_front(item)
                break
            item.t_collect = item.t_collect or time.perf_counter()
            group.append(item)
        return group

    def _session_init(self, req: _Request, bh: int, bw: int) -> np.ndarray:
        """The request's low-res warm-start field: the session's last
        output, or zeros on a fresh/shape-changed session."""
        factor = 2 ** self.cfg.n_downsample
        shape = (bh // factor, bw // factor, 2)
        state = self._sessions.get(req.stream or "")
        if state is not None and state[0] == shape:
            return state[1]
        return np.zeros(shape, np.float32)

    def _dispatch(self, group: List[_Request]) -> None:
        bh, bw, iters, warm, policy, impl = self._group_key(group[0])
        key = BucketKey(bh, bw, len(group), iters, warm, policy, impl)
        padders = []
        im1, im2, inits = [], [], []
        t0 = time.perf_counter()
        for req in group:
            req.t_dispatch = t0
            padder = InputPadder((1,) + req.image1.shape,
                                 divis_by=PAD_DIVIS, target=(bh, bw))
            p1, p2 = padder.pad(
                req.image1[None].astype(np.float32),
                req.image2[None].astype(np.float32))
            padders.append(padder)
            im1.append(np.asarray(p1)[0])
            im2.append(np.asarray(p2)[0])
            if warm:
                inits.append(self._session_init(req, bh, bw))
        try:
            outputs = self.cache(
                key, np.stack(im1), np.stack(im2),
                np.stack(inits) if warm else None)
        except Exception as exc:  # compile/shape failure: fail this batch
            self._fail_group(group, key, exc, kind="dispatch")
            return
        t1 = time.perf_counter()
        for req in group:
            req.t_disp_end = t1
        self._in_flight.append((group, padders, key, outputs))

    def _retire(self) -> None:
        group, padders, key, outputs = self._in_flight.popleft()
        try:
            flow_lr, flow_up, finite, *aux = outputs
            # the host fetch — the device-completion sync point
            flow_lr = np.asarray(flow_lr)
            flow_up = np.asarray(flow_up)
            finite = np.asarray(finite)
            # aux slots, in program-output order: converge's (iters, B)
            # per-sample curves first, the adaptive flavor's (B,)
            # iters_taken after them, the numerics tap-stats dict LAST
            # (adaptive and numerics never combine — cache ctor guard)
            deltas = None
            taps = None
            taken = None
            if aux and self.serve.numerics:
                taps = {k: np.asarray(v) for k, v in aux.pop().items()}
            if aux and key.policy:
                taken = np.asarray(aux.pop())
            if aux and getattr(self.cache, "converge", self.serve.converge):
                deltas = np.asarray(aux[0])
        except Exception as exc:  # device-side execution error
            self._fail_group(group, key, exc, kind="dispatch")
            return
        now = time.perf_counter()
        if taps is not None:
            # one numerics record per DISPATCH (the stats are batch-wide)
            numerics_obs.emit(self.telemetry, numerics_obs.taps_payload(
                f"serve:{key.label()}", taps,
                bucket=f"{key.height}x{key.width}", id=group[0].id))
        for j, req in enumerate(group):
            if not bool(finite[j]):
                # per-request isolation: THIS request failed; batchmates
                # retire normally below. Poisoned sessions also reset so
                # one NaN frame doesn't poison the warm-start chain.
                if req.stream is not None:
                    self._sessions.pop(req.stream, None)
                self._finish(req, ServeResult(
                    request_id=req.id, ok=False,
                    error="non-finite values in request output",
                    error_kind="nonfinite_output", stream=req.stream,
                    latency_s=now - req.t_submit,
                    queue_wait_s=req.t_dispatch - req.t_submit,
                    batch_size=len(group), bucket=key.label()))
                continue
            flow = np.asarray(padders[j].unpad(flow_up[j:j + 1]))[0]
            output_min = output_max = None
            if taps is not None:
                # per-request output range feeding the drift gauges —
                # only paid for when the numerics flavor is on
                output_min = float(np.min(flow))
                output_max = float(np.max(flow))
            if req.warm and req.stream is not None:
                self._sessions[req.stream] = (flow_lr[j].shape,
                                              flow_lr[j])
            final_residual = None
            iters_taken = None if taken is None else int(taken[j])
            if deltas is not None:
                extra = {} if iters_taken is None else \
                    {"iters_taken": iters_taken}
                # adaptive programs record 0.0 rows for frozen iterations;
                # the quality gauge wants the residual of the LAST APPLIED
                # update, not the padding
                col = deltas[:, j]
                applied = col[col > 0.0]
                final_residual = float(applied[-1]) if iters_taken is not \
                    None and applied.size else float(col[-1])
                converge_emit(self.telemetry, f"serve:{key.label()}",
                              deltas.shape[0], deltas[:, j],
                              bucket=f"{key.height}x{key.width}",
                              id=req.id, **extra)
            self._finish(req, ServeResult(
                request_id=req.id, ok=True, flow=flow, stream=req.stream,
                latency_s=now - req.t_submit,
                queue_wait_s=req.t_dispatch - req.t_submit,
                batch_size=len(group), bucket=key.label(),
                final_residual=final_residual, iters_taken=iters_taken,
                output_min=output_min, output_max=output_max))

    def _fail_group(self, group: List[_Request], key: BucketKey,
                    exc: BaseException, kind: str) -> None:
        now = time.perf_counter()
        trace = "".join(tb_module.format_exception(
            type(exc), exc, exc.__traceback__))
        logger.warning("serve: batch %s failed (%s); failing %d request(s) "
                       "individually, scheduler continues",
                       key.label(), exc, len(group))
        for req in group:
            self._finish(req, ServeResult(
                request_id=req.id, ok=False,
                error=f"{type(exc).__name__}: {exc}", error_kind=kind,
                traceback=trace, stream=req.stream,
                latency_s=now - req.t_submit,
                queue_wait_s=(req.t_dispatch or now) - req.t_submit,
                batch_size=len(group), bucket=key.label()))

    def _finish(self, req: _Request, result: ServeResult) -> None:
        req.handle._set(result)
        self.slo.retire(
            request_id=req.id, status="ok" if result.ok else "error",
            latency_s=result.latency_s, queue_wait_s=result.queue_wait_s,
            bucket=result.bucket, batch_size=result.batch_size,
            in_flight=len(self._in_flight), stream=req.stream,
            error=result.error, traceback_tail=result.traceback,
            final_residual=result.final_residual,
            iters_taken=result.iters_taken,
            output_min=result.output_min, output_max=result.output_max)
        # the request's span tree, from the lifecycle stamps already taken:
        # queue_wait / collect_group / dispatch / retire tile the root
        # exactly (end = submit + the latency the client was told)
        tracer = getattr(self.telemetry, "tracer", None) or NULL_TRACER
        if tracer.enabled:
            end = req.t_submit + result.latency_s
            tc = req.t_collect or req.t_dispatch or end
            td = req.t_dispatch or tc
            te = req.t_disp_end or td
            # a remote parent came across a process boundary, so its span
            # lives in the CLIENT's log: remote_parent exempts the root
            # from the in-file orphan lint (obs/validate.py); `cli fleet`
            # resolves the join across the fleet dir
            remote = {"remote_parent": True} if req.parent is not None \
                else {}
            root = tracer.record(
                "request", req.t_submit, end, id=req.id,
                parent=req.parent,
                status="ok" if result.ok else "error",
                bucket=result.bucket, batch_size=result.batch_size,
                **remote)
            tracer.record("queue_wait", req.t_submit, tc, parent=root)
            tracer.record("collect_group", tc, td, parent=root)
            tracer.record("dispatch", td, te, parent=root)
            tracer.record("retire", te, end, parent=root)

    def _run(self) -> None:
        try:
            while True:
                self._apply_pending_reload()
                while len(self._in_flight) >= max(1, self.serve.window):
                    self._retire()
                first = self._queue.get(timeout=0.05)
                if first is None:
                    if self._in_flight:
                        self._retire()
                    elif self._queue.closed and len(self._queue) == 0:
                        break
                    continue
                self._dispatch(self._collect(first))
            while self._in_flight:
                self._retire()
            self.slo.flush(in_flight=0)
        finally:
            # drain: flush buffered spans and bank a flight-recorder dump
            # so a post-drain postmortem has the tail of the run
            tracer = getattr(self.telemetry, "tracer", None)
            if tracer is not None:
                tracer.flush()
            flight = getattr(self.telemetry, "flight_dump", None)
            if flight is not None and self._draining:
                flight("drain")
            self._stopped.set()
            logger.info("serve: scheduler stopped (%s)",
                        "drained" if self._draining else "exited")
