"""Continuous-batching stereo serving (ROADMAP item 3).

The streaming evaluator (eval/stream.py) proved the primitives — async
dispatch handles, a bounded in-flight window, consecutive same-shape
micro-batching — against a *dataset*. This package points the same
machinery at *concurrent clients*:

* :mod:`serve.batching` — the one copy of the greedy same-key grouping
  policy, shared with the streaming evaluator (which imports it back);
* :mod:`serve.cache` — shape-bucketed AOT ``lower().compile()`` executable
  cache with per-entry ``xla_memory``/``xla_cost`` introspection and
  in-place hot reload of model variables;
* :mod:`serve.server` — the bounded request queue + scheduler thread:
  continuous micro-batches across client streams, per-request fault
  isolation (a poisoned request fails alone; its batchmates retire
  normally), graceful drain, per-stream ``flow_init`` warm starts for
  video sessions;
* :mod:`serve.slo` — p50/p99 latency, in-flight depth and sustained
  pairs/s as schema-v6 ``request``/``queue``/``slo`` events;
* :mod:`serve.http` — a stdlib-only HTTP front (``cli serve``);
* :mod:`serve.loadtest` — the synthetic many-client trace driver
  (``cli loadtest``; proof harness: scripts/load_drill.py).
"""

from raft_stereo_tpu.serve.batching import (BoundedQueue, QueueClosed,
                                            collect_group, stack_pairs)
from raft_stereo_tpu.serve.server import (ServeConfig, ServeResult,
                                          ServerDraining, StereoServer)
from raft_stereo_tpu.serve.cache import BucketKey, ExecutableCache
from raft_stereo_tpu.serve.slo import SLOTracker

__all__ = [
    "BoundedQueue", "QueueClosed", "collect_group", "stack_pairs",
    "ServeConfig", "ServeResult", "ServerDraining", "StereoServer",
    "BucketKey", "ExecutableCache", "SLOTracker",
]
