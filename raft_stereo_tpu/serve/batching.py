"""Micro-batching primitives shared by the streaming evaluator and the
serving scheduler.

Both consumers face the same problem: a stream of heterogeneous work items
(dataset frames in index order; client requests in arrival order) must be
packed into few dispatches of ONE compiled program each. The policy that
shipped in eval/stream.py's ``_run_streaming`` — greedily take consecutive
items while their shape key matches, push the first mismatch back so it
starts the next group — lives here now as :func:`collect_group`, with the
evaluator importing it back (tests/test_eval_stream.py is the refactor
proof: its grouping semantics are unchanged).

The scheduler additionally needs what a plain ``queue.Queue`` cannot do:
push a mismatched item back to the FRONT (so arrival order is preserved
across group boundaries) and close admission for a graceful drain.
:class:`BoundedQueue` is that structure.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, List, Optional, Tuple

import numpy as np


def collect_group(first: Any, pull: Callable[[], Optional[Any]],
                  push_back: Callable[[Any], None], limit: int,
                  key: Callable[[Any], Any]) -> List[Any]:
    """Greedy consecutive same-key grouping — the micro-batch policy.

    Starting from ``first``, keep ``pull()``-ing while each item's ``key``
    equals ``first``'s, up to ``limit`` items total. ``pull`` returns None
    when nothing further is available without blocking. The first item
    whose key differs is handed to ``push_back`` (it starts the next
    group) and collection stops — items are never reordered, so per-stream
    FIFO semantics (and the evaluator's index-order retirement) hold.
    """
    group = [first]
    k0 = key(first)
    while len(group) < max(1, limit):
        item = pull()
        if item is None:
            break
        if key(item) != k0:
            push_back(item)
            break
        group.append(item)
    return group


def stack_pairs(samples) -> Tuple[np.ndarray, np.ndarray]:
    """Stack a same-shape group's image pairs into batched NHWC arrays."""
    im1 = np.stack([s["image1"] for s in samples])
    im2 = np.stack([s["image2"] for s in samples])
    return im1, im2


class QueueClosed(Exception):
    """put() after close(): the queue is draining and admits nothing new."""


class BoundedQueue:
    """Bounded FIFO with front-pushback and drain-aware close.

    * ``put`` blocks while full (bounded admission — backpressure reaches
      the client instead of growing an unbounded backlog) and raises
      :class:`QueueClosed` once ``close()`` was called;
    * ``get`` blocks up to ``timeout`` and returns None on timeout or when
      the queue is closed AND empty (the scheduler's exit signal);
    * ``get_nowait`` returns None instead of raising (the non-blocking
      pull :func:`collect_group` wants);
    * ``push_front`` re-inserts a pulled item at the head, exempt from the
      capacity bound (the item already held a slot when first admitted).
    """

    def __init__(self, maxsize: int):
        self.maxsize = max(1, int(maxsize))
        self._items: "collections.deque" = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def close(self) -> None:
        """Stop admitting; wakes every blocked producer and consumer."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def put(self, item: Any, timeout: Optional[float] = None) -> bool:
        """Admit one item; False on timeout, QueueClosed after close()."""
        with self._not_full:
            while True:
                if self._closed:
                    raise QueueClosed("queue is closed (draining)")
                if len(self._items) < self.maxsize:
                    self._items.append(item)
                    self._not_empty.notify()
                    return True
                if not self._not_full.wait(timeout=timeout):
                    return False

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        with self._not_empty:
            while True:
                if self._items:
                    item = self._items.popleft()
                    self._not_full.notify()
                    return item
                if self._closed:
                    return None
                if not self._not_empty.wait(timeout=timeout):
                    return None

    def get_nowait(self) -> Optional[Any]:
        with self._lock:
            if not self._items:
                return None
            item = self._items.popleft()
            self._not_full.notify()
            return item

    def push_front(self, item: Any) -> None:
        with self._lock:
            self._items.appendleft(item)
            self._not_empty.notify()
