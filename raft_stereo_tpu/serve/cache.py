"""Shape-bucketed compiled-executable cache for the serving scheduler.

One cache entry = one AOT-compiled inference program for a padded bucket
shape ``(H, W)`` × batch size × refinement iteration count × warm-start
flavor. AOT ``lower().compile()`` (the obs/xla.py pattern every other
compile site uses) instead of first-call jit so that:

* warmup is explicit — ``cli serve`` pre-compiles the configured buckets
  before admitting traffic, so no client pays a compile inside its
  latency budget;
* every entry's memory/cost analysis is emitted as ``xla_memory``/
  ``xla_cost`` events at compile time (``source="serve:<key>"``), making
  the cache's footprint a first-class observable.

The served program is the model's ``test_mode`` forward plus the
device-side per-request guard: a ``(B,)`` finiteness flag vector over each
sample's output — PR 7's anomaly-guard idea re-targeted from "skip the
optimizer update" to "fail exactly the poisoned request". The low-res flow
also comes back, feeding per-stream ``flow_init`` warm starts (RAFT's own
temporal warm start; the warm flavor adds the ``flow_init`` input).

Hot reload swaps the variables the executables are invoked with — entries
are keyed on shapes/dtypes only, and a reload with an identical pytree
structure (enforced via the resilience tree hash) never recompiles.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import create_model

logger = logging.getLogger(__name__)


class BucketKey(NamedTuple):
    """Identity of one compiled serving program."""

    height: int   # padded (bucket) height
    width: int    # padded (bucket) width
    batch: int
    iters: int
    warm: bool    # True = the flavor with a flow_init input
    #: iteration-policy digest (obs/converge.py policy_digest) for the
    #: compiled early-exit flavor — "" is the fixed-trip program. Part of
    #: the key so a policy swap can never silently reuse executables
    #: compiled against different (tau, budget, min_iters) constants.
    policy: str = ""
    #: correlation implementation the program was compiled with — "" is the
    #: server config's default. Part of the key so bucket flavors compiled
    #: against different lookup kernels (e.g. reg vs the memoryless fused)
    #: can coexist in one cache without executable reuse across impls.
    impl: str = ""

    def label(self) -> str:
        return (f"{self.height}x{self.width}b{self.batch}i{self.iters}"
                f"{'w' if self.warm else ''}"
                f"{'@' + self.policy if self.policy else ''}"
                f"{'+' + self.impl if self.impl else ''}")


class ExecutableCache:
    """(bucket H×W, batch, iters, warm) -> compiled test-mode forward.

    ``telemetry`` receives one ``xla_memory``/``xla_cost`` pair per entry
    (fail-open: an introspection error never blocks serving). ``aot=False``
    falls back to plain ``jax.jit`` (first call compiles) — the escape
    hatch for backends where ShapeDtypeStruct lowering misbehaves.
    """

    def __init__(self, cfg: RAFTStereoConfig, variables: Dict, *,
                 telemetry=None, aot: bool = True, converge: bool = False,
                 numerics: bool = False, iter_policy=None,
                 adaptive: Optional[bool] = None):
        self.cfg = cfg
        self.model = create_model(cfg)
        self.telemetry = telemetry
        self.aot = aot
        #: recorded iteration policy (obs/converge.py iter_policy.json,
        #: path or pre-loaded doc) backing the adaptive program flavors;
        #: loading lints it, so a doctored policy fails server construction
        self.policy = None
        self.policy_digest: str = ""
        if iter_policy is not None:
            from raft_stereo_tpu.obs.converge import (load_policy,
                                                      policy_digest)
            self.policy = (load_policy(iter_policy)
                           if isinstance(iter_policy, str) else iter_policy)
            self.policy_digest = policy_digest(self.policy)
        #: serve the compiled early-exit flavors for buckets the policy
        #: covers (fixed-trip programs everywhere else). Default: adaptive
        #: iff a policy was given; adaptive=False with a policy loaded
        #: ignores it (the pre-adaptive bitwise pin).
        self.adaptive = (bool(adaptive) if adaptive is not None
                         else self.policy is not None)
        if self.adaptive and self.policy is None:
            raise ValueError("adaptive serving needs an iter_policy "
                             "(cli converge --emit-policy)")
        if self.adaptive and numerics:
            raise ValueError("the adaptive program flavors carry no "
                             "numerics taps (models/raft_stereo.py); "
                             "serve --numerics needs --adaptive off")
        if self.adaptive:
            converge = True  # the per-sample residual aux is intrinsic
        #: serve the converge flavor: the program additionally returns the
        #: per-sample per-iteration |Δdisparity| curves (``(iters, B)``,
        #: iter_metrics="per_sample") feeding the convergence observatory
        #: and the SLO quality gauges. False keeps the exact 3-output
        #: program of schema v7 (the --no_converge pin).
        self.converge = converge
        #: serve the numerics flavor (obs/numerics.py): the program
        #: additionally returns the per-iteration activation-tap range
        #: statistics ({tap: (iters, 6)}) as the LAST output, feeding the
        #: per-dispatch ``numerics`` events. False keeps the exact prior
        #: program (the --no_numerics pin; serve's default).
        self.numerics = numerics
        self._lock = threading.Lock()
        self._entries: Dict[BucketKey, Any] = {}
        self._variables = variables
        self._tree_hash = self._hash(variables)

    @staticmethod
    def _hash(variables: Dict) -> str:
        from raft_stereo_tpu.training.resilience import tree_structure_hash
        return tree_structure_hash(variables)

    @property
    def variables(self) -> Dict:
        with self._lock:
            return self._variables

    def reload(self, variables: Dict) -> None:
        """Swap the served variables in place (hot model reload).

        The pytree structure (leaf shapes/dtypes) must match what the
        entries were compiled against — a mismatch would need new
        executables and is a config change, not a reload."""
        new_hash = self._hash(variables)
        if new_hash != self._tree_hash:
            raise ValueError(
                f"reload variables have pytree hash {new_hash}, executables "
                f"were compiled against {self._tree_hash} — a structural "
                "change requires a new server, not a hot reload")
        with self._lock:
            self._variables = variables

    # --- compilation ---------------------------------------------------------

    def bucket_entry(self, height: int, width: int) -> Optional[Dict]:
        """The policy entry for a PADDED bucket shape (``{"tau", "budget",
        "min_iters", ...}``), or None when adaptive is off / the bucket is
        uncovered. The scheduler resolves this per group to pick the
        iteration budget and the key's policy digest."""
        if not self.adaptive:
            return None
        from raft_stereo_tpu.obs.converge import policy_lookup
        return policy_lookup(self.policy, f"{height}x{width}")

    def _build(self, key: BucketKey):
        model, iters = self.model, key.iters
        if key.impl and key.impl != self.cfg.corr_implementation:
            # impl-flavored bucket: same variables (the model is fully
            # convolutional and the corr impl touches no parameters), a
            # different lookup program — e.g. the memoryless 'fused' flavor
            # for wide buckets whose reg volume would not fit.
            import dataclasses
            model = create_model(dataclasses.replace(
                self.cfg, corr_implementation=key.impl))
        converge = self.converge
        numerics = self.numerics
        entry = self.bucket_entry(key.height, key.width) if key.policy \
            else None
        if key.policy and entry is None:
            raise ValueError(
                f"bucket key {key.label()} names policy {key.policy} but "
                f"the loaded policy (digest {self.policy_digest}) does not "
                f"cover {key.height}x{key.width}")

        def forward(variables, im1, im2, flow_init=None):
            """(flow_lr, flow_up, finite[, deltas][, iters_taken][, taps])
            — the converge flavor appends the per-sample convergence
            curves, the adaptive flavor additionally the per-sample
            iterations applied, the numerics flavor the per-iteration
            tap-statistics dict (always LAST; never combined with
            adaptive)."""
            metrics = "per_sample" if converge else False
            if entry is not None:
                out = model.apply(variables, im1, im2, iters=iters,
                                  flow_init=flow_init, test_mode=True,
                                  iter_metrics=metrics,
                                  adaptive_tau=float(entry["tau"]),
                                  adaptive_min_iters=int(entry["min_iters"]))
            else:
                out = model.apply(variables, im1, im2, iters=iters,
                                  flow_init=flow_init, test_mode=True,
                                  iter_metrics=metrics, numerics=numerics)
            flow_lr, flow_up = out[0], out[1]
            finite = jnp.all(jnp.isfinite(flow_up), axis=(1, 2, 3))
            ret = (flow_lr, flow_up, finite)
            if converge:
                ret = ret + (out[2],)
            if entry is not None:
                ret = ret + (out[-1],)  # iters_taken (B,)
            if numerics:
                ret = ret + (out[-1],)
            return ret

        if key.warm:
            def run(variables, im1, im2, flow_init):
                return forward(variables, im1, im2, flow_init)
        else:
            def run(variables, im1, im2):
                return forward(variables, im1, im2)

        jitted = jax.jit(run)
        if not self.aot:
            return jitted
        def leaf_spec(leaf):
            # metadata only — np.shape/result_type never touch leaf data
            dtype = getattr(leaf, "dtype", None)
            if dtype is None:
                dtype = np.result_type(leaf)
            return jax.ShapeDtypeStruct(np.shape(leaf), dtype)

        img = jax.ShapeDtypeStruct(
            (key.batch, key.height, key.width, 3), jnp.float32)
        specs = [jax.tree.map(leaf_spec, self.variables), img, img]
        if key.warm:
            factor = 2 ** self.cfg.n_downsample
            specs.append(jax.ShapeDtypeStruct(
                (key.batch, key.height // factor, key.width // factor, 2),
                jnp.float32))
        try:
            compiled = jitted.lower(*specs).compile()
        except Exception:
            logger.exception("AOT compile failed for %s; falling back to "
                             "jit-on-first-call", key.label())
            return jitted
        try:
            from raft_stereo_tpu.obs.xla import introspect_compiled
            introspect_compiled(compiled, telemetry=self.telemetry,
                                source=f"serve:{key.label()}",
                                extra={"bucket": list(key[:2]),
                                       "batch": key.batch,
                                       "iters": key.iters,
                                       "warm": key.warm})
        except Exception:
            logger.exception("executable introspection failed for %s "
                             "(serving continues)", key.label())
        return compiled

    def get(self, key: BucketKey):
        """The compiled program for ``key`` (compiling on miss)."""
        with self._lock:
            fn = self._entries.get(key)
        if fn is None:
            fn = self._build(key)
            with self._lock:
                fn = self._entries.setdefault(key, fn)
        return fn

    def warmup(self, keys) -> int:
        """Pre-compile every key; returns the number of NEW entries."""
        fresh = 0
        for key in keys:
            with self._lock:
                have = key in self._entries
            if not have:
                self.get(key)
                fresh += 1
        return fresh

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self) -> Tuple[BucketKey, ...]:
        with self._lock:
            return tuple(self._entries)

    # --- invocation ----------------------------------------------------------

    def __call__(self, key: BucketKey, im1, im2,
                 flow_init: Optional[np.ndarray] = None):
        """Run the key's program with the CURRENT variables; returns
        ``(flow_lowres, flow_up, finite_flags)`` device arrays — plus a
        ``(iters, B)`` convergence-curve array when the cache was built
        with ``converge=True``, plus (always last) the numerics
        tap-statistics dict when built with ``numerics=True``."""
        fn = self.get(key)
        variables = self.variables
        if key.warm:
            if flow_init is None:
                raise ValueError("warm bucket requires a flow_init batch")
            return fn(variables, im1, im2, flow_init)
        return fn(variables, im1, im2)
