from raft_stereo_tpu.nn.encoder import BasicEncoder, MultiBasicEncoder
from raft_stereo_tpu.nn.gru import (
    BasicMotionEncoder,
    BasicMultiUpdateBlock,
    ConvGRU,
    FlowHead,
    SepConvGRU,
    interp_to,
)
from raft_stereo_tpu.nn.layers import (
    BottleneckBlock,
    Conv,
    FrozenBatchNorm,
    GroupNorm,
    InstanceNorm,
    ResidualBlock,
)
