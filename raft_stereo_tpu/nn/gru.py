"""Recurrent refinement cell: ConvGRU hierarchy + motion encoder + heads.

Re-design of core/update.py for NHWC/flax. The multi-level GRU stack runs
coarse-to-fine with cross-resolution links (pool down, bilinear up), the
motion encoder turns correlation+flow into 128-d features, and the context
biases ``cz, cr, cq`` are precomputed once outside the refinement loop and
*added per gate* inside each GRU (update.py:27-29, raft_stereo.py:87-88).
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.ad_checkpoint import checkpoint_name

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.nn.layers import Conv
from raft_stereo_tpu.obs.numerics import BF16_MAX_FINITE, BF16_MIN_NORMAL
from raft_stereo_tpu.ops.geometry import pool2x, resize_bilinear_align_corners

Dtype = Any


# --- numerics tap sink (obs/numerics.py's in-graph half) ---------------------
#
# The numerics observatory needs per-iteration range statistics at the
# residual tag sites — the exact tensors the bf16 save policy narrows —
# without changing the traced program when it is off. The sink is a
# module-level collection point: :func:`numerics_taps` arms it around a
# model apply (models/raft_stereo.py's scan body trace), every
# ``tag_residual``/``record_numerics_tap`` call that executes while it is
# armed deposits one fused (len(STAT_FIELDS),) stats vector, and the model
# threads the collected dict through the scan's stacked outputs. Sink
# ``None`` (the default, and always the case under training/jit without
# the context) makes every recording call a no-op that returns its input
# untouched — the byte-identical ``--no_numerics`` pin rests on this.

_tap_sink = None

#: bf16 saturation rail — see obs/numerics.py: finite fp32 never rounds to
#: bf16 inf, so "|x| at/above the bf16 max finite" IS the overflow signal
_BF16_MAX = BF16_MAX_FINITE

#: fp32 bit pattern of the smallest normal bf16 — the underflow rail
_BF16_MIN_BITS = np.float32(BF16_MIN_NORMAL).view(np.uint32)


def _tap_stats(x):
    """Fused range/health statistics for one tap: a stacked
    ``[min, max, absmean, nonfinite, sat, underflow]`` vector (fp32; the
    order is obs/numerics.py's STAT_FIELDS). min/max/absmean are over the
    finite values (an all-NaN tensor yields +/-inf sentinels the host
    cleans to null); the bf16 counters are computed against bfloat16
    regardless of the tap's own dtype, because these are the tensors the
    ``residual_dtype="bfloat16"`` save policy and the corr bf16 storage
    policy narrow."""
    x32 = x.astype(jnp.float32)
    finite = jnp.isfinite(x32)
    f32 = jnp.float32
    minv = jnp.min(jnp.where(finite, x32, jnp.inf))
    maxv = jnp.max(jnp.where(finite, x32, -jnp.inf))
    absmean = jnp.mean(jnp.where(finite, jnp.abs(x32), 0.0))
    nonfinite = jnp.sum((~finite).astype(f32))
    sat = jnp.sum((jnp.abs(x32) >= _BF16_MAX).astype(f32))
    # underflow: nonzero magnitudes in bf16's flush-to-zero regime. Tested
    # on the raw bit pattern — XLA's float compares run denormals-as-zero
    # on CPU, so `x != 0` is False for exactly the values this counts
    bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    mag = bits & jnp.uint32(0x7FFFFFFF)
    underflow = jnp.sum(
        ((mag != 0) & (mag < jnp.uint32(_BF16_MIN_BITS))).astype(f32))
    return jnp.stack([minv, maxv, absmean, nonfinite, sat, underflow])


@contextlib.contextmanager
def numerics_taps():
    """Arm the tap sink for the duration of one model apply; yields the
    dict the recording calls fill. Keys are ``"<order>:<label>"`` — the
    2-digit trace-order prefix survives the sorted-key flattening jit
    applies to dict outputs, so consumers (obs/numerics.py
    ``split_label``) recover dataflow order for first-nonfinite
    tie-breaking. Re-entrant: the previous sink is restored on exit."""
    global _tap_sink
    prev = _tap_sink
    _tap_sink = {}
    try:
        yield _tap_sink
    finally:
        _tap_sink = prev


def record_numerics_tap(x, label):
    """Deposit ``x``'s stats in the armed sink (no-op, returning ``x``
    unchanged, when no sink is armed). A label recorded twice in one trace
    (e.g. the slow_fast pre-iterations re-running a GRU) gets ``#2``,
    ``#3``... suffixes — every call site stays distinguishable."""
    if _tap_sink is None:
        return x
    base = label
    n = 2
    while any(k.partition(":")[2] == label for k in _tap_sink):
        label = f"{base}#{n}"
        n += 1
    _tap_sink[f"{len(_tap_sink):02d}:{label}"] = _tap_stats(x)
    return x


class _ConvParams(nn.Module):
    """Declares a conv's ``kernel``/``bias`` params without running the conv,
    so sibling convs over the same input can be fused into one MXU matmul
    while the parameter tree keeps the reference's 1:1 layout."""

    kernel: Tuple[int, int]
    in_features: int
    features: int

    @nn.compact
    def __call__(self):
        from raft_stereo_tpu.nn.layers import kaiming_normal_init
        k = self.param("kernel", kaiming_normal_init(),
                       (*self.kernel, self.in_features, self.features),
                       jnp.float32)
        b = self.param("bias", nn.initializers.zeros, (self.features,),
                       jnp.float32)
        return k, b


# Below this spatial area the concat formulation wins: the layout copy the
# split avoids is small, while the extra conv dispatches dominate (measured:
# splitting costs the realtime preset ~25% inference FPS at its 1/8-res
# 47x156 grids, but gains ~10% train step time at the 80x180 train grids).
_SPLIT_CONV_MIN_AREA = 8192


def split_conv_engages(height: int, width: int) -> bool:
    """Whether the gate convs run split-input (no concat tensor) at this
    grid size — pinned by tests/test_training.py so the calibrated
    crossover fails loudly if the constant drifts."""
    return height * width >= _SPLIT_CONV_MIN_AREA


def _split_input_conv(parts, kernel, bias, pad, dt, tap=None, path=None,
                      kind=None):
    """``conv(concat(parts), kernel) + bias``; computed as a sum of per-part
    convs against input-channel slices of ``kernel`` (no concat tensor) at
    large spatial sizes, as the plain concat conv at small ones.

    ``tap`` (a scoped :class:`~raft_stereo_tpu.ops.scan_grad._ScopedTap`)
    reroutes the conv through the custom-VJP scan's site machinery: the
    batched-weight-grad backward collects the (post-collapse) input parts
    and the output cotangent there instead of running a per-iteration
    weight-grad conv. The primal value is identical either way."""
    h, w = parts[0].shape[1], parts[0].shape[2]
    if not split_conv_engages(h, w):
        # degenerate to one concat conv via the same loop below
        parts = [jnp.concatenate([v.astype(dt) for v in parts], axis=-1)]
    parts = [v.astype(dt) for v in parts]
    if tap is not None:
        return tap.gate_conv(path, kind, parts, kernel, bias, pad)
    out = None
    off = 0
    for v in parts:
        c = v.shape[-1]
        y = jax.lax.conv_general_dilated(
            v, kernel[:, :, off:off + c, :], (1, 1),
            ((pad, pad), (pad, pad)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        out = y if out is None else out + y
        off += c
    return out + bias


def tag_residual(x, name, save_dtype=None, tap=None):
    """``checkpoint_name`` with an optional lean storage dtype.

    With ``save_dtype`` set (``config.residual_dtype`` while a selective
    save policy is engaged on the autodiff path), the TAGGED tensor — the
    one ``save_only_these_names`` keeps across the scan backward — is the
    narrowed copy, and downstream compute continues from its upcast. This
    halves the named residual stacks at the cost of one rounding on the
    saved value (the documented-tolerance regime; the custom-VJP scan
    instead narrows only its saved copies and leaves the forward exact).

    ``tap`` names the site for the numerics observatory: when a
    :func:`numerics_taps` sink is armed, the PRE-cast value's range stats
    are recorded under that label (the pre-cast value is the one whose
    bf16 saturation/underflow the counters measure). Without an armed
    sink the tap is inert — the traced program is unchanged."""
    if tap is not None:
        record_numerics_tap(x, tap)
    if save_dtype is None or x.dtype == jnp.dtype(save_dtype):
        return checkpoint_name(x, name)
    return checkpoint_name(x.astype(save_dtype), name).astype(x.dtype)


class FlowHead(nn.Module):
    """Two 3x3 convs -> delta flow (update.py:6-14).

    ``epipolar=True`` (the stereo model) computes only the x-channel of the
    output conv and concatenates a zero y-channel: the model zeroes the
    y-delta immediately anyway (raft_stereo.py:119-120), and a 2-channel conv
    output forces a pathological (2,128)-tiled layout on TPU (measured ~3
    TF/s). Params keep the reference's (3,3,hidden,2) shape; the y-column
    simply receives zero gradients, exactly as if its output were computed
    and then discarded.
    """

    hidden_dim: int = 256
    output_dim: int = 2
    epipolar: bool = False
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x):
        x = nn.relu(checkpoint_name(
            Conv.make(self.hidden_dim, 3, 1, 1, self.dtype, "conv1")(x),
            "flow_head_hidden"))
        if not self.epipolar or self.output_dim != 2:
            return Conv.make(self.output_dim, 3, 1, 1, self.dtype, "conv2")(x)
        kern, bias = _ConvParams((3, 3), x.shape[-1], 2, name="conv2")()
        dt = self.dtype or x.dtype
        dx = jax.lax.conv_general_dilated(
            x.astype(dt), kern[..., :1].astype(dt), (1, 1), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + bias[:1].astype(dt)
        return jnp.concatenate([dx, jnp.zeros_like(dx)], axis=-1)


class ConvGRU(nn.Module):
    """Convolutional GRU with additive per-gate context biases (update.py:16-32).

    TPU note: the z and r gates share the same input ``hx``, so their convs
    run as ONE conv with the kernels concatenated along the output axis — a
    single larger MXU matmul instead of two half-size ones. The parameters
    stay separate (``convz``/``convr``) so checkpoints map 1:1 to the
    reference's tensors.
    """

    hidden_dim: int
    kernel_size: int = 3
    dtype: Optional[Dtype] = None
    save_dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, h, cz, cr, cq, *x_list, tap=None):
        k, p = self.kernel_size, self.kernel_size // 2
        parts = [h, *x_list]
        in_ch = sum(v.shape[-1] for v in parts)
        path = tuple(self.scope.path)
        # numerics tap labels lead with the GRU level ("gru32.zr"); a
        # top-level application (unit tests) has an empty scope path
        site = path[-1] if path else "gru"

        kz, bz = _ConvParams((k, k), in_ch, self.hidden_dim, name="convz")()
        kr, br = _ConvParams((k, k), in_ch, self.hidden_dim, name="convr")()
        dt = self.dtype or h.dtype
        kernel = jnp.concatenate([kz, kr], axis=-1).astype(dt)
        bias = jnp.concatenate([bz, br]).astype(dt)
        # Summed per-input convs instead of conv(concat(h, x...)): the math
        # is identical (conv is linear in the input-channel axis), each part
        # contracts against its slice of the kernel, and the concatenated
        # activation tensor — whose layout copy showed up at ~1 ms/iteration
        # in profiles — never materializes.
        zr = _split_input_conv(parts, kernel, bias, p, dt, tap, path, "zr")
        # gru_zr/gru_q tags feed the size-conditional save policy in
        # models/raft_stereo.py (save_only_these_names when the estimated
        # residuals fit; full remat otherwise — PERF.md r2 inversion).
        # Inert under the custom-VJP scan, which stacks these sites itself.
        zr = tag_residual(zr, "gru_zr", self.save_dtype,
                          tap=f"{site}.zr")
        z, r = jnp.split(zr, 2, axis=-1)
        z = nn.sigmoid(z + cz)
        r = nn.sigmoid(r + cr)
        kq, bq = _ConvParams((k, k), in_ch, self.hidden_dim, name="convq")()
        q = _split_input_conv([r * h, *x_list], kq.astype(dt),
                              bq.astype(dt), p, dt, tap, path, "q")
        q = tag_residual(q, "gru_q", self.save_dtype,
                         tap=f"{site}.q")
        q = nn.tanh(q + cq)
        return (1 - z) * h + z * q


class SepConvGRU(nn.Module):
    """Separable (1x5 then 5x1) ConvGRU (update.py:34-62; unused by the stereo
    model but part of the reference's component inventory)."""

    hidden_dim: int = 128
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, h, *x_list):
        x = jnp.concatenate(x_list, axis=-1)

        def half(h, suffix, kernel, pad):
            hx = jnp.concatenate([h, x], axis=-1)
            z = nn.sigmoid(Conv.make(self.hidden_dim, kernel, 1, pad,
                                     self.dtype, f"convz{suffix}")(hx))
            r = nn.sigmoid(Conv.make(self.hidden_dim, kernel, 1, pad,
                                     self.dtype, f"convr{suffix}")(hx))
            q = nn.tanh(Conv.make(self.hidden_dim, kernel, 1, pad, self.dtype,
                                  f"convq{suffix}")(
                jnp.concatenate([r * h, x], axis=-1)))
            return (1 - z) * h + z * q

        h = half(h, "1", (1, 5), ((0, 0), (2, 2)))
        h = half(h, "2", (5, 1), ((2, 2), (0, 0)))
        return h


class BasicMotionEncoder(nn.Module):
    """Correlation + flow -> 128-d motion features (update.py:64-85).

    The stereo model's flow y-channel is structurally zero (flow_init's y is
    zeroed on entry and every delta's y is zeroed, raft_stereo.py:119-120),
    so ``convf1`` contracts only the x-channel against kernel column 0: the
    y-column contributes zero forward value AND zero weight gradient
    (grad = input (x) cotangent, input channel = 0), so params keep the
    reference (7,7,2,64) shape with exact training semantics while the TPU
    conv skips the dead half of a pathologically thin 2-input-channel
    contraction (its weight-gradient fusion measured 2.7 TF/s).
    """

    cfg: RAFTStereoConfig
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, flow, corr, corr_state=None, coords_x=None):
        d = self.dtype
        if corr_state is not None:
            # Fused path: the 4-level pyramid lookup and convc1 (1x1) + ReLU
            # run as one Pallas kernel (ops/pallas/lookup_kernels.py); the
            # (B, H, W, 36) corr tensor never exists in HBM. Params are
            # declared with the reference names/shapes so checkpoints map
            # 1:1. convc2 and the flow branch stay XLA convs (they are
            # MXU-shaped; fusing them tripped Mosaic's pathological compile
            # times — the r3 motion_kernels lesson).
            from raft_stereo_tpu.ops.pallas.lookup_kernels import (
                fused_lookup_c1)
            cc = self.cfg.corr_channels
            kc1, bc1 = _ConvParams((1, 1), cc, 64, name="convc1")()
            cor = fused_lookup_c1(corr_state.levels, coords_x,
                                  kc1.reshape(cc, 64), bc1,
                                  corr_state.radius, d)
            cor = checkpoint_name(cor, "motion_c1")
        else:
            cor = nn.relu(checkpoint_name(
                Conv.make(64, 1, 1, 0, d, "convc1")(corr), "motion_c1"))
        cor = nn.relu(checkpoint_name(
            Conv.make(64, 3, 1, 1, d, "convc2")(cor), "motion_c2"))
        kern, bias = _ConvParams((7, 7), 2, 64, name="convf1")()
        dtc = d or flow.dtype
        flo = jax.lax.conv_general_dilated(
            flow[..., :1].astype(dtc), kern[..., :1, :].astype(dtc),
            (1, 1), ((3, 3), (3, 3)),
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + bias.astype(dtc)
        flo = nn.relu(checkpoint_name(flo, "motion_f1"))
        flo = nn.relu(checkpoint_name(
            Conv.make(64, 3, 1, 1, d, "convf2")(flo), "motion_f2"))
        out = nn.relu(checkpoint_name(
            Conv.make(128 - 2, 3, 1, 1, d, "conv")(
                jnp.concatenate([cor, flo], axis=-1)), "motion_out"))
        return jnp.concatenate([out, flow], axis=-1)


def interp_to(x, dest):
    """Bilinear align-corners resize of ``x`` to ``dest``'s spatial shape
    (update.py:93-95)."""
    return resize_bilinear_align_corners(x, (dest.shape[1], dest.shape[2]))


class BasicMultiUpdateBlock(nn.Module):
    """3-level coarse-to-fine GRU refinement cell (update.py:97-138).

    ``net`` is the hidden-state tuple ordered fine->coarse (net[0] finest);
    ``inp`` is the per-level precomputed (cz, cr, cq) context-bias triple.
    Flags ``iter08/16/32`` select which levels update this call; ``update=False``
    runs GRUs only (the slow_fast_gru low-res pre-iterations,
    raft_stereo.py:113-116).
    """

    cfg: RAFTStereoConfig
    dtype: Optional[Dtype] = None
    save_dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, net: Tuple, inp: Tuple, corr=None, flow=None, *,
                 iter08: bool = True, iter16: bool = True, iter32: bool = True,
                 update: bool = True, corr_state=None, coords_x=None,
                 compute_mask: bool = True, wgrad_tap=None):
        cfg = self.cfg
        d = self.dtype
        sd = self.save_dtype
        tap = wgrad_tap
        hd = cfg.hidden_dims
        net = list(net)

        if iter32:
            net[2] = ConvGRU(hd[0], dtype=d, save_dtype=sd, name="gru32")(
                net[2], *inp[2], pool2x(net[1]), tap=tap)
        if iter16:
            if cfg.n_gru_layers > 2:
                net[1] = ConvGRU(hd[1], dtype=d, save_dtype=sd, name="gru16")(
                    net[1], *inp[1], pool2x(net[0]), interp_to(net[2], net[1]),
                    tap=tap)
            else:
                net[1] = ConvGRU(hd[1], dtype=d, save_dtype=sd, name="gru16")(
                    net[1], *inp[1], pool2x(net[0]), tap=tap)
        if iter08:
            motion = BasicMotionEncoder(cfg, dtype=d, name="encoder")(
                flow, corr, corr_state=corr_state, coords_x=coords_x)
            if cfg.n_gru_layers > 1:
                net[0] = ConvGRU(hd[2], dtype=d, save_dtype=sd, name="gru08")(
                    net[0], *inp[0], motion, interp_to(net[1], net[0]),
                    tap=tap)
            else:
                net[0] = ConvGRU(hd[2], dtype=d, save_dtype=sd, name="gru08")(
                    net[0], *inp[0], motion, tap=tap)

        if not update:
            return tuple(net)

        delta_flow = FlowHead(256, 2, epipolar=True, dtype=d,
                              name="flow_head")(net[0])

        # compute_mask=False (static) drops the mask head from the graph:
        # inference consumes only the FINAL iteration's mask, so the scanned
        # iterations skip these two convs entirely (models/raft_stereo.py).
        if not compute_mask:
            return tuple(net), None, delta_flow
        # scale mask to balance gradients (update.py:136-137)
        mask = checkpoint_name(
            Conv.make(256, 3, 1, 1, d, "mask_conv1")(net[0]), "mask_hidden")
        mask = Conv.make(cfg.factor ** 2 * 9, 1, 1, 0, d,
                         "mask_conv2")(nn.relu(mask))
        return tuple(net), 0.25 * mask, delta_flow
