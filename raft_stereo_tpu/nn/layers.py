"""Normalization + conv building blocks (flax, NHWC).

Re-designs the reference's layer vocabulary (core/extractor.py) for TPU:
channel-last convs, fp32 params with an optional bf16 compute dtype (mixed
precision as a dtype policy instead of torch autocast+GradScaler — bf16 needs
no loss scaling), and *frozen* batch norm as an explicit module: the reference
always runs BatchNorm in eval mode during training (``freeze_bn``,
train_stereo.py:151), so its running statistics are constants and the affine
transform is the only trainable part. That contract is made structural here.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.ad_checkpoint import checkpoint_name

Dtype = Any

# Residual-policy tags consumed by ``remat_encoders="norms"``
# (models/raft_stereo.py): under
# ``save_only_these_names("enc_conv", "enc_stat")`` the encoder backward
# keeps every conv output (compute-dtype, the MXU work) plus the tiny norm
# statistics, and recomputes the elementwise norm/relu/add glue — whose
# saved form otherwise dominates residual memory (measured at the SceneFlow
# batch-8 shape: 24.9 GB total, of which 14.1 GB fp32 norm intermediates and
# 3.6 GB bool relu masks vs 7.1 GB of conv outputs). Inert outside a remat
# policy.
ENC_CONV_TAG = "enc_conv"
ENC_STAT_TAG = "enc_stat"


def save_conv_output(x, fold: bool = False):
    """Tag a conv output for the "norms" remat policy; optionally lane-dense.

    TPU layouts put the channel dim on 128 lanes; a 64- or 96-channel
    activation saved as-is is padded 2x / 1.33x in HBM — measured at the
    SceneFlow batch-8 shape, that padding (8.8 GB unpadded -> 14.1 GB
    padded) is what pushes the saved-conv residual set out of a 16 GB chip.
    With ``fold=True``, W is folded into the channel dim up to a 128
    multiple before tagging, so the SAVED form is exactly lane-sized; the
    immediate unfold means the surrounding computation is unchanged
    (reshape-of-reshape cancels to identity whenever no remat policy
    consumes the tag, and is a linear-order-preserving bitcast of the
    unpadded data when one does). Folding costs relayout copies both ways
    (measured −65 ms/step at batch 4, where memory is plentiful), so the
    model enables it only when the padded saves wouldn't fit
    (models/raft_stereo.py auto rule).
    """
    if not fold or x.ndim != 4:
        return checkpoint_name(x, ENC_CONV_TAG)
    b, h, w, c = x.shape
    factor = 1
    for f in (1, 2, 4, 8):
        if (c * f) % 128 == 0 and w % f == 0:
            factor = f
            break
    if factor == 1:
        return checkpoint_name(x, ENC_CONV_TAG)
    folded = checkpoint_name(x.reshape(b, h, w // factor, factor * c),
                             ENC_CONV_TAG)
    return folded.reshape(b, h, w, c)

# torch norm-layer epsilon (BatchNorm2d/InstanceNorm2d/GroupNorm all 1e-5)
NORM_EPS = 1e-5


def kaiming_normal_init():
    """He-normal fan-out init (extractor.py:155-162)."""
    return nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class Conv(nn.Conv):
    """nn.Conv with the framework's defaults: He init, fp32 params."""

    kernel_init: Callable = kaiming_normal_init()

    @staticmethod
    def make(features: int, kernel: int | Tuple[int, int], stride: int = 1,
             padding: int | str = "SAME", dtype: Optional[Dtype] = None,
             name: Optional[str] = None) -> "Conv":
        if isinstance(kernel, int):
            kernel = (kernel, kernel)
        if isinstance(padding, int):
            padding = ((padding, padding), (padding, padding))
        return Conv(features=features, kernel_size=kernel,
                    strides=(stride, stride), padding=padding, dtype=dtype,
                    param_dtype=jnp.float32, name=name)


class FrozenBatchNorm(nn.Module):
    """BatchNorm with constant running statistics.

    Mirrors the reference's invariant that BN never updates stats
    (train_stereo.py:151,193: ``freeze_bn`` after every ``model.train()``):
    ``mean``/``var`` live in the non-trainable ``batch_stats`` collection
    (filled by the checkpoint converter; identity at fresh init), while
    ``scale``/``bias`` are ordinary trainable params.
    """

    features: int
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x):
        mean = self.variable("batch_stats", "mean",
                             lambda: jnp.zeros((self.features,), jnp.float32))
        var = self.variable("batch_stats", "var",
                            lambda: jnp.ones((self.features,), jnp.float32))
        scale = self.param("scale", nn.initializers.ones, (self.features,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (self.features,),
                          jnp.float32)
        dtype = self.dtype or x.dtype
        inv = jax.lax.rsqrt(var.value + NORM_EPS) * scale
        return (x * inv.astype(dtype) +
                (bias - mean.value * inv).astype(dtype))


class InstanceNorm(nn.Module):
    """Per-sample, per-channel normalization over H, W.

    torch ``nn.InstanceNorm2d`` defaults: no affine params, no running stats,
    biased variance, eps 1e-5 (used by the feature encoder, raft_stereo.py:39).
    Statistics are computed in fp32 for bf16 inputs.
    """

    features: int = 0  # unused; kept for a uniform constructor signature

    @nn.compact
    def __call__(self, x):
        # One-pass moments (a single fused reduction) instead of
        # mean-then-variance: at the encoder's full-resolution layers the
        # second sequential pass over a ~0.5 GB activation is pure HBM cost.
        # Shifted by a per-(sample, channel) data point so the
        # E[y^2] - E[y]^2 form cannot catastrophically cancel when
        # |mean| >> std (standard shifted-data variance).
        x32 = x.astype(jnp.float32)
        n = x.shape[1] * x.shape[2]
        shift = x32[:, :1, :1, :]
        y = x32 - shift
        s1 = jnp.sum(y, axis=(1, 2), keepdims=True)
        s2 = jnp.sum(y * y, axis=(1, 2), keepdims=True)
        mean_y = checkpoint_name(s1 / n, ENC_STAT_TAG)
        var = checkpoint_name(
            jnp.maximum(s2 / n - mean_y * mean_y, 0.0), ENC_STAT_TAG)
        out = (y - mean_y) * jax.lax.rsqrt(var + NORM_EPS)
        return out.astype(x.dtype)


class GroupNorm(nn.Module):
    """GroupNorm with torch defaults (affine, eps 1e-5).

    Params (``scale``/``bias``) live directly on this module so the checkpoint
    converter maps torch ``normX.weight/bias`` to a uniform flax path.
    """

    features: int
    num_groups: int

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (self.features,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (self.features,),
                          jnp.float32)
        x32 = x.astype(jnp.float32)
        b, h, w, c = x32.shape
        g = x32.reshape(b, h, w, self.num_groups, c // self.num_groups)
        # one-pass shifted moments (see InstanceNorm)
        n = h * w * (c // self.num_groups)
        y = g - g[:, :1, :1, :, :1]
        s1 = jnp.sum(y, axis=(1, 2, 4), keepdims=True)
        s2 = jnp.sum(y * y, axis=(1, 2, 4), keepdims=True)
        mean_y = checkpoint_name(s1 / n, ENC_STAT_TAG)
        var = checkpoint_name(
            jnp.maximum(s2 / n - mean_y * mean_y, 0.0), ENC_STAT_TAG)
        out = ((y - mean_y) * jax.lax.rsqrt(var + NORM_EPS)).reshape(b, h, w, c)
        return (out * scale + bias).astype(x.dtype)


def make_norm(norm_fn: str, features: int, *, num_groups: Optional[int] = None,
              name: str) -> Optional[nn.Module]:
    """Norm factory for the reference's selectable norms (extractor.py:16-38).

    Returns ``None`` for ``'none'`` (callers treat it as identity). GroupNorm
    group count defaults to ``features // 8`` (ResidualBlock) unless given
    (BasicEncoder stem uses 8 groups, extractor.py:129).
    """
    if norm_fn == "none":
        return None
    if norm_fn == "batch":
        return FrozenBatchNorm(features=features, name=name)
    if norm_fn == "instance":
        return InstanceNorm(features=features, name=name)
    if norm_fn == "group":
        return GroupNorm(features=features,
                         num_groups=num_groups or features // 8, name=name)
    raise ValueError(f"unknown norm_fn {norm_fn!r}")


def apply_norm(norm: Optional[nn.Module], x):
    return x if norm is None else norm(x)


class ResidualBlock(nn.Module):
    """Two 3x3 convs + norms with a strided 1x1 projection shortcut
    (extractor.py:6-60). The projection exists whenever stride != 1 or the
    channel count changes."""

    in_planes: int
    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Optional[Dtype] = None
    fold_saves: bool = False

    @nn.compact
    def __call__(self, x):
        y = save_conv_output(
            Conv.make(self.planes, 3, self.stride, 1, self.dtype, "conv1")(x),
            self.fold_saves)
        y = apply_norm(make_norm(self.norm_fn, self.planes, name="norm1"), y)
        y = nn.relu(y)
        y = save_conv_output(
            Conv.make(self.planes, 3, 1, 1, self.dtype, "conv2")(y),
            self.fold_saves)
        y = apply_norm(make_norm(self.norm_fn, self.planes, name="norm2"), y)
        y = nn.relu(y)

        if not (self.stride == 1 and self.in_planes == self.planes):
            x = save_conv_output(
                Conv.make(self.planes, 1, self.stride, 0, self.dtype,
                          "down_conv")(x), self.fold_saves)
            x = apply_norm(make_norm(self.norm_fn, self.planes, name="norm3"), x)
        return nn.relu(x + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (extractor.py:64-120).

    Dead code in the reference (never instantiated) but part of its component
    inventory; kept for completeness and checkpoint compatibility.
    """

    in_planes: int
    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x):
        p4 = self.planes // 4
        y = Conv.make(p4, 1, 1, 0, self.dtype, "conv1")(x)
        y = nn.relu(apply_norm(make_norm(self.norm_fn, p4, name="norm1"), y))
        y = Conv.make(p4, 3, self.stride, 1, self.dtype, "conv2")(y)
        y = nn.relu(apply_norm(make_norm(self.norm_fn, p4, name="norm2"), y))
        y = Conv.make(self.planes, 1, 1, 0, self.dtype, "conv3")(y)
        y = nn.relu(apply_norm(make_norm(self.norm_fn, self.planes,
                                         name="norm3"), y))
        if self.stride != 1:
            x = Conv.make(self.planes, 1, self.stride, 0, self.dtype,
                          "down_conv")(x)
            x = apply_norm(make_norm(self.norm_fn, self.planes, name="norm4"), x)
        return nn.relu(x + y)
