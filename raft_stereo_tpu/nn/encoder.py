"""Feature and context encoders (core/extractor.py, re-designed NHWC/flax).

Layer naming mirrors the reference so the checkpoint converter is a pure
renaming: ``layer1_0`` = ``layer1.0`` etc. The ``downsample`` parameter sets
the stride pattern exactly as extractor.py:140-146: conv1 stride ``2 if
downsample>2 else 1``, layer2 ``2 if downsample>1``, layer3 ``2 if
downsample>0`` — so the finest feature scale is ``1/2**downsample``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn


from raft_stereo_tpu.nn.layers import (Conv, ResidualBlock, apply_norm,
                                       make_norm, save_conv_output)

Dtype = Any



class _Trunk(nn.Module):
    """Shared stem + layer1-3 trunk used by both encoders (extractor.py:140-146
    stride pattern): conv1 stride ``2 if downsample>2``, layer2 ``2 if
    downsample>1``, layer3 ``2 if downsample>0``.

    ``remat_blocks`` rematerializes each residual block in the backward pass
    (``nn.remat`` on the block class — parameter paths unchanged): only block
    INPUTS are saved, freeing the ~5 per-block full/half-resolution
    activation tensors at the cost of recomputing two convs per block — the
    middle ground between saving everything and recomputing both whole
    encoders (``remat_encoders=True``).
    """

    norm_fn: str
    downsample: int
    dtype: Optional[Dtype] = None
    remat_blocks: "bool | str" = False
    fold_saves: bool = False

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        fs = self.fold_saves

        # True: remat every trunk block. "hires": remat only the blocks that
        # RUN entirely at the post-stem (largest) resolution — their
        # internals are the ~10x saves; every later block's internals are
        # at reduced resolution and cost less to save than to recompute.
        # The first STRIDING block is deliberately excluded even though its
        # input is still post-stem-sized: its internals are already at the
        # next (halved) resolution, and saving them measured another +1%
        # over rematting it (PERF.md r4: 9.57 vs 9.48 pairs/s; rematting
        # layer1_0 alone is rejected by the compile helper — the measured
        # frontier). The set follows the stride pattern: layer2/layer3
        # stride only when downsample exceeds 1/0, so at small downsample
        # later blocks stay at post-stem resolution and join the set.
        remat_set = None
        if self.remat_blocks == "hires":
            remat_set = {"layer1_0", "layer1_1"}
            if self.downsample <= 1:      # layer2 does not stride
                remat_set |= {"layer2_0", "layer2_1"}
                if self.downsample == 0:  # layer3 does not stride either
                    remat_set |= {"layer3_0", "layer3_1"}

        if self.remat_blocks:
            # Remat each block with a LANE-DENSE boundary: jax.checkpoint
            # saves the wrapped function's inputs across the backward, and a
            # sub-128-channel full-resolution activation saved as-is is
            # padded 2x on the 128-lane tile (2x 900 MB for the fnet layer1
            # saves alone at SceneFlow b8 — r4 AOT breakdown). Folding W
            # into channels up to a 128 multiple makes the SAVED form
            # exactly lane-sized; the in-region unfold is a transient
            # relayout the backward recompute repeats.
            def _rb(in_planes, planes, stride, name):
                block = ResidualBlock(in_planes, planes, self.norm_fn,
                                      stride, d, fs, name=name)
                if remat_set is not None and name not in remat_set:
                    return block

                def apply_block(x):
                    b, h, w, c = x.shape
                    factor = 1
                    # Gated on fold_saves (config.fold_enc_saves): the fold
                    # trades saved-bytes lane padding for relayout copies,
                    # a win only when residual pressure is the binding
                    # constraint (see fold_enc_saves_auto's calibration).
                    if fs and c % 128:  # lane-sized saves gain nothing
                        for f in (2, 4):
                            if (c * f) % 128 == 0 and w % f == 0:
                                factor = f
                                break
                    if factor == 1:
                        return nn.remat(
                            lambda mdl, v: mdl(v))(block, x)
                    xf = x.reshape(b, h, w // factor, factor * c)
                    return nn.remat(
                        lambda mdl, v: mdl(v.reshape(b, h, w, c)))(block, xf)

                return apply_block
        else:
            def _rb(in_planes, planes, stride, name):
                return ResidualBlock(in_planes, planes, self.norm_fn, stride,
                                     d, fs, name=name)

        x = save_conv_output(
            Conv.make(64, 7, 1 + (self.downsample > 2), 3, d, "conv1")(x), fs)
        x = apply_norm(make_norm(self.norm_fn, 64, num_groups=8, name="norm1"), x)
        x = nn.relu(x)
        x = _rb(64, 64, 1, "layer1_0")(x)
        x = _rb(64, 64, 1, "layer1_1")(x)
        x = _rb(64, 96, 1 + (self.downsample > 1), "layer2_0")(x)
        x = _rb(96, 96, 1, "layer2_1")(x)
        x = _rb(96, 128, 1 + (self.downsample > 0), "layer3_0")(x)
        x = _rb(128, 128, 1, "layer3_1")(x)
        return x


class BasicEncoder(nn.Module):
    """ResNet-style feature encoder (extractor.py:122-197).

    7x7 stem + three 2-block residual stages (64 -> 96 -> 128) + 1x1 output
    conv. Used as the feature network (``fnet``) with instance norm and
    output_dim 256 (raft_stereo.py:39).
    """

    output_dim: int = 128
    norm_fn: str = "batch"
    downsample: int = 3
    dropout: float = 0.0
    dtype: Optional[Dtype] = None
    remat_blocks: "bool | str" = False
    fold_saves: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        d = self.dtype
        x = _Trunk(self.norm_fn, self.downsample, d, self.remat_blocks,
                   self.fold_saves, name="trunk")(x)

        x = save_conv_output(
            Conv.make(self.output_dim, 1, 1, 0, d, "conv2")(x),
            self.fold_saves)
        if train and self.dropout > 0:
            x = nn.Dropout(rate=self.dropout, deterministic=False)(x)
        return x


class MultiBasicEncoder(nn.Module):
    """Context encoder with multi-scale output heads (extractor.py:199-300).

    The trunk is BasicEncoder's plus two more stride-2 stages (layer4/layer5).
    Each entry of ``output_dim`` (a list of triples ordered coarse->fine, see
    config.hidden_dims) gets one output head per scale:

    * scale "08" (finest, ``1/2**downsample``): ResidualBlock + 3x3 conv to
      ``dim[2]`` channels,
    * scale "16": ResidualBlock + 3x3 conv to ``dim[1]``,
    * scale "32" (coarsest): a single 3x3 conv to ``dim[0]``.

    ``dual_inp=True`` runs the trunk on a doubled batch (left+right stacked)
    and feeds only the first half to the heads, returning the full trunk
    feature for the shared-backbone feature path (extractor.py:283-285).
    Returns ``(outputs08[, outputs16[, outputs32]][, trunk])`` where each
    ``outputsNN`` is a tuple with one tensor per output_dim entry.
    """

    output_dim: Sequence[Sequence[int]] = ((128,),)
    norm_fn: str = "batch"
    downsample: int = 3
    dropout: float = 0.0
    dtype: Optional[Dtype] = None
    remat_blocks: "bool | str" = False
    fold_saves: bool = False

    @nn.compact
    def __call__(self, x, *, dual_inp: bool = False, num_layers: int = 3,
                 train: bool = False):
        d = self.dtype
        x = _Trunk(self.norm_fn, self.downsample, d, self.remat_blocks,
                   self.fold_saves, name="trunk")(x)

        if dual_inp:
            trunk = x
            x = x[: x.shape[0] // 2]

        outputs08 = tuple(self._head(x, "08", i, dim[2], d, with_res=True)
                          for i, dim in enumerate(self.output_dim))
        if num_layers == 1:
            return (outputs08, trunk) if dual_inp else (outputs08,)

        y = ResidualBlock(128, 128, self.norm_fn, 2, d, name="layer4_0")(x)
        y = ResidualBlock(128, 128, self.norm_fn, 1, d, name="layer4_1")(y)
        outputs16 = tuple(self._head(y, "16", i, dim[1], d, with_res=True)
                          for i, dim in enumerate(self.output_dim))
        if num_layers == 2:
            return ((outputs08, outputs16, trunk) if dual_inp
                    else (outputs08, outputs16))

        z = ResidualBlock(128, 128, self.norm_fn, 2, d, name="layer5_0")(y)
        z = ResidualBlock(128, 128, self.norm_fn, 1, d, name="layer5_1")(z)
        outputs32 = tuple(self._head(z, "32", i, dim[0], d, with_res=False)
                          for i, dim in enumerate(self.output_dim))
        return ((outputs08, outputs16, outputs32, trunk) if dual_inp
                else (outputs08, outputs16, outputs32))

    def _head(self, x, scale: str, i: int, out_dim: int, d, *, with_res: bool):
        """Per-scale output head; the coarsest scale has no residual block
        (extractor.py:245-250)."""
        if with_res:
            x = ResidualBlock(128, 128, self.norm_fn, 1, d,
                              name=f"outputs{scale}_{i}_res")(x)
        return Conv.make(out_dim, 3, 1, 1, d, f"outputs{scale}_{i}_conv")(x)
