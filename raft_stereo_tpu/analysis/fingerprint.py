"""Compiled-executable fingerprints: the structural regression gate.

The r7/r8 evidence scripts (scan_wgrad_evidence, serial_floor,
alloc_breakdown) each proved a structural claim ONCE — the wgrad convs are
out of the backward loop, the collectives are the ones the sharding story
names, the peak residency is what the round banked. Nothing re-checked
those claims afterwards; a refactor could quietly undo any of them and the
numeric tests would stay green. This module distills every canonical
lowering (the PR-5 unsharded set from graph_rules.build_targets plus the
sharded set from spmd_rules.build_spmd_targets) into a small JSON
fingerprint and diffs HEAD against the checked-in baseline
(``.graftlint-fingerprint.json``):

* conv placement — ``conv_op_profile``: convs outside scans and per scan
  body (a rise in the last scan's per-step count = the weight-grad convs
  re-entered the backward loop);
* collectives — jaxpr kinds/counts split in-loop vs outside
  (``collective_profile``), plus the compiled post-partitioning kinds
  (``hlo_collective_profile``): a NEW collective kind or one moving into
  the loop is exactly the drift the SPMD rules exist for;
* peak bytes — ``memory_analysis`` of the compiled executable, gated by a
  relative threshold (default 10%);
* donation — declared flag + whether the executable actually aliases.

``cli lint --fingerprint`` runs the diff (drift becomes ordinary
error-severity findings, so the one gate/baseline/report machinery
applies); ``--update-fingerprint`` regenerates the baseline — the diff
review of that file IS the approval of a structural change.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from raft_stereo_tpu.analysis.findings import Finding

FINGERPRINT_VERSION = 1
DEFAULT_FINGERPRINT = ".graftlint-fingerprint.json"

#: relative peak-bytes growth tolerated before the gate trips
DEFAULT_PEAK_TOLERANCE = 0.10

RULE = "fingerprint-drift"
RULE_VERSIONS: Dict[str, int] = {RULE: 1}


def target_fingerprint(target) -> Dict[str, Any]:
    """Distill one Graph/Spmd target into its structural fingerprint."""
    from raft_stereo_tpu.obs.xla import (collective_profile,
                                         conv_op_profile,
                                         hlo_collective_profile,
                                         memory_analysis_dict)

    conv = conv_op_profile(target.closed_jaxpr)
    coll = collective_profile(target.closed_jaxpr)
    rec: Dict[str, Any] = {
        "convs": {"outside_scans": conv["outside_scans"],
                  "scans": [{"length": s["length"],
                             "convs_per_step": s["convs_per_step"]}
                            for s in conv["scans"]],
                  "total": conv["total"]},
        "collectives": {"by_kind": coll["by_kind"],
                        "in_loop": coll["in_loop"]},
    }
    compiled = getattr(target, "compiled", None)
    if compiled is not None:
        mem = memory_analysis_dict(compiled)
        if mem is not None:
            rec["peak_bytes"] = mem["peak_bytes"]
            rec["donation"] = {
                "declared": bool(getattr(target, "donate_declared", False)),
                "aliased": mem.get("alias_bytes", 0) > 0,
                "alias_bytes": mem.get("alias_bytes", 0),
            }
        hlo = None
        getter = getattr(target, "hlo_text", None)
        if callable(getter):
            hlo = getter()
        else:
            try:
                hlo = compiled.as_text()
            except Exception:
                hlo = None
        if hlo is not None:
            hprof = hlo_collective_profile(hlo)
            rec["hlo_collectives"] = {"by_kind": hprof["by_kind"],
                                      "in_loop": hprof["in_loop"]}
    return rec


def compute_fingerprint(targets) -> Dict[str, Any]:
    """Fingerprint doc over a target list (names must be unique)."""
    import jax

    return {
        "version": FINGERPRINT_VERSION,
        "meta": {"jax": jax.__version__,
                 "platform": jax.default_backend(),
                 "device_count": len(jax.devices())},
        "targets": {t.name: target_fingerprint(t) for t in targets},
    }


def load_fingerprint(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != FINGERPRINT_VERSION:
        raise ValueError(f"{path}: fingerprint version "
                         f"{doc.get('version')!r} != {FINGERPRINT_VERSION}")
    return doc


def write_fingerprint(path: str, doc: Dict[str, Any]) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def _err(loc: str, msg: str, **data) -> Finding:
    return Finding(rule=RULE, severity="error",
                   location=f"fingerprint/{loc}", message=msg, data=data)


def _warn(loc: str, msg: str, **data) -> Finding:
    return Finding(rule=RULE, severity="warning",
                   location=f"fingerprint/{loc}", message=msg, data=data)


def _info(loc: str, msg: str, **data) -> Finding:
    return Finding(rule=RULE, severity="info",
                   location=f"fingerprint/{loc}", message=msg, data=data)


def _diff_convs(name: str, base: Dict, cur: Dict) -> List[Finding]:
    out: List[Finding] = []
    if base["outside_scans"] != cur["outside_scans"]:
        out.append(_err(
            f"{name}/convs",
            f"convs outside scans moved {base['outside_scans']} -> "
            f"{cur['outside_scans']} — op placement changed",
            baseline=base["outside_scans"], current=cur["outside_scans"]))
    if len(base["scans"]) != len(cur["scans"]):
        out.append(_err(
            f"{name}/convs",
            f"scan count changed {len(base['scans'])} -> "
            f"{len(cur['scans'])} — the loop structure itself moved",
            baseline=len(base["scans"]), current=len(cur["scans"])))
        return out
    for i, (b, c) in enumerate(zip(base["scans"], cur["scans"])):
        if b["convs_per_step"] != c["convs_per_step"]:
            last = i == len(base["scans"]) - 1
            extra = (" — the weight-grad convs re-entered the backward "
                     "loop body" if last
                     and c["convs_per_step"] > b["convs_per_step"] else "")
            out.append(_err(
                f"{name}/convs/scan[{i}]",
                f"convs per scan step moved {b['convs_per_step']} -> "
                f"{c['convs_per_step']}{extra}",
                baseline=b["convs_per_step"], current=c["convs_per_step"]))
        if b["length"] != c["length"]:
            out.append(_warn(
                f"{name}/convs/scan[{i}]",
                f"scan length moved {b['length']} -> {c['length']}",
                baseline=b["length"], current=c["length"]))
    return out


def _diff_collectives(name: str, kind: str, base: Dict, cur: Dict,
                      hlo: bool = False) -> List[Finding]:
    out: List[Finding] = []
    for k in sorted(set(cur["by_kind"]) - set(base["by_kind"])):
        out.append(_err(
            f"{name}/{kind}",
            f"NEW collective `{k}` (x{cur['by_kind'][k]}) not in the "
            f"baseline — the sharding structure grew a reduction/exchange "
            f"the contract never named",
            collective=k, count=cur["by_kind"][k]))
    for k in sorted(set(base["by_kind"]) - set(cur["by_kind"])):
        out.append(_warn(
            f"{name}/{kind}",
            f"collective `{k}` (baseline x{base['by_kind'][k]}) "
            f"disappeared — if intentional, --update-fingerprint",
            collective=k))
    for k in sorted(set(base["by_kind"]) & set(cur["by_kind"])):
        if base["by_kind"][k] != cur["by_kind"][k]:
            mk = _warn if hlo else _err
            out.append(mk(
                f"{name}/{kind}/{k}",
                f"`{k}` count moved {base['by_kind'][k]} -> "
                f"{cur['by_kind'][k]}",
                baseline=base["by_kind"][k], current=cur["by_kind"][k]))
    for k in sorted(set(cur["in_loop"]) - set(base["in_loop"])):
        out.append(_err(
            f"{name}/{kind}/in-loop",
            f"collective `{k}` MOVED INTO a loop body "
            f"(x{cur['in_loop'][k]} per iteration; baseline ran it only "
            f"outside) — per-iteration ICI traffic on the serial chain",
            collective=k, count=cur["in_loop"][k]))
    for k in sorted(set(base["in_loop"]) & set(cur["in_loop"])):
        if base["in_loop"][k] != cur["in_loop"][k]:
            mk = _warn if hlo else _err
            out.append(mk(
                f"{name}/{kind}/in-loop/{k}",
                f"in-loop `{k}` count moved {base['in_loop'][k]} -> "
                f"{cur['in_loop'][k]}",
                baseline=base["in_loop"][k], current=cur["in_loop"][k]))
    return out


def diff_fingerprint(baseline: Dict[str, Any], current: Dict[str, Any],
                     peak_tolerance: float = DEFAULT_PEAK_TOLERANCE,
                     partial: bool = False) -> List[Finding]:
    """Structural drift between two fingerprint docs, as findings.

    ``partial=True`` means the current doc was computed from a subset of
    the canonical targets (an engine was deselected or compilation was
    skipped): baseline-only targets/fields are then skipped, not failed.
    Full runs treat a missing target or field as drift — "nothing to
    compare" must never read as "no regression".
    """
    out: List[Finding] = []
    bmeta, cmeta = baseline.get("meta", {}), current.get("meta", {})
    if bmeta.get("jax") != cmeta.get("jax"):
        out.append(_info(
            "meta", f"baseline was written under jax {bmeta.get('jax')!r}, "
                    f"running {cmeta.get('jax')!r} — op counts may shift "
                    f"legitimately; regenerate if the diff is noise",
            baseline=bmeta.get("jax"), current=cmeta.get("jax")))
    btargets = baseline.get("targets", {})
    ctargets = current.get("targets", {})
    for name in sorted(set(ctargets) - set(btargets)):
        out.append(_err(
            name, "target not in the baseline — regenerate with "
                  "--update-fingerprint to adopt it",
            target=name))
    for name in sorted(set(btargets) - set(ctargets)):
        if not partial:
            out.append(_err(
                name, "canonical target missing from the current build — "
                      "a lowering was dropped or failed",
                target=name))
    for name in sorted(set(btargets) & set(ctargets)):
        b, c = btargets[name], ctargets[name]
        out.extend(_diff_convs(name, b["convs"], c["convs"]))
        out.extend(_diff_collectives(name, "collectives",
                                     b["collectives"], c["collectives"]))
        for field in ("hlo_collectives", "peak_bytes", "donation"):
            if field in b and field not in c:
                if not partial:
                    out.append(_err(
                        f"{name}/{field}",
                        f"baseline records `{field}` but the current build "
                        f"did not produce it (compile skipped?)",
                        field=field))
                continue
        if "hlo_collectives" in b and "hlo_collectives" in c:
            out.extend(_diff_collectives(name, "hlo_collectives",
                                         b["hlo_collectives"],
                                         c["hlo_collectives"], hlo=True))
        if "peak_bytes" in b and "peak_bytes" in c:
            pb, pc = b["peak_bytes"], c["peak_bytes"]
            rel = (pc - pb) / pb if pb else 0.0
            if rel > peak_tolerance:
                out.append(_err(
                    f"{name}/peak_bytes",
                    f"executable peak bytes jumped {pb} -> {pc} "
                    f"(+{100 * rel:.1f}% > {100 * peak_tolerance:.0f}% "
                    f"threshold)",
                    baseline=pb, current=pc, rel=round(rel, 4)))
            elif rel < -peak_tolerance:
                out.append(_info(
                    f"{name}/peak_bytes",
                    f"peak bytes improved {pb} -> {pc} "
                    f"({100 * rel:.1f}%) — bank it with "
                    f"--update-fingerprint",
                    baseline=pb, current=pc, rel=round(rel, 4)))
        if "donation" in b and "donation" in c:
            db, dc = b["donation"], c["donation"]
            if db["declared"] != dc["declared"] \
                    or db["aliased"] != dc["aliased"]:
                out.append(_err(
                    f"{name}/donation",
                    f"donation pairing changed: declared "
                    f"{db['declared']}->{dc['declared']}, aliased "
                    f"{db['aliased']}->{dc['aliased']} — the state's "
                    f"double-buffering contract moved",
                    baseline=db, current=dc))
    return out


def fingerprint_baseline_path(repo_root: str) -> str:
    return os.path.join(repo_root, DEFAULT_FINGERPRINT)
