"""Concurrency lint (graftlint engine 4) over the threaded host runtime.

Engines 1-3 gate the *compiled* programs; this engine gates the host
threads around them — the serve scheduler, HTTP front, SLO tracker,
loader producer, stall watchdog, heartbeat daemons, flight recorder and
signal handlers. It has three parts:

1. A **thread-topology extractor** over the package AST: every
   ``threading.Thread(target=...)``, ``ThreadPoolExecutor.submit``
   callback and ``signal.signal`` handler becomes a *thread entry*; every
   ``self._lock``-style attribute (``Lock``/``RLock``/``Condition``) a
   *lock object*. Each entry is walked through its statically-resolvable
   call closure (``self.m()``, ``self.attr.m()`` where the attribute's
   class is known, module-level and nested functions) carrying the set of
   locks held at each point, producing per-entry reachable functions,
   attributes read/written (with the locks guaranteed held at each write)
   and the static lock-acquisition-order graph from nested ``with lock:``
   scopes. The map is checked in as ``.graftlint-threads.json`` and
   ``cli lint --fingerprint`` diffs it like the executable fingerprint:
   a new thread entry, a new shared attribute or a lock dropped from a
   path is gated drift until re-banked with ``--update-fingerprint``.

2. **Declarative rules** over that topology (all error severity; the
   suppression baseline with its ``rule_version`` stamp is the vetting
   mechanism for the deliberate exceptions):

   * ``shared-write-unlocked`` — an attribute written from >=2 entries
     with no common lock guaranteed held on at least one write path.
   * ``lock-order-cycle`` — a cycle in the static acquisition-order
     graph (lock B acquired while holding A and vice versa).
   * ``cond-wait-no-predicate`` — ``Condition.wait`` outside a ``while``
     loop (wakeups are advisory; the predicate must be re-checked).
   * ``signal-handler-unsafe`` — a signal handler that reachably does
     I/O, acquires a lock or emits events; the async-signal-safe pattern
     is flag/Event set only.
   * ``daemon-no-join`` — a ``daemon=True`` thread whose owning scope
     has neither a ``.join(...)`` call nor a stop-``Event.set()`` on any
     path (no drain story at all).
   * ``queue-timeout-discipline`` — blocking ``get()``/``put(x)``
     without a timeout inside a loop in a function that is *not* a
     daemon-thread target (a wedged producer then hangs the process
     forever instead of failing loud).

3. A **dynamic lock-order witness** (obs/lockwitness.py records actual
   acquisition orders during the serve/fleet drills): ``check_witness``
   fails when a witnessed edge contradicts the static order graph or
   closes a cycle the static pass missed (rule ``lock-order-witness``).

Static boundaries (documented, not silent): reachability follows
``self.m()``, ``self.<attr>.m()`` when ``self.<attr> = KnownClass(...)``
is visible in the package, bare calls to module-level and sibling nested
functions — not arbitrary aliases, higher-order dispatch or cross-process
hops. Lock holding is modelled from ``with lock:`` scopes only; bare
``.acquire()`` records an ordering edge but not a held region. ``queue``
objects and ``threading.Event`` are synchronizers, not shared state.
Constructor writes (``__init__``/``__post_init__``) happen-before thread
start and are excluded from the race analysis.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

from raft_stereo_tpu.analysis.findings import Finding

TOPOLOGY_VERSION = 1

#: current semantic version per rule (baseline entries record the version
#: they suppress; a bump flags them stale — findings.apply_baseline).
RULE_VERSIONS: Dict[str, int] = {
    "shared-write-unlocked": 1,
    "lock-order-cycle": 1,
    "cond-wait-no-predicate": 1,
    "signal-handler-unsafe": 1,
    "daemon-no-join": 1,
    "queue-timeout-discipline": 1,
    "thread-topology-drift": 1,
    "lock-order-witness": 1,
}

CONCURRENCY_RULES = tuple(RULE_VERSIONS)

_LOCK_FACTORIES = {"Lock": "Lock", "RLock": "RLock",
                   "Condition": "Condition"}
_EVENT_FACTORIES = {"Event", "Barrier", "Semaphore", "BoundedSemaphore"}
_QUEUE_FACTORIES = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                    "BoundedQueue"}

#: ``self.x.<mutator>(...)`` counts as a write to ``x`` (in-place
#: mutation of a shared container).
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear",
})

#: call names that make a signal handler unsafe (I/O, locking, event
#: emission). ``Event.set`` / plain flag stores are the vetted pattern.
_HANDLER_EFFECTS = frozenset({
    "print", "open", "write", "flush", "emit", "log", "warning", "info",
    "error", "debug", "exception", "acquire", "join", "dump", "put",
    "get", "notify", "notify_all",
})

_CTOR_METHODS = frozenset({"__init__", "__post_init__"})

_MAX_WALK_DEPTH = 64


def _attr_chain(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _last_attr(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _factory_kind(value: ast.AST, table: Dict[str, str]
                  ) -> Optional[Tuple[str, ast.Call]]:
    """('Lock'|'RLock'|'Condition', call node) when ``value`` constructs
    one — ``threading.Lock()`` or bare ``Lock()``."""
    if not isinstance(value, ast.Call):
        return None
    chain = _attr_chain(value.func)
    if not chain:
        return None
    if chain[-1] in table and (len(chain) == 1 or chain[0] == "threading"):
        return table[chain[-1]], value
    return None


def _is_factory(value: ast.AST, names: FrozenSet[str] | set) -> bool:
    if not isinstance(value, ast.Call):
        return False
    chain = _attr_chain(value.func)
    return bool(chain) and chain[-1] in names


# --------------------------------------------------------------- indexing

class _ClassInfo:
    def __init__(self, rel: str, name: str) -> None:
        self.rel = rel
        self.name = name
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.locks: Dict[str, str] = {}        # attr -> kind
        self.lock_alias: Dict[str, str] = {}   # Condition attr -> base attr
        self.conds: Set[str] = set()
        self.events: Set[str] = set()
        self.queues: Set[str] = set()
        self.attr_classes: Dict[str, str] = {}  # attr -> class name

    def canonical_lock(self, attr: str) -> Optional[str]:
        if attr in self.lock_alias:
            attr = self.lock_alias[attr]
        if attr in self.locks:
            return f"{self.rel}::{self.name}.{attr}"
        return None


class _ModuleInfo:
    def __init__(self, rel: str, tree: ast.Module) -> None:
        self.rel = rel
        self.tree = tree
        self.classes: Dict[str, _ClassInfo] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.mod_locks: Dict[str, str] = {}
        self.mod_lock_alias: Dict[str, str] = {}
        self.mod_conds: Set[str] = set()
        self.mod_events: Set[str] = set()
        self.mod_queues: Set[str] = set()

    def canonical_mod_lock(self, name: str) -> Optional[str]:
        if name in self.mod_lock_alias:
            name = self.mod_lock_alias[name]
        if name in self.mod_locks:
            return f"{self.rel}::{name}"
        return None


class _FuncScope:
    """Locals of one top-level function/method scope (shared, via closure,
    with its nested defs): locks, events, queues, bound names. ``shared``
    marks scopes a thread entry actually closes over — only those locals
    participate in the shared-state analysis (other functions' locals are
    thread-private)."""

    def __init__(self, rel: str, qual: str) -> None:
        self.rel = rel
        self.qual = qual
        self.shared = False
        self.locks: Dict[str, str] = {}
        self.lock_alias: Dict[str, str] = {}
        self.conds: Set[str] = set()
        self.events: Set[str] = set()
        self.queues: Set[str] = set()
        self.bound: Set[str] = set()

    def canonical_lock(self, name: str) -> Optional[str]:
        if name in self.lock_alias:
            name = self.lock_alias[name]
        if name in self.locks:
            return f"{self.rel}::{self.qual}.{name}"
        return None


def _index_sync_assign(target_attr: str, value: ast.AST, locks: Dict,
                       alias: Dict, conds: Set, events: Set, queues: Set
                       ) -> bool:
    """Classify one ``<target> = <value>`` against the synchronizer
    factories; returns True when it was a synchronizer binding."""
    found = _factory_kind(value, _LOCK_FACTORIES)
    if found:
        kind, call = found
        if kind == "Condition":
            conds.add(target_attr)
            base = None
            if call.args:
                a0 = call.args[0]
                if isinstance(a0, ast.Attribute) \
                        and isinstance(a0.value, ast.Name) \
                        and a0.value.id == "self":
                    base = a0.attr
                elif isinstance(a0, ast.Name):
                    base = a0.id
            if base is not None and base in locks:
                alias[target_attr] = base
                return True
        locks[target_attr] = kind
        return True
    if _is_factory(value, _EVENT_FACTORIES):
        events.add(target_attr)
        return True
    if _is_factory(value, _QUEUE_FACTORIES):
        queues.add(target_attr)
        return True
    return False


def _index_module(rel: str, tree: ast.Module) -> _ModuleInfo:
    mi = _ModuleInfo(rel, tree)
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mi.functions[node.name] = node
        elif isinstance(node, ast.ClassDef):
            ci = _ClassInfo(rel, node.name)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[sub.name] = sub
            for meth in ci.methods.values():
                for stmt in ast.walk(meth):
                    if isinstance(stmt, ast.Assign) \
                            and len(stmt.targets) == 1:
                        t, v = stmt.targets[0], stmt.value
                    elif isinstance(stmt, ast.AnnAssign) \
                            and stmt.value is not None:
                        t, v = stmt.target, stmt.value
                    else:
                        continue
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        if not _index_sync_assign(
                                t.attr, v, ci.locks,
                                ci.lock_alias, ci.conds, ci.events,
                                ci.queues):
                            if isinstance(v, ast.Call):
                                cn = _last_attr(v.func)
                                if cn and cn[:1].isupper():
                                    ci.attr_classes[t.attr] = cn
            mi.classes[node.name] = ci
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            _index_sync_assign(node.targets[0].id, node.value,
                               mi.mod_locks, mi.mod_lock_alias,
                               mi.mod_conds, mi.mod_events, mi.mod_queues)
    return mi


def _func_scope(rel: str, qual: str, fn: ast.AST) -> _FuncScope:
    sc = _FuncScope(rel, qual)
    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            t, v = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and isinstance(stmt.target, ast.Name):
            t, v = stmt.target, stmt.value
        else:
            continue
        sc.bound.add(t.id)
        _index_sync_assign(t.id, v, sc.locks, sc.lock_alias,
                           sc.conds, sc.events, sc.queues)
    return sc


class _Index:
    """All modules under the package root, plus a global class registry
    (class names are unique enough in this package; first wins)."""

    def __init__(self, package_root: str, repo_root: str) -> None:
        self.modules: Dict[str, _ModuleInfo] = {}
        self.class_registry: Dict[str, _ClassInfo] = {}
        for dirpath, dirnames, filenames in os.walk(package_root):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, repo_root)
                try:
                    with open(path) as f:
                        tree = ast.parse(f.read(), filename=rel)
                except (OSError, SyntaxError):
                    continue
                mi = _index_module(rel, tree)
                self.modules[rel] = mi
                for cname, ci in mi.classes.items():
                    self.class_registry.setdefault(cname, ci)


# --------------------------------------------------------- entry discovery

class _Entry:
    def __init__(self, entry_id: str, kind: str, rel: str, target: str,
                 daemon: bool, line: int,
                 body: Optional[ast.AST] = None,
                 cls: Optional[_ClassInfo] = None,
                 scope: Optional[_FuncScope] = None,
                 owner: Optional[str] = None) -> None:
        self.id = entry_id
        self.kind = kind          # thread | executor | signal | callers
        self.rel = rel
        self.target = target
        self.daemon = daemon
        self.line = line
        self.body = body          # FunctionDef/Lambda to walk (None: external)
        self.cls = cls            # class context for self.*
        self.scope = scope        # closure scope for Name locals
        self.owner = owner        # scope key for callers grouping
        # walk results
        self.reachable: Set[str] = set()
        self.locks: Set[str] = set()
        self.edges: Set[Tuple[str, str]] = set()
        self.reads: Dict[str, List[FrozenSet[str]]] = {}
        self.writes: Dict[str, List[FrozenSet[str]]] = {}
        self.effects: List[str] = []


def _creation_sites(mi: _ModuleInfo) -> List[dict]:
    """Every Thread()/submit()/signal.signal() in the module with its
    enclosing (class, method-or-function, nested-def) context."""
    sites: List[dict] = []

    def scan(fn: ast.AST, cls: Optional[str], qual: str,
             encl: Optional[str]) -> None:
        nested = {n.name: n for n in ast.walk(fn)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n is not fn}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain and chain[-1] == "Thread" \
                    and (len(chain) == 1 or chain[0] == "threading"):
                kw = {k.arg: k.value for k in node.keywords}
                target = kw.get("target")
                daemon = kw.get("daemon")
                sites.append({
                    "kind": "thread", "cls": cls, "qual": qual,
                    "nested": nested, "target": target,
                    "daemon": bool(getattr(daemon, "value", False)),
                    "line": node.lineno})
            elif chain and chain[-1] == "submit" and len(chain) >= 2 \
                    and node.args:
                sites.append({
                    "kind": "executor", "cls": cls, "qual": qual,
                    "nested": nested, "target": node.args[0],
                    "daemon": False, "line": node.lineno})
            elif chain == ["signal", "signal"] and len(node.args) >= 2:
                sites.append({
                    "kind": "signal", "cls": cls, "qual": qual,
                    "nested": nested, "target": node.args[1],
                    "daemon": False, "line": node.lineno})

    for cname, ci in mi.classes.items():
        for mname, meth in ci.methods.items():
            scan(meth, cname, f"{cname}.{mname}", None)
    for fname, fn in mi.functions.items():
        scan(fn, None, fname, None)
    return sites


def _discover_entries(index: _Index) -> List[_Entry]:
    entries: List[_Entry] = []
    seen: Set[str] = set()
    for rel, mi in sorted(index.modules.items()):
        for site in _creation_sites(mi):
            target = site["target"]
            if target is None:
                continue
            cls = mi.classes.get(site["cls"]) if site["cls"] else None
            body: Optional[ast.AST] = None
            tqual = None
            scope: Optional[_FuncScope] = None
            owner = f"{rel}::{site['cls'] or site['qual']}"
            if isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self" and cls is not None \
                    and target.attr in cls.methods:
                body = cls.methods[target.attr]
                tqual = f"{cls.name}.{target.attr}"
            elif isinstance(target, ast.Name):
                if target.id in site["nested"]:
                    body = site["nested"][target.id]
                    tqual = f"{site['qual']}.{target.id}"
                    scope = _func_scope(
                        rel, site["qual"],
                        (cls.methods[site["qual"].split(".", 1)[1]]
                         if cls is not None else
                         mi.functions[site["qual"]]))
                elif target.id in mi.functions:
                    body = mi.functions[target.id]
                    tqual = target.id
            elif isinstance(target, ast.Lambda):
                body = target
                tqual = f"{site['qual']}.<lambda>L{target.lineno}"
            if tqual is None:
                # external target (httpd.serve_forever): still an entry,
                # no walkable body
                tqual = ".".join(_attr_chain(target)) or "<unresolved>"
            entry_id = f"{rel}::{tqual}[{site['kind']}]"
            if entry_id in seen:
                continue
            seen.add(entry_id)
            entries.append(_Entry(
                entry_id, site["kind"], rel, tqual, site["daemon"],
                site["line"], body=body, cls=cls, scope=scope,
                owner=owner))

    # callers pseudo-entry per owner scope with >=1 real entry: the code
    # that runs on *other* threads against the same state (all methods of
    # the owning class that are not thread targets and not construction;
    # or the spawning function's own body)
    by_owner: Dict[str, List[_Entry]] = {}
    for e in entries:
        if e.body is not None:
            by_owner.setdefault(e.owner, []).append(e)
    for owner, owned in sorted(by_owner.items()):
        rel = owned[0].rel
        mi = index.modules[rel]
        name = owner.split("::", 1)[1]
        target_names = {e.target for e in owned}
        if name in mi.classes:
            ci = mi.classes[name]
            roots = [(f"{name}.{m}", fn) for m, fn in
                     sorted(ci.methods.items())
                     if m not in _CTOR_METHODS
                     and f"{name}.{m}" not in target_names]
            if not roots:
                continue
            ce = _Entry(f"{rel}::{name}[callers]", "callers", rel, name,
                        False, 0, cls=ci, owner=owner)
            ce.roots = roots  # type: ignore[attr-defined]
            entries.append(ce)
        elif name in mi.functions:
            fn = mi.functions[name]
            ce = _Entry(f"{rel}::{name}[callers]", "callers", rel, name,
                        False, fn.lineno, body=fn, cls=None,
                        scope=_func_scope(rel, name, fn), owner=owner)
            ce.root_is_spawner = True  # type: ignore[attr-defined]
            entries.append(ce)
    return entries


# ------------------------------------------------------------ entry walks

class _Walker:
    """Walk one entry's call closure carrying the held-lock set."""

    def __init__(self, index: _Index, entry: _Entry) -> None:
        self.index = index
        self.entry = entry
        self.visited: Set[Tuple[int, FrozenSet[str]]] = set()
        self.wait_sites: List[Tuple[str, int, bool]] = []
        self.queue_sites: List[Tuple[str, str, int, bool]] = []

    # -- resolution -------------------------------------------------------

    def _resolve_lock(self, expr: ast.AST, ci: Optional[_ClassInfo],
                      sc: Optional[_FuncScope], mi: _ModuleInfo
                      ) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and ci is not None:
            return ci.canonical_lock(expr.attr)
        if isinstance(expr, ast.Name):
            if sc is not None:
                lid = sc.canonical_lock(expr.id)
                if lid:
                    return lid
            return mi.canonical_mod_lock(expr.id)
        return None

    def _is_cond(self, expr: ast.AST, ci: Optional[_ClassInfo],
                 sc: Optional[_FuncScope], mi: _ModuleInfo) -> bool:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and ci is not None:
            return expr.attr in ci.conds
        if isinstance(expr, ast.Name):
            return (sc is not None and expr.id in sc.conds) \
                or expr.id in mi.mod_conds
        return False

    def _is_queue(self, expr: ast.AST, ci: Optional[_ClassInfo],
                  sc: Optional[_FuncScope], mi: _ModuleInfo) -> bool:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and ci is not None:
            return expr.attr in ci.queues
        if isinstance(expr, ast.Name):
            return (sc is not None and expr.id in sc.queues) \
                or expr.id in mi.mod_queues
        return False

    # -- recording --------------------------------------------------------

    def _record_access(self, space_attr: str, write: bool,
                       held: FrozenSet[str]) -> None:
        book = self.entry.writes if write else self.entry.reads
        book.setdefault(space_attr, []).append(held)

    def _acquire(self, lock_id: str, held: FrozenSet[str]) -> None:
        self.entry.locks.add(lock_id)
        if self.entry.kind == "signal":
            self.entry.effects.append(f"acquire {lock_id}")
        for h in held:
            if h != lock_id:
                self.entry.edges.add((h, lock_id))

    # -- the walk ---------------------------------------------------------

    def walk(self, fn: ast.AST, qual: str, ci: Optional[_ClassInfo],
             sc: Optional[_FuncScope], mi: _ModuleInfo,
             held: FrozenSet[str], depth: int = 0,
             constructing: bool = False) -> None:
        key = (id(fn), held)
        if key in self.visited or depth > _MAX_WALK_DEPTH:
            return
        self.visited.add(key)
        self.entry.reachable.add(qual)
        nested = {n.name: n for n in ast.walk(fn)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n is not fn}
        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        nonlocals: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Nonlocal):
                nonlocals.update(node.names)

        spawner_root = getattr(self.entry, "root_is_spawner", False) \
            and depth == 0

        def visit(node: ast.AST, held: FrozenSet[str],
                  in_while: bool, in_loop: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return  # only entered via call edges
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in node.items:
                    lid = self._resolve_lock(item.context_expr, ci, sc, mi)
                    if lid is not None:
                        self._acquire(lid, new_held)
                        new_held = new_held | {lid}
                    else:
                        visit(item.context_expr, held, in_while, in_loop)
                for stmt in node.body:
                    visit(stmt, new_held, in_while, in_loop)
                return
            if isinstance(node, ast.While):
                for child in ast.iter_child_nodes(node):
                    visit(child, held, True, True)
                return
            if isinstance(node, (ast.For, ast.AsyncFor)):
                for child in ast.iter_child_nodes(node):
                    visit(child, held, in_while, True)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    self._store(t, held, qual, ci, sc, mi, nonlocals,
                                spawner_root, constructing)
                visit(node.value, held, in_while, in_loop)
                return
            if isinstance(node, ast.Call):
                self._call(node, held, qual, ci, sc, mi, nested, depth,
                           in_while, in_loop, constructing)
                for child in ast.iter_child_nodes(node):
                    visit(child, held, in_while, in_loop)
                return
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" and ci is not None \
                    and isinstance(node.ctx, ast.Load):
                if node.attr not in ci.locks and node.attr not in ci.conds \
                        and node.attr not in ci.events \
                        and node.attr not in ci.queues \
                        and node.attr not in ci.attr_classes \
                        and node.attr not in ci.methods:
                    self._record_access(f"{ci.rel}::{ci.name}.{node.attr}",
                                        False, held)
                return
            if isinstance(node, ast.Name) and sc is not None \
                    and sc.shared and isinstance(node.ctx, ast.Load) \
                    and node.id in sc.bound \
                    and node.id not in sc.locks \
                    and node.id not in sc.conds \
                    and node.id not in sc.events \
                    and node.id not in sc.queues:
                self._record_access(f"{sc.rel}::{sc.qual}.{node.id}",
                                    False, held)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held, in_while, in_loop)

        for stmt in body:
            visit(stmt, held, False, False)

    def _store(self, t: ast.AST, held: FrozenSet[str], qual: str,
               ci: Optional[_ClassInfo], sc: Optional[_FuncScope],
               mi: _ModuleInfo, nonlocals: Set[str],
               spawner_root: bool, constructing: bool) -> None:
        if constructing:
            return
        base = t
        subscript = False
        while isinstance(base, ast.Subscript):
            base = base.value
            subscript = True
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and ci is not None:
            if base.attr in ci.locks or base.attr in ci.conds \
                    or base.attr in ci.events:
                return
            self._record_access(f"{ci.rel}::{ci.name}.{base.attr}",
                                True, held)
        elif isinstance(base, ast.Name) and sc is not None \
                and sc.shared and base.id in sc.bound:
            if base.id in sc.locks or base.id in sc.conds \
                    or base.id in sc.events:
                return
            # in the spawning function's own body a plain rebinding is
            # (re)creation, which happens-before/after the threads via
            # start/join; mutations of the shared object still count
            if spawner_root and not subscript \
                    and not isinstance(t, ast.Subscript):
                return
            if not subscript and not spawner_root \
                    and base.id not in nonlocals:
                return  # plain Name store in a thread body = new local
            self._record_access(f"{sc.rel}::{sc.qual}.{base.id}",
                                True, held)

    def _call(self, node: ast.Call, held: FrozenSet[str], qual: str,
              ci: Optional[_ClassInfo], sc: Optional[_FuncScope],
              mi: _ModuleInfo, nested: Dict[str, ast.AST], depth: int,
              in_while: bool, in_loop: bool, constructing: bool) -> None:
        fname = _last_attr(node.func)
        recv = node.func.value if isinstance(node.func, ast.Attribute) \
            else None

        # explicit acquire: an ordering edge, not a tracked held region
        if fname == "acquire" and recv is not None:
            lid = self._resolve_lock(recv, ci, sc, mi)
            if lid is not None:
                self._acquire(lid, held)
                return
        # Condition.wait outside a while loop
        if fname == "wait" and recv is not None \
                and self._is_cond(recv, ci, sc, mi):
            self.wait_sites.append((f"{mi.rel}::{qual}", node.lineno,
                                    in_while))
        # blocking queue ops without timeout, inside a loop
        if fname in ("get", "put") and recv is not None \
                and self._is_queue(recv, ci, sc, mi):
            has_timeout = any(k.arg == "timeout" for k in node.keywords) \
                or (fname == "get" and len(node.args) >= 1) \
                or (fname == "put" and len(node.args) >= 2)
            if not has_timeout and in_loop:
                self.queue_sites.append((f"{mi.rel}::{qual}", fname,
                                         node.lineno, in_loop))
        # mutator call on shared state = write
        if fname in _MUTATORS and recv is not None:
            self._store_recv(recv, held, ci, sc, constructing)
        # signal-handler effects
        if self.entry.kind == "signal" and fname in _HANDLER_EFFECTS:
            self.entry.effects.append(
                f"{fname}() at {mi.rel}:{node.lineno}")
        if self.entry.kind == "signal" and isinstance(node.func, ast.Name) \
                and node.func.id in ("print", "open"):
            self.entry.effects.append(
                f"{node.func.id}() at {mi.rel}:{node.lineno}")

        # call edges
        callee: Optional[Tuple[ast.AST, str, Optional[_ClassInfo],
                               Optional[_FuncScope], _ModuleInfo]] = None
        if isinstance(node.func, ast.Attribute):
            v = node.func.value
            if isinstance(v, ast.Name) and v.id == "self" \
                    and ci is not None and fname in ci.methods:
                callee = (ci.methods[fname], f"{ci.name}.{fname}", ci, sc,
                          self.index.modules[ci.rel])
            elif isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self" and ci is not None:
                # self.<attr>.<m>() with a known attribute class
                cname = ci.attr_classes.get(v.attr)
                tci = self.index.class_registry.get(cname) if cname else None
                if tci is not None and fname in tci.methods:
                    callee = (tci.methods[fname],
                              f"{tci.name}.{fname}", tci, None,
                              self.index.modules[tci.rel])
        elif isinstance(node.func, ast.Name):
            if node.func.id in nested:
                callee = (nested[node.func.id],
                          f"{qual}.{node.func.id}", ci, sc, mi)
            elif node.func.id in mi.functions:
                callee = (mi.functions[node.func.id], node.func.id,
                          None, None, mi)
        if callee is not None:
            cfn, cqual, cci, csc, cmi = callee
            self.walk(cfn, cqual, cci, csc, cmi, held, depth + 1,
                      constructing=constructing
                      or cqual.split(".")[-1] in _CTOR_METHODS)

    def _store_recv(self, recv: ast.AST, held: FrozenSet[str],
                    ci: Optional[_ClassInfo], sc: Optional[_FuncScope],
                    constructing: bool) -> None:
        if constructing:
            return
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self" and ci is not None:
            if recv.attr in ci.locks or recv.attr in ci.conds \
                    or recv.attr in ci.events or recv.attr in ci.queues:
                return
            self._record_access(f"{ci.rel}::{ci.name}.{recv.attr}",
                                True, held)
        elif isinstance(recv, ast.Name) and sc is not None \
                and recv.id in sc.bound:
            if recv.id in sc.locks or recv.id in sc.conds \
                    or recv.id in sc.events or recv.id in sc.queues:
                return
            self._record_access(f"{sc.rel}::{sc.qual}.{recv.id}",
                                True, held)


def _walk_entries(index: _Index, entries: List[_Entry]
                  ) -> Tuple[List[_Walker], Set[str]]:
    walkers: List[_Walker] = []
    daemon_targets: Set[str] = set()
    # scopes a thread entry closes over are shared; a root whose qual
    # matches one reuses it so caller-side and thread-side accesses land
    # in the same space
    shared_scopes: Dict[Tuple[str, str], _FuncScope] = {}
    for e in entries:
        if e.scope is not None:
            e.scope.shared = True
            shared_scopes[(e.rel, e.scope.qual)] = e.scope
    for e in entries:
        if e.daemon and e.body is not None:
            daemon_targets.add(f"{e.rel}::{e.target}")
        w = _Walker(index, e)
        mi = index.modules[e.rel]
        if e.body is not None:
            sc = e.scope
            if sc is None:
                sc = shared_scopes.get((e.rel, e.target)) \
                    or _func_scope(e.rel, e.target, e.body)
            w.walk(e.body, e.target, e.cls, sc, mi, frozenset())
        for root_qual, root_fn in getattr(e, "roots", []):
            sc = shared_scopes.get((e.rel, root_qual)) \
                or _func_scope(e.rel, root_qual, root_fn)
            w.walk(root_fn, root_qual, e.cls, sc, mi, frozenset())
        walkers.append(w)
    return walkers, daemon_targets


# ------------------------------------------------------------- the rules

def _guard(helds: List[FrozenSet[str]]) -> FrozenSet[str]:
    """Locks guaranteed held across every one of these access sites."""
    out: Optional[FrozenSet[str]] = None
    for h in helds:
        out = h if out is None else out & h
    return out if out is not None else frozenset()


def _shared_map(entries: List[_Entry]) -> Dict[str, dict]:
    """attr -> {writers, readers, common_locks} for every attribute
    touched by >=2 entries with at least one writer."""
    writers: Dict[str, Dict[str, List[FrozenSet[str]]]] = {}
    readers: Dict[str, Set[str]] = {}
    for e in entries:
        for attr, helds in e.writes.items():
            writers.setdefault(attr, {})[e.id] = helds
        for attr in e.reads:
            readers.setdefault(attr, set()).add(e.id)
    shared: Dict[str, dict] = {}
    for attr, per_entry in writers.items():
        touching = set(per_entry) | readers.get(attr, set())
        if len(touching) < 2:
            continue
        common: Optional[FrozenSet[str]] = None
        for helds in per_entry.values():
            g = _guard(helds)
            common = g if common is None else common & g
        shared[attr] = {
            "writers": sorted(per_entry),
            "readers": sorted(readers.get(attr, set()) - set(per_entry)),
            "common_locks": sorted(common or frozenset()),
        }
    return shared


def _find_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    """Simple cycles in the acquisition-order digraph (DFS, deduped by
    node set; the graphs here are tiny)."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles: List[List[str]] = []
    seen_sets: Set[FrozenSet[str]] = set()

    def dfs(start: str, node: str, path: List[str],
            on_path: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(list(path))
            elif nxt not in on_path and len(path) < 16:
                path.append(nxt)
                on_path.add(nxt)
                dfs(start, nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def _has_path(edges: Set[Tuple[str, str]], src: str, dst: str) -> bool:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    stack, seen = [src], {src}
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        for nxt in graph.get(n, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


def run_concurrency_rules(package_root: str,
                          repo_root: Optional[str] = None
                          ) -> List[Finding]:
    """All six static rules over the package tree."""
    repo_root = repo_root or os.path.dirname(package_root)
    index = _Index(package_root, repo_root)
    entries = _discover_entries(index)
    walkers, daemon_targets = _walk_entries(index, entries)
    findings: List[Finding] = []

    # shared-write-unlocked
    shared = _shared_map([w.entry for w in walkers])
    for attr, info in sorted(shared.items()):
        if len(info["writers"]) >= 2 and not info["common_locks"]:
            findings.append(Finding(
                rule="shared-write-unlocked", severity="error",
                location=attr,
                message=f"written from {len(info['writers'])} thread "
                        f"entries ({', '.join(info['writers'])}) with no "
                        f"common lock guaranteed held on every write path",
                data={"writers": info["writers"],
                      "readers": info["readers"]}))

    # lock-order-cycle
    all_edges: Set[Tuple[str, str]] = set()
    for w in walkers:
        all_edges |= w.entry.edges
    for cyc in _find_cycles(all_edges):
        findings.append(Finding(
            rule="lock-order-cycle", severity="error",
            location="lock-order::" + "->".join(sorted(cyc)),
            message=f"static acquisition-order cycle: "
                    f"{' -> '.join(cyc + [cyc[0]])} — two threads taking "
                    f"these in opposite orders deadlock",
            data={"cycle": cyc}))

    # cond-wait-no-predicate / queue-timeout-discipline (deduped across
    # entries reaching the same site)
    seen_sites: Set[Tuple[str, int]] = set()
    for w in walkers:
        for loc, line, in_while in w.wait_sites:
            if not in_while and (loc, line) not in seen_sites:
                seen_sites.add((loc, line))
                findings.append(Finding(
                    rule="cond-wait-no-predicate", severity="error",
                    location=loc,
                    message=f"Condition.wait at line {line} is not inside "
                            f"a while loop re-checking its predicate — "
                            f"spurious wakeups and missed notifies race",
                    data={"line": line}))
        for loc, op, line, _ in w.queue_sites:
            if loc in daemon_targets:
                continue
            if (loc, line) in seen_sites:
                continue
            seen_sites.add((loc, line))
            findings.append(Finding(
                rule="queue-timeout-discipline", severity="error",
                location=loc,
                message=f"blocking {op}() without timeout inside a loop "
                        f"at line {line} in a non-daemon context — a "
                        f"wedged peer hangs the process forever instead "
                        f"of failing loud",
                data={"op": op, "line": line}))

    # signal-handler-unsafe
    for w in walkers:
        e = w.entry
        if e.kind == "signal" and e.effects:
            findings.append(Finding(
                rule="signal-handler-unsafe", severity="error",
                location=f"{e.rel}::{e.target}",
                message=f"signal handler reachably performs "
                        f"{'; '.join(sorted(set(e.effects))[:4])} — only "
                        f"flag/Event stores are async-signal-safe",
                data={"effects": sorted(set(e.effects))}))

    # daemon-no-join: the owning scope must show a drain story
    findings.extend(_daemon_no_join(index))
    return findings


def _daemon_no_join(index: _Index) -> List[Finding]:
    findings: List[Finding] = []
    for rel, mi in sorted(index.modules.items()):
        for site in _creation_sites(mi):
            if site["kind"] != "thread" or not site["daemon"]:
                continue
            scope_node: Optional[ast.AST] = None
            loc = f"{rel}::{site['qual']}"
            if site["cls"]:
                cname = site["cls"]
                for top in mi.tree.body:
                    if isinstance(top, ast.ClassDef) and top.name == cname:
                        scope_node = top
                        loc = f"{rel}::{cname}"
                        break
            else:
                scope_node = mi.functions.get(site["qual"])
            if scope_node is None:
                continue
            has_drain = False
            for node in ast.walk(scope_node):
                if isinstance(node, ast.Call):
                    la = _last_attr(node.func)
                    if la == "join" or la == "set":
                        has_drain = True
                        break
            if not has_drain:
                findings.append(Finding(
                    rule="daemon-no-join", severity="error",
                    location=loc,
                    message=f"daemon thread created at line "
                            f"{site['line']} but its owning scope has no "
                            f".join() and no stop-Event .set() — no drain "
                            f"path; in-flight work dies silently at exit",
                    data={"line": site["line"]}))
    return findings


# -------------------------------------------------------- topology document

def build_topology(package_root: str,
                   repo_root: Optional[str] = None) -> Dict[str, Any]:
    """The checked-in ``.graftlint-threads.json`` document: entries,
    locks, the static acquisition-order graph and the shared-attribute
    map, all deterministically sorted."""
    repo_root = repo_root or os.path.dirname(package_root)
    index = _Index(package_root, repo_root)
    entries = _discover_entries(index)
    walkers, _ = _walk_entries(index, entries)

    locks: Dict[str, str] = {}
    for rel, mi in sorted(index.modules.items()):
        for name, kind in sorted(mi.mod_locks.items()):
            locks[f"{rel}::{name}"] = kind
        for cname, ci in sorted(mi.classes.items()):
            for attr, kind in sorted(ci.locks.items()):
                locks[f"{rel}::{cname}.{attr}"] = kind
    # function-scope locks surface through the walkers' acquire sets (the
    # dynamic witness reports the same ids, so they must be "known")
    for w in walkers:
        for lid in w.entry.locks:
            locks.setdefault(lid, "Lock")

    edges: Set[Tuple[str, str]] = set()
    doc_entries: Dict[str, Any] = {}
    for w in walkers:
        e = w.entry
        edges |= e.edges
        doc_entries[e.id] = {
            "kind": e.kind,
            "daemon": e.daemon,
            "target": e.target,
            "reachable": sorted(e.reachable),
            "locks": sorted(e.locks),
            "reads": sorted(e.reads),
            "writes": {a: sorted(_guard(h)) for a, h in
                       sorted(e.writes.items())},
        }
    for e in entries:
        if e.body is None and not hasattr(e, "roots"):
            doc_entries.setdefault(e.id, {
                "kind": e.kind, "daemon": e.daemon, "target": e.target,
                "reachable": [], "locks": [], "reads": [], "writes": {},
            })

    return {
        "version": TOPOLOGY_VERSION,
        "entries": {k: doc_entries[k] for k in sorted(doc_entries)},
        "locks": locks,
        "lock_order": sorted(list(e) for e in edges),
        "shared": _shared_map([w.entry for w in walkers]),
    }


def load_topology(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != TOPOLOGY_VERSION:
        raise ValueError(
            f"thread-topology version {doc.get('version')!r} != "
            f"{TOPOLOGY_VERSION} — regenerate with "
            f"`cli lint --update-fingerprint`")
    return doc


def write_topology(path: str, doc: Dict[str, Any]) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def _drift(sev: str, loc: str, msg: str, **data: Any) -> Finding:
    return Finding(rule="thread-topology-drift", severity=sev,
                   location=f"threads/{loc}", message=msg, data=data)


def diff_topology(baseline: Dict[str, Any],
                  current: Dict[str, Any]) -> List[Finding]:
    """Gated drift between the checked-in and the current thread
    topology. New/removed entries, a lock dropped from an entry's path
    and a new shared attribute are errors (re-bank with
    ``--update-fingerprint`` after review); everything else is
    informational context for the review."""
    fs: List[Finding] = []
    b_entries = baseline.get("entries", {})
    c_entries = current.get("entries", {})
    for eid in sorted(set(c_entries) - set(b_entries)):
        fs.append(_drift(
            "error", eid,
            f"new thread entry {eid} — review its shared state and "
            f"locks, then re-bank the topology"))
    for eid in sorted(set(b_entries) - set(c_entries)):
        fs.append(_drift(
            "error", eid,
            f"thread entry {eid} disappeared from the topology — if "
            f"intentional, re-bank"))
    for eid in sorted(set(b_entries) & set(c_entries)):
        b, c = b_entries[eid], c_entries[eid]
        dropped = sorted(set(b.get("locks", [])) - set(c.get("locks", [])))
        if dropped:
            fs.append(_drift(
                "error", eid,
                f"lock(s) dropped from {eid}'s path: "
                f"{', '.join(dropped)} — previously-guarded state may "
                f"now race", dropped=dropped))
        added = sorted(set(c.get("locks", [])) - set(b.get("locks", [])))
        if added:
            fs.append(_drift(
                "info", eid,
                f"{eid} now acquires {', '.join(added)}", added=added))
        if bool(b.get("daemon")) != bool(c.get("daemon")):
            fs.append(_drift(
                "warning", eid,
                f"{eid} daemon flag changed "
                f"{b.get('daemon')} -> {c.get('daemon')}"))
        new_writes = sorted(set(c.get("writes", {}))
                            - set(b.get("writes", {})))
        if new_writes:
            fs.append(_drift(
                "warning", eid,
                f"{eid} writes new attribute(s): "
                f"{', '.join(new_writes)}", attrs=new_writes))
    b_shared, c_shared = baseline.get("shared", {}), current.get("shared", {})
    for attr in sorted(set(c_shared) - set(b_shared)):
        fs.append(_drift(
            "error", f"shared/{attr}",
            f"new shared attribute {attr} (written from >=2 entries: "
            f"{', '.join(c_shared[attr]['writers'])}) — review its "
            f"locking, then re-bank", info=c_shared[attr]))
    for attr in sorted(set(b_shared) - set(c_shared)):
        fs.append(_drift(
            "info", f"shared/{attr}",
            f"shared attribute {attr} no longer shared"))
    b_edges = {tuple(e) for e in baseline.get("lock_order", [])}
    c_edges = {tuple(e) for e in current.get("lock_order", [])}
    for a, b2 in sorted(c_edges - b_edges):
        fs.append(_drift(
            "warning", f"order/{a}->{b2}",
            f"new static acquisition-order edge {a} -> {b2}"))
    for a, b2 in sorted(b_edges - c_edges):
        fs.append(_drift(
            "info", f"order/{a}->{b2}",
            f"acquisition-order edge {a} -> {b2} gone"))
    return fs


# ------------------------------------------------------------ the witness

def load_witness(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def check_witness(topology: Dict[str, Any],
                  witness: Dict[str, Any]) -> List[Finding]:
    """Hold the dynamically-witnessed acquisition orders (from
    obs/lockwitness.py) against the static topology: a witnessed edge
    that contradicts the static order, or that closes a cycle the static
    pass missed, is an error; locks the static pass never saw are
    informational."""
    fs: List[Finding] = []
    static_edges = {tuple(e) for e in topology.get("lock_order", [])}
    witnessed = [(e[0], e[1]) for e in witness.get("edges", [])]
    known_locks = set(topology.get("locks", {}))

    for a, b in sorted(set(witnessed)):
        if _has_path(static_edges, b, a):
            fs.append(Finding(
                rule="lock-order-witness", severity="error",
                location=f"witness/{a}->{b}",
                message=f"witnessed acquisition {a} -> {b} contradicts "
                        f"the static order ({b} ..-> {a}) — deadlock "
                        f"window under the drilled interleaving",
                data={"edge": [a, b]}))

    union = static_edges | set(witnessed)
    witnessed_set = set(witnessed)
    for cyc in _find_cycles(union):
        cyc_edges = set(zip(cyc, cyc[1:] + cyc[:1]))
        if cyc_edges & witnessed_set \
                and not all(e in static_edges for e in cyc_edges):
            loc = "witness-cycle::" + "->".join(sorted(cyc))
            if any(f.location == loc for f in fs):
                continue
            fs.append(Finding(
                rule="lock-order-witness", severity="error",
                location=loc,
                message=f"witnessed acquisitions close a lock-order "
                        f"cycle the static pass missed: "
                        f"{' -> '.join(cyc + [cyc[0]])}",
                data={"cycle": cyc}))

    for lid in sorted({lk for e in witnessed for lk in e} - known_locks):
        fs.append(Finding(
            rule="lock-order-witness", severity="info",
            location=f"witness/{lid}",
            message=f"witnessed lock {lid} is not in the static "
                    f"topology (dynamically created, or created outside "
                    f"the linted package root)"))
    if not any(f.severity == "error" for f in fs):
        fs.append(Finding(
            rule="lock-order-witness", severity="info",
            location="witness",
            message=f"witness consistent with the static order: "
                    f"{len(witness.get('locks', {}))} lock(s), "
                    f"{len(witnessed)} ordered edge(s)",
            data={"locks": len(witness.get("locks", {})),
                  "edges": len(witnessed)}))
    return fs
