"""Jaxpr/compiled-artifact contract rules over the canonical step functions.

The performance story rests on structural invariants of the lowered graph —
*which ops live inside the refinement scan body* sets the serial floor
(RAFT's recurrent loop, arXiv 2003.12039; the recurrent-backward placement
question formalized in arXiv 1709.04057), dtype policy decides the stack
residency the r7 breakdown named dominant, and donation/host-sync hazards
silently cost a copy of the train state or a device round-trip per step.
Until now each invariant was policed by one hand-written test or a comment;
this module makes them declarative rules over two canonical lowerings:

* ``train_step`` — grad of the fused-loss step at a tiny CPU shape
  (autodiff backward; compiled donated, like bench.py's and the DP path's
  ``donate_argnums=(0,)``), plus a ``train_step[batched]`` variant with the
  custom-VJP scan + bf16 residuals engaged and its autodiff twin traced for
  comparison;
* ``train_step[update]`` — the FULL :func:`make_train_step` program
  (grad + optimizer + the r11 device-side anomaly guard's ``lax.cond``),
  compiled with the state donated: the guard's skip path is jit-reachable
  production code, so host-sync/dtype/donation contracts must hold over it
  too — in particular that the cond does not break state donation (the
  aliasing is re-verified on the compiled executable every lint run);
* ``inference`` — the ``test_mode`` forward ``StereoPredictor`` jits;
* ``inference[adaptive]`` — the compiled early-exit flavor (masked
  fixed-trip scan with per-sample freeze, models/raft_stereo.py
  ``_refine_adaptive``) the ``--iter_policy`` eval/serve paths run;
* ``train_step[batched,fused]`` — the batched custom-VJP step under the
  memoryless ``fused`` correlation (r18): the residual-dtype and
  wgrad-placement contracts must hold when the scan-carried corr state is
  the feature pyramid instead of the volume;
* ``inference[wide]`` / ``inference[fused]`` — the same forward compiled
  at a WIDE width (where the B·H·W² volume, quadratic in W, overtakes the
  linear-in-W encoder activations): the pair's peak-bytes gap in the
  fingerprint is the standing record that ``fused`` deletes the volume's
  residency, and the gate that notices it quietly coming back.

Same jaxpr topology as the real shapes (shape enters only aval sizes), so
every placement/dtype/callback contract checked here holds for the TPU
executable. Each rule returns :class:`~.findings.Finding`s; the runner
(analysis/runner.py) merges them with the AST lint and gates on errors.

Rule ids: ``wgrad-in-loop``, ``dtype-drift``,
``residual-dtype-conformance``, ``host-sync``, ``donation``,
``carry-growth``, ``constant-bloat``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from raft_stereo_tpu.analysis.findings import Finding

#: current semantic version per rule (suppression baseline entries record
#: the version they were written against; findings.apply_baseline flags a
#: mismatch stale instead of silently matching a changed rule).
#: residual-dtype-conformance is v2: the contract now also runs over the
#: ``train_step[batched,fused]`` lowering (r18) — an old suppression could
#: not have meant the fused-corr residual stacks, so it goes stale.
RULE_VERSIONS: Dict[str, int] = {
    "wgrad-in-loop": 1,
    "dtype-drift": 1,
    "residual-dtype-conformance": 2,
    "host-sync": 1,
    "donation": 1,
    "carry-growth": 1,
    "constant-bloat": 1,
}

# Thresholds a caller (or a fixture test) can override per run.
DEFAULT_THRESHOLDS: Dict[str, int] = {
    # scan carry resident per backward iteration — warn past this
    "carry_bytes": 1 << 30,          # 1 GiB
    # one constant folded into the executable — warn past this
    "const_bytes": 2 << 20,          # 2 MiB
    # undonated argument buffers on a target that declares no donation
    "nondonated_arg_bytes": 512 << 20,
    # a convert round-trip on arrays at or below this many elements is
    # scalar glue, not a bandwidth hazard
    "roundtrip_min_elems": 2,
    # the wgrad-in-loop contract (mirrors tests/test_scan_grad.py's pin):
    # >= hoisted_min wgrad convs leave the backward body, and the same
    # count appears outside as batched contractions; slack covers the
    # replay ops the custom path adds back into the body
    "wgrad_hoisted_min": 6,
    "wgrad_body_slack": 3,
}


@dataclasses.dataclass
class GraphTarget:
    """One lowered artifact under analysis."""

    name: str
    cfg: Any                      # RAFTStereoConfig
    closed_jaxpr: Any             # jax.core.ClosedJaxpr
    compiled: Any = None          # jax.stages.Compiled, when compiled
    donate_declared: bool = False
    platform: str = "cpu"
    #: comparison lowerings, e.g. {"autodiff": ClosedJaxpr} on the batched
    #: train variant (the wgrad rule diffs placement against it)
    variants: Dict[str, Any] = dataclasses.field(default_factory=dict)


def _aval_bytes(aval) -> int:
    try:
        return int(aval.size) * aval.dtype.itemsize
    except Exception:
        return 0


def _walk(target):
    from raft_stereo_tpu.obs.xla import iter_eqns
    return iter_eqns(target.closed_jaxpr, path=target.name)


# --- rule: wgrad-in-loop -----------------------------------------------------

def check_wgrad_hoisting(profile_autodiff: Dict[str, Any],
                         profile_batched: Dict[str, Any],
                         hoisted_min: int = 6, body_slack: int = 3,
                         location: str = "train_step[batched]"
                         ) -> List[Finding]:
    """The shared form of tests/test_scan_grad.py's op-placement pin.

    Inputs are two :func:`~raft_stereo_tpu.obs.xla.conv_op_profile` results
    (autodiff vs batched lowering of the SAME step). Contract: the batched
    path's backward scan body (the last scan in jaxpr order for a grad
    lowering) runs at least ``hoisted_min`` fewer convs per iteration
    (minus ``body_slack`` for the replay ops it adds), and at least
    ``hoisted_min`` batched contractions appear outside any scan. Both the
    lint rule and the test assert through this function, so they cannot
    drift apart."""
    findings: List[Finding] = []
    if not profile_autodiff["scans"] or not profile_batched["scans"]:
        return [Finding(
            rule="wgrad-in-loop", severity="error", location=location,
            message="no refinement scan found in one of the lowerings "
                    "(profile has no scans) — the placement contract "
                    "cannot hold",
            data={"autodiff": profile_autodiff, "batched": profile_batched})]
    bwd_auto = profile_autodiff["scans"][-1]["convs_per_step"]
    bwd_cust = profile_batched["scans"][-1]["convs_per_step"]
    out_auto = profile_autodiff["outside_scans"]
    out_cust = profile_batched["outside_scans"]
    data = {"backward_convs_per_step": {"autodiff": bwd_auto,
                                        "batched": bwd_cust},
            "outside_scan_convs": {"autodiff": out_auto,
                                   "batched": out_cust},
            "hoisted_min": hoisted_min, "body_slack": body_slack}
    if bwd_cust > bwd_auto - hoisted_min + body_slack:
        findings.append(Finding(
            rule="wgrad-in-loop", severity="error",
            location=f"{location}/backward-scan",
            message=f"backward scan body still runs {bwd_cust} convs/step "
                    f"(autodiff: {bwd_auto}) — the per-iteration weight-"
                    f"grad convs were not hoisted out of the loop",
            data=data))
    if out_cust < out_auto + hoisted_min:
        findings.append(Finding(
            rule="wgrad-in-loop", severity="error",
            location=f"{location}/outside-scans",
            message=f"only {out_cust - out_auto} extra convs outside the "
                    f"scans (expected >= {hoisted_min} batched wgrad "
                    f"contractions)",
            data=data))
    return findings


def rule_wgrad_in_loop(target: GraphTarget,
                       thresholds: Dict[str, int]) -> List[Finding]:
    """When ``batched_scan_wgrad`` is on, the weight-grad convs must be
    out of the backward scan body (vs the autodiff twin lowering)."""
    if not bool(target.cfg.batched_scan_wgrad):
        return []
    autodiff = target.variants.get("autodiff")
    if autodiff is None:
        return []
    from raft_stereo_tpu.obs.xla import conv_op_profile
    return check_wgrad_hoisting(
        conv_op_profile(autodiff), conv_op_profile(target.closed_jaxpr),
        hoisted_min=thresholds["wgrad_hoisted_min"],
        body_slack=thresholds["wgrad_body_slack"], location=target.name)


# --- rule: dtype-drift -------------------------------------------------------

def rule_dtype_drift(target: GraphTarget,
                     thresholds: Dict[str, int]) -> List[Finding]:
    """fp32<->bf16 round-trip convert chains (a rounding pass that buys
    nothing — storage narrowing pays for itself only across a scan/stack
    boundary, which is not a direct chain) and any float64 op (silent 2x
    memory and, on TPU, a catastrophic emulation path)."""
    import numpy as np

    findings: List[Finding] = []
    f32, bf16 = np.dtype("float32"), np.dtype("bfloat16") if hasattr(
        np, "bfloat16") else None
    try:
        import ml_dtypes
        bf16 = np.dtype(ml_dtypes.bfloat16)
    except Exception:
        pass
    roundtrips: Dict[str, int] = {}
    f64_ops: Dict[str, int] = {}
    # Per (jaxpr path) producer map: var id -> producing convert eqn.
    producers: Dict[int, Any] = {}
    min_elems = thresholds["roundtrip_min_elems"]
    for eqn, path in _walk(target):
        for ov in eqn.outvars:
            aval = getattr(ov, "aval", None)
            if aval is not None and getattr(aval, "dtype", None) is not None \
                    and aval.dtype == np.dtype("float64"):
                f64_ops[path] = f64_ops.get(path, 0) + 1
        if eqn.primitive.name != "convert_element_type":
            continue
        (out,) = eqn.outvars
        src = eqn.invars[0]
        prev = producers.get(id(src))
        if prev is not None:
            prev_eqn, prev_path = prev
            a = prev_eqn.invars[0].aval
            b = out.aval
            if (a.dtype == b.dtype and a.size >= min_elems
                    and bf16 is not None
                    and {a.dtype, src.aval.dtype} == {f32, bf16}):
                roundtrips[path] = roundtrips.get(path, 0) + 1
        producers[id(out)] = (eqn, path)
    for path, n in sorted(roundtrips.items()):
        findings.append(Finding(
            rule="dtype-drift", severity="warning", location=path,
            message=f"{n} fp32<->bf16 round-trip convert chain(s): a value "
                    f"is narrowed and immediately widened back — pure "
                    f"rounding, no storage or bandwidth win",
            data={"count": n}))
    for path, n in sorted(f64_ops.items()):
        findings.append(Finding(
            rule="dtype-drift", severity="error", location=path,
            message=f"{n} float64-producing op(s) in a jitted graph "
                    f"(accidental x64 promotion)",
            data={"count": n}))
    return findings


# --- rule: residual-dtype-conformance ---------------------------------------

def _scan_stacks(target) -> List[Tuple[str, Any]]:
    """(scan path, ys aval) for every scan's stacked outputs, in walk
    order; scans are indexed per nesting path so two sibling scans get
    distinct locations."""
    out = []
    scan_i: Dict[str, int] = {}
    for eqn, path in _walk(target):
        if eqn.primitive.name != "scan":
            continue
        i = scan_i.get(path, 0)
        scan_i[path] = i + 1
        nc = eqn.params["num_carry"]
        for ov in eqn.outvars[nc:]:
            out.append((f"{path}/scan[{i}]", ov.aval))
    return out


def rule_residual_dtype(target: GraphTarget,
                        thresholds: Dict[str, int]) -> List[Finding]:
    """When ``residual_dtype`` is configured on the custom-VJP path, the
    scan residual stacks must actually be stored in it — the failure mode
    is the knob silently doing nothing (the dtype policy previously policed
    by comments). Model outputs legitimately stacked in fp32 (the deferred
    upsample's mask/flow stacks) are why this is a presence contract, not
    an everything-narrowed contract; under the ``"corr"`` save policy the
    corr-channel stack must exist in the storage dtype too."""
    import numpy as np

    cfg = target.cfg
    if cfg.residual_dtype is None or not bool(cfg.batched_scan_wgrad):
        return []
    want = np.dtype(cfg.residual_dtype)
    stacks = _scan_stacks(target)
    conforming = [(p, a) for p, a in stacks if a.dtype == want]
    by_dtype: Dict[str, int] = {}
    for _, a in stacks:
        by_dtype[str(a.dtype)] = by_dtype.get(str(a.dtype), 0) \
            + _aval_bytes(a)
    data = {"configured": str(want), "stack_bytes_by_dtype": by_dtype,
            "n_stacks": len(stacks), "n_conforming": len(conforming)}
    findings: List[Finding] = []
    if not conforming:
        findings.append(Finding(
            rule="residual-dtype-conformance", severity="error",
            location=target.name,
            message=f"residual_dtype={cfg.residual_dtype!r} is configured "
                    f"but no scan residual stack is stored in it — the "
                    f"narrowing knob is dead in this lowering",
            data=data))
        return findings
    # The custom path stacks residuals in BOTH directions: forward saves
    # (carries/policy stacks) and the backward scan's wgrad input/cotangent
    # stacks. Conformance on only one side means half the residency win
    # silently evaporated.
    scans_with_stacks = {p for p, _ in stacks}
    scans_conforming = {p for p, _ in conforming}
    if len(scans_with_stacks) >= 2 and len(scans_conforming) < 2:
        findings.append(Finding(
            rule="residual-dtype-conformance", severity="warning",
            location=target.name,
            message=f"residual stacks in {cfg.residual_dtype!r} appear in "
                    f"only one scan — forward saves and backward wgrad "
                    f"stacks should both be narrowed",
            data=data))
    if cfg.refinement_save_policy == "corr":
        ch = cfg.corr_channels
        if not any(a.shape and a.shape[-1] == ch and a.dtype == want
                   for _, a in stacks):
            findings.append(Finding(
                rule="residual-dtype-conformance", severity="error",
                location=target.name,
                message=f"save policy 'corr' engaged but no "
                        f"{ch}-channel stack in {cfg.residual_dtype!r} "
                        f"found",
                data=data))
    return findings


# --- rule: host-sync ---------------------------------------------------------

HOST_SYNC_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "debug_print", "infeed", "outfeed", "host_callback_call",
    "outside_call",
})


def rule_host_sync(target: GraphTarget,
                   thresholds: Dict[str, int]) -> List[Finding]:
    """Host callbacks / infeed / outfeed inside a jitted hot path force a
    device<->host round trip per execution (and on tunneled TPUs, a tunnel
    RTT) — never acceptable in the canonical step functions."""
    hits: Dict[Tuple[str, str], int] = {}
    for eqn, path in _walk(target):
        if eqn.primitive.name in HOST_SYNC_PRIMITIVES:
            key = (path, eqn.primitive.name)
            hits[key] = hits.get(key, 0) + 1
    return [Finding(
        rule="host-sync", severity="error", location=path,
        message=f"{n} `{prim}` op(s) inside the jitted graph — host sync "
                f"in the hot path",
        data={"primitive": prim, "count": n})
        for (path, prim), n in sorted(hits.items())]


# --- rule: donation ----------------------------------------------------------

def rule_donation(target: GraphTarget,
                  thresholds: Dict[str, int]) -> List[Finding]:
    """Declared donations must materialize as input/output aliases in the
    compiled executable (XLA drops donation silently when shapes/layouts
    mismatch — the state then costs a second copy of itself); without any
    donation, large argument buffers are flagged for review."""
    if target.compiled is None:
        return []
    from raft_stereo_tpu.obs.xla import memory_analysis_dict
    mem = memory_analysis_dict(target.compiled)
    if mem is None:
        return []
    alias = mem.get("alias_bytes", 0)
    args = mem.get("argument_bytes", 0)
    findings: List[Finding] = []
    if target.donate_declared and alias == 0:
        findings.append(Finding(
            rule="donation", severity="error", location=target.name,
            message="donate_argnums declared but the compiled executable "
                    "aliases 0 bytes — donation was dropped and the state "
                    "is double-buffered",
            data={"argument_bytes": args, "platform": target.platform}))
    if not target.donate_declared \
            and args > thresholds["nondonated_arg_bytes"]:
        findings.append(Finding(
            rule="donation", severity="info", location=target.name,
            message=f"{args} argument bytes with no donation declared — "
                    f"if any input is dead after the call, donating it "
                    f"saves its residency",
            data={"argument_bytes": args}))
    return findings


# --- rule: carry-growth ------------------------------------------------------

def rule_carry_growth(target: GraphTarget,
                      thresholds: Dict[str, int]) -> List[Finding]:
    """A scan carry is resident for the whole loop; a carry past the
    threshold (default 1 GiB) says something bulky (a param tree, a full
    activation set) is riding the loop instead of living outside it."""
    limit = thresholds["carry_bytes"]
    findings: List[Finding] = []
    scan_i: Dict[str, int] = {}
    for eqn, path in _walk(target):
        if eqn.primitive.name != "scan":
            continue
        i = scan_i.get(path, 0)
        scan_i[path] = i + 1
        nc = eqn.params["num_carry"]
        num_consts = eqn.params.get("num_consts", 0)
        carry_bytes = sum(_aval_bytes(v.aval)
                          for v in eqn.invars[num_consts:num_consts + nc])
        if carry_bytes > limit:
            findings.append(Finding(
                rule="carry-growth", severity="warning",
                location=f"{path}/scan[{i}]",
                message=f"scan carry is {carry_bytes} bytes "
                        f"(> {limit}): resident every iteration of the "
                        f"loop",
                data={"carry_bytes": carry_bytes, "limit": limit,
                      "length": int(eqn.params.get("length") or 0)}))
    return findings


# --- rule: constant-bloat ----------------------------------------------------

def rule_constant_bloat(target: GraphTarget,
                        thresholds: Dict[str, int]) -> List[Finding]:
    """Constants folded into the jaxpr ship inside every executable (and
    the compilation cache); one past the threshold usually means an array
    was closed over instead of passed as an argument."""
    import numpy as np

    limit = thresholds["const_bytes"]
    findings: List[Finding] = []
    consts = getattr(target.closed_jaxpr, "consts", ()) or ()
    total = 0
    for i, c in enumerate(consts):
        try:
            arr = np.asarray(c)
        except Exception:
            continue
        nbytes = int(arr.size) * arr.dtype.itemsize
        total += nbytes
        if nbytes > limit:
            findings.append(Finding(
                rule="constant-bloat", severity="warning",
                location=f"{target.name}/const[{i}]",
                message=f"constant of {nbytes} bytes (> {limit}) folded "
                        f"into the lowering (shape {tuple(arr.shape)}, "
                        f"{arr.dtype})",
                data={"const_bytes": nbytes, "limit": limit,
                      "shape": list(arr.shape), "dtype": str(arr.dtype)}))
    return findings


GRAPH_RULES: Dict[str, Callable[[GraphTarget, Dict[str, int]],
                                List[Finding]]] = {
    "wgrad-in-loop": rule_wgrad_in_loop,
    "dtype-drift": rule_dtype_drift,
    "residual-dtype-conformance": rule_residual_dtype,
    "host-sync": rule_host_sync,
    "donation": rule_donation,
    "carry-growth": rule_carry_growth,
    "constant-bloat": rule_constant_bloat,
}


def run_rules_on_target(target: GraphTarget,
                        thresholds: Optional[Dict[str, int]] = None
                        ) -> List[Finding]:
    th = dict(DEFAULT_THRESHOLDS, **(thresholds or {}))
    findings: List[Finding] = []
    for fn in GRAPH_RULES.values():
        findings.extend(fn(target, th))
    return findings


# --- canonical targets -------------------------------------------------------

def build_targets(batch: int = 1, h: int = 32, w: int = 48, iters: int = 3,
                  compile_train: bool = True,
                  fused_w: int = 24576) -> List[GraphTarget]:
    """Lower the canonical step functions at a tiny shape (same topology as
    the production shapes — only aval sizes differ).

    Four targets: the default autodiff ``train_step`` (compiled with
    ``donate_argnums=(0,)`` like bench.py / the DP path — the donation rule
    needs the executable), ``train_step[batched]`` (custom-VJP scan + bf16
    residual stacks, jaxpr-only, with its autodiff twin attached for the
    wgrad placement diff), ``train_step[update]`` (the full grad+optimizer
    step with the anomaly-guard ``lax.cond``, compiled donated), and the
    ``test_mode`` ``inference`` forward. One model init is shared: the
    variant configs differ only in backward scheduling, never in
    parameters.

    ``fused_w`` sets the width of the ``inference[wide]``/
    ``inference[fused]`` pair (compiled only when ``compile_train``): wide
    enough that the reg volume pyramid — quadratic in W — dominates the
    program peak, so the fingerprint's peak-bytes field records the
    residency the memoryless kernel deletes. Compile cost is
    width-independent (op counts, not aval sizes, drive XLA here)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import create_model, init_model
    from raft_stereo_tpu.training.loss import loss_mask, sequence_loss_fused

    base = RAFTStereoConfig()
    model, variables = init_model(jax.random.PRNGKey(0), base,
                                  (1, h, w, 3))
    rng = np.random.default_rng(0)
    img1 = jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)), jnp.float32)
    gt = jnp.asarray(rng.uniform(-8, 0, (batch, h, w, 1)), jnp.float32)
    mask = loss_mask(gt, jnp.ones((batch, h, w), jnp.float32))
    rest = {k: v for k, v in variables.items() if k != "params"}
    platform = jax.default_backend()

    def grad_fn(cfg):
        m = create_model(cfg)

        def loss(p):
            err, final = m.apply({"params": p, **rest}, img1, img2,
                                 iters=iters, flow_gt=gt, loss_mask=mask)
            return sequence_loss_fused(err, final, gt, mask)[0]

        return jax.grad(loss)

    params = variables["params"]
    targets: List[GraphTarget] = []

    # 1) default autodiff train step, donated compile
    g = grad_fn(base)
    compiled = None
    if compile_train:
        compiled = jax.jit(g, donate_argnums=(0,)).lower(params).compile()
    targets.append(GraphTarget(
        name="train_step", cfg=base, closed_jaxpr=jax.make_jaxpr(g)(params),
        compiled=compiled, donate_declared=True, platform=platform))

    # 2) batched custom-VJP train step with bf16 residual stacks + twin
    cfg_b = dataclasses.replace(base, batched_scan_wgrad=True,
                                refinement_save_policy=False,
                                residual_dtype="bfloat16")
    cfg_a = dataclasses.replace(base, refinement_save_policy=False)
    targets.append(GraphTarget(
        name="train_step[batched]", cfg=cfg_b,
        closed_jaxpr=jax.make_jaxpr(grad_fn(cfg_b))(params),
        platform=platform,
        variants={"autodiff": jax.make_jaxpr(grad_fn(cfg_a))(params)}))

    # 3) full train step: grad + optimizer + the device-side anomaly guard
    #    (training/state.py lax.cond), compiled with the state donated —
    #    the guard must neither host-sync nor drop the donation aliasing
    from raft_stereo_tpu.config import TrainConfig
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState, make_train_step

    tx = fetch_optimizer(TrainConfig(batch_size=batch, train_iters=iters,
                                     image_size=(h, w)))
    state = TrainState.create(variables, tx)
    full_step = make_train_step(model, tx, iters, fused_loss=True,
                                anomaly_guard=True)
    batch_data = {"image1": img1, "image2": img2, "flow": gt,
                  "valid": jnp.ones((batch, h, w), jnp.float32)}
    compiled_full = None
    if compile_train:
        compiled_full = jax.jit(full_step, donate_argnums=(0,)).lower(
            state, batch_data).compile()
    targets.append(GraphTarget(
        name="train_step[update]", cfg=base,
        closed_jaxpr=jax.make_jaxpr(full_step)(state, batch_data),
        compiled=compiled_full, donate_declared=True, platform=platform))

    # 4) inference forward (what StereoPredictor jits)
    def infer(v, a, b):
        return model.apply(v, a, b, iters=iters, test_mode=True)

    targets.append(GraphTarget(
        name="inference", cfg=base,
        closed_jaxpr=jax.make_jaxpr(infer)(variables, img1, img2),
        platform=platform))

    # 5) adaptive inference forward (the compiled early-exit flavor the
    # iter_policy path serves, models/raft_stereo.py _refine_adaptive —
    # masked fixed-trip scan, so carry-growth/collective rules see the
    # same static-shape program the AOT serve cache compiles)
    def infer_adaptive(v, a, b):
        return model.apply(v, a, b, iters=iters, test_mode=True,
                           iter_metrics="per_sample", adaptive_tau=0.05,
                           adaptive_min_iters=1)

    targets.append(GraphTarget(
        name="inference[adaptive]", cfg=base,
        closed_jaxpr=jax.make_jaxpr(infer_adaptive)(variables, img1, img2),
        platform=platform))

    # 6) batched custom-VJP step under the memoryless fused correlation:
    # the residual-dtype and wgrad-placement contracts must hold when the
    # corr state carried by the scan is the feature pyramid, not the
    # volume (+ its autodiff twin for the placement diff)
    cfg_fb = dataclasses.replace(base, corr_implementation="fused",
                                 batched_scan_wgrad=True,
                                 refinement_save_policy=False,
                                 residual_dtype="bfloat16")
    cfg_fa = dataclasses.replace(base, corr_implementation="fused",
                                 refinement_save_policy=False)
    targets.append(GraphTarget(
        name="train_step[batched,fused]", cfg=cfg_fb,
        closed_jaxpr=jax.make_jaxpr(grad_fn(cfg_fb))(params),
        platform=platform,
        variants={"autodiff": jax.make_jaxpr(grad_fn(cfg_fa))(params)}))

    # 7) the wide fused-vs-reg inference pair: at fused_w the reg volume
    # pyramid (quadratic in W) overtakes the linear-in-W encoder stem
    # activations, so the two targets' peak_bytes fields bank the claim
    # "fused deletes the volume's residency" as a diffable number
    img1_w = jnp.asarray(rng.uniform(0, 255, (1, h, fused_w, 3)),
                         jnp.float32)
    img2_w = jnp.asarray(rng.uniform(0, 255, (1, h, fused_w, 3)),
                         jnp.float32)
    cfg_f = dataclasses.replace(base, corr_implementation="fused")
    for name, cfg_w in (("inference[wide]", base),
                        ("inference[fused]", cfg_f)):
        m_w = create_model(cfg_w)

        def infer_w(v, a, b, m_w=m_w):
            return m_w.apply(v, a, b, iters=iters, test_mode=True)

        compiled_w = None
        if compile_train:
            compiled_w = jax.jit(infer_w).lower(variables, img1_w,
                                                img2_w).compile()
        targets.append(GraphTarget(
            name=name, cfg=cfg_w,
            closed_jaxpr=jax.make_jaxpr(infer_w)(variables, img1_w, img2_w),
            compiled=compiled_w, platform=platform))
    return targets


def run_graph_rules(thresholds: Optional[Dict[str, int]] = None,
                    compile_train: bool = True,
                    targets: Optional[List[GraphTarget]] = None
                    ) -> List[Finding]:
    """Build the canonical targets (unless given) and run every rule."""
    if targets is None:
        targets = build_targets(compile_train=compile_train)
    findings: List[Finding] = []
    for t in targets:
        findings.extend(run_rules_on_target(t, thresholds))
    return findings
