"""Finding record, JSON report and suppression baseline — graftlint's spine.

Every engine (graph_rules.py over lowered jaxprs/compiled artifacts,
ast_rules.py over the package source, spmd_rules.py over the sharded
lowerings, fingerprint.py's drift diff) emits the same record: a rule id, a
severity, a *line-stable* location, a human message and a machine ``data``
payload. The runner merges them, applies the checked-in suppression
baseline (``.graftlint.json`` at the repo root), renders the report and
gates on unsuppressed error-severity findings.

Locations are deliberately line-free (``path::qualname`` for AST findings,
``target/scan[i]``-style for graph findings; line numbers ride in
``data``): a baseline keyed on line numbers would rot on every unrelated
edit, which is how suppression files turn into noise generators.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

SEVERITIES = ("error", "warning", "info")

BASELINE_VERSION = 1
DEFAULT_BASELINE = ".graftlint.json"


@dataclasses.dataclass
class Finding:
    """One rule violation (or observation, at info severity)."""

    rule: str
    severity: str
    location: str
    message: str
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: set by :func:`apply_baseline` when a suppression matches
    suppressed: bool = False

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r} not in {SEVERITIES}")

    @property
    def key(self) -> Tuple[str, str]:
        """The identity a suppression matches on."""
        return (self.rule, self.location)

    def to_dict(self) -> Dict[str, Any]:
        out = {"rule": self.rule, "severity": self.severity,
               "location": self.location, "message": self.message}
        if self.data:
            out["data"] = self.data
        if self.suppressed:
            out["suppressed"] = True
        return out


# --- suppression baseline ----------------------------------------------------

def load_baseline(path: str) -> List[Dict[str, Any]]:
    """Read ``.graftlint.json``; a missing file is an empty baseline."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: baseline version {doc.get('version')!r} "
                         f"!= {BASELINE_VERSION}")
    entries = doc.get("suppressions", [])
    for e in entries:
        if not isinstance(e, dict) or "rule" not in e or "location" not in e:
            raise ValueError(f"{path}: suppression entries need "
                             f"'rule' and 'location': {e!r}")
    return entries


def apply_baseline(findings: Iterable[Finding],
                   suppressions: List[Dict[str, Any]],
                   rule_versions: Optional[Dict[str, int]] = None
                   ) -> Tuple[List[Finding], List[Dict[str, Any]]]:
    """Mark findings matched by the baseline; return (findings, stale).

    ``stale`` is the suppressions that matched nothing — a fixed violation
    whose baseline entry should be deleted (reported, never fatal: a stale
    entry must not block the gate the way a real finding does). Each stale
    entry carries a ``stale_reason``.

    ``rule_versions`` (current rule id -> semantic version, merged from the
    engines that ran) lets a renamed/retired rule or a version bump
    invalidate its suppressions EXPLICITLY: an entry whose rule is unknown,
    or whose recorded ``rule_version`` differs from the rule's current
    version, is flagged stale and never matches — previously such entries
    were silently inert forever (a rename left zombie suppressions; worse,
    a rule whose semantics changed kept suppressing findings it no longer
    meant).
    """
    findings = list(findings)
    used = set()
    stale: List[Dict[str, Any]] = []
    by_key: Dict[Tuple[str, str], int] = {}
    for i, e in enumerate(suppressions):
        if rule_versions is not None:
            cur = rule_versions.get(e["rule"])
            if cur is None:
                stale.append({**e, "stale_reason":
                              "rule renamed or retired — no engine exposes "
                              "it anymore"})
                continue
            ev = e.get("rule_version")
            if ev is not None and ev != cur:
                stale.append({**e, "stale_reason":
                              f"written against rule_version {ev}, rule is "
                              f"now v{cur} — re-triage and re-baseline"})
                continue
        by_key[(e["rule"], e["location"])] = i
    for f in findings:
        idx = by_key.get(f.key)
        if idx is not None:
            f.suppressed = True
            used.add(idx)
    stale.extend({**e, "stale_reason": "matches nothing (violation fixed)"}
                 for i, e in enumerate(suppressions)
                 if i not in used and (e["rule"], e["location"]) in by_key)
    return findings, stale


def baseline_from_findings(findings: Iterable[Finding],
                           reason: str = "baselined pre-existing finding",
                           rule_versions: Optional[Dict[str, int]] = None
                           ) -> Dict[str, Any]:
    """Serialize current unsuppressed findings as a fresh baseline doc
    (the ``--update-baseline`` round-trip). When ``rule_versions`` is
    given, each entry records the version of the rule it suppresses, so a
    future semantic bump flags it stale instead of silently matching."""
    seen = set()
    entries = []
    for f in findings:
        if f.suppressed or f.key in seen:
            continue
        seen.add(f.key)
        entry = {"rule": f.rule, "location": f.location,
                 "reason": reason, "severity": f.severity}
        if rule_versions and f.rule in rule_versions:
            entry["rule_version"] = rule_versions[f.rule]
        entries.append(entry)
    return {"version": BASELINE_VERSION, "suppressions": entries}


def write_baseline(path: str, doc: Dict[str, Any]) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")


# --- report ------------------------------------------------------------------

def severity_counts(findings: Iterable[Finding],
                    suppressed: Optional[bool] = None) -> Dict[str, int]:
    """Count findings per severity; ``suppressed`` filters when not None."""
    counts = {s: 0 for s in SEVERITIES}
    for f in findings:
        if suppressed is None or f.suppressed == suppressed:
            counts[f.severity] += 1
    return counts


def make_report(findings: List[Finding], rules_run: List[str],
                engines: List[str],
                stale_suppressions: Optional[List[Dict[str, Any]]] = None
                ) -> Dict[str, Any]:
    """The JSON report ``cli lint --json`` writes: per-finding detail plus
    the summary the ``lint`` event mirrors."""
    return {
        "report": "graftlint",
        "version": 1,
        "engines": engines,
        "rules_run": sorted(rules_run),
        "counts": severity_counts(findings),
        "unsuppressed": severity_counts(findings, suppressed=False),
        "suppressed_total": sum(1 for f in findings if f.suppressed),
        "stale_suppressions": stale_suppressions or [],
        "findings": [f.to_dict() for f in findings],
    }


def gate(findings: Iterable[Finding]) -> int:
    """Exit status: 1 when any unsuppressed error-severity finding remains."""
    return 1 if any(f.severity == "error" and not f.suppressed
                    for f in findings) else 0
