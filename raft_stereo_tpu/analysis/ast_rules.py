"""Tracer-safety AST lint over the package source.

The graph rules (graph_rules.py) check what the lowered artifact *is*; this
engine checks what the source *would do under tracing* — the class of bug
that doesn't change the jaxpr but breaks or silently de-optimizes it:

* ``tracer-unsafe`` — ``float()``/``int()``/``bool()``/``.item()``/
  ``np.asarray``/``np.array`` applied to values inside jit-reachable
  functions. Under tracing these either raise ``ConcretizationTypeError``
  or silently force a device sync. Static shape arithmetic is exempt:
  names bound from ``.shape`` unpacking, ``len(...)``, ``.ndim`` (shapes
  are python ints under jit) don't trip the rule.
* ``wall-clock`` — ``time.time()``/``perf_counter()`` and friends inside
  jit-reachable code measure *trace* time once, then become constants.
* ``import-time-jnp`` — module-level ``jnp.*`` calls run device work (and
  initialize the backend) at import, before the entry point can pick a
  platform.
* ``cli-drift`` — the argparse flag surface in cli.py vs the config.py
  dataclasses: a constructor keyword that isn't a real field, a declared
  flag that no config constructor consumes, and (info) config fields with
  no flag exposure.

Jit-reachability is a per-module static heuristic, not a call graph: a
function is reachable when it is (a) referenced by name in a call to a
tracing transform (``jax.jit``/``grad``/``vmap``/``lax.scan``/
``nn.scan``/``pallas_call``/``custom_vjp`` & co., including through
``functools.partial``), (b) decorated by one, (c) defined *inside* a
reachable function, or (d) a method of a ``nn.Module`` subclass (flax
methods are always traced). Helpers merely *called* from traced code are
not chased — that keeps the lint fast and the false-positive rate near
zero; the suppression baseline absorbs the remainder.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from raft_stereo_tpu.analysis.findings import Finding

#: current semantic version per rule (baseline entries record the version
#: they suppress; a bump flags them stale — findings.apply_baseline).
#: cli-drift is v5: v2 extended the rule to the evaluate_stereo/demo
#: parser surfaces and the bench config-constructor call sites; v3 added
#: the serving surfaces (build_serve_parser/build_loadtest_parser); v4
#: added the tracing/diagnosis surfaces (build_timeline_parser/
#: build_doctor_parser, consumed by obs/timeline.py and obs/doctor.py)
#: plus the serve --no_metrics plumbing; v5 adds the convergence surface
#: (build_converge_parser, consumed by obs/converge.py) plus the
#: --no_converge/--iter_epe plumbing on the eval and serve surfaces; v6
#: adds the numerics surface (build_numerics_parser, consumed by
#: obs/numerics.py) plus the --no_numerics/--numerics_every/--numerics
#: plumbing on the train, eval and serve surfaces; v7 adds the adaptive-
#: iteration plumbing — --iter_policy on the eval surface, --iter_policy/
#: --adaptive on the serve/loadtest surfaces, and the policy-emission
#: flags (--emit-policy/--policy-tau/--policy-min-iters/--policy-margin)
#: on the converge surface — so earlier suppressions no longer mean what
#: they said; v8 adds the fleet surface (build_fleet_parser, consumed by
#: obs/fleet.py) plus the fleet-observatory plumbing (--no_fleet/
#: --host_id/--heartbeat_every) on the train, serve and loadtest
#: surfaces; v9 adds the memoryless fused-correlation plumbing (r18) —
#: --fused_block_w and the fused/fused_cuda/memoryless impl choices on
#: the shared model-config surface, plus --fused_width (the per-bucket
#: program-swap threshold) on the serve surface; v10 adds the lint/drill
#: surfaces (r19) — the graftlint runner's own argparse module
#: (--concurrency engine selector, --threads-baseline/--witness
#: lock-order flags) and the load/rehearsal/fleet drill scripts join
#: ENTRY_SCRIPTS as self-consumed surfaces, and dest= keywords now
#: override the flag-derived dest (an aliased flag no longer
#: false-fires).
RULE_VERSIONS: Dict[str, int] = {
    "tracer-unsafe": 1,
    "wall-clock": 1,
    "import-time-jnp": 1,
    "cli-drift": 10,
}

# Call names (last attribute segment) that trace their function arguments.
TRACING_TRANSFORMS = frozenset({
    "jit", "pmap", "grad", "value_and_grad", "vmap", "checkpoint", "remat",
    "scan", "while_loop", "fori_loop", "cond", "switch", "map",
    "custom_vjp", "custom_jvp", "defvjp", "defjvp", "pallas_call",
    "shard_map", "eval_shape", "make_jaxpr", "named_call",
})

# Module aliases whose call results are host-side numpy, not tracers.
NUMPY_NAMES = frozenset({"np", "numpy", "onp"})

TRACER_UNSAFE_CASTS = frozenset({"float", "int", "bool"})

# Names whose attribute reads are static at trace time: config dataclasses
# and flax hyperparameters (`self.*` on a Module) are python values, not
# tracers — `bool(cfg.fold_enc_saves)` is mode selection, not
# concretization. Traced values always arrive as call arguments.
STATIC_ROOTS = frozenset({"cfg", "config", "self"})
WALL_CLOCK_CALLS = frozenset({
    ("time", "time"), ("time", "perf_counter"), ("time", "monotonic"),
    ("time", "process_time"), ("time", "time_ns"),
    ("time", "perf_counter_ns"), ("datetime", "now"), ("datetime", "utcnow"),
})


def _last_attr(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _attr_chain(node: ast.AST) -> List[str]:
    """``a.b.c`` -> ["a", "b", "c"]; empty when not a name/attr chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _called_functions(call: ast.Call) -> List[str]:
    """Names of functions passed (positionally or by keyword) to a tracing
    transform, unwrapping ``functools.partial(fn, ...)``."""
    out: List[str] = []

    def visit(arg):
        if isinstance(arg, ast.Name):
            out.append(arg.id)
        elif isinstance(arg, ast.Attribute):
            chain = _attr_chain(arg)
            if chain:
                out.append(chain[-1])
        elif isinstance(arg, ast.Call) and _last_attr(arg.func) == "partial":
            for a in arg.args:
                visit(a)

    for a in call.args:
        visit(a)
    for kw in call.keywords:
        if kw.arg in ("f", "fun", "fn", "body_fun", "cond_fun", "kernel"):
            visit(kw.value)
    # method-style: fwd.defvjp(fwd_rule, bwd_rule) — the receiver is
    # reachable too
    chain = _attr_chain(call.func)
    if chain and chain[-1] in ("defvjp", "defjvp") and len(chain) >= 2:
        out.append(chain[-2])
    return out


class _ModuleIndex(ast.NodeVisitor):
    """One pass over a module: function defs (with qualnames), nn.Module
    classes, names referenced by tracing transforms, jit-ish decorators."""

    def __init__(self):
        self.functions: Dict[str, List[ast.AST]] = {}   # name -> def nodes
        self.qualname: Dict[int, str] = {}              # id(node) -> qual
        self.parent_fn: Dict[int, Optional[ast.AST]] = {}
        self.module_classes: Set[str] = set()           # nn.Module classes
        self.traced_names: Set[str] = set()
        self.decorated: Set[int] = set()                # id(def) jit-deco
        self.module_level_stmts: List[ast.stmt] = []
        self._stack: List[ast.AST] = []
        self._class_stack: List[ast.ClassDef] = []

    def visit_Module(self, node):
        self.module_level_stmts = list(node.body)
        self.generic_visit(node)

    def _qual(self, name: str) -> str:
        parts = [n.name for n in self._stack if hasattr(n, "name")]
        return ".".join([c.name for c in self._class_stack] + parts + [name])

    def visit_ClassDef(self, node):
        for base in node.bases:
            chain = _attr_chain(base)
            if chain and chain[-1] == "Module":
                self.module_classes.add(node.name)
        self._class_stack.append(node)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_def(self, node):
        self.functions.setdefault(node.name, []).append(node)
        self.qualname[id(node)] = self._qual(node.name)
        self.parent_fn[id(node)] = self._stack[-1] if self._stack else None
        for deco in node.decorator_list:
            target = deco.func if isinstance(deco, ast.Call) else deco
            name = _last_attr(target)
            if name in TRACING_TRANSFORMS or name == "compact":
                self.decorated.add(id(node))
            if isinstance(deco, ast.Call) \
                    and _last_attr(deco.func) == "partial":
                for a in deco.args:
                    if _last_attr(a) in TRACING_TRANSFORMS:
                        self.decorated.add(id(node))
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def visit_Call(self, node):
        name = _last_attr(node.func)
        if name in TRACING_TRANSFORMS:
            self.traced_names.update(_called_functions(node))
        self.generic_visit(node)


def _reachable_defs(index: _ModuleIndex) -> Dict[int, str]:
    """id(def node) -> qualname for every jit-reachable function."""
    reachable: Dict[int, str] = {}
    # seeds: referenced in a transform call, decorated, or nn.Module method
    for name in index.traced_names:
        for node in index.functions.get(name, ()):
            reachable[id(node)] = index.qualname[id(node)]
    for name, nodes in index.functions.items():
        for node in nodes:
            if id(node) in index.decorated:
                reachable[id(node)] = index.qualname[id(node)]
            qual = index.qualname[id(node)]
            cls = qual.split(".")[0] if "." in qual else None
            if cls in index.module_classes:
                reachable[id(node)] = qual
    # closure: nested defs of reachable functions
    changed = True
    while changed:
        changed = False
        for name, nodes in index.functions.items():
            for node in nodes:
                if id(node) in reachable:
                    continue
                parent = index.parent_fn.get(id(node))
                if parent is not None and id(parent) in reachable:
                    reachable[id(node)] = index.qualname[id(node)]
                    changed = True
    return reachable


# --- per-function checks -----------------------------------------------------

def _shape_derived_names(fn: ast.AST) -> Set[str]:
    """Names bound (anywhere in the function) from shape-like expressions:
    ``b, h, w, c = x.shape``, ``n = x.shape[0]``, ``k = len(xs)``,
    ``r = x.ndim`` — static python ints under tracing."""
    names: Set[str] = set()

    def shape_like(expr) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Attribute) and sub.attr in ("shape",
                                                               "ndim"):
                return True
            if isinstance(sub, ast.Call) and _last_attr(sub.func) == "len":
                return True
        return False

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and shape_like(node.value):
            for t in node.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
    return names


def _names_in(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _is_static_arg(expr: ast.AST, static_names: Set[str],
                   neutral_names: Set[str]) -> bool:
    """True when every name feeding the expression is statically known
    (shape-derived or a module alias) or the expression itself reads
    ``.shape``/``.ndim``/``len``."""
    if isinstance(expr, ast.Constant):
        return True
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim"):
            return True
        if isinstance(sub, ast.Call) and _last_attr(sub.func) == "len":
            return True
    return _names_in(expr) <= (static_names | neutral_names | STATIC_ROOTS)


def check_function(fn: ast.AST, relpath: str, qual: str,
                   neutral_names: Set[str]) -> List[Finding]:
    """tracer-unsafe + wall-clock findings for one jit-reachable function
    (``fn``'s own body only — nested defs are visited separately)."""
    findings: List[Finding] = []
    static_names = _shape_derived_names(fn)

    skip: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            for sub in ast.walk(node):
                skip.add(id(sub))

    loc = f"{relpath}::{qual}"
    for node in ast.walk(fn):
        if id(node) in skip or not isinstance(node, ast.Call):
            continue
        name = _last_attr(node.func)
        chain = _attr_chain(node.func)
        # float()/int()/bool() on a traced value
        if isinstance(node.func, ast.Name) \
                and name in TRACER_UNSAFE_CASTS and node.args:
            if not _is_static_arg(node.args[0], static_names, neutral_names):
                findings.append(Finding(
                    rule="tracer-unsafe", severity="error", location=loc,
                    message=f"`{name}()` on a value inside a jit-reachable "
                            f"function forces concretization "
                            f"(line {node.lineno})",
                    data={"call": name, "line": node.lineno}))
        # .item()
        elif name == "item" and isinstance(node.func, ast.Attribute) \
                and not node.args:
            findings.append(Finding(
                rule="tracer-unsafe", severity="error", location=loc,
                message=f"`.item()` inside a jit-reachable function "
                        f"(line {node.lineno})",
                data={"call": "item", "line": node.lineno}))
        # np.asarray / np.array on a traced value
        elif name in ("asarray", "array") and len(chain) >= 2 \
                and chain[-2] in NUMPY_NAMES and node.args:
            if not _is_static_arg(node.args[0], static_names, neutral_names):
                findings.append(Finding(
                    rule="tracer-unsafe", severity="error", location=loc,
                    message=f"`{'.'.join(chain)}` materializes a host array "
                            f"inside a jit-reachable function "
                            f"(line {node.lineno})",
                    data={"call": ".".join(chain), "line": node.lineno}))
        # wall clock
        if len(chain) >= 2 and (chain[-2], chain[-1]) in WALL_CLOCK_CALLS:
            findings.append(Finding(
                rule="wall-clock", severity="error", location=loc,
                message=f"`{'.'.join(chain)}` inside a jit-reachable "
                        f"function is evaluated once at trace time "
                        f"(line {node.lineno})",
                data={"call": ".".join(chain), "line": node.lineno}))
    return findings


# --- module-level jnp work ---------------------------------------------------

def _jnp_aliases(tree: ast.Module) -> Set[str]:
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.numpy":
                    aliases.add(a.asname or "jax")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "numpy":
                        aliases.add(a.asname or "numpy")
    return aliases


def check_import_time_jnp(tree: ast.Module, relpath: str) -> List[Finding]:
    """Module-level ``jnp.*``/``jax.numpy.*`` calls (device work + backend
    init at import). Defs/classes don't execute at import; guarded blocks
    (``if __name__``, ``TYPE_CHECKING``) are left alone."""
    aliases = _jnp_aliases(tree)
    findings: List[Finding] = []
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Import, ast.ImportFrom,
                             ast.If)):
            continue
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            hit = (chain and chain[0] in aliases) \
                or (len(chain) >= 2 and chain[0] == "jax"
                    and chain[1] == "numpy")
            if hit:
                findings.append(Finding(
                    rule="import-time-jnp", severity="error",
                    location=f"{relpath}::<module>",
                    message=f"`{'.'.join(chain)}(...)` runs at import time "
                            f"(line {node.lineno}): device work before the "
                            f"entry point can pick a platform",
                    data={"call": ".".join(chain), "line": node.lineno}))
    return findings


# --- cli.py <-> config.py drift ----------------------------------------------

def _argparse_dests(fn: ast.AST) -> Set[str]:
    dests: Set[str] = set()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and _last_attr(node.func) == "add_argument"):
            continue
        explicit = next((k.value.value for k in node.keywords
                         if k.arg == "dest"
                         and isinstance(k.value, ast.Constant)), None)
        if explicit is not None:
            dests.add(explicit)
            continue
        for a in node.args:
            if isinstance(a, ast.Constant) and isinstance(a.value, str) \
                    and a.value.startswith("--"):
                dests.add(a.value.lstrip("-").replace("-", "_"))
    return dests


def _consumed_and_kwargs(fn: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(args.<x> / getattr(args, "x") reads, config-constructor keywords)
    in one ``*_config`` builder."""
    consumed: Set[str] = set()
    kwargs: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "args":
            consumed.add(node.attr)
        if isinstance(node, ast.Call) \
                and _last_attr(node.func) == "getattr" and node.args:
            if isinstance(node.args[0], ast.Name) \
                    and node.args[0].id == "args" and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant):
                consumed.add(node.args[1].value)
        if isinstance(node, ast.Call) \
                and _last_attr(node.func) in ("RAFTStereoConfig",
                                              "TrainConfig"):
            kwargs.update(kw.arg for kw in node.keywords
                          if kw.arg is not None)
    return consumed, kwargs


def check_cli_config_drift(cli_path: str, relpath: str) -> List[Finding]:
    """The flag surface is the public API; the dataclasses are the
    implementation. Three drift modes: a constructor keyword naming a
    non-existent field (typo — would only explode at runtime), a declared
    flag that the matching ``*_config`` builder never reads (parsed then
    silently dropped), and — informational — config fields with no flag."""
    import dataclasses as dc

    from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig

    with open(cli_path) as f:
        tree = ast.parse(f.read(), filename=cli_path)
    fns = {n.name: n for n in tree.body
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    findings: List[Finding] = []
    pairs = [("add_model_args", "model_config", RAFTStereoConfig),
             ("add_train_args", "train_config", TrainConfig)]
    for add_fn, cfg_fn, cls in pairs:
        if add_fn not in fns or cfg_fn not in fns:
            continue
        fields = {f.name for f in dc.fields(cls)}
        dests = _argparse_dests(fns[add_fn])
        consumed, kwargs = _consumed_and_kwargs(fns[cfg_fn])
        for kw in sorted(kwargs - fields):
            findings.append(Finding(
                rule="cli-drift", severity="error",
                location=f"{relpath}::{cfg_fn}",
                message=f"{cfg_fn}() passes keyword {kw!r} but "
                        f"{cls.__name__} has no such field",
                data={"keyword": kw}))
        for d in sorted(dests - consumed):
            findings.append(Finding(
                rule="cli-drift", severity="error",
                location=f"{relpath}::{add_fn}",
                message=f"flag --{d} is declared in {add_fn}() but "
                        f"{cfg_fn}() never reads args.{d} — parsed then "
                        f"dropped",
                data={"dest": d}))
        unexposed = sorted(fields - kwargs)
        if unexposed:
            findings.append(Finding(
                rule="cli-drift", severity="info",
                location=f"{relpath}::{cfg_fn}",
                message=f"{len(unexposed)} {cls.__name__} field(s) not "
                        f"settable from the CLI: {', '.join(unexposed)}",
                data={"fields": unexposed}))
    return findings


# --- entry-script surfaces (evaluate_stereo / demo / bench) ------------------
#
# The v1 rule checked only the shared add_model_args/add_train_args pairs;
# the other de-facto public surfaces drift the same way: the eval/demo
# parser builders whose flags are consumed across module boundaries
# (evaluate_stereo.py/demo.py wrap builders living in cli.py), and the
# bench harness's direct RAFTStereoConfig/TrainConfig constructor calls
# (a typo'd keyword there only explodes on benchmark day).

#: parser-builder function in cli.py -> module relpaths allowed to consume
#: its flags (declaration and consumption legitimately live in different
#: files; a dest no file reads is parsed-then-dropped)
ENTRY_SURFACES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("build_eval_parser", ("raft_stereo_tpu/cli.py", "evaluate_stereo.py")),
    ("build_demo_parser", ("raft_stereo_tpu/cli.py", "demo.py")),
    # serving surfaces (rule v3): the serve/loadtest mains consume most
    # flags in cli.py itself; loadtest's trace knobs are read by the
    # driver module
    ("build_serve_parser", ("raft_stereo_tpu/cli.py",)),
    ("build_loadtest_parser", ("raft_stereo_tpu/cli.py",
                               "raft_stereo_tpu/serve/loadtest.py")),
    # tracing/diagnosis surfaces (rule v4): the parsers are declared in
    # cli.py, their mains live next to the implementations
    ("build_timeline_parser", ("raft_stereo_tpu/cli.py",
                               "raft_stereo_tpu/obs/timeline.py")),
    ("build_doctor_parser", ("raft_stereo_tpu/cli.py",
                             "raft_stereo_tpu/obs/doctor.py")),
    # convergence surface (rule v5): declared in cli.py, consumed by the
    # early-exit simulator's main
    ("build_converge_parser", ("raft_stereo_tpu/cli.py",
                               "raft_stereo_tpu/obs/converge.py")),
    # numerics surface (rule v6): declared in cli.py, consumed by the
    # numerics-observatory replay's main
    ("build_numerics_parser", ("raft_stereo_tpu/cli.py",
                               "raft_stereo_tpu/obs/numerics.py")),
    # fleet surface (rule v8): declared in cli.py, consumed by the
    # fleet-rollup aggregator's main
    ("build_fleet_parser", ("raft_stereo_tpu/cli.py",
                            "raft_stereo_tpu/obs/fleet.py")),
)

#: modules whose own argparse surface must be self-consumed, and whose
#: config-constructor keywords are checked against the dataclass fields
#: (rule v10 added the graftlint runner — the --concurrency/--witness
#: engine-4 surface — and the drill/rehearsal scripts)
ENTRY_SCRIPTS: Tuple[str, ...] = (
    "bench.py", "scripts/bench_inference.py",
    "raft_stereo_tpu/analysis/runner.py",
    "scripts/load_drill.py", "scripts/rehearse_round.py",
    "scripts/fleet_drill.py",
)


def _parse_file(path: str) -> Optional[ast.Module]:
    try:
        with open(path) as f:
            return ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None


def _module_args_reads(tree: ast.Module) -> Set[str]:
    """Every ``args.<x>`` / ``getattr(args, "x")`` read anywhere in a
    module (any function; the conventional namespace name is ``args``)."""
    reads: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "args":
            reads.add(node.attr)
        if isinstance(node, ast.Call) \
                and _last_attr(node.func) == "getattr" \
                and len(node.args) >= 2 \
                and isinstance(node.args[0], ast.Name) \
                and node.args[0].id == "args" \
                and isinstance(node.args[1], ast.Constant):
            reads.add(node.args[1].value)
    return reads


def _config_ctor_kwargs(tree: ast.Module) -> List[Tuple[str, str, str, int]]:
    """(class name, keyword, enclosing scope, line) for every keyword passed
    to a RAFTStereoConfig/TrainConfig constructor call in the module.
    ``**kwargs`` splats are invisible to this check by design."""
    scopes: Dict[int, str] = {}
    for top in tree.body:
        if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(top):
                scopes.setdefault(id(sub), top.name)
    out: List[Tuple[str, str, str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and _last_attr(node.func) in ("RAFTStereoConfig",
                                              "TrainConfig"):
            scope = scopes.get(id(node), "<module>")
            for kw in node.keywords:
                if kw.arg is not None:
                    out.append((_last_attr(node.func), kw.arg, scope,
                                node.lineno))
    return out


def check_entry_surface_drift(repo_root: str) -> List[Finding]:
    """cli-drift over the entry-script surfaces (rule v2): eval/demo parser
    flags must be consumed somewhere in their consumer set, script-local
    argparse flags must be consumed in their own module, and every config
    constructor keyword in the bench harnesses must name a real field."""
    import dataclasses as dc

    from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig

    fields = {"RAFTStereoConfig": {f.name for f in dc.fields(RAFTStereoConfig)},
              "TrainConfig": {f.name for f in dc.fields(TrainConfig)}}
    findings: List[Finding] = []
    trees: Dict[str, Optional[ast.Module]] = {}

    def tree_for(rel: str) -> Optional[ast.Module]:
        if rel not in trees:
            trees[rel] = _parse_file(os.path.join(repo_root, rel))
        return trees[rel]

    cli_tree = tree_for("raft_stereo_tpu/cli.py")
    if cli_tree is not None:
        builders = {n.name: n for n in cli_tree.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
        for builder, consumers in ENTRY_SURFACES:
            fn = builders.get(builder)
            if fn is None:
                continue
            dests = _argparse_dests(fn)
            consumed: Set[str] = set()
            for rel in consumers:
                t = tree_for(rel)
                if t is not None:
                    consumed |= _module_args_reads(t)
            for d in sorted(dests - consumed):
                findings.append(Finding(
                    rule="cli-drift", severity="error",
                    location=f"raft_stereo_tpu/cli.py::{builder}",
                    message=f"flag --{d} is declared in {builder}() but no "
                            f"consumer module ({', '.join(consumers)}) ever "
                            f"reads args.{d} — parsed then dropped",
                    data={"dest": d, "surface": builder}))
    for rel in ENTRY_SCRIPTS:
        t = tree_for(rel)
        if t is None:
            continue
        dests = _argparse_dests(t)
        consumed = _module_args_reads(t)
        for d in sorted(dests - consumed):
            findings.append(Finding(
                rule="cli-drift", severity="error",
                location=f"{rel}::<module>",
                message=f"flag --{d} is declared but args.{d} is never "
                        f"read in {rel} — parsed then dropped",
                data={"dest": d}))
        for cls, kw, scope, line in _config_ctor_kwargs(t):
            if kw not in fields[cls]:
                findings.append(Finding(
                    rule="cli-drift", severity="error",
                    location=f"{rel}::{scope}",
                    message=f"{scope}() passes keyword {kw!r} to {cls} "
                            f"but no such field exists (line {line})",
                    data={"keyword": kw, "class": cls, "line": line}))
    return findings


# --- engine ------------------------------------------------------------------

def lint_source(text: str, relpath: str) -> List[Finding]:
    """All per-module AST rules over one file's source."""
    tree = ast.parse(text, filename=relpath)
    index = _ModuleIndex()
    index.visit(tree)
    # module aliases are neutral in static-arg analysis (math.sqrt(d) etc.)
    neutral: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            neutral.update((a.asname or a.name).split(".")[0]
                           for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            neutral.update(a.asname or a.name for a in node.names)
    findings = check_import_time_jnp(tree, relpath)
    defs_by_id = {id(n): n for nodes in index.functions.values()
                  for n in nodes}
    for fn_id, qual in sorted(_reachable_defs(index).items(),
                              key=lambda kv: kv[1]):
        findings.extend(check_function(defs_by_id[fn_id], relpath, qual,
                                       neutral))
    return findings


def run_ast_rules(package_root: str,
                  repo_root: Optional[str] = None) -> List[Finding]:
    """Lint every module under ``package_root`` + the cli/config drift
    check. Locations are repo-relative."""
    repo_root = repo_root or os.path.dirname(package_root)
    findings: List[Finding] = []
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = [d for d in sorted(dirnames) if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            relpath = os.path.relpath(path, repo_root)
            with open(path) as f:
                text = f.read()
            try:
                findings.extend(lint_source(text, relpath))
            except SyntaxError as e:
                findings.append(Finding(
                    rule="tracer-unsafe", severity="error",
                    location=relpath,
                    message=f"unparseable module: {e}", data={}))
    cli_path = os.path.join(package_root, "cli.py")
    if os.path.exists(cli_path):
        findings.extend(check_cli_config_drift(
            cli_path, os.path.relpath(cli_path, repo_root)))
    findings.extend(check_entry_surface_drift(repo_root))
    return findings
