"""graftlint runner: merge all engines, apply the baseline, gate, report.

``python -m raft_stereo_tpu.cli lint`` runs every engine by default
(``--ast`` / ``--graph`` / ``--spmd`` / ``--concurrency`` restrict the
set), holds the merged
findings against the checked-in suppression baseline (``.graftlint.json``),
prints a human report, optionally writes the JSON report and emits one
schema-v4 ``lint`` event, and exits non-zero when any *unsuppressed
error-severity* finding remains — the gate scripts/rehearse_round.py's
``lint`` leg runs every round.

``--fingerprint`` additionally diffs the canonical executables' structural
fingerprint (conv placement, collective kinds/counts, peak bytes, donation
pairs — analysis/fingerprint.py) against the checked-in baseline
(``.graftlint-fingerprint.json``), and the host thread topology
(analysis/concurrency_rules.py) against ``.graftlint-threads.json``;
drift becomes ordinary error findings, so the same gate applies. ``--update-baseline`` / ``--update-fingerprint``
rewrite the respective baselines from the current state — the escape hatch
for intentionally accepting a violation or a structural change; the diff
review is the policy.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from raft_stereo_tpu.analysis.findings import (Finding, apply_baseline,
                                               baseline_from_findings, gate,
                                               load_baseline, make_report,
                                               severity_counts,
                                               write_baseline)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def rule_versions(graph: bool = True, ast: bool = True,
                  spmd: bool = True,
                  fingerprint: bool = True,
                  concurrency: bool = True) -> Dict[str, int]:
    """Current rule id -> semantic version over the selected engines (the
    map baseline entries are validated against)."""
    versions: Dict[str, int] = {}
    if graph:
        from raft_stereo_tpu.analysis.graph_rules import \
            RULE_VERSIONS as graph_v
        versions.update(graph_v)
    if ast:
        from raft_stereo_tpu.analysis.ast_rules import \
            RULE_VERSIONS as ast_v
        versions.update(ast_v)
    if spmd:
        from raft_stereo_tpu.analysis.spmd_rules import \
            RULE_VERSIONS as spmd_v
        versions.update(spmd_v)
    if fingerprint:
        from raft_stereo_tpu.analysis.fingerprint import \
            RULE_VERSIONS as fp_v
        versions.update(fp_v)
    if concurrency:
        from raft_stereo_tpu.analysis.concurrency_rules import \
            RULE_VERSIONS as conc_v
        versions.update(conc_v)
    return versions


def run_lint(graph: bool = True, ast: bool = True, spmd: bool = True,
             package_root: Optional[str] = None,
             thresholds: Optional[Dict[str, int]] = None,
             spmd_thresholds: Optional[Dict[str, int]] = None,
             compile_train: bool = True,
             collect_targets: bool = False,
             concurrency: bool = True
             ) -> Any:
    """Run the selected engines; raw findings (baseline not applied).

    ``collect_targets=True`` additionally returns the lowered targets
    (graph + spmd) so a caller — the fingerprint gate — can reuse them
    without paying the lowerings twice: ``(findings, targets)``.
    """
    findings: List[Finding] = []
    targets: List[Any] = []
    if ast:
        from raft_stereo_tpu.analysis.ast_rules import run_ast_rules
        root = package_root or os.path.join(REPO_ROOT, "raft_stereo_tpu")
        findings.extend(run_ast_rules(root))
    if concurrency:
        from raft_stereo_tpu.analysis.concurrency_rules import \
            run_concurrency_rules
        root = package_root or os.path.join(REPO_ROOT, "raft_stereo_tpu")
        findings.extend(run_concurrency_rules(root))
    if graph:
        from raft_stereo_tpu.analysis.graph_rules import (build_targets,
                                                          run_graph_rules)
        gt = build_targets(compile_train=compile_train)
        findings.extend(run_graph_rules(thresholds=thresholds, targets=gt))
        targets.extend(gt)
    if spmd:
        from raft_stereo_tpu.analysis.spmd_rules import (build_spmd_targets,
                                                         ensure_host_devices,
                                                         run_spmd_rules)
        if ensure_host_devices():
            st = build_spmd_targets(compile_programs=compile_train)
            findings.extend(run_spmd_rules(thresholds=spmd_thresholds,
                                           targets=st))
            targets.extend(st)
        else:
            findings.append(Finding(
                rule="spmd-skipped", severity="info", location="spmd",
                message="SPMD engine skipped: the initialized backend "
                        "cannot provide the 8-device mesh (run under "
                        "JAX_PLATFORMS=cpu before jax initializes, or on "
                        "a slice)"))
    return (findings, targets) if collect_targets else findings


def _rules_run(graph: bool, ast: bool, spmd: bool,
               fingerprint: bool = False,
               concurrency: bool = False) -> List[str]:
    rules: List[str] = []
    if graph:
        from raft_stereo_tpu.analysis.graph_rules import GRAPH_RULES
        rules.extend(GRAPH_RULES)
    if ast:
        from raft_stereo_tpu.analysis.ast_rules import \
            RULE_VERSIONS as ast_v
        rules.extend(ast_v)
    if spmd:
        from raft_stereo_tpu.analysis.spmd_rules import SPMD_RULES
        rules.extend(SPMD_RULES)
    if fingerprint:
        from raft_stereo_tpu.analysis.fingerprint import RULE
        rules.append(RULE)
    if concurrency:
        from raft_stereo_tpu.analysis.concurrency_rules import \
            CONCURRENCY_RULES
        rules.extend(CONCURRENCY_RULES)
    return rules


def format_findings(findings: List[Finding],
                    stale: List[dict]) -> str:
    lines: List[str] = []
    for f in sorted(findings, key=lambda f: ("ewi".index(f.severity[0]),
                                             f.location)):
        mark = " [suppressed]" if f.suppressed else ""
        lines.append(f"{f.severity:7s} {f.rule:28s} {f.location}{mark}")
        lines.append(f"        {f.message}")
    for e in stale:
        reason = e.get("stale_reason", "matches nothing")
        lines.append(f"stale   suppression ({reason}): "
                     f"{e['rule']} @ {e['location']}")
    unsup = severity_counts(findings, suppressed=False)
    sup = sum(1 for f in findings if f.suppressed)
    lines.append(f"graftlint: {unsup['error']} error(s), "
                 f"{unsup['warning']} warning(s), {unsup['info']} info "
                 f"({sup} suppressed, {len(stale)} stale suppression(s))")
    return "\n".join(lines)


def _fingerprint_findings(args, targets: List[Any], partial: bool
                          ) -> Tuple[List[Finding], Optional[Dict]]:
    """The fingerprint leg of main(): compute/load the current doc, handle
    ``--update-fingerprint``, diff against the baseline. Returns (findings,
    current_doc); current_doc is None only on the precomputed-diff path."""
    from raft_stereo_tpu.analysis.fingerprint import (compute_fingerprint,
                                                      diff_fingerprint,
                                                      load_fingerprint,
                                                      write_fingerprint)
    if args.fingerprint_current:
        current = load_fingerprint(args.fingerprint_current)
        partial = False
    else:
        current = compute_fingerprint(targets)
    if args.update_fingerprint:
        write_fingerprint(args.fingerprint_baseline, current)
        print(f"fingerprint baseline rewritten: "
              f"{args.fingerprint_baseline} "
              f"({len(current['targets'])} target(s))")
        return [], current
    if not os.path.exists(args.fingerprint_baseline):
        return [Finding(
            rule="fingerprint-drift", severity="error",
            location="fingerprint",
            message=f"no fingerprint baseline at "
                    f"{args.fingerprint_baseline} — generate one with "
                    f"--update-fingerprint and check it in")], current
    baseline = load_fingerprint(args.fingerprint_baseline)
    return diff_fingerprint(baseline, current,
                            peak_tolerance=args.fingerprint_tolerance,
                            partial=partial), current


def _topology_findings(args) -> Tuple[List[Finding], Optional[Dict]]:
    """The thread-topology leg of ``--fingerprint``: build the current
    topology (engine 4's extractor), handle ``--update-fingerprint``, diff
    against the checked-in map. Returns (findings, current_doc)."""
    from raft_stereo_tpu.analysis.concurrency_rules import (build_topology,
                                                            diff_topology,
                                                            load_topology,
                                                            write_topology)
    root = args.package_root or os.path.join(REPO_ROOT, "raft_stereo_tpu")
    current = build_topology(root)
    if args.update_fingerprint:
        write_topology(args.threads_baseline, current)
        print(f"thread-topology baseline rewritten: "
              f"{args.threads_baseline} ({len(current['entries'])} "
              f"entries, {len(current['locks'])} lock(s))")
        return [], current
    if not os.path.exists(args.threads_baseline):
        return [Finding(
            rule="thread-topology-drift", severity="error",
            location="threads",
            message=f"no thread-topology baseline at "
                    f"{args.threads_baseline} — generate one with "
                    f"--update-fingerprint and check it in")], current
    baseline = load_topology(args.threads_baseline)
    return diff_topology(baseline, current), current


def _witness_findings(args) -> List[Finding]:
    """Hold a dynamic lock-acquisition log (obs/lockwitness.py dump)
    against the static topology."""
    from raft_stereo_tpu.analysis.concurrency_rules import (build_topology,
                                                            check_witness,
                                                            load_witness)
    if not os.path.exists(args.witness):
        return [Finding(
            rule="lock-order-witness", severity="error",
            location="witness",
            message=f"witness log not found: {args.witness} — run the "
                    f"drill leg with RAFT_LOCK_WITNESS set first")]
    root = args.package_root or os.path.join(REPO_ROOT, "raft_stereo_tpu")
    topology = build_topology(root)
    return check_witness(topology, load_witness(args.witness))


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="raft_stereo_tpu.cli lint",
        description="graftlint: jaxpr/HLO contract checker (single-device "
                    "+ SPMD engines), tracer-safety AST lint, and the "
                    "compiled-executable fingerprint gate (see "
                    "raft_stereo_tpu/analysis/)")
    p.add_argument("--graph", action="store_true",
                   help="run only the unsharded jaxpr/compiled-artifact "
                        "rule engine")
    p.add_argument("--ast", action="store_true",
                   help="run only the source AST lint")
    p.add_argument("--spmd", action="store_true",
                   help="run only the SPMD engine (sharded programs on the "
                        "fake 8-device mesh)")
    p.add_argument("--concurrency", action="store_true",
                   help="run only the host-thread concurrency engine "
                        "(thread topology + lock rules over the package "
                        "AST)")
    p.add_argument("--no-compile", action="store_true",
                   help="skip the AOT compiles (faster; the donation/"
                        "replication rules need executables and are "
                        "skipped, and a fingerprint computed this way is "
                        "partial)")
    p.add_argument("--baseline",
                   default=os.path.join(REPO_ROOT, ".graftlint.json"),
                   help="suppression baseline path")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings")
    p.add_argument("--fingerprint", action="store_true",
                   help="also diff the canonical executables' structural "
                        "fingerprint against the checked-in baseline")
    p.add_argument("--update-fingerprint", action="store_true",
                   help="rewrite the fingerprint baseline from the current "
                        "lowerings (implies --fingerprint)")
    p.add_argument("--fingerprint-baseline",
                   default=os.path.join(REPO_ROOT,
                                        ".graftlint-fingerprint.json"),
                   help="fingerprint baseline path")
    p.add_argument("--fingerprint-tolerance", type=float, default=0.10,
                   help="relative peak-bytes growth tolerated (default "
                        "0.10)")
    p.add_argument("--fingerprint-current", default=None,
                   help="diff this precomputed fingerprint JSON instead of "
                        "lowering anything (test/debug hook; skips every "
                        "engine)")
    p.add_argument("--threads-baseline",
                   default=os.path.join(REPO_ROOT,
                                        ".graftlint-threads.json"),
                   help="thread-topology baseline path (diffed by "
                        "--fingerprint, rewritten by --update-fingerprint)")
    p.add_argument("--witness", default=None,
                   help="check this dynamic lock-acquisition log "
                        "(obs/lockwitness.py dump) against the static "
                        "thread topology")
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the full JSON report here")
    p.add_argument("--run_dir", default=None,
                   help="emit a schema-v4 `lint` event into this run dir's "
                        "events.jsonl")
    p.add_argument("--package-root", default=None,
                   help="lint this package tree instead of the installed "
                        "raft_stereo_tpu/ (fixture trees in tests)")
    args = p.parse_args(argv)

    any_engine_flag = (args.graph or args.ast or args.spmd
                       or args.concurrency)
    graph = args.graph or not any_engine_flag
    ast_on = args.ast or not any_engine_flag
    spmd_on = args.spmd or not any_engine_flag
    conc_on = args.concurrency or not any_engine_flag
    fingerprint_on = (args.fingerprint or args.update_fingerprint
                      or bool(args.fingerprint_current))
    if args.fingerprint_current:
        graph = ast_on = spmd_on = conc_on = False

    # the SPMD engine needs its virtual devices BEFORE any engine first
    # imports jax (backends initialize once per process)
    spmd_ready = True
    if spmd_on:
        from raft_stereo_tpu.analysis.spmd_rules import ensure_host_devices
        spmd_ready = ensure_host_devices()

    findings, targets = run_lint(
        graph=graph, ast=ast_on, spmd=spmd_on, concurrency=conc_on,
        package_root=args.package_root,
        compile_train=not args.no_compile, collect_targets=True)

    fp_doc = None
    topo_doc = None
    if fingerprint_on:
        # a fingerprint over a subset of engines/compiles must not read a
        # baseline-only target's absence as drift
        partial = not (graph and spmd_on and spmd_ready) \
            or args.no_compile
        fp_findings, fp_doc = _fingerprint_findings(args, targets, partial)
        findings.extend(fp_findings)
        if not args.fingerprint_current:
            # the thread-topology map rides the same gate (the
            # --fingerprint-current hook diffs executables only)
            topo_findings, topo_doc = _topology_findings(args)
            findings.extend(topo_findings)
        if args.update_fingerprint:
            return 0
    if args.witness:
        findings.extend(_witness_findings(args))

    # staleness is validated against EVERY engine's rule map, not just the
    # selected ones — a single-engine run must not declare the other
    # engines' rules retired
    versions = rule_versions()
    suppressions = load_baseline(args.baseline)
    findings, stale = apply_baseline(findings, suppressions,
                                     rule_versions=versions)

    if args.update_baseline:
        doc = baseline_from_findings(
            [f for f in findings if f.severity == "error"],
            rule_versions=versions)
        write_baseline(args.baseline, doc)
        print(f"baseline rewritten: {args.baseline} "
              f"({len(doc['suppressions'])} suppression(s))")
        return 0

    print(format_findings(findings, stale))

    engines = [e for e, on in (("graph", graph), ("ast", ast_on),
                               ("spmd", spmd_on and spmd_ready),
                               ("concurrency", conc_on),
                               ("fingerprint", fingerprint_on)) if on]
    report = make_report(findings, _rules_run(graph, ast_on, spmd_on,
                                              fingerprint_on, conc_on),
                         engines, stale_suppressions=stale)
    if fp_doc is not None:
        report["fingerprint"] = {"baseline": args.fingerprint_baseline,
                                 "current": fp_doc}
    if topo_doc is not None:
        report["thread_topology"] = {"baseline": args.threads_baseline,
                                     "entries": len(topo_doc["entries"]),
                                     "locks": len(topo_doc["locks"]),
                                     "shared": len(topo_doc["shared"])}
    if args.json_out:
        os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    if args.run_dir:
        from raft_stereo_tpu.obs import Telemetry
        tel = Telemetry(args.run_dir, stall_deadline_s=None)
        tel.emit("lint", source="cli_lint",
                 findings=len(findings),
                 errors=report["unsuppressed"]["error"],
                 warnings=report["unsuppressed"]["warning"],
                 suppressed=report["suppressed_total"],
                 engines=engines, rules=report["rules_run"])
        tel.close()
    return gate(findings)


if __name__ == "__main__":
    sys.exit(main())
