"""graftlint runner: merge both engines, apply the baseline, gate, report.

``python -m raft_stereo_tpu.cli lint`` runs both engines by default
(``--ast`` / ``--graph`` restrict to one), holds the merged findings
against the checked-in suppression baseline (``.graftlint.json``), prints
a human report, optionally writes the JSON report and emits one schema-v4
``lint`` event, and exits non-zero when any *unsuppressed error-severity*
finding remains — the gate scripts/rehearse_round.py's ``lint`` leg runs
every round.

``--update-baseline`` rewrites the baseline from the current findings —
the escape hatch for intentionally accepting a violation; the diff review
is the policy.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from raft_stereo_tpu.analysis.findings import (Finding, apply_baseline,
                                               baseline_from_findings, gate,
                                               load_baseline, make_report,
                                               severity_counts,
                                               write_baseline)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_lint(graph: bool = True, ast: bool = True,
             package_root: Optional[str] = None,
             thresholds: Optional[Dict[str, int]] = None,
             compile_train: bool = True) -> List[Finding]:
    """Run the selected engines; raw findings (baseline not applied)."""
    findings: List[Finding] = []
    if ast:
        from raft_stereo_tpu.analysis.ast_rules import run_ast_rules
        root = package_root or os.path.join(REPO_ROOT, "raft_stereo_tpu")
        findings.extend(run_ast_rules(root))
    if graph:
        from raft_stereo_tpu.analysis.graph_rules import run_graph_rules
        findings.extend(run_graph_rules(thresholds=thresholds,
                                        compile_train=compile_train))
    return findings


def _rules_run(graph: bool, ast: bool) -> List[str]:
    rules: List[str] = []
    if graph:
        from raft_stereo_tpu.analysis.graph_rules import GRAPH_RULES
        rules.extend(GRAPH_RULES)
    if ast:
        rules.extend(["tracer-unsafe", "wall-clock", "import-time-jnp",
                      "cli-drift"])
    return rules


def format_findings(findings: List[Finding],
                    stale: List[dict]) -> str:
    lines: List[str] = []
    for f in sorted(findings, key=lambda f: ("ewi".index(f.severity[0]),
                                             f.location)):
        mark = " [suppressed]" if f.suppressed else ""
        lines.append(f"{f.severity:7s} {f.rule:28s} {f.location}{mark}")
        lines.append(f"        {f.message}")
    for e in stale:
        lines.append(f"stale   suppression matches nothing: "
                     f"{e['rule']} @ {e['location']}")
    unsup = severity_counts(findings, suppressed=False)
    sup = sum(1 for f in findings if f.suppressed)
    lines.append(f"graftlint: {unsup['error']} error(s), "
                 f"{unsup['warning']} warning(s), {unsup['info']} info "
                 f"({sup} suppressed, {len(stale)} stale suppression(s))")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="raft_stereo_tpu.cli lint",
        description="graftlint: jaxpr/HLO contract checker + tracer-safety "
                    "AST lint (see raft_stereo_tpu/analysis/)")
    p.add_argument("--graph", action="store_true",
                   help="run only the jaxpr/compiled-artifact rule engine")
    p.add_argument("--ast", action="store_true",
                   help="run only the source AST lint")
    p.add_argument("--no-compile", action="store_true",
                   help="skip the donated train-step compile (faster; the "
                        "donation rule needs the executable and is skipped)")
    p.add_argument("--baseline",
                   default=os.path.join(REPO_ROOT, ".graftlint.json"),
                   help="suppression baseline path")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings")
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the full JSON report here")
    p.add_argument("--run_dir", default=None,
                   help="emit a schema-v4 `lint` event into this run dir's "
                        "events.jsonl")
    p.add_argument("--package-root", default=None,
                   help="lint this package tree instead of the installed "
                        "raft_stereo_tpu/ (fixture trees in tests)")
    args = p.parse_args(argv)

    graph = args.graph or not args.ast
    ast_on = args.ast or not args.graph

    findings = run_lint(graph=graph, ast=ast_on,
                        package_root=args.package_root,
                        compile_train=not args.no_compile)
    suppressions = load_baseline(args.baseline)
    findings, stale = apply_baseline(findings, suppressions)

    if args.update_baseline:
        doc = baseline_from_findings(
            [f for f in findings if f.severity == "error"])
        write_baseline(args.baseline, doc)
        print(f"baseline rewritten: {args.baseline} "
              f"({len(doc['suppressions'])} suppression(s))")
        return 0

    print(format_findings(findings, stale))

    engines = [e for e, on in (("graph", graph), ("ast", ast_on)) if on]
    report = make_report(findings, _rules_run(graph, ast_on), engines,
                         stale_suppressions=stale)
    if args.json_out:
        os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    if args.run_dir:
        from raft_stereo_tpu.obs import Telemetry
        tel = Telemetry(args.run_dir, stall_deadline_s=None)
        tel.emit("lint", source="cli_lint",
                 findings=len(findings),
                 errors=report["unsuppressed"]["error"],
                 warnings=report["unsuppressed"]["warning"],
                 suppressed=report["suppressed_total"],
                 engines=engines, rules=report["rules_run"])
        tel.close()
    return gate(findings)


if __name__ == "__main__":
    sys.exit(main())
