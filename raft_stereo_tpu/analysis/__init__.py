"""graftlint — static analysis over the lowered graph and the source tree.

Three engines plus a structural regression gate, one report format
(findings.py):

* graph_rules.py — declarative contract rules over the canonical train
  step and inference lowerings (jaxpr + compiled artifact): op placement
  inside the refinement scan's backward body (``wgrad-in-loop``), dtype
  policy (``dtype-drift``, ``residual-dtype-conformance``), host sync,
  donation aliasing, scan carry size, folded-constant size.
* ast_rules.py — tracer-safety lint over the package source:
  concretizing calls and wall-clock reads in jit-reachable functions,
  module-import-time ``jnp`` work, argparse <-> config drift across the
  shared ``cli.py`` builders and the entry-script surfaces.
* spmd_rules.py — SPMD contracts over the canonical *sharded* lowerings
  on a fake 8-device host mesh: collective placement
  (``collective-in-loop``, ring-rotation whitelisted by structure),
  sharding propagation (``accidental-replication``), reduction dtype
  (``collective-dtype``), axis plumbing (``axis-leak``), donation under
  partitioning (``donation-under-mesh``).
* fingerprint.py — each canonical executable distilled to a checked-in
  structural fingerprint (``.graftlint-fingerprint.json``: conv
  placement, collective kinds in/out of loop, peak bytes, donation
  pairs); ``cli lint --fingerprint`` fails on drift.

Entry point: ``python -m raft_stereo_tpu.cli lint`` (runner.py) — exits
non-zero on unsuppressed error-severity findings; ``.graftlint.json`` at
the repo root is the checked-in suppression baseline (entries carry the
``rule_version`` they were written against; version bumps flag them
stale instead of silently matching).
"""

from raft_stereo_tpu.analysis.findings import (Finding, apply_baseline,
                                               load_baseline, make_report,
                                               severity_counts)

__all__ = ["Finding", "apply_baseline", "load_baseline", "make_report",
           "severity_counts"]
