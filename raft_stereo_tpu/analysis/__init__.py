"""graftlint — static analysis over the lowered graph and the source tree.

Two engines, one report format (findings.py):

* graph_rules.py — declarative contract rules over the canonical train
  step and inference lowerings (jaxpr + compiled artifact): op placement
  inside the refinement scan's backward body (``wgrad-in-loop``), dtype
  policy (``dtype-drift``, ``residual-dtype-conformance``), host sync,
  donation aliasing, scan carry size, folded-constant size.
* ast_rules.py — tracer-safety lint over the package source:
  concretizing calls and wall-clock reads in jit-reachable functions,
  module-import-time ``jnp`` work, argparse <-> config drift.

Entry point: ``python -m raft_stereo_tpu.cli lint`` (runner.py) — exits
non-zero on unsuppressed error-severity findings; ``.graftlint.json`` at
the repo root is the checked-in suppression baseline.
"""

from raft_stereo_tpu.analysis.findings import (Finding, apply_baseline,
                                               load_baseline, make_report,
                                               severity_counts)

__all__ = ["Finding", "apply_baseline", "load_baseline", "make_report",
           "severity_counts"]
