"""SPMD contract rules over the canonical *sharded* lowerings (engine 3).

The graph rules (graph_rules.py) police the single-device program; this
engine polices what changes when a mesh appears: collective placement,
sharding propagation, axis plumbing and donation *under SPMD partitioning*
— the invariants that are silent on 1 device and ruinous on 8 (a psum
inside the 22-iteration refinement scan pays ICI latency per iteration; a
replicated B*H*W^2 correlation volume multiplies the dominant residency by
the mesh size).

Canonical sharded programs, lowered on a fake 8-device host mesh
(``--xla_force_host_platform_device_count=8`` — same partitioner, same
jaxpr topology as a TPU slice; only layouts differ):

* ``train_step[dp]`` — the explicit shard_map DP step
  (parallel/data_parallel.py) with psum'd gradients, AOT-compiled donated;
* ``train_step[dp,batched]`` — the custom-VJP refinement scan + bf16
  residual stacks under the same shard_map (jaxpr only);
* ``inference[ring]`` — the dp x sp ring-correlation forward
  (parallel/ring_corr.py) on a (data=2, seq=4) mesh, compiled.

Rule ids: ``collective-in-loop`` (any collective inside a scan body is an
error — the ring pipeline's block-rotation ppermute, recognized by
structure via :func:`~raft_stereo_tpu.parallel.ring_corr.is_ring_perm`, is
the one whitelisted shape), ``accidental-replication`` (a per-device
buffer in the partitioned executable above the size threshold),
``collective-dtype`` (fp32 reduction over values that were bf16 directly
upstream — 2x the ICI bytes needed, warning), ``axis-leak`` (a mesh axis
the target promises to reduce over that no collective touches, and axes
bound but never used), ``donation-under-mesh`` (the donation contract
re-checked on the sharded executable, where layout changes under
partitioning are exactly what makes XLA drop aliasing silently).
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Any, Callable, Dict, List, Optional, Tuple

from raft_stereo_tpu.analysis.findings import Finding

#: current semantic version of every rule this engine exposes (suppression
#: baseline entries carry the version they were written against; a bump
#: flags them stale instead of silently matching a changed rule)
RULE_VERSIONS: Dict[str, int] = {
    "collective-in-loop": 1,
    "accidental-replication": 1,
    "collective-dtype": 1,
    "axis-leak": 1,
    "donation-under-mesh": 1,
}

DEFAULT_SPMD_THRESHOLDS: Dict[str, int] = {
    # per-device buffer in the partitioned module above this = replication
    # suspicion (the canonical targets' largest legitimate per-device
    # activation is far below; a replicated volume lands far above)
    "replicated_bytes": 8 << 20,          # 8 MiB
    # collectives over fewer elements than this are metric/scalar glue, not
    # an ICI bandwidth concern (the collective-dtype rule's floor)
    "collective_min_elems": 1 << 10,
    # at most this many accidental-replication findings per target (the
    # largest ones; a systematically replicated graph would flood otherwise)
    "replication_top": 4,
}

#: how many virtual host devices the canonical mesh needs
MESH_DEVICES = 8


def ensure_host_devices(n: int = MESH_DEVICES) -> bool:
    """Make sure >= n devices exist, forcing a virtual host platform when
    jax has not been imported yet (the ``cli lint`` path). Returns False
    when the already-initialized backend cannot provide them — the caller
    skips the engine instead of crashing the lint."""
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
        import jax
        # some sandbox images force-register an accelerator plugin at
        # import; pin the analysis to the virtual host platform regardless
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    import jax
    try:
        return len(jax.devices()) >= n
    except Exception:
        return False


@dataclasses.dataclass
class SpmdTarget:
    """One sharded lowering under analysis."""

    name: str
    cfg: Any                        # RAFTStereoConfig
    closed_jaxpr: Any               # jax.core.ClosedJaxpr
    compiled: Any = None            # jax.stages.Compiled, when compiled
    donate_declared: bool = False
    platform: str = "cpu"
    #: logical mesh (axis name -> size) the program was lowered for
    mesh_shape: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: axes the target PROMISES at least one collective over (the DP step's
    #: gradient psum, the ring's seq-axis rotation)
    reduce_axes: Tuple[str, ...] = ()
    _hlo_text: Optional[str] = dataclasses.field(default=None, repr=False)

    def hlo_text(self) -> Optional[str]:
        """Post-partitioning HLO of the compiled executable (cached); None
        when uncompiled or the backend withholds it."""
        if self._hlo_text is None and self.compiled is not None:
            try:
                self._hlo_text = self.compiled.as_text()
            except Exception:
                self._hlo_text = None
        return self._hlo_text


def _walk(target):
    from raft_stereo_tpu.obs.xla import iter_eqns
    return iter_eqns(target.closed_jaxpr, path=target.name)


# --- rule: collective-in-loop ------------------------------------------------

def rule_collective_in_loop(target: SpmdTarget,
                            thresholds: Dict[str, int]) -> List[Finding]:
    """A collective inside a scan body executes once per refinement
    iteration, serialized against the loop's dependence chain — per-iter
    ICI latency the serial-floor decomposition (PERF.md r7) says the model
    cannot hide. The one legitimate shape is the ring-corr pipeline's block
    rotation: a ppermute whose permutation is a pure ring
    (parallel/ring_corr.py's structure tag)."""
    from raft_stereo_tpu.obs.xla import COLLECTIVE_PRIMITIVES
    from raft_stereo_tpu.parallel.ring_corr import is_ring_perm

    hits: Dict[Tuple[str, str], int] = {}
    whitelisted = 0
    for eqn, path in _walk(target):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMITIVES or "/scan[" not in path:
            continue
        if name == "ppermute" and is_ring_perm(eqn.params.get("perm", ())):
            whitelisted += 1
            continue
        key = (path, name)
        hits[key] = hits.get(key, 0) + 1
    return [Finding(
        rule="collective-in-loop", severity="error", location=path,
        message=f"{n} `{prim}` op(s) inside the scan body — a collective "
                f"per refinement iteration rides the loop's serial "
                f"dependence chain (only the ring-corr block rotation is "
                f"whitelisted, by its permutation structure)",
        data={"primitive": prim, "count": n,
              "whitelisted_ring_ppermutes": whitelisted})
        for (path, prim), n in sorted(hits.items())]


# --- rule: accidental-replication --------------------------------------------

def rule_accidental_replication(target: SpmdTarget,
                                thresholds: Dict[str, int]) -> List[Finding]:
    """After SPMD partitioning the module's shapes are per-device: any
    buffer above the threshold is a tensor sharding propagation decided to
    materialize (near-)unsharded on every device. The canonical catch is
    the B*H*W^2 correlation volume going replicated — the single residency
    that caps batch and resolution, silently multiplied by mesh size."""
    text = target.hlo_text()
    if text is None:
        return []
    from raft_stereo_tpu.obs.xla import hlo_large_instructions
    hits = hlo_large_instructions(text, thresholds["replicated_bytes"],
                                  top=thresholds["replication_top"])
    findings: List[Finding] = []
    for i, ins in enumerate(hits):
        findings.append(Finding(
            rule="accidental-replication", severity="error",
            location=f"{target.name}/hlo/{ins['op']}[{i}]",
            message=f"per-device buffer of {ins['bytes']} bytes "
                    f"({ins['dtype']}{ins['shape']} from `{ins['op']}`) "
                    f"exceeds the {thresholds['replicated_bytes']}-byte "
                    f"replication threshold — sharding propagation "
                    f"materialized an (effectively) unsharded tensor on "
                    f"every device",
            data={"bytes": ins["bytes"], "shape": ins["shape"],
                  "dtype": ins["dtype"], "op": ins["op"],
                  "instruction": ins["name"],
                  "threshold": thresholds["replicated_bytes"]}))
    return findings


# --- rule: collective-dtype --------------------------------------------------

_REDUCING_COLLECTIVES = ("psum", "psum2", "all_gather", "reduce_scatter",
                         "psum_scatter", "all_to_all")


def rule_collective_dtype(target: SpmdTarget,
                          thresholds: Dict[str, int]) -> List[Finding]:
    """An fp32 collective over a value that was bf16 immediately upstream
    moves twice the ICI bytes the information needs: reduce in bf16 (or
    widen after the collective) instead. Warning — fp32 accumulation across
    many shards is sometimes a deliberate precision choice."""
    import numpy as np

    min_elems = thresholds["collective_min_elems"]
    f32 = np.dtype("float32")
    try:
        import ml_dtypes
        bf16 = np.dtype(ml_dtypes.bfloat16)
    except Exception:  # no bf16 on this install: nothing to compare against
        return []
    hits: Dict[Tuple[str, str], Dict[str, int]] = {}
    producers: Dict[int, Any] = {}
    for eqn, path in _walk(target):
        if eqn.primitive.name == "convert_element_type":
            producers[id(eqn.outvars[0])] = eqn
        if eqn.primitive.name not in _REDUCING_COLLECTIVES:
            continue
        for iv in eqn.invars:
            aval = getattr(iv, "aval", None)
            if aval is None or getattr(aval, "dtype", None) != f32 \
                    or aval.size < min_elems:
                continue
            prev = producers.get(id(iv))
            if prev is None:
                continue
            src = getattr(prev.invars[0], "aval", None)
            if src is not None and src.dtype == bf16:
                key = (path, eqn.primitive.name)
                rec = hits.setdefault(key, {"count": 0, "elems": 0})
                rec["count"] += 1
                rec["elems"] += int(aval.size)
    return [Finding(
        rule="collective-dtype", severity="warning", location=path,
        message=f"fp32 `{prim}` over {rec['elems']} element(s) widened "
                f"from bf16 immediately upstream — the reduction moves "
                f"2x the ICI bytes the values carry; psum in bf16 or "
                f"narrow before the collective",
        data={"primitive": prim, **rec})
        for (path, prim), rec in sorted(hits.items())]


# --- rule: axis-leak ---------------------------------------------------------

def _shard_map_bindings(target) -> List[Dict[str, Any]]:
    """Per shard_map eqn: bound mesh axes/sizes, axes used by in/out specs,
    and collective axes inside the body."""
    from raft_stereo_tpu.obs.xla import (COLLECTIVE_PRIMITIVES,
                                         collective_axis_names, iter_eqns,
                                         iter_subjaxprs)

    out: List[Dict[str, Any]] = []
    for eqn, path in _walk(target):
        if eqn.primitive.name != "shard_map":
            continue
        p = eqn.params
        mesh = p.get("mesh")
        axis_sizes: Dict[str, int] = {}
        if mesh is not None:
            try:
                axis_sizes = dict(mesh.shape)
            except Exception:
                axis_sizes = {a: int(s) for a, s in
                              zip(getattr(mesh, "axis_names", ()),
                                  getattr(mesh, "axis_sizes", ()))}
        spec_axes: set = set()
        for names in (p.get("in_names") or ()) + (p.get("out_names") or ()):
            if isinstance(names, dict):
                for axes in names.values():
                    spec_axes.update(a for a in axes if isinstance(a, str))
        coll_axes: set = set()
        for sub in iter_subjaxprs(p):
            for seqn, _ in iter_eqns(sub, path=path):
                if seqn.primitive.name in COLLECTIVE_PRIMITIVES:
                    coll_axes.update(collective_axis_names(seqn))
        out.append({"path": path, "axis_sizes": axis_sizes,
                    "spec_axes": spec_axes, "collective_axes": coll_axes})
    return out


def rule_axis_leak(target: SpmdTarget,
                   thresholds: Dict[str, int]) -> List[Finding]:
    """Axis-name plumbing bugs: a target that promises a reduction over an
    axis (the DP step's gradient psum over ``data``, the ring's rotation
    over ``seq``) but whose lowering never runs a collective over it —
    per-shard results silently diverge, which on a mesh means every device
    trains on 1/n-th of the batch and believes it. Secondarily, an axis
    bound by shard_map that neither any spec nor any collective references
    is dead plumbing (warning)."""
    bindings = _shard_map_bindings(target)
    findings: List[Finding] = []
    if target.reduce_axes and not bindings:
        return [Finding(
            rule="axis-leak", severity="error", location=target.name,
            message="target declares reduce axes "
                    f"{list(target.reduce_axes)} but its lowering contains "
                    f"no shard_map at all — the program is not sharded",
            data={"reduce_axes": list(target.reduce_axes)})]
    all_coll = set().union(*(b["collective_axes"] for b in bindings)) \
        if bindings else set()
    sizes: Dict[str, int] = {}
    for b in bindings:
        sizes.update(b["axis_sizes"])
    sizes.update({a: s for a, s in target.mesh_shape.items()
                  if a not in sizes})
    for axis in target.reduce_axes:
        if sizes.get(axis, 0) > 1 and axis not in all_coll:
            findings.append(Finding(
                rule="axis-leak", severity="error", location=target.name,
                message=f"axis {axis!r} (size {sizes[axis]}) must carry a "
                        f"collective on this target but none reduces over "
                        f"it — psum over the wrong axis, or a reduction "
                        f"dropped: per-shard results never combine",
                data={"axis": axis, "size": sizes[axis],
                      "collective_axes": sorted(all_coll)}))
    for b in bindings:
        for axis, size in sorted(b["axis_sizes"].items()):
            if size > 1 and axis not in b["spec_axes"] \
                    and axis not in b["collective_axes"]:
                findings.append(Finding(
                    rule="axis-leak", severity="warning",
                    location=f"{b['path']}/shard_map",
                    message=f"mesh axis {axis!r} (size {size}) is bound by "
                            f"shard_map but appears in no in/out spec and "
                            f"no collective — dead axis plumbing",
                    data={"axis": axis, "size": size}))
    return findings


# --- rule: donation-under-mesh -----------------------------------------------

def rule_donation_under_mesh(target: SpmdTarget,
                             thresholds: Dict[str, int]) -> List[Finding]:
    """The unsharded donation rule re-run where it breaks most quietly:
    partitioning changes layouts, and a layout mismatch is exactly what
    makes XLA drop a declared donation — then every device double-buffers
    the replicated train state."""
    if target.compiled is None or not target.donate_declared:
        return []
    from raft_stereo_tpu.obs.xla import memory_analysis_dict
    mem = memory_analysis_dict(target.compiled)
    if mem is None:
        return []
    if mem.get("alias_bytes", 0) == 0:
        return [Finding(
            rule="donation-under-mesh", severity="error",
            location=target.name,
            message="donate_argnums declared but the SHARDED executable "
                    "aliases 0 bytes — donation was dropped under the mesh "
                    "and the replicated state is double-buffered on every "
                    "device",
            data={"argument_bytes": mem.get("argument_bytes", 0),
                  "platform": target.platform,
                  "mesh": dict(target.mesh_shape)})]
    return []


SPMD_RULES: Dict[str, Callable[[SpmdTarget, Dict[str, int]],
                               List[Finding]]] = {
    "collective-in-loop": rule_collective_in_loop,
    "accidental-replication": rule_accidental_replication,
    "collective-dtype": rule_collective_dtype,
    "axis-leak": rule_axis_leak,
    "donation-under-mesh": rule_donation_under_mesh,
}


def run_rules_on_target(target: SpmdTarget,
                        thresholds: Optional[Dict[str, int]] = None
                        ) -> List[Finding]:
    th = dict(DEFAULT_SPMD_THRESHOLDS, **(thresholds or {}))
    findings: List[Finding] = []
    for fn in SPMD_RULES.values():
        findings.extend(fn(target, th))
    return findings


# --- canonical sharded targets -----------------------------------------------

def build_spmd_targets(batch: int = 8, h: int = 32, w: int = 48,
                       iters: int = 3, ring_batch: int = 2,
                       ring_w: int = 128, ring_iters: int = 2,
                       compile_programs: bool = True) -> List[SpmdTarget]:
    """Lower the canonical sharded programs on the fake 8-device mesh.

    Same jaxpr topology as the production shapes — shape only enters aval
    sizes, so collective placement/axis contracts checked here hold for the
    TPU slice. ``ring_w`` satisfies the ring's width constraint at seq=4:
    lcm(32, factor * seq * 2^(levels-1)) = 128.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
    from raft_stereo_tpu.models import create_model, init_model
    from raft_stereo_tpu.parallel.data_parallel import (
        make_shardmap_train_step)
    from raft_stereo_tpu.parallel.mesh import (DATA_AXIS, SEQ_AXIS,
                                               make_mesh, replicated)
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState

    if len(jax.devices()) < MESH_DEVICES:
        raise RuntimeError(
            f"SPMD targets need {MESH_DEVICES} devices, have "
            f"{len(jax.devices())} (force them with "
            f"--xla_force_host_platform_device_count={MESH_DEVICES} before "
            f"jax import, or call ensure_host_devices() first)")

    platform = jax.default_backend()
    base = RAFTStereoConfig()
    model, variables = init_model(jax.random.PRNGKey(0), base, (1, h, w, 3))
    tcfg = TrainConfig(batch_size=batch, train_iters=iters,
                       image_size=(h, w))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)

    rng = np.random.default_rng(0)

    def batch_for(b, hh, ww):
        return {
            "image1": jnp.asarray(rng.uniform(0, 255, (b, hh, ww, 3)),
                                  jnp.float32),
            "image2": jnp.asarray(rng.uniform(0, 255, (b, hh, ww, 3)),
                                  jnp.float32),
            "flow": jnp.asarray(rng.uniform(-8, 0, (b, hh, ww, 1)),
                                jnp.float32),
            "valid": jnp.ones((b, hh, ww), jnp.float32),
        }

    targets: List[SpmdTarget] = []
    batch_data = batch_for(batch, h, w)

    # 1) explicit shard_map DP train step, compiled donated (the bench/DP
    #    production recipe: fused in-scan loss, psum'd gradients)
    mesh_dp = make_mesh(MESH_DEVICES, 1)
    dp_step = make_shardmap_train_step(model, tx, iters, mesh_dp,
                                       fused_loss=True)
    dp_jaxpr = jax.make_jaxpr(lambda s, bd: dp_step(s, bd))(state,
                                                            batch_data)
    compiled = None
    if compile_programs:
        with mesh_dp:
            state_r = jax.device_put(
                jax.tree.map(jnp.array, state), replicated(mesh_dp))
            dp_batch = {k: jax.device_put(
                v, NamedSharding(mesh_dp, P(DATA_AXIS)))
                for k, v in batch_data.items()}
            compiled = dp_step.lower(state_r, dp_batch).compile()
    targets.append(SpmdTarget(
        name="train_step[dp]", cfg=base, closed_jaxpr=dp_jaxpr,
        compiled=compiled, donate_declared=True, platform=platform,
        mesh_shape={DATA_AXIS: MESH_DEVICES, SEQ_AXIS: 1},
        reduce_axes=(DATA_AXIS,)))

    # 2) the custom-VJP batched-weight-grad path under the same shard_map
    #    (jaxpr only: placement/axis contracts; the unsharded wgrad pin
    #    lives in graph_rules)
    cfg_b = dataclasses.replace(base, batched_scan_wgrad=True,
                                refinement_save_policy=False,
                                residual_dtype="bfloat16")
    model_b = create_model(cfg_b)
    dp_step_b = make_shardmap_train_step(model_b, tx, iters, mesh_dp,
                                         fused_loss=True)
    targets.append(SpmdTarget(
        name="train_step[dp,batched]", cfg=cfg_b,
        closed_jaxpr=jax.make_jaxpr(
            lambda s, bd: dp_step_b(s, bd))(state, batch_data),
        platform=platform,
        mesh_shape={DATA_AXIS: MESH_DEVICES, SEQ_AXIS: 1},
        reduce_axes=(DATA_AXIS,)))

    # 3) dp x sp ring-correlation inference on a (2, 4) mesh: the explicit
    #    sequence-parallel path whose in-scan ppermute is the whitelist's
    #    reason to exist
    cfg_ring = dataclasses.replace(base, corr_implementation="ring")
    model_ring = create_model(cfg_ring)
    mesh_ring = make_mesh(2, 4)
    ring_batch_data = batch_for(ring_batch, h, ring_w)

    def infer(v, a, b):
        return model_ring.apply(v, a, b, iters=ring_iters, test_mode=True)

    with mesh_ring:
        ring_jaxpr = jax.make_jaxpr(infer)(
            variables, ring_batch_data["image1"], ring_batch_data["image2"])
        compiled_ring = None
        if compile_programs:
            spec = NamedSharding(mesh_ring, P(DATA_AXIS, None, SEQ_AXIS,
                                              None))
            im1 = jax.device_put(ring_batch_data["image1"], spec)
            im2 = jax.device_put(ring_batch_data["image2"], spec)
            compiled_ring = jax.jit(infer).lower(variables, im1,
                                                 im2).compile()
    targets.append(SpmdTarget(
        name="inference[ring]", cfg=cfg_ring, closed_jaxpr=ring_jaxpr,
        compiled=compiled_ring, platform=platform,
        mesh_shape={DATA_AXIS: 2, SEQ_AXIS: 4},
        reduce_axes=(SEQ_AXIS,)))
    return targets


def run_spmd_rules(thresholds: Optional[Dict[str, int]] = None,
                   compile_programs: bool = True,
                   targets: Optional[List[SpmdTarget]] = None
                   ) -> List[Finding]:
    """Build the canonical sharded targets (unless given) and run every
    SPMD rule."""
    if targets is None:
        targets = build_spmd_targets(compile_programs=compile_programs)
    findings: List[Finding] = []
    for t in targets:
        findings.extend(run_rules_on_target(t, thresholds))
    return findings
